"""rankDAD learner/reducer — distributed-AD with low-rank (grad, activation)
exchange.

Capability parity with the reference ``distrib/rankdad/`` (``DADLearner``,
``DADReducer``, ``DADParallel`` module wrapper, ``power_iteration_BC``):
instead of shipping weight gradients, each site ships per-layer
(output-gradient, input-activation) pairs compressed to rank r; the
aggregator concatenates sites' pairs along the rank axis — mathematically a
sum of per-site gradient contributions — and re-compresses.  TPU-first
re-design:

- **No module hooks.**  torch's fwd/bwd hooks (``rankdad/spi.py:126-163``)
  become a flax ``intercept_methods`` interceptor + the zero-perturbation
  trick: every ``nn.Dense`` output gets ``h + ε`` with ``ε ≡ 0``; then
  ``∂L/∂ε`` IS the layer's output gradient, obtained from the same
  ``jax.grad`` call that records input activations.  The whole site-side
  computation (forward, one backward, per-layer compression) is ONE jitted
  call.
- **Exact bias handling.**  A ones-column is appended to each activation
  before compression, so the bias gradient rides inside the factorization
  (the reference approximates bias grads from the compressed delta,
  ``spi.py:190-210``).
- **Fixed-iteration block power method** (:func:`..ops.power_iteration_BC`)
  instead of sequential deflation with data-dependent early stop.

Scope parity note: like the reference (leaf ``Linear`` modules; norm layers
skipped, ``spi.py:6,89-95``), compression applies to ``nn.Dense`` layers;
gradients of any other parameters are exchanged dSGD-style alongside.
"""
import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from .. import config
from ..ops import power_iteration_BC
from ..telemetry import get_active as _telemetry
from ..telemetry import health as _health
from ..utils import logger, tensorutils
from .learner import COINNLearner
from .reducer import COINNReducer

dad_rest_file = "dad_rest.npy"

_STATE_KEY = "_rankdad_state"


def _flatten2d(x):
    """(B, ..., d) → (B·..., d) (≙ ref ``_mm_flatten``)."""
    return x.reshape(-1, x.shape[-1])


def _capture_interceptor(acts, counts, perturbs=None, shapes=None):
    """Records every ``nn.Dense`` input activation; optionally adds the zero
    perturbation to its output (making ``∂L/∂ε`` the output gradient).

    Keys are ``<module/path>@<occurrence>`` — unique and deterministic in
    call order even for shared/repeated modules.
    """

    def interceptor(next_fun, args, kwargs, context):
        out = next_fun(*args, **kwargs)
        if isinstance(context.module, nn.Dense) and context.method_name == "__call__":
            path = "/".join(context.module.path)
            k = counts.get(path, 0)
            counts[path] = k + 1
            key = f"{path}@{k}"
            acts[key] = args[0]
            if shapes is not None:
                shapes[key] = (tuple(out.shape), out.dtype)
            if perturbs is not None and key in perturbs:
                out = out + perturbs[key]
        return out

    return interceptor


class _DADState:
    """Site-side capture plan, discovered once per (model, batch-shape)."""

    def __init__(self):
        self.layer_keys = None  # ordered captured-layer keys
        self.perturbs = None  # zero pytree, one leaf per captured output
        self.leaf_map = None  # layer key -> (kernel_leaf_ix, bias_leaf_ix|None)
        self.rest_ix = None  # flat-leaf indices exchanged dSGD-style
        self.compiled = {}  # with_health flag -> jitted step


def _leaf_paths(params):
    """Flat leaves of ``params`` with '/'-joined string paths."""
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    out = []
    for kp, _ in leaves:
        parts = [str(getattr(p, "key", getattr(p, "idx", p))) for p in kp]
        out.append(parts)
    return out


def discover_capture(iteration, params, batch, rng):
    """Shape-only discovery of the capture plan (shared by the file learner
    and the mesh transport).

    Returns ``(layer_keys, perturb_shapes, leaf_map, rest_ix)``:
    ordered captured-layer keys, their output ``(shape, dtype)``s, a map
    layer→(kernel leaf ix, bias leaf ix|None) into ``tree_leaves(params)``,
    and the flat-leaf indices exchanged dSGD-style.
    """
    shapes = {}

    def run(params, batch, rng):
        acts, counts = {}, {}
        with nn.intercept_methods(
            _capture_interceptor(acts, counts, shapes=shapes)
        ):
            it = iteration(params, batch, rng)
        return it["loss"]

    jax.eval_shape(run, params, batch, rng)  # traces, zero FLOPs
    layer_keys = list(shapes.keys())
    paths = _leaf_paths(params)
    leaf_map = {}
    covered = set()
    for key in layer_keys:
        mparts = key.split("@")[0].split("/")

        def _match(i, leaf_name):
            want = mparts + [leaf_name]
            return paths[i][-len(want):] == want

        kern = [i for i in range(len(paths)) if _match(i, "kernel")]
        bias = [i for i in range(len(paths)) if _match(i, "bias")]
        if len(kern) != 1:
            raise ValueError(
                f"rankDAD: cannot uniquely map layer {key!r} to a kernel "
                f"leaf (matches: {len(kern)}); use unique module names."
            )
        b = bias[0] if len(bias) == 1 else None
        leaf_map[key] = (kern[0], b)
        covered.add(kern[0])
        if b is not None:
            covered.add(b)
    rest_ix = [i for i in range(len(paths)) if i not in covered]
    return layer_keys, dict(shapes), leaf_map, rest_ix


def make_dad_loss(iteration):
    """Loss wrapper whose second grad argument (the zero perturbations) yields
    the per-layer output gradients; also returns captured activations."""

    def _loss(params, perturbs, batch, rng):
        acts, counts = {}, {}
        with nn.intercept_methods(
            _capture_interceptor(acts, counts, perturbs=perturbs)
        ):
            it = iteration(params, batch, rng)
        return it["loss"], (it, acts)

    return _loss


def compress_layer_factors(pgrads, acts, layer_keys, leaf_map, key, rank, iters):
    """Per-layer (delta, act) → rank-``rank`` (B, C) factors.

    The ones-column append makes the bias gradient exact inside the
    factorization (beyond the reference's approximation, ``spi.py:190-210``).
    """
    Brs, Crs = {}, {}
    for i, lk in enumerate(layer_keys):
        delta = _flatten2d(pgrads[lk]).astype(jnp.float32)
        act = _flatten2d(acts[lk]).astype(jnp.float32)
        if leaf_map[lk][1] is not None:
            act = jnp.concatenate(
                [act, jnp.ones((act.shape[0], 1), act.dtype)], axis=1
            )
        Brs[lk], Crs[lk] = power_iteration_BC(
            delta, act, jax.random.fold_in(key, i), rank=rank, iterations=iters
        )
    return Brs, Crs


class DADLearner(COINNLearner):
    """Site-side rankDAD (≙ ref ``DADLearner`` + ``DADParallel``)."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.rank = int(self.cache.get("dad_reduction_rank", 10))
        self.iters = int(self.cache.get("dad_num_pow_iters", 5))
        if int(self.cache.get("local_iterations", 1)) > 1:
            # ref hard-breaks on grad accumulation (rankdad/__init__.py:48-49)
            logger.warn("rankDAD does not support local_iterations > 1; using 1.")
            self.cache["local_iterations"] = 1

    @property
    def dad(self) -> _DADState:
        st = self.cache.get(_STATE_KEY)
        if st is None:
            st = self.cache[_STATE_KEY] = _DADState()
        return st

    # ------------------------------------------------------------- discovery
    def _discover(self, params, batch, rng):
        """Shape-only pass: find captured layers + map them to param leaves."""
        st = self.dad
        st.layer_keys, shapes, st.leaf_map, st.rest_ix = discover_capture(
            self.trainer.iteration, params, batch, rng
        )
        st.perturbs = {k: jnp.zeros(s, d) for k, (s, d) in shapes.items()}

    # ------------------------------------------------------------- site steps
    def _dad_compiled(self, with_health=False):
        """The compiled site step.  ``with_health`` (telemetry enabled only —
        the disabled path never pays for it) additionally computes the
        factorization's aggregate relative reconstruction error
        ``‖G − CᵀB‖/‖G‖`` against the exact per-layer kernel gradients
        ``G = actᵀ·delta`` inside the same compiled call; the flag is
        constant for a run, so the two variants never churn retraces."""
        st = self.dad
        fn = st.compiled.get(bool(with_health))
        if fn is not None:
            return fn
        rank, iters = self.rank, self.iters
        layer_keys = tuple(st.layer_keys)
        leaf_map = dict(st.leaf_map)
        rest_ix = tuple(st.rest_ix)
        _loss = make_dad_loss(self.trainer.iteration)

        def _fn(params, perturbs, batch, rng, key):
            # one backward pass for both the output-grads (∂L/∂ε) and the
            # plain grads of uncaptured leaves
            (loss, (it, acts)), (vgrads, pgrads) = jax.value_and_grad(
                _loss, argnums=(0, 1), has_aux=True
            )(params, perturbs, batch, rng)
            Brs, Crs = compress_layer_factors(
                pgrads, acts, layer_keys, leaf_map, key, rank, iters
            )
            vleaves = jax.tree_util.tree_leaves(vgrads)
            rest = [vleaves[i] for i in rest_ix]
            rel_err = jnp.zeros(())
            if with_health:
                num = jnp.zeros(())
                den = jnp.zeros(())
                for lk in layer_keys:
                    delta = _flatten2d(pgrads[lk]).astype(jnp.float32)
                    act = _flatten2d(acts[lk]).astype(jnp.float32)
                    if leaf_map[lk][1] is not None:
                        act = jnp.concatenate(
                            [act, jnp.ones((act.shape[0], 1), act.dtype)],
                            axis=1,
                        )
                    G = act.T @ delta  # exact (din[+1], dout) kernel grad
                    R = Crs[lk].T @ Brs[lk]
                    num = num + jnp.sum(jnp.square(G - R))
                    den = den + jnp.sum(jnp.square(G))
                rel_err = jnp.sqrt(num / jnp.maximum(den, 1e-30))
            return Brs, Crs, rest, loss, it, rel_err

        fn = st.compiled[bool(with_health)] = jax.jit(_fn)
        return fn

    def to_reduce(self):
        """One batch → per-layer compressed (delta, act) factors on the wire
        (≙ ref ``dad_backward``, ``spi.py:212-250``)."""
        out = {}
        batch, nxt = self.trainer.data_handle.next_iter()
        out.update(nxt)
        if batch is None:
            return out
        batch = {k: jnp.asarray(v) for k, v in batch.items()}
        ts = self.trainer.train_state
        st = self.dad
        if st.layer_keys is None:
            self._discover(ts.params, batch, ts.rng)
        rng, sub = jax.random.split(ts.rng)
        key = jax.random.fold_in(sub, 17)
        rec = _telemetry()
        Brs, Crs, rest, loss, it, rel_err = self._dad_compiled(
            with_health=rec.enabled
        )(ts.params, st.perturbs, batch, sub, key)
        self.trainer.train_state = ts.replace(rng=rng)
        wire = config.wire_dtype(self.precision_bits)
        payload = []
        for lk in st.layer_keys:
            payload.append(np.asarray(Brs[lk], wire))
            payload.append(np.asarray(Crs[lk], wire))
        if rec.enabled:
            eff = (
                float(np.mean([_health.effective_rank(np.asarray(Brs[lk]))
                               for lk in st.layer_keys]))
                if st.layer_keys else None
            )
            _health.record_compression_health(
                self.cache, float(np.asarray(rel_err)), eff,
                recorder=rec, engine="rankDAD",
            )
            # (delta, activation) factor bytes vs what the full per-layer
            # kernel grads would have weighed at the same wire dtype
            itemsize = np.dtype(wire).itemsize
            factored = sum(int(a.size) for a in payload)
            full = sum(
                int(payload[2 * i].shape[1]) * int(payload[2 * i + 1].shape[1])
                for i in range(len(st.layer_keys))
            )
            rec.event(
                "rankdad:compress", cat="compress",
                rank=int(self.cache.get("dad_reduction_rank", 10)),
                layers=len(st.layer_keys),
                full_bytes=full * itemsize, factored_bytes=factored * itemsize,
            )
        self._save_wire(config.dad_data_file, payload)
        self._save_wire(dad_rest_file, [np.asarray(g, wire) for g in rest])
        out["dad_data_file"] = config.dad_data_file
        out["dad_rest_file"] = dad_rest_file
        out["reduce"] = True
        mask = batch.get("_mask")
        out["grad_weight"] = (
            1.0 if mask is None or float(np.sum(np.asarray(mask))) > 0 else 0.0
        )
        self._track_dad_scores(batch, loss, it)
        return out

    def _track_dad_scores(self, batch, loss, it):
        averages = self.cache.get("_ep_averages")
        if averages is None:
            averages = self.cache["_ep_averages"] = self.trainer.new_averages()
            self.cache["_ep_metrics"] = self.trainer.new_metrics()
        mask = batch.get("_mask")
        n = float(np.sum(np.asarray(mask))) if mask is not None else 1.0
        averages.add(float(loss), max(n, 1.0))
        metrics = self.cache["_ep_metrics"]
        if metrics.jit_safe and "pred" in it and "true" in it:
            metrics.add(np.asarray(it["pred"]), np.asarray(it["true"]),
                        mask=np.asarray(mask) if mask is not None else None)

    def step(self):
        """Reconstruct per-layer grads from the aggregated factors and step
        (≙ ref ``synced_param_update``, ``spi.py:190-210``)."""
        out = {}
        st = self.dad
        data = self._load_wire(self._base_path(self.input["dad_data_file"]))
        rest = self._load_wire(self._base_path(self.input["dad_rest_file"]))
        ts = self.trainer.train_state
        leaves = jax.tree_util.tree_leaves(ts.params)
        flat = [None] * len(leaves)
        for i, lk in enumerate(st.layer_keys):
            B = jnp.asarray(data[2 * i], jnp.float32)  # (R, dout)
            C = jnp.asarray(data[2 * i + 1], jnp.float32)  # (R, din[+1])
            G = C.T @ B  # (din[+1], dout) — kernel grad (+ bias row)
            kern_ix, bias_ix = st.leaf_map[lk]
            if bias_ix is not None:
                flat[kern_ix] = G[:-1]
                flat[bias_ix] = G[-1]
            else:
                flat[kern_ix] = G
        for j, i in enumerate(st.rest_ix):
            flat[i] = jnp.asarray(rest[j])
        grads = tensorutils.grads_like(ts.params, flat)
        self.trainer.train_state = self.trainer.apply_grads(ts, grads)
        return out


class DADReducer(COINNReducer):
    """Aggregator-side rankDAD (≙ ref ``DADReducer``)."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.rank = int(self.cache.get("dad_reduction_rank", 10))
        self.iters = int(self.cache.get("dad_num_pow_iters", 5))

    def reduce(self):
        site_payloads = self._load("dad_data_file")
        n_layers = len(site_payloads[0]) // 2
        _telemetry().event(
            "reduce:rankDAD", cat="reduce", sites=len(self.input),
            layers=n_layers, rank=self.rank, pow_iters=self.iters,
        )
        wire = config.wire_dtype(self.precision_bits)
        out_payload = []
        key = jax.random.PRNGKey(int(self.cache.get("seed", 0)) + 29)
        # mean semantics across sites: scale the grad side by w_i/Σw so
        # concat-and-multiply averages PARTICIPATING site contributions
        # (dSGD parity incl. fully-padded lockstep rounds — mesh
        # ``_site_weight`` semantics)
        w = np.asarray(self._site_weights(), np.float32)
        scales = w / max(float(w.sum()), 1.0)
        for li in range(n_layers):
            # concat along the rank axis = summed per-site approximations —
            # the exact-concat semantics of ref ``rankdad/__init__.py:70-98``
            B = jnp.concatenate(
                [jnp.asarray(sp[2 * li], jnp.float32) * s
                 for sp, s in zip(site_payloads, scales)], 0
            )
            C = jnp.concatenate(
                [jnp.asarray(sp[2 * li + 1], jnp.float32) for sp in site_payloads], 0
            )
            if self.cache.get("dad_recompress", True):
                B, C = power_iteration_BC(
                    B, C, jax.random.fold_in(key, li), rank=self.rank,
                    iterations=self.iters,
                )
            out_payload.append(np.asarray(B, wire))
            out_payload.append(np.asarray(C, wire))
        fname = self._save_out(config.dad_data_file, out_payload)
        rest_avg = self._average(self._load("dad_rest_file"), payload="dad_rest")
        rname = self._save_out(dad_rest_file, rest_avg)
        return {"dad_data_file": fname, "dad_rest_file": rname, "update": True}
