"""Pipeline parallelism — GPipe-style microbatched stages over a ``pp`` axis.

Completes the framework's parallelism matrix (dp / tp / sp / ep live in
``sequence.py``; federated site-DP in ``mesh.py``).  No reference counterpart
(SURVEY.md §2 "Absent": pipeline parallelism) — designed TPU-first:

- The transformer's blocks are **stacked into one pytree with a leading layer
  axis** and sharded ``P('pp')``: each pipeline rank holds ``L/pp``
  contiguous blocks and applies them with a ``lax.scan`` (one trace,
  whatever the depth).
- The schedule is the classic GPipe loop inside ``shard_map``: ``M + pp - 1``
  ticks; every tick each rank runs its stage on the activation in hand, then
  the activations hop one rank forward with ``lax.ppermute`` (neighbor ICI
  transfer, overlapped with the next tick's compute by XLA).  Rank 0 injects
  microbatch ``i`` at tick ``i``; the last rank banks a finished microbatch
  each tick from ``pp - 1`` on.  Bubble fraction is the standard
  ``(pp-1)/(M+pp-1)``.
- Loss (mean-pool classifier head) is computed on the last rank and
  ``psum``-shared; grads of replicated params are ``psum``ed over ``pp`` (each
  rank contributes only its stages' terms), stacked-layer grads stay sharded.
  Batch additionally shards over a ``dp`` axis.

Uses the same ``TSPConfig``/``init_tsp_params`` parameter pytree as
``sequence.py`` (dense FFN path), so the two scale-out strategies are
interchangeable on one checkpoint.

Why pp is NOT a trainer-stack knob like ``sequence_parallel``/
``tensor_parallel`` (``seq_mesh.py``/``tp_mesh.py``): those integrations
keep the TrainState full-shape and replicated — the invariant the whole
federated stack (dSGD/PowerSGD plane, tp/sp-independent checkpoints,
cross-site lockstep) is built on — because sp shards activations by
TIME and tp shards COMPUTE, neither needing sharded parameter storage.
Pipelining's entire value is sharding the LAYER PARAMETERS' memory across
ranks; a replicated-storage pp would pay the bubble for no memory win.  A
model too big for one chip's HBM therefore uses this module's explicitly
sharded step (or the GSPMD path in ``sequence.py``) directly — at that
scale the per-site training loop IS the pipelined step, and the federated
layer above it is unchanged.
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from functools import partial

from ..config.keys import MeshAxis
from ..ops import flash_attention
from ..utils.jax_compat import resolve_donate_argnums, shard_map
from .sequence import _layernorm, transformer_block

__all__ = ["build_pp_mesh", "stack_layers", "make_pp_train_step",
           "shard_pp_params", "shard_pp_batch"]


def build_pp_mesh(pp=2, dp=1, devices=None):
    devices = list(devices if devices is not None else jax.devices())
    need = pp * dp
    if need > len(devices):
        raise ValueError(f"need {need} devices, have {len(devices)}")
    return Mesh(np.array(devices[:need]).reshape(pp, dp), (MeshAxis.PP, MeshAxis.DP))


def stack_layers(params):
    """List-of-layer-dicts → one pytree with a leading (n_layers,) axis."""
    layers = params["layers"]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)
    rest = {k: v for k, v in params.items() if k != "layers"}
    rest["layers"] = stacked
    return rest


def _pp_specs(params):
    def spec_for(path, leaf):
        return P(MeshAxis.PP) if any(
            getattr(p, "key", None) == "layers" for p in path
        ) else P()
    return jax.tree_util.tree_map_with_path(spec_for, params)


def shard_pp_params(params, mesh):
    """Stacked params → device_put with layers over pp, rest replicated."""
    specs = _pp_specs(params)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


def shard_pp_batch(x, y, mesh):
    x = jax.device_put(x, NamedSharding(mesh, P(MeshAxis.DP)))
    y = jax.device_put(y, NamedSharding(mesh, P(MeshAxis.DP)))
    return x, y


def _block(h, lp, cfg):
    """Shared block math from ``sequence.py`` with plain (local) flash
    attention and no sharding constraints."""
    attn = lambda q, k, v: flash_attention(
        q, k, v, causal=cfg.causal, impl=cfg.attn_impl
    )
    h, _ = transformer_block(h, lp, cfg, attn)
    return h


def make_pp_train_step(cfg, mesh, lr=1e-3, num_microbatches=None,
                       donate=True):
    """Jit-compiled SGD step with GPipe pipelining over ``pp``.

    ``num_microbatches`` defaults to the pp size (minimum that fills the
    pipe; raise it to shrink the bubble).  The incoming params are DONATED
    on accelerator backends (the step returns their successor — the
    tier-3 perf-donation contract; a no-op on CPU); callers re-reading
    the pre-step params after the call must pass ``donate=False``."""
    pp = mesh.shape[MeshAxis.PP]
    M = int(num_microbatches or pp)
    assert cfg.num_experts == 0, "pipeline path uses the dense-FFN layers"

    def local_loss(params, x, y):
        # x: (B_local, T, F) — this dp rank's batch, replicated across pp
        r = lax.axis_index(MeshAxis.PP)
        b = x.shape[0]
        assert b % M == 0, f"batch {b} must divide microbatches {M}"
        mb = b // M
        dtype = cfg.dtype
        t = x.shape[1]

        # stage input for microbatch injection (stage 0 only uses this)
        emb_all = (
            jnp.asarray(x, dtype) @ params["in_proj"].astype(dtype)
            + params["pos"][:t][None].astype(dtype)
        ).reshape(M, mb, t, cfg.d_model)

        def stage(h):
            return lax.scan(
                lambda c, lp: (_block(c, lp, cfg), None), h, params["layers"]
            )[0]

        def tick(carry, i):
            h, outs = carry
            inject = emb_all[jnp.clip(i, 0, M - 1)]
            h = jnp.where((r == 0)[None, None, None], inject, h)
            h = stage(h)
            # bank the last rank's finished microbatch (valid from tick pp-1)
            j = i - (pp - 1)
            outs = lax.dynamic_update_index_in_dim(
                outs, jnp.where(j >= 0, h, outs[jnp.clip(j, 0, M - 1)]),
                jnp.clip(j, 0, M - 1), 0,
            )
            h = lax.ppermute(
                h, MeshAxis.PP, perm=[(k, (k + 1) % pp) for k in range(pp)]
            )
            return (h, outs), None

        h0 = jnp.zeros((mb, t, cfg.d_model), dtype) + 0.0 * emb_all[0]
        outs0 = jnp.zeros((M, mb, t, cfg.d_model), dtype) + 0.0 * emb_all
        (_, outs), _ = lax.scan(tick, (h0, outs0), jnp.arange(M + pp - 1))

        # classifier head on the last rank.  IMPORTANT: this is the rank-LOCAL
        # loss term (ce on the last pp rank, 0 elsewhere) — the cross-rank
        # reductions happen on the VALUE and on the GRADS explicitly in
        # sharded_step, never inside the differentiated expression, so no
        # collective transpose can double-count cotangents.
        hfin = _layernorm(
            outs.reshape(b, t, cfg.d_model).astype(jnp.float32), params["lnf"]
        )
        logits = jnp.mean(hfin, axis=1) @ params["head"]
        logp = jax.nn.log_softmax(logits)
        ce = -jnp.mean(jnp.take_along_axis(logp, y[:, None], 1))
        is_last = (r == pp - 1).astype(jnp.float32)
        return ce * is_last

    def sharded_step(params, x, y):
        local, grads = jax.value_and_grad(local_loss)(params, x, y)
        # pp-sharded layer grads arrive complete via the ppermute-transposed
        # chain; replicated params hold only this rank's stages' terms → sum.
        grads = jax.tree_util.tree_map_with_path(
            lambda path, g: g if any(
                getattr(p, "key", None) == "layers" for p in path
            ) else lax.psum(g, MeshAxis.PP),
            grads,
        )
        # global loss = mean over dp of per-rank ce → grads average over dp
        grads = jax.tree_util.tree_map(lambda g: lax.pmean(g, MeshAxis.DP), grads)
        params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
        loss = lax.pmean(lax.psum(local, MeshAxis.PP), MeshAxis.DP)
        return params, loss

    p_specs = _pp_specs  # resolved per-call against the actual pytree

    donate_argnums = resolve_donate_argnums(None, (0,)) if donate else ()

    @partial(jax.jit, donate_argnums=donate_argnums)
    def step(params, x, y):
        specs = p_specs(params)
        return shard_map(
            sharded_step,
            mesh=mesh,
            in_specs=(specs, P(MeshAxis.DP), P(MeshAxis.DP)),
            out_specs=(specs, P()),
            check_vma=False,
        )(params, x, y)

    return step
