"""Mesh transport — federated sites as ranks on a ``jax.sharding.Mesh``.

This is the TPU-native inversion of the reference's file+JSON gradient plane
(SURVEY.md §2 "Distributed communication backend"): when simulated sites live
on one pod (slice), a whole dSGD round — N sites' forward/backward, gradient
averaging, and the synchronized optimizer step — is ONE jit-compiled
``shard_map`` step whose cross-site mean lowers to an XLA ``psum`` over ICI.
The compression engines collapse too: PowerSGD's two wire rounds become two
in-step collectives (mean-P, mean-Q); rankDAD's sample-axis concat becomes an
``all_gather`` of the per-site factors.

Mesh axes:
- ``site``  — one rank per federated site (≙ one ``COINNLocal`` process).
- ``device`` — intra-site data parallelism over the site's chips
  (≙ the reference's ``torch.nn.DataParallel``, ``nn/basetrainer.py:66-69``).

Control (epoch barriers, validation cadence, early stop, fold rotation) stays
host-side in :class:`MeshFederation`'s caller — see ``nodes/``; only the hot
gradient plane is compiled here.
"""
import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config.keys import MeshAxis
from ..ops import orthogonalize
from ..utils.jax_compat import resolve_donate_argnums, shard_map


from .powersgd import _aslist  # msgpack list/dict normalization (shared)


def build_site_only_mesh(n_shards, devices=None):
    """1-D ``(site,)`` mesh for the site-vectorized federation
    (:mod:`~..federation.vector`): the stacked ``MeshAxis.SITE`` axis of B
    simulated sites shards across ``n_shards`` physical devices
    (Anakin-style — many logical workers per device rank), instead of the
    one-rank-per-site mapping of :func:`build_site_mesh`."""
    devices = list(devices if devices is not None else jax.devices())
    if n_shards > len(devices):
        raise ValueError(
            f"site-only mesh needs {n_shards} devices; only "
            f"{len(devices)} available"
        )
    return Mesh(np.array(devices[:n_shards]), (MeshAxis.SITE,))


def build_site_mesh(n_sites, devices=None, devices_per_site=None):
    """Mesh of shape (site, device) over the available devices.

    Each site gets ``devices_per_site`` chips (default: as many as fit
    evenly); a 32-site config on a v4-32 maps sites across slices via DCN
    transparently — the axes are logical.
    """
    devices = list(devices if devices is not None else jax.devices())
    if devices_per_site is None:
        devices_per_site = max(len(devices) // n_sites, 1)
    need = n_sites * devices_per_site
    if need > len(devices):
        raise ValueError(
            f"Mesh needs {need} devices ({n_sites} sites × {devices_per_site}); "
            f"only {len(devices)} available."
        )
    arr = np.array(devices[:need]).reshape(n_sites, devices_per_site)
    return Mesh(arr, (MeshAxis.SITE, MeshAxis.DEVICE))


class MeshFederation:
    """Drives federated rounds where the gradient plane is XLA collectives.

    Wraps a :class:`~..nn.basetrainer.NNTrainer`; the trainer's pure pieces
    (``_grads_uncompiled``, ``_apply_updates``) are composed into one
    ``shard_map``-ped step so nothing leaves the devices between a site's
    backward pass and the globally averaged update.

    Replication invariant: params/opt_state/rng stay bitwise identical across
    sites (identical init + identical averaged update — the reference's
    weight-sync-by-construction, SURVEY §3.3); only the per-site
    error-feedback state (PowerSGD) is site-sharded.
    """

    SUPPORTED_ENGINES = ("dSGD", "powerSGD", "rankDAD")

    def __init__(self, trainer, n_sites, agg_engine="dSGD", devices=None,
                 devices_per_site=None):
        self.trainer = trainer
        self.n_sites = int(n_sites)
        self.agg_engine = str(agg_engine)
        if self.agg_engine not in self.SUPPORTED_ENGINES:
            raise ValueError(
                f"agg_engine {self.agg_engine!r} is not supported on the mesh "
                f"transport (supported: {self.SUPPORTED_ENGINES}); refusing to "
                "silently change the algorithm"
            )
        if self.agg_engine == "rankDAD" and devices_per_site is None:
            devices_per_site = 1  # factor rows cannot split over devices
        self.mesh = build_site_mesh(self.n_sites, devices, devices_per_site)
        if self.agg_engine == "rankDAD":
            if self.mesh.devices.shape[1] != 1:
                raise ValueError(
                    "mesh rankDAD requires devices_per_site == 1 (per-sample "
                    "factor rows cannot split over the device axis)"
                )
            if int(self.trainer.cache.get("local_iterations", 1)) > 1:
                raise ValueError(
                    "rankDAD does not support local_iterations > 1 "
                    "(ref rankdad/__init__.py:48-49)"
                )
        self.comm_state = {}  # site-sharded engine state (PowerSGD EF memory)
        self._sample_batch_keys = None  # batch keys (per-key spec subclasses)
        self._hi_ix = None  # static: flat-leaf indices compressed by PowerSGD
        self._dad = None  # rankDAD capture plan (layer keys, leaf map, shapes)
        self._step = None
        self._warmup_step = None  # plain-dSGD step used for PowerSGD warm-up
        self._eval = None
        # completed update rounds — drives the PowerSGD dSGD warm-up window
        # (≙ file transport's ``_PowerSGDState.iteration`` and ref
        # ``powersgd/__init__.py:61-64``)
        self.rounds_done = 0

    # -------------------------------------------------------------- batching
    def stack_site_batches(self, per_site_batches):
        """[site → list of k micro-batches] → pytree with leading (site, k)
        axes, placed with the step's input sharding (site-sharded, batch dim
        split over the device axis).

        Multi-process runtime (a real pod, one process per host): every
        process calls this with the FULL cross-site batch list and only its
        addressable shards are materialized — ``device_put`` cannot target
        non-addressable devices, so stacking stays on the HOST (no staging
        of the whole global batch through one device's HBM) and placement
        goes through ``make_array_from_callback``."""
        if jax.process_count() > 1:
            cast = self.trainer._input_cast_dtype()
            per_site_host = []
            for batches in per_site_batches:
                bs = [self.trainer._cast_batch_inputs(b, cast) if cast
                      else b for b in batches]
                per_site_host.append({
                    k: np.stack([np.asarray(b[k]) for b in bs])
                    for k in bs[0]
                })
            glob_h = {
                k: np.stack([s[k] for s in per_site_host])
                for k in per_site_host[0]
            }
            self._sample_batch_keys = tuple(glob_h.keys())
            spec = self._train_batch_specs()
            out = {}
            for k, host in glob_h.items():
                sharding = NamedSharding(self.mesh, self._spec_for(spec, k))
                out[k] = jax.make_array_from_callback(
                    host.shape, sharding, lambda idx, a=host: a[idx]
                )
            return out
        stacked = [self.trainer._stack_batches(b) for b in per_site_batches]
        glob = {k: jnp.stack([s[k] for s in stacked]) for k in stacked[0]}
        self._sample_batch_keys = tuple(glob.keys())
        spec = self._train_batch_specs()
        shardings = {
            k: NamedSharding(self.mesh, self._spec_for(spec, k)) for k in glob
        }
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, s), glob, shardings
        )

    # ------------------------------------------------------- powerSGD state
    def init_powersgd_state(self, rank=1, seed=0):
        """Per-site error-feedback + warm-start Q for every ≥2-D leaf.

        Stored with a leading ``site`` axis; Qs start identical at every site
        AND identical to the file transport's (same :func:`.powersgd.seeded_Q`
        keyed by the leaf's position in the high-rank list), and stay
        identical because both wire rounds end in a mean."""
        from .powersgd import seeded_Q

        leaves = jax.tree_util.tree_leaves(self.trainer.train_state.params)
        self._hi_ix = tuple(i for i, l in enumerate(leaves) if l.ndim >= 2)
        # build on the HOST: under a multi-process runtime the full global
        # state must never transit one device's HBM (the same rationale as
        # stack_site_batches); _place_site_sharded materializes only the
        # addressable site rows
        errors, qs = [], []
        for j, i in enumerate(self._hi_ix):
            leaf = leaves[i]
            m = (leaf.shape[0], int(np.prod(leaf.shape[1:])))
            errors.append(np.zeros((self.n_sites, *m), np.float32))
            q = np.asarray(seeded_Q(seed, j, m[1], rank))
            qs.append(np.tile(q[None], (self.n_sites, 1, 1)))
        self.comm_state = self._place_site_sharded({"errors": errors, "qs": qs})
        return self.comm_state

    def _place_site_sharded(self, tree):
        """Place leading-``site``-axis state as a global sharded array.

        Single-process: a no-op (the jitted step reshards on entry).
        Multi-process: every process holds the identical host value
        (deterministic zeros + seeded Qs) and materializes only its
        addressable site rows — uncommitted local arrays cannot enter a
        multi-controller jit under a site-sharded spec."""
        if jax.process_count() <= 1:
            return tree

        def place(x):
            host = np.asarray(jax.device_get(x))
            s = NamedSharding(
                self.mesh, P(*([MeshAxis.SITE] + [None] * (host.ndim - 1)))
            )
            return jax.make_array_from_callback(
                host.shape, s, lambda idx, a=host: a[idx]
            )

        return jax.tree_util.tree_map(place, tree)

    def serialize_comm_state(self):
        """Host-side snapshot of the carried engine state (PowerSGD EF
        memory + warm-started Qs, with their leading site axis) + the
        warm-up round counter — what a mesh-run resume point must carry."""
        def host_value(x):
            arr = jnp.asarray(x)
            if (jax.process_count() > 1
                    and not getattr(arr, "is_fully_addressable", True)):
                # site-sharded global array: reassemble the full value from
                # every process's addressable rows (device_get would raise)
                from jax.experimental import multihost_utils

                return np.asarray(
                    multihost_utils.process_allgather(arr, tiled=True)
                )
            return np.asarray(jax.device_get(arr))

        comm = jax.tree_util.tree_map(host_value, self.comm_state)
        return {"comm": comm, "rounds_done": int(self.rounds_done)}

    def restore_comm_state(self, payload):
        """Rebuild carried engine state from :meth:`serialize_comm_state`."""
        self.rounds_done = int(payload.get("rounds_done", 0))
        comm = payload.get("comm") or {}
        if self.agg_engine == "powerSGD" and comm:
            # establish _hi_ix (and default shapes) first, then overwrite
            self.init_powersgd_state(
                rank=int(self.trainer.cache.get("matrix_approximation_rank", 1)),
                seed=int(self.trainer.cache.get("seed", 0)),
            )
            self.comm_state = self._place_site_sharded({
                "errors": [np.asarray(e, np.float32)
                           for e in _aslist(comm.get("errors"))],
                "qs": [np.asarray(q, np.float32)
                       for q in _aslist(comm.get("qs"))],
            })

    # ------------------------------------------------------- rankDAD plumbing
    def init_rankdad_plan(self, site_batch):
        """Shape-only capture discovery from one site-local batch (shared
        machinery with the file-transport learner, ``rankdad.py``)."""
        from .rankdad import discover_capture

        ts = self.trainer.train_state
        layer_keys, shapes, leaf_map, rest_ix = discover_capture(
            self.trainer.iteration, ts.params, site_batch, ts.rng
        )
        self._dad = {
            "layer_keys": tuple(layer_keys),
            "shapes": dict(shapes),
            "leaf_map": dict(leaf_map),
            "rest_ix": tuple(rest_ix),
        }
        return self._dad

    def _build_rankdad_step(self):
        """One compiled rankDAD round: per-site capture + rank-r compression,
        ``all_gather`` of the (B, C) factors over the ``site`` axis (concat
        along the rank axis ≙ the reference reducer's sample-axis concat,
        ``rankdad/__init__.py:70-98``), local reconstruction, synchronized
        update.  Reconstruction is local, so no re-compression round is
        needed (≙ file path with ``dad_recompress=False``)."""
        from .rankdad import compress_layer_factors, make_dad_loss

        trainer = self.trainer
        metrics_shell, averages_shell = trainer._metrics_shell()
        dad = self._dad
        layer_keys = dad["layer_keys"]
        leaf_map = dad["leaf_map"]
        rest_ix = set(dad["rest_ix"])
        shapes = dad["shapes"]
        rank = int(trainer.cache.get("dad_reduction_rank", 10))
        iters = int(trainer.cache.get("dad_num_pow_iters", 5))
        _loss = make_dad_loss(trainer.iteration)

        def site_step(ts, stacked):
            # (1, k=1, B, ...) site shard → the site's single batch
            batch = jax.tree_util.tree_map(lambda x: x[0, 0], stacked)
            orig_rng = ts.rng
            # same rng derivation as the file learner (``DADLearner.to_reduce``)
            # so both transports compress with identical power-iteration seeds
            rng_next, sub = jax.random.split(orig_rng)
            key = jax.random.fold_in(sub, 17)
            perturbs = {k: jnp.zeros(s, d) for k, (s, d) in shapes.items()}
            (loss, (it, acts)), (vgrads, pgrads) = jax.value_and_grad(
                _loss, argnums=(0, 1), has_aux=True
            )(ts.params, perturbs, batch, sub)
            Brs, Crs = compress_layer_factors(
                pgrads, acts, layer_keys, leaf_map, key, rank, iters
            )
            # participation weight: a site whose batch is fully masked
            # contributes nothing and is excluded from the denominator
            mask = batch.get("_mask")
            w = ((jnp.sum(jnp.asarray(mask, jnp.float32)) > 0).astype(jnp.float32)
                 if mask is not None else jnp.float32(1))
            wsum = jnp.maximum(jax.lax.psum(w, MeshAxis.SITE), 1.0)
            leaves, treedef = jax.tree_util.tree_flatten(vgrads)
            flat = list(leaves)
            for lk in layer_keys:
                B_all = jax.lax.all_gather(Brs[lk] * w, MeshAxis.SITE, axis=0, tiled=True)
                C_all = jax.lax.all_gather(Crs[lk], MeshAxis.SITE, axis=0, tiled=True)
                G = (C_all.T @ B_all) / wsum  # (din[+1], dout)
                kern_ix, bias_ix = leaf_map[lk]
                if bias_ix is not None:
                    flat[kern_ix] = G[:-1].astype(leaves[kern_ix].dtype)
                    flat[bias_ix] = G[-1].astype(leaves[bias_ix].dtype)
                else:
                    flat[kern_ix] = G.astype(leaves[kern_ix].dtype)
            for i in rest_ix:
                flat[i] = jax.lax.psum(leaves[i] * w, MeshAxis.SITE) / wsum
            grads = jax.tree_util.tree_unflatten(treedef, flat)
            ts = trainer._apply_updates(ts, grads)
            ts = ts.replace(rng=rng_next)
            m_state, a_state = trainer._step_outputs(
                it, batch, metrics_shell, averages_shell
            )
            aux = {"loss": jax.lax.pmean(loss, MeshAxis.SITE), "rng": ts.rng}
            if m_state is not None:
                aux["metrics"] = jax.lax.psum(m_state, MeshAxis.SITE)
            elif not getattr(metrics_shell, "jit_safe", True):
                hs = trainer.host_scores_payload(it, batch)
                if hs is not None:
                    aux["host_scores"] = jax.tree_util.tree_map(
                        lambda x: jax.lax.all_gather(x, MeshAxis.SITE, axis=0, tiled=True),
                        hs,
                    )
            aux["averages"] = jax.lax.psum(a_state, MeshAxis.SITE)
            return ts, aux

        batch_spec = P(MeshAxis.SITE, None, MeshAxis.DEVICE)
        mesh = self.mesh
        donate = resolve_donate_argnums(self.trainer.cache, (0,))

        @functools.partial(jax.jit, donate_argnums=donate)
        def step(ts, stacked, comm):
            ts, aux = shard_map(
                site_step,
                mesh=mesh,
                in_specs=(P(), batch_spec),
                out_specs=(P(), P()),
                check_vma=False,
            )(ts, stacked)
            return ts, aux, comm

        return step

    # ---------------------------------------------------------- compiled step
    # ---- intra-site axis hooks -------------------------------------------
    # The compiled round's scaffold (site collectives, PowerSGD exchange,
    # donate/jit/shard_map wrapper) is shared between intra-site DATA
    # parallelism (this class: batch shards over ``device``) and intra-site
    # SEQUENCE parallelism (:class:`~.seq_mesh.SeqMeshFederation`: sequences
    # shard over ``sp``).  Subclasses override only these hooks.

    def _iteration_fn(self):
        """Iteration override passed into the trainer's grad scan (None =
        the trainer's plain ``iteration``)."""
        return None

    def _intra_grad_reduce(self):
        """Per-micro-batch gradient reduction over the intra-site axis."""
        # mask-weighted mean over the batch shards (exact masked-mean even
        # when the padded tail splits unevenly across devices)
        return self.trainer.make_grad_reduce(MeshAxis.DEVICE)

    def _site_weight(self, stacked):
        """1 iff this site's round carried any unmasked sample."""
        mask = stacked.get("_mask")
        if mask is None:
            return jnp.float32(1)
        n_site = jax.lax.psum(
            jnp.sum(jnp.asarray(mask, jnp.float32)), MeshAxis.DEVICE
        )
        return (n_site > 0).astype(jnp.float32)

    def _aux_axes(self):
        """Mesh axes the aux outputs (metrics/averages/loss) reduce over —
        every axis whose shards carry DISTINCT samples."""
        return (MeshAxis.SITE, MeshAxis.DEVICE)

    def _train_batch_specs(self):
        """in_specs entry for the stacked (site, k, B, ...) batch pytree —
        a single spec, or a per-key dict (see :meth:`_spec_for`)."""
        return P(MeshAxis.SITE, None, MeshAxis.DEVICE)

    @staticmethod
    def _spec_for(spec, k):
        """Resolve a batch-spec hook result for key ``k`` (dict or single)."""
        return spec[k] if isinstance(spec, dict) else spec

    def _build_step(self, engine=None):
        trainer = self.trainer
        metrics_shell, averages_shell = trainer._metrics_shell()
        engine = engine or self.agg_engine
        hi_ix = self._hi_ix
        iteration_fn = self._iteration_fn()
        intra_grad_reduce = self._intra_grad_reduce()
        aux_axes = self._aux_axes()

        def _site_mean(x, w, wsum):
            """Participation-weighted mean over the site axis: a site whose
            round carried no unmasked samples contributes nothing AND is
            excluded from the denominator (file-transport parity — a site
            that never ships grads is absent from the reducer's average)."""
            return jax.lax.psum(x * w, MeshAxis.SITE) / wsum

        def _powersgd_exchange(grads, comm, w, wsum):
            """Both PowerSGD wire rounds as in-step collectives, built from
            the SAME per-leaf kernels as the file transport
            (:mod:`.powersgd` ``compress_P/compress_Q/reconstruct``)."""
            from .powersgd import compress_P, compress_Q, reconstruct

            leaves, treedef = jax.tree_util.tree_flatten(grads)
            new_err, new_q, out = [], [], list(leaves)
            for j, i in enumerate(hi_ix):
                leaf = leaves[i]
                # grads are already intra-site-reduced inside the scan
                # (intra_grad_reduce), so only the site axis remains
                m2 = leaf.reshape(leaf.shape[0], -1).astype(jnp.float32)
                # comm leaves keep their (sharded, now size-1) site axis
                M = m2 + comm["errors"][j][0]
                p = _site_mean(compress_P(M, comm["qs"][j][0]), w, wsum)  # wire round 1
                phat = orthogonalize(p)
                qn = _site_mean(compress_Q(M, phat), w, wsum)  # wire round 2
                recon = reconstruct(phat, qn)
                new_err.append((M - recon)[None])
                new_q.append(qn[None])
                out[i] = recon.reshape(leaf.shape).astype(leaf.dtype)
            lo = set(hi_ix)
            for i in range(len(out)):
                if i not in lo:
                    out[i] = _site_mean(leaves[i], w, wsum)
            grads = jax.tree_util.tree_unflatten(treedef, out)
            return grads, {"errors": new_err, "qs": new_q}

        def site_step(ts, stacked, comm):
            # drop the sharded (now size-1) site axis from the batch view
            stacked = jax.tree_util.tree_map(lambda x: x[0], stacked)
            # per-site decorrelated randomness for the forward pass…
            # (both split halves consumed: [0] carries — bit-identical to
            # the historical split(rng)[0] — and [1] seeds the site streams)
            next_rng, site_rng = jax.random.split(ts.rng)
            ts = ts.replace(rng=jax.random.fold_in(site_rng, jax.lax.axis_index(MeshAxis.SITE)))
            grads, aux = trainer._grads_uncompiled(
                ts, stacked, metrics_shell, averages_shell,
                grad_reduce=intra_grad_reduce, iteration_fn=iteration_fn,
            )
            w = self._site_weight(stacked)
            wsum = jnp.maximum(jax.lax.psum(w, MeshAxis.SITE), 1.0)
            if engine == "powerSGD":
                grads, comm = _powersgd_exchange(grads, comm, w, wsum)
            else:
                # intra-site axis already reduced inside the scan
                grads = jax.tree_util.tree_map(
                    lambda g: _site_mean(g, w, wsum), grads
                )
            ts = trainer._apply_updates(ts, grads)
            # …but the carried rng advances identically everywhere, keeping
            # the train state bitwise replicated across sites
            ts = ts.replace(rng=next_rng)
            aux = dict(aux)
            if aux.get("metrics") is not None:
                aux["metrics"] = jax.lax.psum(aux["metrics"], aux_axes)
            if "host_scores" in aux:
                # per-site score streams (non-jit-safe metrics, e.g. AUC):
                # gather along the micro-batch axis so the replicated output
                # carries every site's samples for host accumulation
                aux["host_scores"] = jax.tree_util.tree_map(
                    lambda x: jax.lax.all_gather(
                        x, aux_axes, axis=0, tiled=True
                    ),
                    aux["host_scores"],
                )
            aux["averages"] = jax.lax.psum(aux["averages"], aux_axes)
            aux["loss"] = jax.lax.pmean(aux["loss"], aux_axes)
            aux["rng"] = ts.rng
            return ts, aux, comm

        comm_spec = jax.tree_util.tree_map(lambda _: P(MeshAxis.SITE), self.comm_state)
        batch_spec = self._train_batch_specs()
        mesh = self.mesh

        # donate train state + engine comm state (both replaced every round)
        donate = resolve_donate_argnums(self.trainer.cache, (0, 2))

        @functools.partial(jax.jit, donate_argnums=donate)
        def step(ts, stacked, comm):
            return shard_map(
                site_step,
                mesh=mesh,
                in_specs=(P(), batch_spec, comm_spec),
                out_specs=(P(), P(), comm_spec),
                check_vma=False,
            )(ts, stacked, comm)

        return step

    def train_step(self, site_batches):
        """One federated round: per-site grad accumulation, cross-site
        aggregation, synchronized update — a single compiled call.

        PowerSGD honors ``start_powerSGD_iter``: the first N rounds run the
        plain-dSGD step (error feedback untouched), matching the file
        transport and ref ``powersgd/__init__.py:61-64,130-134``."""
        # batch key set first: per-key-spec subclasses build specs from it
        if isinstance(site_batches, (list, tuple)):
            self._sample_batch_keys = tuple(site_batches[0][0].keys())
        else:
            self._sample_batch_keys = tuple(site_batches.keys())
        if self._step is None:
            if self.agg_engine == "powerSGD" and not self.comm_state:
                self.init_powersgd_state(
                    rank=int(self.trainer.cache.get("matrix_approximation_rank", 1)),
                    seed=int(self.trainer.cache.get("seed", 0)),
                )
            if self.agg_engine == "rankDAD":
                if self._dad is None:
                    if not isinstance(site_batches, (list, tuple)):
                        raise ValueError(
                            "first rankDAD train_step needs per-site batch "
                            "lists (capture discovery reads one batch's shapes)"
                        )
                    first = {
                        k: jnp.asarray(v) for k, v in site_batches[0][0].items()
                    }
                    self.init_rankdad_plan(first)
                self._step = self._build_rankdad_step()
            else:
                self._step = self._build_step()
        step = self._step
        if self.agg_engine == "powerSGD" and self.rounds_done < int(
            self.trainer.cache.get("start_powerSGD_iter", 10)
        ):
            if self._warmup_step is None:
                self._warmup_step = self._build_step(engine="dSGD")
            step = self._warmup_step
        stacked = (
            self.stack_site_batches(site_batches)
            if isinstance(site_batches, (list, tuple))
            else site_batches
        )
        ts, aux, self.comm_state = step(
            self.trainer.train_state, stacked, self.comm_state
        )
        self.trainer.train_state = ts
        self.rounds_done += 1
        return aux

    # ------------------------------------------------------------- evaluation
    def _eval_batch_specs(self):
        """in_specs entry for the (site, B, ...) eval batch pytree."""
        return P(MeshAxis.SITE, MeshAxis.DEVICE)

    def _build_eval(self):
        trainer = self.trainer
        metrics_shell, averages_shell = trainer._metrics_shell()
        mesh = self.mesh
        iteration_fn = self._iteration_fn() or trainer.iteration
        aux_axes = self._aux_axes()
        eval_spec = self._eval_batch_specs()

        def site_eval(ts, batch):
            batch = jax.tree_util.tree_map(lambda x: x[0], batch)
            it = iteration_fn(ts.params, batch, None)
            m_state, a_state = trainer._step_outputs(
                it, batch, metrics_shell, averages_shell
            )
            hs = None
            if m_state is not None:
                m_state = jax.lax.psum(m_state, aux_axes)
            elif not getattr(metrics_shell, "jit_safe", True):
                # non-jit-safe metrics (AUC): gather every site's (score,
                # true, mask) along the batch axis so the HOST accumulates
                # while the eval itself stays one compiled mesh step per
                # batch index — without this, a 32-site AUC run degraded
                # to a serial per-site host loop (32x the eval wall-clock)
                hs = trainer.host_scores_payload(it, batch)
                if hs is not None:
                    hs = jax.tree_util.tree_map(
                        lambda x: jax.lax.all_gather(
                            x, aux_axes, axis=0, tiled=True
                        ),
                        hs,
                    )
            a_state = jax.lax.psum(a_state, aux_axes)
            return m_state, a_state, hs

        @jax.jit
        def ev(ts, batch):
            return shard_map(
                site_eval,
                mesh=mesh,
                in_specs=(P(), eval_spec),
                out_specs=(P(), P(), P()),
                check_vma=False,
            )(ts, batch)

        return ev

    def eval_step(self, site_batches):
        """Globally-reduced evaluation over one batch per site: returns
        ``(metrics_state, averages_state, host_scores)`` — metrics_state
        for jit-safe metrics (host_scores None), or host_scores (gathered
        score/true/mask arrays) for host-accumulated metrics like AUC."""
        if isinstance(site_batches, (list, tuple)):
            # staging-time input cast (nn/basetrainer.py::_input_cast_dtype):
            # the host→device transfer ships the compute dtype and the
            # compiled eval consumes it directly — no in-step re-cast
            site_batches = [
                self.trainer._cast_batch_inputs(b) for b in site_batches
            ]
            self._sample_batch_keys = tuple(site_batches[0].keys())
            glob = {
                k: jnp.stack([jnp.asarray(b[k]) for b in site_batches])
                for k in site_batches[0]
            }
        else:
            self._sample_batch_keys = tuple(site_batches.keys())
            glob = site_batches
        if self._eval is None:
            self._eval = self._build_eval()
        spec = self._eval_batch_specs()
        shardings = {
            k: NamedSharding(self.mesh, self._spec_for(spec, k)) for k in glob
        }
        glob = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, s), glob, shardings
        )
        return self._eval(self.trainer.train_state, glob)


class ReplicatedBatchFederation(MeshFederation):
    """Shared hooks for intra-site axes that do NOT shard samples.

    Sequence parallelism (``seq_mesh.py``) and tensor parallelism
    (``tp_mesh.py``) both keep every intra-site rank holding the site's
    full mask and produce aux outputs replicated across the intra axis
    (the model's own collective — pooling psum or row-parallel psum) —
    so the participation weight needs no intra-axis reduction and aux
    reduces over ``site`` only."""

    def _site_weight(self, stacked):
        mask = stacked.get("_mask")
        if mask is None:
            return jnp.float32(1)
        return (jnp.sum(jnp.asarray(mask, jnp.float32)) > 0).astype(
            jnp.float32
        )

    def _aux_axes(self):
        # reducing over the intra axis too would multi-count every sample
        return (MeshAxis.SITE,)


def lockstep_batches(n_sites, site_sizes, batch_size):
    """Equal-length epochs for every site (≙ the padded sampler invariant,
    ref ``data/data.py:203-242``): global batches per epoch = ceil(max/B)."""
    return max(math.ceil(s / batch_size) for s in site_sizes)
