"""COINNLearner — site-side half of a federated round (dSGD baseline).

Capability parity with the reference ``distrib/learner.py:9-59``:
``backward`` runs ``local_iterations`` micro-batches of forward/backward,
``to_reduce`` ships gradients to the aggregator, ``step`` applies the averaged
gradients that came back.  TPU-first differences:

- ``backward`` is ONE jit-compiled call (``trainer.compute_grads`` — grad
  accumulation is a ``lax.scan``), not a Python loop of ``loss.backward()``.
- Gradients are a pytree over **all** models in the scheme; the reference
  ships only the first model's grads (``learner.py:24-29,51-53`` — a known
  defect, SURVEY §2).
- The wire format is the packed tensor payload of ``utils.tensorutils``
  (manifest + contiguous buffers), not pickled object-dtype ``.npy``.
"""
import os

import numpy as np

from .. import config
from ..config.keys import Key, Mode
from ..resilience.retry import RetryPolicy
from ..utils import tensorutils


class COINNLearner:
    """Baseline distributed-SGD learner (one per site node)."""

    def __init__(self, trainer=None, mp_pool=None, **kw):
        self.trainer = trainer
        self.mp_pool = mp_pool  # accepted for API parity; IO here is async-free
        self.cache = trainer.cache
        self.input = trainer.input
        self.state = trainer.state
        self.global_modes = self.input.get("global_modes", {})

    # ------------------------------------------------------------------ wire
    @property
    def precision_bits(self):
        return self.cache.get("precision_bits", config.default_precision_bits)

    def _transfer_path(self, fname):
        d = self.state.get("transferDirectory", ".")
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, fname)

    def _save_wire(self, fname, arrays):
        """Outbound payload at the configured wire precision, rounding seed
        salted by this site's id (see :func:`tensorutils.save_wire`)."""
        tensorutils.save_wire(
            self._transfer_path(fname), arrays,
            salt=str(self.state.get("clientId", "")),
            cache=self.cache, precision_bits=self.precision_bits,
        )
        return fname

    def _base_path(self, fname):
        return os.path.join(self.state.get("baseDirectory", "."), fname)

    def _load_wire(self, path):
        """Inbound payload load under the site's wire retry policy
        (``Retry.WIRE_*`` cache keys): an absent/incomplete/corrupt payload
        — usually a broadcast still mid-relay — is retried with backoff
        before it can surface as a site failure."""
        return tensorutils.load_arrays(
            path, retry=RetryPolicy.for_wire(self.cache)
        )

    # ------------------------------------------------------------- site steps
    def step(self):
        """Apply the averaged gradients broadcast by the aggregator, then one
        optimizer step (≙ ref ``learner.py:20-30`` — but via a compiled
        ``apply_grads`` and across ALL models)."""
        out = {}
        fname = self.input.get("avg_grads_file", config.avg_grads_file)
        flat = self._load_wire(self._base_path(fname))
        ts = self.trainer.train_state
        grads = tensorutils.grads_like(ts.params, flat)
        self.trainer.train_state = self.trainer.apply_grads(ts, grads)
        return out

    def backward(self):
        """Accumulate gradients over up to ``local_iterations`` batches.

        Returns ``(grads, out, aux)``; ``grads is None`` means the epoch is
        exhausted and ``out['mode']`` carries the barrier signal
        (VALIDATION_WAITING)."""
        out = {}
        batches = []
        k = int(self.cache.get("local_iterations", 1))
        for _ in range(k):
            batch, nxt = self.trainer.data_handle.next_iter()
            out.update(nxt)
            if batch is None:
                break
            batches.append(batch)
        if not batches:
            return None, out, None
        stacked = self.trainer._stack_batches(batches)
        ts = self.trainer.train_state
        grads, aux = self.trainer.compute_grads(ts, stacked)
        self.trainer.train_state = ts.replace(rng=aux["rng"])
        aux = dict(aux)
        # participation: did this round carry ANY unmasked sample?  Ships as
        # ``grad_weight`` so the reducer can exclude fully-padded lockstep
        # rounds from the average (mesh-transport parity)
        mask = stacked.get("_mask")
        aux["participation"] = (
            float(np.asarray(mask).sum()) if mask is not None else 1.0
        )
        return grads, out, aux

    def to_reduce(self):
        """Compute local grads and ship them (≙ ref ``learner.py:49-59``)."""
        grads, out, aux = self.backward()
        if grads is None:
            return out
        flat = tensorutils.extract_grads(grads, self.precision_bits)
        self._save_wire(config.grads_file, flat)
        out["grads_file"] = config.grads_file
        out["reduce"] = True
        out["grad_weight"] = 1.0 if aux.get("participation", 1.0) > 0 else 0.0
        self._track_train_scores(aux)
        return out

    # --------------------------------------------------------------- tracking
    def _track_train_scores(self, aux):
        """Fold the compiled step's metric/average states into the epoch-level
        accumulators living in the cache (serialized at the epoch barrier)."""
        if aux is None:
            return
        averages = self.cache.get("_ep_averages")
        metrics = self.cache.get("_ep_metrics")
        if averages is None:
            averages = self.cache["_ep_averages"] = self.trainer.new_averages()
            metrics = self.cache["_ep_metrics"] = self.trainer.new_metrics()
        self.trainer.fold_train_outputs(aux, averages, metrics)

    def train_serializable(self):
        """Pop the epoch accumulators as a wire payload (epoch barrier)."""
        averages = self.cache.pop("_ep_averages", None) or self.trainer.new_averages()
        metrics = self.cache.pop("_ep_metrics", None) or self.trainer.new_metrics()
        return {
            Key.TRAIN_SERIALIZABLE.value: [
                {"averages": averages.serialize(), "metrics": metrics.serialize()}
            ]
        }
