"""Distributed algorithms (agg engines) + the mesh transport.

Site-side :class:`COINNLearner` and aggregator-side :class:`COINNReducer`
(dSGD), with gradient-compressed variants (PowerSGD, rankDAD) — capability
parity with the reference ``distrib/`` package, plus :mod:`.mesh`, the
TPU-native transport where simulated sites are ranks on a
``jax.sharding.Mesh`` and a whole federated round is ONE compiled step.
"""
from .learner import COINNLearner  # noqa: F401
from .reducer import COINNReducer  # noqa: F401
from .powersgd import PowerSGDLearner, PowerSGDReducer  # noqa: F401
from .rankdad import DADLearner, DADReducer  # noqa: F401
from .ring_attention import ring_attention  # noqa: F401
from . import hosts, pipeline, sequence  # noqa: F401

__all__ = [
    "pipeline",
    "sequence",
    "COINNLearner",
    "COINNReducer",
    "PowerSGDLearner",
    "PowerSGDReducer",
    "DADLearner",
    "DADReducer",
    "ring_attention",
]
