"""Federated mesh transport with intra-site sequence parallelism.

:class:`SeqMeshFederation` runs the same federated round contract as
:class:`~.mesh.MeshFederation` — N sites as ranks of a mesh, one compiled
``shard_map`` step per round, participation-weighted cross-site aggregation,
optax update, metric/average reduction — but the intra-site axis shards the
SEQUENCE dimension instead of the batch:

- mesh ``(site, sp)``: each site's rank group holds its batch whole and
  splits every sequence into ``sp`` contiguous blocks;
- attention is exact global ring attention over ``sp``
  (:func:`~.ring_attention.ring_attention` inside the model, reached through
  the trainer's ``iteration_sharded`` hook);
- ``shard_map`` autodiff computes the gradient of the SUM of per-rank
  losses; the loss is replicated across ``sp`` (the model's pooling
  collective), so every rank's gradient is uniformly sp× the true one and
  ``pmean`` over ``sp`` is exact (measured: matches unsharded grads to
  float tolerance, pre- AND post-pooling params);
- logits/metrics come out replicated across ``sp``, so aux outputs reduce
  over ``site`` only.

The round scaffold (site collectives, PowerSGD exchange, donate/jit
wrapper) is SHARED with ``MeshFederation._build_step`` via its intra-site
hooks — only the hooks differ here.  This composes the long-context stack
with the full federated trainer stack (optax, metrics, checkpoints,
MeshEngine fold lifecycle) — the reference has neither (SURVEY §5); the
sp=1 degenerate case reproduces ``MeshFederation``'s dSGD math exactly.
Runs single- AND multi-process (one process per host: sites across hosts,
sp over each host's local chips, so the ring's ppermute hops ride ICI and
only the site mean crosses DCN — ``tests/test_multihost.py``).
"""
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..config.keys import MeshAxis
from .mesh import ReplicatedBatchFederation

__all__ = ["SeqMeshFederation"]


class SeqMeshFederation(ReplicatedBatchFederation):
    """Federated rounds over a ``(site, sp)`` mesh (sequence parallelism).

    ``rankDAD`` is rejected: its per-sample factor capture assumes each rank
    holds whole samples, which sequence sharding breaks.
    """

    SUPPORTED_ENGINES = ("dSGD", "powerSGD")

    def __init__(self, trainer, n_sites, sp=2, agg_engine="dSGD", devices=None):
        self.sp = int(sp)
        if self.sp < 1:
            raise ValueError(f"sp must be >= 1, got {sp}")
        super().__init__(
            trainer, n_sites, agg_engine=agg_engine, devices=devices,
            devices_per_site=self.sp,
        )
        # same device grid, but the intra-site axis is the sequence axis
        self.mesh = Mesh(self.mesh.devices, (MeshAxis.SITE, MeshAxis.SP))

    # ---- intra-site axis hooks (see MeshFederation._build_step) ----------
    def _iteration_fn(self):
        trainer = self.trainer

        def sp_iteration(params, batch, rng):
            return trainer.iteration_sharded(params, batch, rng, sp_axis=MeshAxis.SP)

        return sp_iteration

    def _intra_grad_reduce(self):
        # see module docstring: replicated loss → uniform sp× grads → pmean
        def sp_grad_reduce(g, batch):
            return jax.tree_util.tree_map(
                lambda x: jax.lax.pmean(x, MeshAxis.SP), g
            )

        return sp_grad_reduce

    # _site_weight/_aux_axes: inherited from ReplicatedBatchFederation —
    # every sp rank holds the site's full mask, aux replicated across sp

    def _train_batch_specs(self):
        """``inputs`` (site, k, B, T, F) shards T over ``sp``; labels/_mask
        carry no sequence axis and stay replicated within the site."""
        keys = self._sample_batch_keys or ("inputs",)
        return {
            k: (P(MeshAxis.SITE, None, None, MeshAxis.SP) if k == "inputs" else P(MeshAxis.SITE))
            for k in keys
        }

    def _eval_batch_specs(self):
        keys = self._sample_batch_keys or ("inputs",)
        return {
            k: (P(MeshAxis.SITE, None, MeshAxis.SP) if k == "inputs" else P(MeshAxis.SITE))
            for k in keys
        }

    # batching: inherited — MeshFederation.stack_site_batches resolves the
    # per-key placement through _train_batch_specs in BOTH the single- and
    # multi-process branches (sites across hosts, sp within a host's chips)
