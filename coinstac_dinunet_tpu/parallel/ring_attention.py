"""Ring attention — sequence/context parallelism over a mesh axis.

First-class long-context support for the TPU framework (the reference has no
sequence dimension anywhere — SURVEY.md §5 "Long-context: absent" — so this
subsystem is new capability, designed TPU-first rather than ported).

Each rank of the ``axis_name`` mesh axis holds a contiguous shard of the
sequence: ``q, k, v`` are the local ``(batch, heads, T_local, head_dim)``
blocks of a global ``T = n_ranks * T_local`` sequence.  The algorithm is the
blockwise-parallel ring of Liu et al. (Ring Attention): every step each rank

1. attends its resident queries to the key/value block currently in hand
   (a fused :func:`~..ops.flash_attention.flash_attention` call that returns
   the block's normalized output and log-sum-exp), and
2. rotates the key/value block one hop around the ring with
   ``lax.ppermute`` — a neighbor-to-neighbor transfer that rides ICI, which
   XLA overlaps with the next step's attention compute.

Partial outputs combine with the standard two-softmax merge: with per-row
``lse`` values the merged output is the lse-weighted average and the merged
lse the ``logaddexp``.  Blocks that are entirely causally masked report the
``-1e30`` sentinel lse and thus merge with weight 0 — no special-casing, no
NaNs, and no data-dependent control flow (the step count is the static ring
size, so the whole loop jits into one ``lax.scan``).

After ``n`` steps every k/v block has visited every rank and is back home;
peak memory per rank stays O(T_local) regardless of global T.
"""
import jax
import jax.numpy as jnp
from jax import lax

from ..ops.flash_attention import _NEG, flash_attention
from ..utils.jax_compat import axis_size


def _merge(acc, lse, o_new, lse_new):
    """Combine two normalized softmax partials by their log-sum-exps."""
    m = jnp.maximum(lse, lse_new)
    w1 = jnp.exp(lse - m)
    w2 = jnp.exp(lse_new - m)
    tot = jnp.maximum(w1 + w2, 1e-30)
    out = (acc * w1[..., None] + o_new * w2[..., None]) / tot[..., None]
    return out, m + jnp.log(tot)


def ring_attention(q, k, v, axis_name, causal=False, scale=None, impl=None,
                   block_q=128, block_k=128):
    """Exact global attention over a sequence sharded on ``axis_name``.

    Must run inside ``shard_map`` (or any context where ``axis_name`` is
    bound).  Args/returns are the local shards:

    Args:
      q, k, v: ``(batch, heads, T_local, head_dim)`` — this rank's sequence
        block (rank r holds global positions ``[r*T_local, (r+1)*T_local)``).
      axis_name: mesh axis to ring over (the ``sp`` axis).
      causal, scale, impl, block_q, block_k: forwarded to
        :func:`flash_attention`.

    Returns:
      ``(batch, heads, T_local, head_dim)`` local output block, ``q.dtype``.
    """
    n = axis_size(axis_name)
    # only the causal mask consumes global positions; an unconsumed
    # axis_index would leave a dangling partition-id instruction that 0.4.x
    # XLA's CPU SPMD partitioner rejects
    r = lax.axis_index(axis_name) if causal else 0
    t_local = q.shape[2]
    b, h = q.shape[0], q.shape[1]

    def step(carry, s):
        acc, lse, kk, vv = carry
        src = (r - s) % n  # rank the in-hand kv block originated from
        o_new, lse_new = flash_attention(
            q, kk, vv,
            q_offset=r * t_local, k_offset=src * t_local,
            causal=causal, scale=scale, impl=impl,
            block_q=block_q, block_k=block_k, return_lse=True,
        )
        acc, lse = _merge(acc, lse, o_new.astype(jnp.float32), lse_new)
        kk, vv = jax.tree_util.tree_map(
            lambda x: lax.ppermute(
                x, axis_name, perm=[(i, (i + 1) % n) for i in range(n)]
            ),
            (kk, vv),
        )
        return (acc, lse, kk, vv), None

    # derive the init buffers from q so they carry its device-varying type
    # (shard_map's vma check rejects a replicated scan carry init)
    acc0 = jnp.zeros_like(q, jnp.float32)
    lse0 = jnp.full((b, h, t_local), _NEG, jnp.float32) + 0.0 * q[..., 0].astype(jnp.float32)
    (acc, _, _, _), _ = lax.scan(step, (acc0, lse0, k, v), jnp.arange(n))
    return acc.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name, causal=False, scale=None, impl=None,
                      block_q=128, block_k=128):
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    The alternative SP schedule to :func:`ring_attention`: instead of rotating
    K/V blocks, one ``all_to_all`` reshards from sequence-sharded to
    HEAD-sharded — each rank then holds ``heads/n`` full-length sequences,
    runs ordinary (flash) attention locally, and a second ``all_to_all``
    reshards back.  Two collectives total (vs ``n-1`` ring hops), but each
    rank must fit the full sequence for its head slice — the ring wins at
    extreme lengths, Ulysses wins when heads ≥ ranks and T_local·n fits.

    Args/returns match :func:`ring_attention` (local ``(batch, heads,
    T_local, head_dim)`` shards under ``shard_map``); ``heads`` must be
    divisible by the axis size.
    """
    n = axis_size(axis_name)
    b, h, t_local, d = q.shape
    if h % n != 0:
        raise ValueError(f"heads {h} must divide the '{axis_name}' axis size {n}")

    def seq_to_heads(x):
        # (b, h, t_local, d) → (b, h/n, n*t_local, d): scatter heads, gather seq
        x = x.reshape(b, n, h // n, t_local, d)
        x = lax.all_to_all(x, axis_name, split_axis=1, concat_axis=0, tiled=False)
        # leading axis is now the source rank (= sequence block) dimension
        return x.transpose(1, 2, 0, 3, 4).reshape(b, h // n, n * t_local, d)

    def heads_to_seq(x):
        x = x.reshape(b, h // n, n, t_local, d).transpose(2, 0, 1, 3, 4)
        x = lax.all_to_all(x, axis_name, split_axis=0, concat_axis=1, tiled=False)
        return x.reshape(b, h, t_local, d)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = flash_attention(
        qh, kh, vh, causal=causal, scale=scale, impl=impl,
        block_q=block_q, block_k=block_k,
    )
    return heads_to_seq(out)
