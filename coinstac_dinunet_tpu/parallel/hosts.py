"""Multi-host federation: the same mesh code on a real multi-host pod.

The reference scales across machines through its external engine relaying
files over the WAN; the TPU-native equivalent is a multi-host JAX runtime
(one process per host) where cross-site collectives ride ICI within a slice
and DCN across slices.  Everything in :mod:`.mesh` is already written
against logical mesh axes, so multi-host is purely an initialization +
device-layout concern, handled here.

Usage on a pod (one process per host)::

    from coinstac_dinunet_tpu.parallel import hosts
    hosts.initialize_multihost()          # no-op on a single process
    mesh = hosts.host_aligned_site_mesh(n_sites=8)
    fed = MeshFederation(trainer, 8, devices=mesh.devices.ravel(),
                         devices_per_site=mesh.devices.shape[1])
"""
import os

import jax
import numpy as np

from ..config.keys import MeshAxis
from .mesh import build_site_mesh


def initialize_multihost(coordinator_address=None, num_processes=None,
                         process_id=None):
    """Initialize the multi-process JAX runtime (≙ the role torchrun/NCCL
    init plays for torch DDP — the reference has no equivalent; its engine
    IS the transport).

    All arguments default to the standard environment variables
    (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``,
    or the TPU pod metadata when running on Cloud TPU).  A single-process
    run (no coordinator configured) is a no-op, so the same script works
    on a laptop, one host, or a pod.
    """
    addr = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    nproc = num_processes or os.environ.get("JAX_NUM_PROCESSES")
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = os.environ["JAX_PROCESS_ID"]
    if addr is None and nproc is None and process_id is None:
        if os.environ.get("TPU_WORKER_HOSTNAMES", "").count(",") == 0:
            return False  # single process: nothing to initialize
    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=int(nproc) if nproc is not None else None,
        process_id=int(process_id) if process_id is not None else None,
    )
    return True


def host_aligned_site_mesh(n_sites, devices_per_site=None):
    """(site, device) mesh whose device axis never crosses a host.

    Lays sites out so every site's chips belong to one process/host: the
    intra-site data-parallel `psum` stays on that host's ICI, and only the
    cross-site gradient mean touches DCN — the layout the scaling-book
    recipe prescribes for hierarchical reductions.  Falls back to the plain
    row-major mesh when sites must span hosts (more sites than hosts or
    uneven division).
    """
    devices = jax.devices()
    n_hosts = max(getattr(jax, "process_count", lambda: 1)(), 1)
    per_host = len(devices) // n_hosts
    if devices_per_site is None:
        devices_per_site = max(len(devices) // n_sites, 1)
    # host-aligned only when a site's chips fit within one host's complement
    if n_hosts > 1 and devices_per_site <= per_host and per_host % devices_per_site == 0:
        by_host = {}
        for d in devices:
            by_host.setdefault(d.process_index, []).append(d)
        ordered = [d for h in sorted(by_host) for d in by_host[h]]
        need = n_sites * devices_per_site
        if need <= len(ordered):
            arr = np.array(ordered[:need]).reshape(n_sites, devices_per_site)
            from jax.sharding import Mesh

            return Mesh(arr, (MeshAxis.SITE, MeshAxis.DEVICE))
    return build_site_mesh(n_sites, devices, devices_per_site)
