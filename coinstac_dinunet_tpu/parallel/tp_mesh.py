"""Federated mesh transport with intra-site tensor parallelism.

:class:`TPMeshFederation` runs the same federated round contract as
:class:`~.mesh.MeshFederation` — N sites as ranks of a mesh, one compiled
``shard_map`` step per round, participation-weighted cross-site aggregation,
optax update, metric/average reduction — but the intra-site axis shards the
MODEL's heavy matmuls (Megatron tensor parallelism) instead of the batch:

- mesh ``(site, tp)``: each site's rank group holds its batch whole (inputs
  replicated across ``tp``) and computes 1/tp of every attention-head and
  MLP-hidden matmul (``TPDense`` in ``models/transformer.py``, reached
  through the trainer's ``iteration_tp`` hook);
- parameters stay FULL-SHAPE and replicated: checkpoints, the cross-site
  replication invariant, and the dSGD/PowerSGD gradient plane are all
  independent of ``tp`` (the tp=1 degenerate case reproduces
  ``MeshFederation``'s math exactly) — what shards is the compute and the
  intermediate activations, which is where a transformer's cost lives;
- gradient assembly over ``tp`` is a uniform ``pmean`` — and that is EXACT,
  not an approximation (measured to float tolerance at tp∈{2,4}, sliced and
  replicated leaves alike, ``tests/test_tp_mesh.py``).  Why: shard_map
  autodiff differentiates the SUM of per-rank losses, and every psum in the
  forward transposes to a psum of cotangents.  A sliced leaf's path to the
  loss passes the row-parallel psum, whose transpose collapses the per-rank
  partial cotangents into tp× the single-loss cotangent — so its local grad
  is tp× its true slice (zero outside the slice, from the slice transpose),
  and pmean = psum/tp assembles the exact full gradient.  A replicated-use
  leaf (LayerNorm, embeddings, classifier head) either carries the full
  gradient on every rank (downstream of all psums) or tp× a rank-partial
  (upstream); pmean resolves both to the exact full gradient;
- the loss and logits come out replicated across ``tp`` (the row-parallel
  psum inside the model), so aux outputs reduce over ``site`` only.

The round scaffold (site collectives, PowerSGD exchange, donate/jit wrapper)
is SHARED with ``MeshFederation._build_step`` via its intra-site hooks —
only the hooks differ here, exactly like the sequence-parallel integration
(``seq_mesh.py``).  No reference counterpart exists (SURVEY §2 "Absent":
the reference's only intra-site scaling is torch DataParallel); this
composes tensor parallelism with the full federated trainer stack.
"""
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..config.keys import MeshAxis
from .mesh import ReplicatedBatchFederation

__all__ = ["TPMeshFederation"]


class TPMeshFederation(ReplicatedBatchFederation):
    """Federated rounds over a ``(site, tp)`` mesh (tensor parallelism).

    ``rankDAD`` is rejected: its per-layer activation/delta factor capture
    assumes each rank computes the full layer, which head/hidden slicing
    breaks.
    """

    SUPPORTED_ENGINES = ("dSGD", "powerSGD")

    def __init__(self, trainer, n_sites, tp=2, agg_engine="dSGD", devices=None):
        self.tp = int(tp)
        if self.tp < 1:
            raise ValueError(f"tp must be >= 1, got {tp}")
        super().__init__(
            trainer, n_sites, agg_engine=agg_engine, devices=devices,
            devices_per_site=self.tp,
        )
        # same device grid, but the intra-site axis is the tensor axis
        self.mesh = Mesh(self.mesh.devices, (MeshAxis.SITE, MeshAxis.TP))

    # ---- intra-site axis hooks (see MeshFederation._build_step) ----------
    def _iteration_fn(self):
        trainer = self.trainer

        def tp_iteration(params, batch, rng):
            return trainer.iteration_tp(params, batch, rng, tp_axis=MeshAxis.TP)

        return tp_iteration

    def _intra_grad_reduce(self):
        # uniform pmean is EXACT for sliced and replicated leaves alike —
        # see the module docstring's cotangent derivation
        def tp_grad_reduce(g, batch):
            return jax.tree_util.tree_map(
                lambda x: jax.lax.pmean(x, MeshAxis.TP), g
            )

        return tp_grad_reduce

    # _site_weight/_aux_axes: inherited from ReplicatedBatchFederation —
    # every tp rank holds the site's full mask, aux replicated across tp

    def _train_batch_specs(self):
        """(site, k, B, ...) — replicated within the site: every tp rank
        needs the whole batch (activations shard by FEATURE, not sample)."""
        keys = self._sample_batch_keys or ("inputs",)
        return {k: P(MeshAxis.SITE) for k in keys}

    def _eval_batch_specs(self):
        keys = self._sample_batch_keys or ("inputs",)
        return {k: P(MeshAxis.SITE) for k in keys}

    # batching: inherited — MeshFederation.stack_site_batches resolves the
    # per-key placement through _train_batch_specs in BOTH the single- and
    # multi-process branches (sites across hosts, tp within a host's chips,
    # so the row-parallel psums ride ICI and only the site mean crosses DCN)
