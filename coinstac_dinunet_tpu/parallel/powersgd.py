"""PowerSGD learner/reducer — rank-r gradient compression with error feedback.

Capability parity with the reference ``distrib/powersgd/__init__.py:15-219``:
warm-up rounds of plain dSGD, then a two-round P-sync/Q-sync wire protocol
with warm-started Q, seeded identical init across sites, Gram-Schmidt
orthogonalization, and per-site error-feedback memory.  TPU-first
differences:

- All per-leaf compression math (``P = M @ Q``, ``Q = Mᵀ P̂``, reconstruction
  ``P̂ Qᵀ`` + error update) runs as ONE jit-compiled call over the pytree of
  2-D-reshaped leaves — batched MXU matmuls, no Python per-parameter loop.
- Orthogonalization is XLA-native QR (:func:`..ops.orthogonalize`), not
  column-wise Gram-Schmidt.
- On the mesh transport the two wire rounds collapse into a single compiled
  step (see :mod:`.mesh`); this module implements the file/JSON transport.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np

from .. import config
from ..ops import orthogonalize
from ..telemetry import get_active as _telemetry
from ..telemetry import health as _health
from ..utils import tensorutils
from .learner import COINNLearner
from .reducer import COINNReducer

PHASE_P_SYNC = "phase_P_sync"
PHASE_Q_SYNC = "phase_Q_sync"
rank1_file = "powerSGD_rank1.npy"

_STATE_KEY = "_powersgd_state"


def _split_leaves(flat):
    """Indices of high-rank (≥2-D, compressed) vs rank-1 (shipped raw) leaves
    (≙ ref ``powersgd/__init__.py:41-48`` rank-1/high-rank split)."""
    hi = [i for i, g in enumerate(flat) if np.ndim(g) >= 2]
    lo = [i for i, g in enumerate(flat) if np.ndim(g) < 2]
    return hi, lo


def _as_matrix(g):
    g = jnp.asarray(g, jnp.float32)
    return g.reshape(g.shape[0], -1)


# ---- per-leaf compression kernels, shared by BOTH transports (the file/JSON
# ---- protocol below and the mesh's in-step collectives, ``mesh.py``) ------
def compress_P(M, Q):
    """Wire round 1 payload: P = M @ Q (ref ``powersgd/__init__.py:93-128``)."""
    return M @ Q


def compress_Q(M, Phat):
    """Wire round 2 payload: Q = Mᵀ @ P̂ (ref ``:147-177``)."""
    return M.T @ Phat


def reconstruct(Phat, Q):
    """Rank-r gradient estimate P̂ Qᵀ; error feedback = M − estimate."""
    return Phat @ Q.T


def seeded_Q(seed, j, ncols, rank):
    """Deterministic Q init for the j-th high-rank leaf — the SAME key on
    every site and on both transports (≙ ref seeded randn,
    ``powersgd/__init__.py:101-107``)."""
    key = jax.random.PRNGKey(int(seed) * 1000 + int(j))
    return jax.random.normal(key, (int(ncols), int(rank)), dtype=jnp.float32)


@jax.jit
def _compute_P(Ms, Qs):
    return [compress_P(M, Q) for M, Q in zip(Ms, Qs)]


@jax.jit
def _compute_Q(Ms, Ps):
    Phats = [orthogonalize(P) for P in Ps]
    return [compress_Q(M, Ph) for M, Ph in zip(Ms, Phats)], Phats


@jax.jit
def _reconstruct(Ms, Phats, Qs):
    recon = [reconstruct(Ph, Q) for Ph, Q in zip(Phats, Qs)]
    errors = [M - R for M, R in zip(Ms, recon)]
    return recon, errors


class _PowerSGDState:
    """Per-site compression state that persists across engine invocations
    (≙ ref ``PowerSGDState`` living in cache, ``powersgd/__init__.py:41-48``)."""

    def __init__(self):
        self.iteration = 0  # completed update rounds (drives warm-up)
        self.errors = None  # list of (n, m) error-feedback matrices
        self.Qs = None  # warm-started Q per high-rank leaf
        self.Ms = None  # error-fed gradient matrices, P-phase → Q-phase
        self.Phats = None  # orthogonalized averaged P, Q-phase → step
        self.rank1 = None  # raw low-rank leaves riding along
        self.shapes = None  # original high-rank leaf shapes
        self.hi = self.lo = None  # leaf index split
        self.weight = 1.0  # this round's participation (P-phase → Q-phase)

    # ---- checkpointable carried state (epoch-barrier resume) -------------
    # Ms/Phats/rank1/shapes/hi/lo are transient within one two-round wire
    # protocol and are rebuilt every round; only the error-feedback memory,
    # the warm-started Qs and the warm-up counter carry across ROUNDS —
    # losing them silently degrades convergence (VERDICT r2 weak #2).
    # ``full=True`` additionally captures the mid-protocol fields, so a
    # FRESH-PROCESS engine can restart between the P-sync and Q-sync
    # invocations of one round (``persist_round_state``, DEPLOY.md §3).
    def serialize(self, full=False):
        out = {
            "iteration": int(self.iteration),
            "weight": float(self.weight),
            "errors": ([np.asarray(e, np.float32) for e in self.errors]
                       if self.errors is not None else []),
            "Qs": ([np.asarray(q, np.float32) for q in self.Qs]
                   if self.Qs is not None else []),
        }
        if full:
            if self.Ms is not None:
                out["Ms"] = [np.asarray(m, np.float32) for m in self.Ms]
            if self.Phats is not None:
                out["Phats"] = [np.asarray(p, np.float32) for p in self.Phats]
            if self.rank1 is not None:
                out["rank1"] = [np.asarray(r) for r in self.rank1]
            if self.shapes is not None:
                out["shapes"] = [list(s) for s in self.shapes]
            if self.hi is not None:
                out["hi"] = [int(i) for i in self.hi]
                out["lo"] = [int(i) for i in self.lo]
        return out

    @classmethod
    def deserialize(cls, payload):
        st = cls()
        st.iteration = int(payload.get("iteration", 0))
        st.weight = float(payload.get("weight", 1.0))
        errors = [jnp.asarray(np.asarray(e), jnp.float32)
                  for e in _aslist(payload.get("errors"))]
        qs = [jnp.asarray(np.asarray(q), jnp.float32)
              for q in _aslist(payload.get("Qs"))]
        st.errors = errors or None
        st.Qs = qs or None
        if payload.get("Ms") is not None:
            st.Ms = [jnp.asarray(np.asarray(m), jnp.float32)
                     for m in _aslist(payload["Ms"])]
        if payload.get("Phats") is not None:
            st.Phats = [jnp.asarray(np.asarray(p), jnp.float32)
                        for p in _aslist(payload["Phats"])]
        if payload.get("rank1") is not None:
            st.rank1 = [np.asarray(r) for r in _aslist(payload["rank1"])]
        if payload.get("shapes") is not None:
            st.shapes = [tuple(int(d) for d in s)
                         for s in _aslist(payload["shapes"])]
        if payload.get("hi") is not None:
            st.hi = [int(i) for i in _aslist(payload["hi"])]
            st.lo = [int(i) for i in _aslist(payload["lo"])]
        return st


# canonical home: utils.tensorutils.aslist (kept under the old name for the
# mesh/module-internal imports)
_aslist = tensorutils.aslist


class PowerSGDLearner(COINNLearner):
    """Site-side PowerSGD (≙ ref ``PowerSGDLearner``)."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.rank = int(self.cache.get("matrix_approximation_rank", 1))
        self.start_iter = int(self.cache.get("start_powerSGD_iter", 10))

    @property
    def psgd(self) -> _PowerSGDState:
        st = self.cache.get(_STATE_KEY)
        if st is None:
            st = self.cache[_STATE_KEY] = _PowerSGDState()
        return st

    def _seeded_Q(self, i, shape):
        """Same seed at every site ⇒ identical Q init everywhere (the
        reference's seeded randn, ``powersgd/__init__.py:101-107``)."""
        return seeded_Q(self.cache.get("seed", 0), i, shape[1], self.rank)

    # ---------------------------------------------------------------- phases
    def to_reduce(self):
        st = self.psgd
        if st.iteration < self.start_iter:
            # dSGD warm-up round (≙ ref ``:61-64,130-134``)
            out = super().to_reduce()
            out["powerSGD_phase"] = "dSGD"
            return out
        phase = self.input.get("powerSGD_phase", PHASE_P_SYNC)
        if phase == PHASE_P_SYNC:
            return self._phase_P()
        return self._phase_Q()

    def _phase_P(self):
        grads, out, aux = self.backward()
        if grads is None:
            return out
        self._track_train_scores(aux)
        flat = [jnp.asarray(g) for g in jax.tree_util.tree_leaves(grads)]
        st = self.psgd
        # participation rides BOTH wire rounds of this protocol round (the
        # Q-sync happens in a later engine invocation)
        st.weight = 1.0 if aux.get("participation", 1.0) > 0 else 0.0
        st.hi, st.lo = _split_leaves(flat)
        st.rank1 = [np.asarray(flat[i], config.wire_dtype(self.precision_bits)) for i in st.lo]
        Ms = [_as_matrix(flat[i]) for i in st.hi]
        st.shapes = [tuple(flat[i].shape) for i in st.hi]
        if st.errors is None:
            st.errors = [jnp.zeros_like(M) for M in Ms]
        if st.Qs is None:
            st.Qs = [self._seeded_Q(i, M.shape) for i, M in enumerate(Ms)]
        st.Ms = [M + e for M, e in zip(Ms, st.errors)]
        Ps = _compute_P(st.Ms, st.Qs)
        wire = config.wire_dtype(self.precision_bits)
        rec = _telemetry()
        if rec.enabled:
            # rank compression accounting: what the full gradient would
            # have weighed vs the factorized (P now, Q + rank-1 next
            # round) payloads — wire events add the codec ratio on top
            itemsize = np.dtype(wire).itemsize
            full = sum(int(np.prod(s)) for s in st.shapes) + sum(
                int(a.size) for a in st.rank1
            )
            factored = sum(
                (int(s[0]) + int(np.prod(s[1:]))) * self.rank
                for s in st.shapes
            ) + sum(int(a.size) for a in st.rank1)
            rec.event(
                "powersgd:compress", cat="compress", rank=self.rank,
                matrices=len(Ps), rank1=len(st.rank1),
                full_bytes=full * itemsize, factored_bytes=factored * itemsize,
            )
        self._save_wire(config.powersgd_P_file, [np.asarray(P, wire) for P in Ps])
        out["powerSGD_P_file"] = config.powersgd_P_file
        out["powerSGD_phase"] = PHASE_P_SYNC
        out["reduce"] = True
        out["grad_weight"] = st.weight
        return out

    def _phase_Q(self):
        """Averaged P arrived: orthogonalize, compute Q, ship Q + rank-1."""
        out = {}
        st = self.psgd
        avg_P = self._load_wire(
            self._base_path(self.input["powerSGD_P_file"])
        )
        Qs, Phats = _compute_Q(st.Ms, [jnp.asarray(P, jnp.float32) for P in avg_P])
        st.Phats = Phats
        wire = config.wire_dtype(self.precision_bits)
        self._save_wire(config.powersgd_Q_file, [np.asarray(Q, wire) for Q in Qs])
        self._save_wire(rank1_file, st.rank1)
        out["powerSGD_Q_file"] = config.powersgd_Q_file
        out["rank1_file"] = rank1_file
        out["powerSGD_phase"] = PHASE_Q_SYNC
        out["reduce"] = True
        out["grad_weight"] = st.weight
        return out

    def step(self):
        st = self.psgd
        if st.iteration < self.start_iter:
            out = super().step()
            st.iteration += 1
            return out
        out = {}
        avg_Q = [
            jnp.asarray(q, jnp.float32)
            for q in self._load_wire(self._base_path(self.input["powerSGD_Q_file"]))
        ]
        avg_rank1 = self._load_wire(self._base_path(self.input["rank1_file"]))
        recon, errors = _reconstruct(st.Ms, st.Phats, avg_Q)
        rec = _telemetry()
        if rec.enabled:
            # reconstruction health: how much gradient the rank-r estimate
            # lost this round (‖M − P̂Qᵀ‖/‖M‖ over all leaves), and the
            # entropy effective rank of the factor spectrum (σ(P̂Qᵀ) = σ(Q)
            # since P̂ has orthonormal columns) — the rank-collapse signal
            eff = (
                float(np.mean([_health.effective_rank(np.asarray(q))
                               for q in avg_Q]))
                if avg_Q else None
            )
            _health.record_compression_health(
                self.cache, _health.relative_error(errors, st.Ms), eff,
                recorder=rec, engine="powerSGD",
            )
        st.errors = errors
        st.Qs = avg_Q  # warm start next round (≙ ref warm_start)
        # reassemble the full flat gradient list at original shapes
        ts = self.trainer.train_state
        leaves, treedef = jax.tree_util.tree_flatten(ts.params)
        flat = [None] * len(leaves)
        for j, i in enumerate(st.hi):
            flat[i] = jnp.asarray(recon[j]).reshape(st.shapes[j])
        for j, i in enumerate(st.lo):
            flat[i] = jnp.asarray(avg_rank1[j])
        grads = tensorutils.grads_like(ts.params, flat)
        self.trainer.train_state = self.trainer.apply_grads(ts, grads)
        st.Ms = st.Phats = None
        st.iteration += 1
        return out


class PowerSGDReducer(COINNReducer):
    """Aggregator-side PowerSGD (≙ ref ``PowerSGDReducer``)."""

    def reduce(self):
        phases = {
            self.input[s].get("powerSGD_phase") for s in self.input
        }
        if phases == {"dSGD"}:
            # warm-up round: plain gradient averaging
            out = super().reduce()
            out["powerSGD_phase"] = PHASE_P_SYNC
            return out
        if phases == {PHASE_P_SYNC}:
            avg_P = self._average(self._load("powerSGD_P_file"),
                                  payload="powerSGD_P")
            _telemetry().event(
                "reduce:powerSGD", cat="reduce", phase=PHASE_P_SYNC,
                sites=len(self.input), matrices=len(avg_P),
                rank=int(self.cache.get("matrix_approximation_rank", 1)),
            )
            fname = self._save_out(config.powersgd_P_file, avg_P)
            return {"powerSGD_P_file": fname, "powerSGD_phase": PHASE_Q_SYNC}
        if phases == {PHASE_Q_SYNC}:
            avg_Q = self._average(self._load("powerSGD_Q_file"),
                                  payload="powerSGD_Q")
            qname = self._save_out(config.powersgd_Q_file, avg_Q)
            avg_r1 = self._average(self._load("rank1_file"), payload="rank1")
            rname = self._save_out(rank1_file, avg_r1)
            _telemetry().event(
                "reduce:powerSGD", cat="reduce", phase=PHASE_Q_SYNC,
                sites=len(self.input), matrices=len(avg_Q),
                rank=int(self.cache.get("matrix_approximation_rank", 1)),
            )
            return {
                "powerSGD_Q_file": qname,
                "rank1_file": rname,
                "update": True,
                "powerSGD_phase": PHASE_P_SYNC,
            }
        raise RuntimeError(f"Sites disagree on powerSGD phase: {phases}")
