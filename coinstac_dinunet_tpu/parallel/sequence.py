"""Tensor + sequence + data parallel transformer step (dp × tp × sp mesh).

The scale-out path for the long-context family: a Megatron-style sharded
transformer where

- **dp** shards the batch (federated-site data parallelism maps here too),
- **tp** shards attention heads and the MLP hidden dimension,
- **sp** shards the sequence, with exact global attention via
  :func:`~.ring_attention.ring_attention`.

Idiomatic split of responsibilities (the scaling-book recipe): parameters and
inputs get ``NamedSharding`` annotations and GSPMD derives every tensor- and
data-parallel collective (all-reduces for the row/column-sharded matmuls, the
gradient reductions) from them — nothing is hand-scheduled.  Only the ring is
manual: GSPMD cannot infer a ring schedule, so the attention inner loop runs
in a nested ``shard_map`` over the ``sp`` axis where ``ppermute`` hops are
explicit.  No reference counterpart (SURVEY.md §5: sequence parallelism
absent there); mesh/axis conventions follow ``parallel/mesh.py``.
"""
import math
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .ring_attention import ring_attention

__all__ = ["TSPConfig", "build_tsp_mesh", "init_tsp_params", "shard_tsp_params",
           "tsp_forward", "make_tsp_train_step"]


class TSPConfig:
    """Static transformer hyperparameters for the sharded step."""

    def __init__(self, num_features=16, num_classes=2, d_model=128, num_heads=8,
                 num_layers=2, mlp_ratio=4, max_len=4096, causal=False,
                 dtype=jnp.float32, attn_impl=None):
        self.num_features = num_features
        self.num_classes = num_classes
        self.d_model = d_model
        self.num_heads = num_heads
        self.num_layers = num_layers
        self.d_ff = mlp_ratio * d_model
        self.max_len = max_len
        self.causal = causal
        self.dtype = dtype
        self.attn_impl = attn_impl
        self.head_dim = d_model // num_heads
        assert d_model % num_heads == 0


def build_tsp_mesh(dp=1, tp=1, sp=1, devices=None):
    devices = list(devices if devices is not None else jax.devices())
    need = dp * tp * sp
    if need > len(devices):
        raise ValueError(f"need {need} devices, have {len(devices)}")
    arr = np.array(devices[:need]).reshape(dp, tp, sp)
    return Mesh(arr, ("dp", "tp", "sp"))


def init_tsp_params(key, cfg):
    """Plain pytree of arrays; sharding is applied by :func:`shard_tsp_params`."""
    d, h, hd, ff = cfg.d_model, cfg.num_heads, cfg.head_dim, cfg.d_ff
    k = iter(jax.random.split(key, 4 + 8 * cfg.num_layers))
    init = lambda kk, shape, scale: (
        jax.random.normal(kk, shape, jnp.float32) * scale
    )
    params = {
        "in_proj": init(next(k), (cfg.num_features, d), 1 / math.sqrt(cfg.num_features)),
        "pos": init(next(k), (cfg.max_len, d), 0.02),
        "head": init(next(k), (d, cfg.num_classes), 1 / math.sqrt(d)),
        "lnf": {"scale": jnp.ones((d,)), "bias": jnp.zeros((d,))},
        "layers": [],
    }
    for _ in range(cfg.num_layers):
        params["layers"].append({
            "ln1": {"scale": jnp.ones((d,)), "bias": jnp.zeros((d,))},
            "wqkv": init(next(k), (3, d, h, hd), 1 / math.sqrt(d)),
            "wo": init(next(k), (h, hd, d), 1 / math.sqrt(d)),
            "ln2": {"scale": jnp.ones((d,)), "bias": jnp.zeros((d,))},
            "w1": init(next(k), (d, ff), 1 / math.sqrt(d)),
            "b1": jnp.zeros((ff,)),
            "w2": init(next(k), (ff, d), 1 / math.sqrt(ff)),
            "b2": jnp.zeros((d,)),
        })
    return params


def _param_specs(params):
    """PartitionSpec tree: heads / d_ff sharded over tp, rest replicated."""
    def spec_for(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        return {
            "wqkv": P(None, None, "tp", None),  # (3, d, h/tp, hd)
            "wo": P("tp", None, None),          # (h/tp, hd, d)
            "w1": P(None, "tp"),                # (d, ff/tp)
            "b1": P("tp"),
            "w2": P("tp", None),                # (ff/tp, d)
        }.get(name, P())
    return jax.tree_util.tree_map_with_path(spec_for, params)


def shard_tsp_params(params, mesh):
    specs = _param_specs(params)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


def _layernorm(x, p):
    mu = jnp.mean(x, -1, keepdims=True)
    var = jnp.var(x, -1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + 1e-6) * p["scale"] + p["bias"]


def tsp_forward(params, x, cfg, mesh):
    """Logits for (B, T, F) inputs; B sharded over dp, T over sp, heads/ff
    over tp — all via sharding constraints except the explicit ring."""
    dtype = cfg.dtype
    x = jnp.asarray(x, dtype)
    b, t, _ = x.shape
    constrain = lambda a, s: lax.with_sharding_constraint(a, NamedSharding(mesh, s))

    h = x @ params["in_proj"].astype(dtype) + params["pos"][:t].astype(dtype)
    h = constrain(h, P("dp", "sp", None))

    qkv_spec = P("dp", "tp", "sp", None)
    ring = jax.shard_map(
        partial(
            ring_attention, axis_name="sp", causal=cfg.causal,
            impl=cfg.attn_impl,
        ),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec),
        out_specs=qkv_spec,
    )

    for lp in params["layers"]:
        z = _layernorm(h, lp["ln1"]).astype(dtype)
        qkv = jnp.einsum("btd,cdhe->cbhte", z, lp["wqkv"].astype(dtype))
        qkv = constrain(qkv, P(None, "dp", "tp", "sp", None))
        attn = ring(qkv[0], qkv[1], qkv[2])
        o = jnp.einsum("bhte,hed->btd", attn, lp["wo"].astype(dtype))
        h = h + constrain(o, P("dp", "sp", None))

        z = _layernorm(h, lp["ln2"]).astype(dtype)
        m = jax.nn.gelu(z @ lp["w1"].astype(dtype) + lp["b1"].astype(dtype))
        m = constrain(m, P("dp", "sp", "tp"))
        h = h + constrain(m @ lp["w2"].astype(dtype) + lp["b2"].astype(dtype),
                          P("dp", "sp", None))

    h = _layernorm(h.astype(jnp.float32), params["lnf"])
    pooled = jnp.mean(h, axis=1)  # (B, d) — mean over the full sequence
    return pooled @ params["head"]


def make_tsp_train_step(cfg, mesh, lr=1e-3):
    """Jit-compiled SGD step over the dp×tp×sp mesh.

    Gradient collectives (dp/sp reductions, tp-sharded layouts) all come from
    GSPMD transposing the forward shardings — returns ``(params, loss)``.
    """

    def loss_fn(params, x, y):
        logits = tsp_forward(params, x, cfg, mesh)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], 1))

    @jax.jit
    def step(params, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
        return params, loss

    return step


def shard_tsp_batch(x, y, mesh):
    x = jax.device_put(x, NamedSharding(mesh, P("dp", "sp", None)))
    y = jax.device_put(y, NamedSharding(mesh, P("dp")))
    return x, y
