"""Tensor + sequence + data parallel transformer step (dp × tp × sp mesh).

The scale-out path for the long-context family: a Megatron-style sharded
transformer where

- **dp** shards the batch (federated-site data parallelism maps here too),
- **tp** shards attention heads and the MLP hidden dimension,
- **sp** shards the sequence, with exact global attention via
  :func:`~.ring_attention.ring_attention`.

Idiomatic split of responsibilities (the scaling-book recipe): parameters and
inputs get ``NamedSharding`` annotations and GSPMD derives every tensor- and
data-parallel collective (all-reduces for the row/column-sharded matmuls, the
gradient reductions) from them — nothing is hand-scheduled.  Only the ring is
manual: GSPMD cannot infer a ring schedule, so the attention inner loop runs
in a nested ``shard_map`` over the ``sp`` axis where ``ppermute`` hops are
explicit.  No reference counterpart (SURVEY.md §5: sequence parallelism
absent there); mesh/axis conventions follow ``parallel/mesh.py``.
"""
import math
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config.keys import MeshAxis
from ..utils.jax_compat import resolve_donate_argnums, shard_map
from .ring_attention import ring_attention

__all__ = ["TSPConfig", "build_tsp_mesh", "init_tsp_params", "shard_tsp_params",
           "tsp_forward", "make_tsp_train_step"]


class TSPConfig:
    """Static transformer hyperparameters for the sharded step.

    ``num_experts > 0`` replaces every block's dense FFN with a top-1
    (switch-style) mixture of experts whose expert dimension shards over the
    ``ep`` mesh axis — expert parallelism; GSPMD inserts the token
    all-to-alls from the shardings.  ``capacity_factor`` bounds tokens per
    expert (overflow tokens pass through the residual untouched, standard
    switch behavior).
    """

    def __init__(self, num_features=16, num_classes=2, d_model=128, num_heads=8,
                 num_layers=2, mlp_ratio=4, max_len=4096, causal=False,
                 dtype=jnp.float32, attn_impl=None, num_experts=0,
                 capacity_factor=1.25, moe_aux_weight=0.01):
        self.num_features = num_features
        self.num_classes = num_classes
        self.d_model = d_model
        self.num_heads = num_heads
        self.num_layers = num_layers
        self.d_ff = mlp_ratio * d_model
        self.max_len = max_len
        self.causal = causal
        self.dtype = dtype
        self.attn_impl = attn_impl
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.moe_aux_weight = moe_aux_weight
        self.head_dim = d_model // num_heads
        assert d_model % num_heads == 0


def build_tsp_mesh(dp=1, tp=1, sp=1, ep=1, devices=None):
    devices = list(devices if devices is not None else jax.devices())
    need = dp * tp * sp * ep
    if need > len(devices):
        raise ValueError(f"need {need} devices, have {len(devices)}")
    arr = np.array(devices[:need]).reshape(dp, tp, sp, ep)
    return Mesh(arr, (MeshAxis.DP, MeshAxis.TP, MeshAxis.SP, MeshAxis.EP))


def init_tsp_params(key, cfg):
    """Plain pytree of arrays; sharding is applied by :func:`shard_tsp_params`."""
    d, h, hd, ff = cfg.d_model, cfg.num_heads, cfg.head_dim, cfg.d_ff
    k = iter(jax.random.split(key, 4 + 8 * cfg.num_layers))
    init = lambda kk, shape, scale: (
        jax.random.normal(kk, shape, jnp.float32) * scale
    )
    params = {
        "in_proj": init(next(k), (cfg.num_features, d), 1 / math.sqrt(cfg.num_features)),
        "pos": init(next(k), (cfg.max_len, d), 0.02),
        "head": init(next(k), (d, cfg.num_classes), 1 / math.sqrt(d)),
        "lnf": {"scale": jnp.ones((d,)), "bias": jnp.zeros((d,))},
        "layers": [],
    }
    for _ in range(cfg.num_layers):
        layer = {
            "ln1": {"scale": jnp.ones((d,)), "bias": jnp.zeros((d,))},
            "wqkv": init(next(k), (3, d, h, hd), 1 / math.sqrt(d)),
            "wo": init(next(k), (h, hd, d), 1 / math.sqrt(d)),
            "ln2": {"scale": jnp.ones((d,)), "bias": jnp.zeros((d,))},
        }
        if cfg.num_experts > 0:
            e = cfg.num_experts
            layer.update({
                "wg": init(next(k), (d, e), 1 / math.sqrt(d)),
                "w1e": init(next(k), (e, d, ff), 1 / math.sqrt(d)),
                "b1e": jnp.zeros((e, ff)),
                "w2e": init(next(k), (e, ff, d), 1 / math.sqrt(ff)),
                "b2e": jnp.zeros((e, d)),
            })
        else:
            layer.update({
                "w1": init(next(k), (d, ff), 1 / math.sqrt(d)),
                "b1": jnp.zeros((ff,)),
                "w2": init(next(k), (ff, d), 1 / math.sqrt(ff)),
                "b2": jnp.zeros((d,)),
            })
        params["layers"].append(layer)
    return params


def _param_specs(params):
    """PartitionSpec tree: heads / d_ff sharded over tp, rest replicated."""
    def spec_for(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        return {
            "wqkv": P(None, None, MeshAxis.TP, None),  # (3, d, h/tp, hd)
            "wo": P(MeshAxis.TP, None, None),          # (h/tp, hd, d)
            "w1": P(None, MeshAxis.TP),                # (d, ff/tp)
            "b1": P(MeshAxis.TP),
            "w2": P(MeshAxis.TP, None),                # (ff/tp, d)
            # MoE: experts over ep, each expert's hidden dim over tp
            "w1e": P(MeshAxis.EP, None, MeshAxis.TP),         # (E/ep, d, ff/tp)
            "b1e": P(MeshAxis.EP, MeshAxis.TP),
            "w2e": P(MeshAxis.EP, MeshAxis.TP, None),         # (E/ep, ff/tp, d)
            "b2e": P(MeshAxis.EP, None),
        }.get(name, P())
    return jax.tree_util.tree_map_with_path(spec_for, params)


def shard_tsp_params(params, mesh):
    specs = _param_specs(params)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


def _layernorm(x, p):
    mu = jnp.mean(x, -1, keepdims=True)
    var = jnp.var(x, -1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + 1e-6) * p["scale"] + p["bias"]


def _no_constrain(a, _spec):
    return a


def transformer_block(h, lp, cfg, attn_fn, constrain=_no_constrain):
    """One pre-LN block: attention + (dense | switch-MoE) FFN.

    The single source of the block math for BOTH scale-out strategies —
    ``tsp_forward`` (dp×tp×sp×ep, sharding constraints live) and the GPipe
    pipeline (``pipeline.py``, constraints off) — so the two stay
    interchangeable on one parameter pytree.  ``attn_fn(q, k, v)`` supplies
    the attention implementation (ring over ``sp``, or plain flash).
    Returns ``(h, moe_aux_loss)``.
    """
    dtype = cfg.dtype
    z = _layernorm(h, lp["ln1"]).astype(dtype)
    qkv = jnp.einsum("btd,cdhe->cbhte", z, lp["wqkv"].astype(dtype))
    qkv = constrain(qkv, P(None, MeshAxis.DP, MeshAxis.TP, MeshAxis.SP, None))
    attn = attn_fn(qkv[0], qkv[1], qkv[2])
    o = jnp.einsum("bhte,hed->btd", attn, lp["wo"].astype(dtype))
    h = h + constrain(o, P(MeshAxis.DP, MeshAxis.SP, None))

    z = _layernorm(h, lp["ln2"]).astype(dtype)
    if cfg.num_experts > 0:
        m, aux = _switch_moe(z, lp, cfg, constrain)
        h = h + constrain(m, P(MeshAxis.DP, MeshAxis.SP, None))
        return h, aux
    m = jax.nn.gelu(z @ lp["w1"].astype(dtype) + lp["b1"].astype(dtype))
    m = constrain(m, P(MeshAxis.DP, MeshAxis.SP, MeshAxis.TP))
    h = h + constrain(m @ lp["w2"].astype(dtype) + lp["b2"].astype(dtype),
                      P(MeshAxis.DP, MeshAxis.SP, None))
    return h, jnp.zeros((), jnp.float32)


def _switch_moe(z, lp, cfg, constrain):
    """Top-1 (switch) mixture-of-experts FFN with capacity.

    Tokens route to their argmax expert; at most ``C = capacity_factor ·
    tokens/expert`` land per expert (overflow contributes nothing — it rides
    the residual, standard switch behavior).  Dispatch/combine are one-hot
    einsums, so the whole layer is static-shape and GSPMD turns the sharded
    einsums into the expert all-to-alls.  Returns (output, aux_loss) where
    aux_loss is the switch load-balancing term (mean gate prob × mean
    assignment rate per expert, scaled by E).
    """
    b, t, d = z.shape
    e = cfg.num_experts
    tokens = b * t
    cap = max(int(cfg.capacity_factor * tokens / e), 1)
    zf = z.reshape(tokens, d)
    gates = jax.nn.softmax(zf.astype(jnp.float32) @ lp["wg"], axis=-1)  # (N, E)
    expert = jnp.argmax(gates, axis=-1)
    onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)  # (N, E)
    # position of each token within its expert's capacity buffer
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0  # (N, E), -1 where unrouted
    kept = (pos >= 0) & (pos < cap)
    dispatch = jnp.einsum(
        "ne,nec->nec", onehot * kept,
        jax.nn.one_hot(
            jnp.clip(pos, 0, cap - 1).astype(jnp.int32), cap, dtype=jnp.float32
        ),
    )  # (N, E, C)
    dispatch = constrain(dispatch, P(None, MeshAxis.EP, None))
    xe = jnp.einsum("nec,nd->ecd", dispatch, zf.astype(jnp.float32))
    xe = constrain(xe, P(MeshAxis.EP, None, None)).astype(cfg.dtype)
    h = jax.nn.gelu(
        jnp.einsum("ecd,edf->ecf", xe, lp["w1e"].astype(cfg.dtype))
        + lp["b1e"][:, None].astype(cfg.dtype)
    )
    h = constrain(h, P(MeshAxis.EP, None, MeshAxis.TP))
    ye = (jnp.einsum("ecf,efd->ecd", h, lp["w2e"].astype(cfg.dtype))
          + lp["b2e"][:, None].astype(cfg.dtype))
    ye = constrain(ye.astype(jnp.float32), P(MeshAxis.EP, None, None))
    gate_val = jnp.sum(gates * onehot, axis=-1, keepdims=True)  # (N, 1)
    out = jnp.einsum("nec,ecd->nd", dispatch, ye) * gate_val
    # switch load-balancing auxiliary loss
    density = jnp.mean(onehot, axis=0)
    density_proxy = jnp.mean(gates, axis=0)
    aux = jnp.sum(density * density_proxy) * e
    return out.reshape(b, t, d).astype(cfg.dtype), aux


def tsp_forward(params, x, cfg, mesh):
    """(logits, moe_aux_loss) for (B, T, F) inputs; B sharded over dp, T over
    sp, heads/ff over tp, experts over ep — all via sharding constraints
    except the explicit ring.  ``moe_aux_loss`` is 0 for dense FFNs."""
    dtype = cfg.dtype
    x = jnp.asarray(x, dtype)
    b, t, _ = x.shape
    constrain = lambda a, s: lax.with_sharding_constraint(a, NamedSharding(mesh, s))

    h = x @ params["in_proj"].astype(dtype) + params["pos"][:t].astype(dtype)
    h = constrain(h, P(MeshAxis.DP, MeshAxis.SP, None))

    qkv_spec = P(MeshAxis.DP, MeshAxis.TP, MeshAxis.SP, None)
    ring = shard_map(
        partial(
            ring_attention, axis_name=MeshAxis.SP, causal=cfg.causal,
            impl=cfg.attn_impl,
        ),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec),
        out_specs=qkv_spec,
    )

    moe_aux = jnp.zeros((), jnp.float32)
    for lp in params["layers"]:
        h, aux = transformer_block(h, lp, cfg, ring, constrain)
        moe_aux = moe_aux + aux

    h = _layernorm(h.astype(jnp.float32), params["lnf"])
    pooled = jnp.mean(h, axis=1)  # (B, d) — mean over the full sequence
    return pooled @ params["head"], moe_aux


def make_tsp_train_step(cfg, mesh, lr=1e-3, donate=True):
    """Jit-compiled SGD step over the dp×tp×sp mesh.

    Gradient collectives (dp/sp reductions, tp-sharded layouts) all come from
    GSPMD transposing the forward shardings — returns ``(params, loss)``.

    The incoming params are DONATED on accelerator backends (the step
    returns their successor, so the old tree's buffers are reused in place
    — the tier-3 perf-donation contract; a no-op on CPU).  Callers that
    re-read the pre-step params after the call (old-vs-new comparisons)
    must pass ``donate=False``.
    """

    def loss_fn(params, x, y):
        logits, moe_aux = tsp_forward(params, x, cfg, mesh)
        logp = jax.nn.log_softmax(logits)
        ce = -jnp.mean(jnp.take_along_axis(logp, y[:, None], 1))
        return ce + cfg.moe_aux_weight * moe_aux

    donate_argnums = resolve_donate_argnums(None, (0,)) if donate else ()

    @partial(jax.jit, donate_argnums=donate_argnums)
    def step(params, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
        return params, loss

    return step


def shard_tsp_batch(x, y, mesh):
    x = jax.device_put(x, NamedSharding(mesh, P(MeshAxis.DP, MeshAxis.SP, None)))
    y = jax.device_put(y, NamedSharding(mesh, P(MeshAxis.DP)))
    return x, y
