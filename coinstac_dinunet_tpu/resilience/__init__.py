"""Resilience layer: integrity-checked atomic wire transport, retry/backoff
policies, and the deterministic chaos/fault-injection harness.

The paper's deployment relays tensors between nodes as bare files dropped in
``transferDirectory`` by an external engine — so partial writes, truncated
relays, hung sites and transient crashes are *normal* operating conditions,
not exceptional ones.  This package makes every one of them a typed,
retryable, observable event:

- :mod:`.transport` — atomic commit (tmp + fsync + rename), CRC32-checksummed
  payload format, per-directory commit manifests, typed
  :class:`~.transport.WireCorruption`/:class:`~.transport.WireIncomplete`
  errors, opt-in background commit thread.
- :mod:`.retry` — :class:`~.retry.RetryPolicy` (deadline, exponential
  backoff + deterministic jitter) applied to wire-payload loads and to
  engine node invocations, configured by the
  :class:`~..config.keys.Retry` cache-key vocabulary.
- :mod:`.chaos` — JSON fault plans pinning site crashes, hangs, payload
  truncation/corruption, dropped/duplicated relays to exact rounds+sites,
  so every recovery path runs in CI (``scripts/telemetry_smoke.py
  --fault-plan``).

See docs/RESILIENCE.md for the operator guide.
"""
from .chaos import (  # noqa: F401
    NULL_CHAOS,
    ChaosCrash,
    ChaosFault,
    ChaosHang,
    ChaosSession,
    churn_plan,
    fraction_kill_plan,
    load_fault_plan,
)
from .retry import RetryExhausted, RetryPolicy  # noqa: F401
from .transport import (  # noqa: F401
    WireCorruption,
    WireError,
    WireIncomplete,
    atomic_copy,
    flush_async,
)
