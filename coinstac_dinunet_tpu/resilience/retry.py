"""Retry/backoff policies for transient federation failures.

One :class:`RetryPolicy` class serves both retry surfaces the
invocation-per-round process model exposes:

- **wire loads** (``utils/tensorutils.py::load_arrays``): a payload that is
  absent/incomplete/corrupt may simply be mid-relay — retry with
  exponential backoff before the quorum machinery ever sees the failure.
  Enabled by default (3 attempts, ~50 ms base) because the transient is the
  common case and the cost of a false retry is milliseconds.
- **node invocations** (``engine.py``): a crashed or hung invocation is
  re-run before :meth:`~..engine.InProcessEngine._site_failure` declares the
  site dead.  Disabled by default (1 attempt) — re-invoking a node has side
  effects (data pipeline state, partial cache mutation) the operator must
  opt into via the ``invoke_retry_*`` cache keys.

Every knob is a cache key declared in
:class:`~..config.keys.Retry`; jitter is drawn from a seeded RNG so chaos
runs (and their golden comparisons) are deterministic.
"""
import threading
import time

from ..config.keys import Daemon, Retry

# stats sinks are plain cache dicts shared with the caller thread (and, at
# the aggregator fan-in, across pool threads) — one lock keeps increments
# exact without per-policy state
_NOTE_LOCK = threading.Lock()

#: wire-load defaults: retry is cheap, mid-relay payloads are common
WIRE_DEFAULTS = dict(attempts=3, base_delay=0.05, max_delay=2.0, deadline=30.0)
#: invocation defaults: retry is side-effectful — OFF until configured
INVOKE_DEFAULTS = dict(attempts=1, base_delay=0.5, max_delay=30.0,
                       deadline=None)
#: daemon-worker supervision defaults: restarting a warm worker is
#: side-effect-free at the node level (its durable state lives in the
#: engine's round-tripped cache + on disk), so restart is ON by default —
#: a crashed worker is a process to replace, not a site to bury
WORKER_DEFAULTS = dict(attempts=3, base_delay=0.1, max_delay=5.0,
                       deadline=None)


class RetryExhausted(RuntimeError):
    """Raised by :meth:`RetryPolicy.run` when every attempt failed; carries
    ``attempts`` and the final underlying error as ``__cause__``."""

    def __init__(self, describe, attempts, last):
        super().__init__(
            f"{describe or 'operation'} failed after {attempts} attempt(s): "
            f"{type(last).__name__}: {last}"
        )
        self.attempts = attempts
        self.last = last


class RetryPolicy:
    """Deadline-bounded exponential backoff with deterministic jitter.

    ``attempts`` is the TOTAL number of tries (1 = no retry).  ``deadline``
    (seconds, from the first attempt) stops retrying even with attempts
    left — a hung relay must not stall the round forever.  ``stats`` is an
    optional mutable dict (e.g. ``cache['wire_retry_stats']``) the policy
    increments so retry pressure rides the health rollup over the wire.
    """

    def __init__(self, attempts=3, base_delay=0.05, max_delay=2.0,
                 deadline=None, jitter=0.25, seed=0, stats=None):
        self.attempts = max(int(attempts), 1)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.deadline = float(deadline) if deadline else None
        self.jitter = float(jitter)
        self.stats = stats
        self.last_attempts = 0
        self._seed = int(seed)
        import random

        self._rng = random.Random(seed)

    # ------------------------------------------------------------- factories
    @classmethod
    def _from_cache(cls, cache, keys, defaults, stats=None):
        cache = cache if isinstance(cache, dict) else {}

        def get(key, dflt):
            v = cache.get(key)
            return dflt if v is None else v

        return cls(
            attempts=int(get(keys[0], defaults["attempts"])),
            base_delay=float(get(keys[1], defaults["base_delay"])),
            max_delay=float(get(keys[2], defaults["max_delay"])),
            deadline=get(keys[3], defaults["deadline"]),
            seed=int(cache.get("seed") or 0),
            stats=stats,
        )

    @classmethod
    def for_wire(cls, cache):
        """Wire-load policy from a node cache (defaults: 3 attempts); retry
        counts land in ``cache['wire_retry_stats']`` for the health rollup."""
        stats = None
        if isinstance(cache, dict):
            stats = cache.setdefault("wire_retry_stats", {})
        return cls._from_cache(
            cache,
            (Retry.WIRE_ATTEMPTS, Retry.WIRE_BASE_DELAY,
             Retry.WIRE_MAX_DELAY, Retry.WIRE_DEADLINE),
            WIRE_DEFAULTS, stats=stats,
        )

    @classmethod
    def for_invoke(cls, cache):
        """Node-invocation policy (defaults: 1 attempt — retry OFF)."""
        return cls._from_cache(
            cache,
            (Retry.INVOKE_ATTEMPTS, Retry.INVOKE_BASE_DELAY,
             Retry.INVOKE_MAX_DELAY, Retry.INVOKE_DEADLINE),
            INVOKE_DEFAULTS,
        )

    @classmethod
    def for_worker(cls, cache):
        """Daemon-worker supervision policy
        (:mod:`~..federation.daemon`): how many times a crashed/wedged
        long-lived worker is killed and restarted per invocation before
        the failure surfaces to the (separate, default-off) invoke retry
        and quorum machinery.  Defaults ON (3 attempts)."""
        return cls._from_cache(
            cache,
            (Daemon.RESTART_ATTEMPTS, Daemon.RESTART_BASE_DELAY,
             Daemon.RESTART_MAX_DELAY, Daemon.RESTART_DEADLINE),
            WORKER_DEFAULTS,
        )

    # -------------------------------------------------------------- behavior
    def delay(self, attempt):
        """Backoff before retry number ``attempt`` (1-based): exponential,
        capped, with ±``jitter`` fractional spread from the seeded RNG."""
        d = min(self.base_delay * (2.0 ** (attempt - 1)), self.max_delay)
        return max(d * (1.0 + self.jitter * self._rng.uniform(-1.0, 1.0)), 0.0)

    def fork(self, k):
        """Per-task copy for concurrent use (the aggregator's N-payload
        fan-in): same knobs and the same stats sink, but its own RNG seeded
        by ``seed + k + 1`` — concurrent tasks never share a jitter stream,
        so the draw order stays deterministic under any thread schedule."""
        return RetryPolicy(
            attempts=self.attempts, base_delay=self.base_delay,
            max_delay=self.max_delay, deadline=self.deadline,
            jitter=self.jitter, seed=self._seed + int(k) + 1,
            stats=self.stats,
        )

    def note(self, key, n=1):
        if self.stats is not None:
            with _NOTE_LOCK:
                self.stats[key] = int(self.stats.get(key, 0)) + n

    def should_retry(self, attempt, started_at):
        """True when try number ``attempt`` (1-based, already failed) leaves
        budget for another: attempts remaining AND deadline not exceeded."""
        if attempt >= self.attempts:
            return False
        if self.deadline is not None and (
            time.monotonic() - started_at
        ) >= self.deadline:
            return False
        return True

    def run(self, fn, retryable=(Exception,), describe="", on_retry=None):
        """Call ``fn()`` under this policy.

        ``on_retry(exc, attempt, delay)`` fires before each backoff sleep
        (telemetry hook).  Exhaustion raises :class:`RetryExhausted` (the
        final error as ``__cause__``) so callers can attribute *exhausted
        retries* vs *hard failure*; a non-retryable error propagates as-is
        with ``last_attempts`` still recording the tries spent."""
        started = time.monotonic()
        attempt = 0
        while True:
            attempt += 1
            self.last_attempts = attempt
            try:
                return fn()
            except retryable as exc:
                if not self.should_retry(attempt, started):
                    if self.attempts > 1:
                        raise RetryExhausted(describe, attempt, exc) from exc
                    raise
                d = self.delay(attempt)
                self.note("retries")
                if on_retry is not None:
                    on_retry(exc, attempt, d)
                if d > 0:
                    time.sleep(d)
