"""Integrity-checked atomic wire transport — the single sanctioned way to
commit and consume payload files crossing node boundaries.

The paper's execution model ships tensors between nodes as bare files in
``transferDirectory`` relayed by an external engine, which makes partial
writes, truncated relays and stale copies first-class failure modes: a
reader that ``open()``s a file mid-copy silently trains on garbage.  This
module closes every window:

- **Atomic commit** (:func:`commit_bytes`, :func:`atomic_copy`): payloads are
  written to a process-unique temp name, fsync'd, then ``os.replace``d into
  place — a reader can observe *absent* or *complete*, never *partial*, and
  a crash mid-write can never clobber a previous good payload.
- **Checksummed format**: ``utils/tensorutils._pack_parts`` embeds a CRC32 of
  the data section in the payload header (wire format v2); every load
  verifies it, so relay-level truncation/corruption surfaces as a typed
  error instead of NaNs three rounds later.
- **Per-directory manifest** (``.wire_manifest.json``): every committed
  payload is recorded (bytes + crc) in an atomically-updated manifest next
  to it.  A receiver can distinguish *not yet relayed* (no entry — keep
  waiting) from *partially relayed* (entry says N bytes, file has fewer —
  :class:`WireIncomplete`) from *corrupted* (:class:`WireCorruption`).
- **Typed errors**: :class:`WireCorruption` / :class:`WireIncomplete`
  subclass :class:`WireError` (a ``ValueError`` for backward compatibility)
  and are the retryable vocabulary ``resilience.retry`` policies act on
  before the quorum machinery ever sees a failure.
- **Opt-in background commit** (:class:`BackgroundCommitter`, enabled via
  ``cache['async_wire_commit']``): outbound serialization + fsync run on a
  worker thread, overlapping the next compute step (the spirit of
  computation/communication-decoupled SGD, arXiv:1906.12043); the node
  flushes — and re-raises the first commit error — before its output JSON
  names the files (:func:`flush_async`).

The ``wire-atomic-commit`` dinulint rule statically flags direct
``open(..., "wb")`` / ``np.save`` writes aimed at a transfer directory
anywhere outside this module.
"""
import contextlib
import json
import os
import threading
import zlib

from .. import native

#: name of the per-directory commit manifest written next to payloads
MANIFEST_NAME = ".wire_manifest.json"

# serializes manifest read-modify-write across the async committer and the
# caller thread (the process model is one writer per transfer directory,
# but both threads of one writer may commit concurrently)
_MANIFEST_LOCK = threading.Lock()


class WireError(ValueError):
    """Base for wire-payload integrity failures.

    Subclasses ``ValueError`` so pre-resilience callers that caught the old
    ``unpack_arrays`` ``ValueError`` keep working unchanged."""


class WireCorruption(WireError):
    """Payload bytes fail their embedded checksum (or the header is not a
    COINN wire payload at all) — the content is wrong, not merely late."""


class WireIncomplete(WireError):
    """Payload is shorter than its header/manifest says it should be —
    a truncated write or a relay caught mid-copy.  Retryable: the complete
    payload may still arrive."""


# ------------------------------------------------------------ atomic commit
def crc32(*buffers):
    """CRC32 over a sequence of byte buffers (the payload data section)."""
    c = 0
    for b in buffers:
        c = zlib.crc32(b, c)
    return c & 0xFFFFFFFF


def _fsync_file(path):
    with open(path, "rb+") as f:
        os.fsync(f.fileno())


def _tmp_name(path):
    """Process- AND thread-unique temp name: the async committer thread and
    the caller thread may commit concurrently in one process, and two
    writers sharing a tmp file would interleave into a garbled payload."""
    return f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"


def commit_bytes(path, header, blobs, crc=None, fsync=True, manifest=True):
    """Atomically commit ``header + blobs`` to ``path``; returns total bytes.

    tmp file (process-unique) → fsync → ``os.replace`` — the only visible
    states are *absent*, *previous payload* and *complete new payload*.
    Uses the native gather-write (``native/wire.cc``) when available so the
    blob buffers go straight to the file with no join copy.  ``crc`` (CRC32
    of ``blobs``, computed by the packer) lands in the directory manifest so
    receivers can classify failures; pass ``manifest=False`` for payloads
    outside the wire protocol.
    """
    path = str(path)
    blobs = list(blobs)
    tmp = _tmp_name(path)
    try:
        if not native.pack_file(tmp, header, blobs):
            with open(tmp, "wb") as f:
                f.write(header)
                for b in blobs:
                    f.write(b)
                if fsync:
                    f.flush()
                    os.fsync(f.fileno())
        elif fsync:
            _fsync_file(tmp)
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise
    nbytes = len(header) + sum(len(b) for b in blobs)
    if manifest:
        record_manifest(
            os.path.dirname(path) or ".", os.path.basename(path),
            nbytes, crc if crc is not None else crc32(*blobs),
        )
    return nbytes


def atomic_copy(src, dst):
    """Relay-side atomic file copy: a reader of ``dst`` can never observe a
    partial copy (the failure mode of a bare ``shutil.copy`` relay)."""
    import shutil

    tmp = _tmp_name(dst)
    try:
        shutil.copyfile(src, tmp)
        os.replace(tmp, dst)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


# ----------------------------------------------------------------- manifest
def _manifest_path(dirpath):
    return os.path.join(dirpath, MANIFEST_NAME)


def read_manifest(dirpath):
    """The directory's commit manifest (``{} `` when absent/corrupt — an
    unreadable manifest must degrade to 'no expectation', never crash)."""
    try:
        with open(_manifest_path(dirpath), "r", encoding="utf-8") as f:
            data = json.load(f)
        return data if isinstance(data, dict) else {}
    except (OSError, ValueError):
        return {}


def record_manifest(dirpath, fname, nbytes, crc):
    """Atomically record one committed payload in the directory manifest."""
    with _MANIFEST_LOCK:
        data = read_manifest(dirpath)
        files = data.setdefault("files", {})
        data["v"] = 1
        seq = int(data.get("seq", 0)) + 1
        data["seq"] = seq
        files[str(fname)] = {"bytes": int(nbytes), "crc32": int(crc), "seq": seq}
        tmp = _tmp_name(_manifest_path(dirpath))
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(data, f, separators=(",", ":"))
            os.replace(tmp, _manifest_path(dirpath))
        except BaseException:
            with contextlib.suppress(OSError):
                os.unlink(tmp)
            raise


def manifest_entry(path):
    """The manifest entry naming ``path``, or None (no expectation)."""
    d, fname = os.path.split(str(path))
    return read_manifest(d or ".").get("files", {}).get(fname)


def classify_load_failure(path, exc):
    """Map a raw load failure to the most specific typed error.

    - file absent + manifest names it → :class:`WireIncomplete` (committed
      by the sender, not (fully) relayed yet);
    - file absent + no manifest entry → the original error (*not yet sent* —
      the caller's protocol problem, not a transport one);
    - file present but shorter than the manifest's byte count →
      :class:`WireIncomplete`;
    - anything already typed passes through.
    """
    if isinstance(exc, WireError):
        return exc
    entry = manifest_entry(path)
    if not os.path.exists(path):
        if entry:
            return WireIncomplete(
                f"{path}: named in the wire manifest ({entry['bytes']} bytes "
                "committed by the sender) but not present — relay incomplete"
            )
        return exc
    if entry:
        size = os.path.getsize(path)
        if size < int(entry["bytes"]):
            return WireIncomplete(
                f"{path}: {size} bytes on disk, manifest says "
                f"{entry['bytes']} — partially relayed"
            )
    return exc


# ------------------------------------------------------- load-failure hooks
# In-process observers notified when a verified load fails (attempt-scoped).
# The chaos harness registers here so a deterministically-damaged payload
# can be "repaired" (the relay completing) between retry attempts.
_LOAD_FAILURE_HOOKS = []


def add_load_failure_hook(fn):
    _LOAD_FAILURE_HOOKS.append(fn)


def remove_load_failure_hook(fn):
    with contextlib.suppress(ValueError):
        _LOAD_FAILURE_HOOKS.remove(fn)


def notify_load_failure(path, attempt, exc):
    """Run registered hooks; True when any hook claims it changed the world
    (e.g. repaired the payload) so a retry is worth attempting."""
    changed = False
    for fn in list(_LOAD_FAILURE_HOOKS):
        try:
            changed = bool(fn(path, attempt, exc)) or changed
        except Exception:  # noqa: BLE001 — hooks must never mask the load error
            pass
    return changed


# -------------------------------------------------------- background commit
class BackgroundCommitter:
    """Single worker thread overlapping outbound payload serialization +
    commit with the caller's next compute step.

    ``submit`` enqueues a zero-argument commit thunk; ``flush`` blocks until
    the queue drains and re-raises the FIRST error (a payload that never
    committed must fail the round loudly before the protocol names it)."""

    def __init__(self):
        import queue

        self._q = queue.Queue()
        self._errors = []
        self._lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._worker, name="coinn-wire-commit", daemon=True
        )
        self._thread.start()

    def _worker(self):
        while True:
            fn = self._q.get()
            try:
                fn()
            except BaseException as exc:  # noqa: BLE001 — reported at flush
                with self._lock:
                    self._errors.append(exc)
            finally:
                self._q.task_done()

    def submit(self, fn):
        self._q.put(fn)

    def flush(self, raise_errors=True):
        """Wait for every pending commit; re-raise the first failure (or,
        with ``raise_errors=False``, return the error list — the drain path
        a FAILED invocation uses so its errors never leak into the next
        node served by this process)."""
        self._q.join()
        with self._lock:
            errors, self._errors = self._errors, []
        if errors and raise_errors:
            raise errors[0]
        return errors


_COMMITTER = None
_COMMITTER_LOCK = threading.Lock()


def async_committer():
    """The process-wide background committer (created on first use)."""
    global _COMMITTER
    with _COMMITTER_LOCK:
        if _COMMITTER is None:
            _COMMITTER = BackgroundCommitter()
        return _COMMITTER


def flush_async(raise_errors=True):
    """Drain pending async commits (no-op when none were ever submitted);
    re-raises the first commit error.  Nodes call this before returning the
    output JSON that names the committed files — and again (with
    ``raise_errors=False``) when an invocation fails, so one node's commit
    errors are never misattributed to the next node in this process."""
    if _COMMITTER is not None:
        return _COMMITTER.flush(raise_errors=raise_errors)
    return []
