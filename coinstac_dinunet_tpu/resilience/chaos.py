"""Deterministic chaos/fault-injection harness for federated runs.

Every recovery path the resilience layer promises — wire retry, corruption
recovery, invocation retry, quorum dropout after retry exhaustion — is only
trustworthy if CI can exercise it on demand.  This module injects the
failure modes of the paper's file-relay deployment *deterministically*: a
JSON **fault plan** pins each fault to an engine round (1-based) + site, so
a chaos run is reproducible bit-for-bit and comparable against a golden run.

Fault plan schema (a dict, or a path to a JSON file)::

    {"faults": [
        {"kind": "crash",    "round": 3, "site": "site_2"},
        {"kind": "hang",     "round": 3, "site": "site_1"},
        {"kind": "slow",     "round": 2, "site": "site_0", "seconds": 0.5},
        {"kind": "truncate_payload",  "round": 2, "site": "site_0",
         "file": "grads.npy"},
        {"kind": "corrupt_payload",   "round": 2, "site": "site_1",
         "file": "grads.npy"},
        {"kind": "drop_relay",        "round": 4, "site": "site_0",
         "file": "avg_grads.npy"},
        {"kind": "duplicate_delivery","round": 4, "site": "site_1",
         "file": "avg_grads.npy"},
        {"kind": "stale",    "round": 2, "site": "site_1"},
        {"kind": "reappear", "round": 3, "site": "site_2"},
        {"kind": "worker_kill", "round": 3, "site": "site_1",
         "when": "invoke"}
    ]}

``stale`` replays the site's previous round output in place of a fresh
invocation (a delayed duplicate of the site→aggregator message);
``reappear`` kills the site permanently at the pinned round and redelivers
its stale last output ONE round later — the dropped-site-reappears
scenario.  Both are the tier-4 model checker's counterexample vocabulary
(``dinulint --model``, docs/ANALYSIS.md "Tier 4").  ``worker_kill``
SIGKILLs a daemon engine's long-lived worker process (``when`` picks the
kill point: mid-invocation or between rounds) — the supervision drill
whose expected outcome is a ``worker:restart``, never a dead site; serial
per-invocation engines ignore it.

Optional per-fault keys: ``times`` (how many firings before the fault heals;
default 1 for payload/relay faults, *permanent* for crash/hang — a hung
process stays hung) and ``heal_after`` (failed load attempts before a
damaged payload is repaired, default 1 — models the relay completing).

``drop_relay`` on a destination that still holds the previous round's
payload, and ``duplicate_delivery`` (the previous round's payload arriving
*after* the fresh one and clobbering it), both leave an intact,
self-validating stale payload — the cases only the manifest CRC
cross-check in ``unpack_arrays`` can catch.

Hook points: the engines call :meth:`ChaosSession.invoke_fault` before every
node invocation (crash/hang raise a :class:`ChaosFault`; the retry policy
sees an ordinary failure), :meth:`ChaosSession.payload_faults` after a site
commits its outbound payloads, and :meth:`ChaosSession.relay_fault` per
relayed file; the transport's load-failure hook lets a damaged payload heal
between retry attempts so "recovered via retry" is a real, CI-exercisable
path.  Every firing emits a ``chaos:inject`` event (and heals emit
``chaos:heal``) on the engine's telemetry lane, which ``telemetry doctor``
folds into the postmortem.
"""
import contextlib
import json
import os

from . import transport

#: every fault kind the harness understands
FAULT_KINDS = (
    "crash", "hang", "slow",
    "truncate_payload", "corrupt_payload",
    "drop_relay", "duplicate_delivery",
    "stale", "reappear",
    "worker_kill",
    "join", "leave", "rejoin",
)
_INVOKE_KINDS = ("crash", "hang", "slow")
_PAYLOAD_KINDS = ("truncate_payload", "corrupt_payload")
_RELAY_KINDS = ("drop_relay", "duplicate_delivery")
#: site-message replay faults (the tier-4 model checker's counterexample
#: vocabulary, docs/ANALYSIS.md "Tier 4"):
#: - ``stale``: the site is not invoked this round; its PREVIOUS round's
#:   output JSON (and the untouched previous payload files in its transfer
#:   directory) stand in — a delayed duplicate of the site→aggregator
#:   message arriving in place of the fresh one.
#: - ``reappear``: the site dies at the pinned round (a permanent crash,
#:   quorum-dropped like any other), and ONE round later its last committed
#:   output is redelivered — the dropped-site-reappears scenario whose
#:   stale payload only the aggregator's roster filtering can reject.
_REPLAY_KINDS = ("stale", "reappear")
#: elastic-membership churn ops (ISSUE 15, ``federation/membership.py``):
#: not faults at all but deterministic ROSTER transitions the engines'
#: churn hook executes at the pinned round — ``leave`` retires the site
#: gracefully (flagged final contribution, never a site_died), ``join``
#: admits a brand-new site mid-run through the admission handshake, and
#: ``rejoin`` re-admits a previously dead or left site with a fresh
#: incarnation (its old payloads refused by roster epoch).  Engines query
#: :meth:`ChaosSession.membership_ops` once per round;
#: :func:`churn_plan` builds the "churn N% of the roster per round"
#: drill schedules from them.
_MEMBERSHIP_KINDS = ("join", "leave", "rejoin")
#: daemon-only process fault (``federation/daemon.py``): SIGKILL the
#: target's long-lived worker process.  ``when`` picks the kill point:
#: ``"invoke"`` (default) kills it mid-invocation — the supervisor must
#: restart it and re-run the invocation within the same round; ``"idle"``
#: kills it between rounds (during the relay) — the next round's
#: invocation finds it dead and restarts it.  Either way the SITE
#: survives: worker death is a supervision event, not a quorum event.
#: Serial per-invocation engines have no worker processes and ignore it.
_WORKER_KINDS = ("worker_kill",)
#: bytes XOR-flipped at the payload tail by corrupt_payload (data section —
#: past any header/manifest bytes, so the CRC check is what catches it)
_CORRUPT_TAIL = 8


class ChaosFault(RuntimeError):
    """An injected invocation fault (simulated crash/hang)."""

    kind = "chaos"


class ChaosCrash(ChaosFault):
    kind = "crash"


class ChaosHang(ChaosFault):
    """A hung site: the invocation never returns and the engine's timeout
    kills it — simulated by raising instead of invoking, which is exactly
    the observable behavior (no output, no cache advance)."""

    kind = "hang"


class ChaosReappear(ChaosFault):
    """The death half of a ``reappear`` fault: the site's process dies at
    the pinned round (permanently — the engine quorum-drops it), and its
    stale last output is redelivered one round later."""

    kind = "reappear"


class Fault:
    """One pinned fault from the plan."""

    __slots__ = ("kind", "round", "site", "file", "times", "seconds",
                 "heal_after", "when", "fired")

    def __init__(self, spec, index):
        if not isinstance(spec, dict):
            raise ValueError(f"fault[{index}]: expected an object, got {spec!r}")
        self.kind = spec.get("kind")
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"fault[{index}]: unknown kind {self.kind!r} "
                f"(known: {', '.join(FAULT_KINDS)})"
            )
        if spec.get("round") is None:
            raise ValueError(
                f"fault[{index}] ({self.kind}): 'round' is required — every "
                "fault is pinned to a 1-based engine round for determinism"
            )
        self.round = int(spec["round"])
        self.site = str(spec["site"]) if spec.get("site") is not None else None
        self.file = str(spec["file"]) if spec.get("file") is not None else None
        if self.site is None and self.kind in (
            _INVOKE_KINDS + _PAYLOAD_KINDS + _REPLAY_KINDS + _WORKER_KINDS
            + _MEMBERSHIP_KINDS
        ):
            raise ValueError(
                f"fault[{index}] ({self.kind}): 'site' is required"
            )
        if self.file is None and self.kind in _PAYLOAD_KINDS + _RELAY_KINDS:
            raise ValueError(
                f"fault[{index}] ({self.kind}): 'file' is required"
            )
        # crash/hang/reappear default to PERMANENT (a dead process stays
        # dead, so the invocation retries exhaust); everything else fires
        # once (a reappear's single stale REDELIVERY is tracked separately)
        default_times = (
            None if self.kind in ("crash", "hang", "reappear") else 1
        )
        self.times = (
            int(spec["times"]) if spec.get("times") is not None
            else default_times
        )
        self.seconds = float(spec.get("seconds", 0.25))
        self.heal_after = int(spec.get("heal_after", 1))
        self.when = str(spec.get("when", "invoke"))
        if self.kind in _WORKER_KINDS and self.when not in ("invoke", "idle"):
            raise ValueError(
                f"fault[{index}] ({self.kind}): 'when' must be 'invoke' "
                f"(kill mid-invocation) or 'idle' (kill between rounds), "
                f"got {self.when!r}"
            )
        self.fired = 0

    def matches(self, rnd, site=None):
        if self.round != int(rnd):
            return False
        return self.site is None or site is None or self.site == str(site)

    def can_fire(self):
        return self.times is None or self.fired < self.times

    def describe(self):
        where = f"round {self.round}"
        if self.site:
            where += f"/{self.site}"
        if self.file:
            where += f" ({self.file})"
        return f"{self.kind} @ {where}"


def fraction_kill_plan(n_sites, fraction, round=2, seed=0, kind="crash"):
    """Deterministic mega-federation fault plan: permanently kill
    ``ceil(fraction · n_sites)`` distinct sites at engine round ``round``
    (1-based) — the ISSUE-6 "kill 5% of 2,000 sites" scenario, scaled to
    any roster.  Site choice is a seeded shuffle of the roster, so the same
    ``(n_sites, fraction, seed)`` always kills the same sites and a chaos
    run stays comparable against its golden run.

    Returns a plan dict in the :func:`load_fault_plan` schema (pass it as
    ``fault_plan=`` to any engine)."""
    import math

    n_sites = int(n_sites)
    if not 0.0 < float(fraction) < 1.0:
        raise ValueError(
            f"fraction {fraction!r} must be strictly in (0, 1) — killing "
            "nobody or everybody is not a chaos scenario"
        )
    n_kill = min(int(math.ceil(float(fraction) * n_sites)), n_sites - 1)
    # seeded Fisher-Yates via numpy-free LCG would do, but random.Random is
    # deterministic for a fixed seed across platforms, which is all we need
    import random as _random

    roster = [f"site_{i}" for i in range(n_sites)]
    _random.Random(int(seed)).shuffle(roster)
    return {"faults": [
        {"kind": str(kind), "round": int(round), "site": s}
        for s in sorted(roster[:n_kill])
    ]}


def slow_site_plan(site="site_0", seconds=0.25, first_round=2,
                   last_round=64):
    """Deterministic straggler plan: slow ONE site by ``seconds`` on every
    engine round in ``[first_round, last_round]`` — the ISSUE-12 "one site
    slowed Nx" scenario the async round engine exists to hide
    (``engine.py::_step_round_async``; ``scripts/bench_federation.py
    --async-staleness``).  Faults stay pinned per round (the schema's
    determinism contract), so a persistent slowdown is simply one ``slow``
    entry per round.  The sleep happens on the invoking thread, so under
    the async engine's bounded pool it delays only the slowed site's own
    invocation — the span-overlap property ``tests/test_async.py``
    asserts.

    Returns a plan dict in the :func:`load_fault_plan` schema (pass it as
    ``fault_plan=`` to any engine)."""
    first_round, last_round = int(first_round), int(last_round)
    if not 0 < first_round <= last_round:
        raise ValueError(
            f"need 0 < first_round <= last_round, got "
            f"[{first_round}, {last_round}]"
        )
    return {"faults": [
        {"kind": "slow", "round": r, "site": str(site),
         "seconds": float(seconds)}
        for r in range(first_round, last_round + 1)
    ]}


def churn_plan(n_sites, fraction, first_round=2, rounds=4, seed=0,
               min_active_frac=0.5):
    """Deterministic elastic-membership churn plan (ISSUE 15): every round
    in ``[first_round, first_round + rounds)`` accrues ``fraction ·
    active`` of churn credit and fires one roster transition per whole
    credit, cycling leave → join → rejoin — the "churn 10% of 2,000 sites
    per round" drill, scaled to any roster.  Fractional credit CARRIES
    (10% of 3 sites is one op every ~3 rounds, not one per round — the
    ceil would triple the drill on small rosters).

    The generator simulates the roster as it schedules, so the plan is
    always self-consistent: only active sites leave, joins mint fresh site
    ids past the founding roster, rejoins re-admit previously-left sites
    (falling back to a join when nobody has left yet), and the active
    roster never drops below ``min_active_frac`` of the founding size
    (leaves degrade to joins there — churn must drill elasticity, not
    starve quorum).  Same ``(n_sites, fraction, seed)`` → the same
    schedule, so a churn run stays comparable against its golden run.

    Returns a plan dict in the :func:`load_fault_plan` schema (pass it as
    ``fault_plan=`` to any engine; the engines' churn hook
    ``_membership_round`` executes the ops)."""
    import math
    import random as _random

    n_sites = int(n_sites)
    if not 0.0 < float(fraction) < 1.0:
        raise ValueError(
            f"fraction {fraction!r} must be strictly in (0, 1) — churning "
            "nobody or everybody is not an elasticity drill"
        )
    rng = _random.Random(int(seed))
    active = [f"site_{i}" for i in range(n_sites)]
    left = []
    next_new = n_sites
    floor = max(1, int(math.ceil(float(min_active_frac) * n_sites)))
    faults = []
    ops_cycle = ("leave", "join", "rejoin")
    op_ix = 0
    credit = 0.0
    for r in range(int(first_round), int(first_round) + int(rounds)):
        credit += float(fraction) * len(active)
        n_ops = int(credit)
        credit -= n_ops
        for _ in range(n_ops):
            kind = ops_cycle[op_ix % len(ops_cycle)]
            op_ix += 1
            if kind == "rejoin" and not left:
                kind = "join"
            if kind == "leave" and len(active) <= floor:
                kind = "join"
            if kind == "leave":
                site = active.pop(rng.randrange(len(active)))
                left.append(site)
            elif kind == "rejoin":
                site = left.pop(0)
                active.append(site)
            else:  # join
                site = f"site_{next_new}"
                next_new += 1
                active.append(site)
            faults.append({"kind": kind, "round": r, "site": site})
    return {"faults": faults}


def load_fault_plan(spec):
    """Fault plan (dict or JSON file path) → validated list of faults."""
    if isinstance(spec, (str, os.PathLike)):
        with open(spec, "r", encoding="utf-8") as f:
            spec = json.load(f)
    if not isinstance(spec, dict) or not isinstance(spec.get("faults"), list):
        raise ValueError(
            "fault plan must be an object with a 'faults' list "
            "(see docs/RESILIENCE.md)"
        )
    return [Fault(s, i) for i, s in enumerate(spec["faults"])]


class _NullChaos:
    """Disabled-mode fast path: every hook is a constant-return no-op (the
    no-fault-plan overhead bound in tests/test_resilience.py)."""

    __slots__ = ()
    enabled = False

    def __bool__(self):
        return False

    def invoke_fault(self, rnd, site, rec):
        return None

    def payload_faults(self, rnd, site, dirpath, rec):
        return ()

    def relay_fault(self, rnd, fname, site, rec):
        return None

    def stale_fault(self, rnd, site, rec):
        return None

    def worker_fault(self, rnd, site, rec, when="invoke"):
        return None

    def membership_ops(self, rnd, rec):
        return ()

    def reappear_deliveries(self, rnd, rec):
        return ()

    def heal_for_retry(self, rec=None, target=None):
        return 0

    @contextlib.contextmanager
    def activate(self, rec):
        yield self


NULL_CHAOS = _NullChaos()


class ChaosSession:
    """One engine run's fault injector (holds firing counts + repairs)."""

    enabled = True

    def __init__(self, plan):
        self.faults = load_fault_plan(plan)
        # abs payload path -> [repair_fn, fault, failed_attempts, reader]:
        # pending damage a retry can heal (the deterministic stand-in for
        # "the relay completed").  ``reader`` is the node that consumes the
        # damaged path, so an invocation retry only heals damage blocking
        # ITS OWN reads — co-scheduled faults must not cancel each other.
        self._repairs = {}
        # site -> engine round at which a reappear fault's stale output is
        # redelivered (the round after the injected death)
        self._reappear_due = {}
        self._rec = None

    @classmethod
    def from_spec(cls, spec):
        """``None`` → the no-op singleton; anything else → a live session."""
        if spec is None:
            return NULL_CHAOS
        if isinstance(spec, (ChaosSession, _NullChaos)):
            return spec
        return cls(spec)

    # ------------------------------------------------------------ fault query
    def _fire(self, fault, rec, **attrs):
        fault.fired += 1
        if rec is not None:
            # NB: the injected fault kind rides as ``fault`` — ``kind`` is
            # the telemetry record-schema discriminator
            rec.event(
                "chaos:inject", cat="chaos", fault=fault.kind,
                fault_round=fault.round,
                **({"site": fault.site} if fault.site else {}),
                **({"file": fault.file} if fault.file else {}),
                **attrs,
            )

    def invoke_fault(self, rnd, site, rec):
        """Called before every node invocation attempt; raises for crash/
        hang (each ATTEMPT at the pinned round fires — a transient fault
        heals after ``times`` attempts, a permanent one exhausts the retry
        budget), sleeps for slow."""
        for fault in self.faults:
            if fault.kind not in _INVOKE_KINDS:
                continue
            if not (fault.matches(rnd, site) and fault.can_fire()):
                continue
            self._fire(fault, rec, attempt=fault.fired + 1)
            if fault.kind == "slow":
                import time

                time.sleep(fault.seconds)
                continue
            exc_cls = ChaosCrash if fault.kind == "crash" else ChaosHang
            raise exc_cls(
                f"injected {fault.kind} ({fault.describe()}, "
                f"firing {fault.fired})"
            )
        # the death half of a reappear: the process dies permanently here;
        # the stale last output redelivers one round later (the engine
        # queries reappear_deliveries)
        for fault in self.faults:
            if fault.kind != "reappear":
                continue
            if not (fault.matches(rnd, site) and fault.can_fire()):
                continue
            self._fire(fault, rec, attempt=fault.fired + 1)
            self._reappear_due.setdefault(str(site), int(rnd) + 1)
            raise ChaosReappear(
                f"injected reappear death ({fault.describe()}, stale "
                f"output redelivers at round {int(rnd) + 1})"
            )
        return None

    # ---------------------------------------------------------- replay faults
    def stale_fault(self, rnd, site, rec):
        """A matching ``stale`` fault for this (round, site), or None: the
        engine skips the invocation and replays the site's previous output
        — its payload files are the untouched previous round's, exactly a
        delayed duplicate of the site→aggregator message."""
        for fault in self.faults:
            if fault.kind != "stale":
                continue
            if not (fault.matches(rnd, site) and fault.can_fire()):
                continue
            self._fire(fault, rec)
            return fault
        return None

    def worker_fault(self, rnd, site, rec, when="invoke"):
        """A matching ``worker_kill`` fault for this (round, site, kill
        point), or None.  The DAEMON engine acts on it (SIGKILLs the
        target's live worker process — ``federation/daemon.py``); serial
        per-invocation engines never call this hook.  ``when="invoke"``
        faults fire right before the request is written (the worker dies
        mid-round, the supervisor restarts it and re-invokes);
        ``when="idle"`` faults fire at the round's relay barrier (the
        next invocation finds the worker dead)."""
        for fault in self.faults:
            if fault.kind not in _WORKER_KINDS:
                continue
            if fault.when != str(when):
                continue
            if not (fault.matches(rnd, site) and fault.can_fire()):
                continue
            self._fire(fault, rec, when=fault.when)
            return fault
        return None

    def membership_ops(self, rnd, rec):
        """Elastic-membership churn ops pinned to this round, in plan
        order: ``[(kind, site), ...]`` with kind in join/leave/rejoin.
        The ENGINES act on them (``add_site``/``remove_site`` — the churn
        hook ``_membership_round``); each op fires exactly once."""
        ops = []
        for fault in self.faults:
            if fault.kind not in _MEMBERSHIP_KINDS:
                continue
            if not (fault.matches(rnd) and fault.can_fire()):
                continue
            self._fire(fault, rec)
            ops.append((fault.kind, fault.site))
        return ops

    def reappear_deliveries(self, rnd, rec):
        """Sites whose reappear redelivery is due this round (their death
        fired one round earlier); each delivers exactly once."""
        due = sorted(
            s for s, r in self._reappear_due.items() if r == int(rnd)
        )
        for s in due:
            del self._reappear_due[s]
            if rec is not None:
                rec.event(
                    "chaos:inject", cat="chaos", fault="reappear",
                    fault_round=int(rnd), site=s, delivery=True,
                )
        return due

    # ---------------------------------------------------------- payload damage
    def payload_faults(self, rnd, site, dirpath, rec):
        """Damage committed payloads in ``dirpath`` (a site's transfer
        directory) per the plan; returns the faults that fired.  The
        original bytes are stashed so a later heal restores them exactly —
        a recovered payload is bit-identical to the committed one."""
        fired = []
        for fault in self.faults:
            if fault.kind not in _PAYLOAD_KINDS + ("drop_relay",):
                continue
            if not (fault.matches(rnd, site) and fault.can_fire()):
                continue
            path = os.path.join(dirpath, fault.file)
            if not os.path.exists(path):
                continue
            with open(path, "rb") as f:
                original = f.read()
            if fault.kind == "truncate_payload":
                damaged = original[: max(len(original) * 3 // 5, 1)]
            elif fault.kind == "corrupt_payload":
                tail = bytes(b ^ 0xFF for b in original[-_CORRUPT_TAIL:])
                damaged = original[:-_CORRUPT_TAIL] + tail
            else:  # drop_relay on a site payload: vanish it entirely
                damaged = None
            if damaged is None:
                os.unlink(path)
            else:
                with open(path, "wb") as f:  # dinulint: disable=wire-atomic-commit
                    f.write(damaged)
            # a site's outbound payloads are read by the aggregator
            self._register_repair(path, original, fault, reader="remote")
            self._fire(fault, rec, bytes=len(original))
            fired.append(fault)
        return fired

    def _register_repair(self, path, original, fault, reader=None):
        def repair(path=path, original=original):
            with open(path, "wb") as f:  # dinulint: disable=wire-atomic-commit
                f.write(original)

        self._repairs[os.path.abspath(path)] = [repair, fault, 0, reader]

    # ----------------------------------------------------------------- relay
    def relay_fault(self, rnd, fname, site, rec):
        """Engine relay hook (aggregator → site copies): returns the
        matching drop/duplicate fault (the engine acts on it) or None."""
        for fault in self.faults:
            if fault.kind not in _RELAY_KINDS:
                continue
            if fault.file != fname:
                continue
            if not (fault.matches(rnd, site) and fault.can_fire()):
                continue
            self._fire(fault, rec, target=str(site))
            return fault
        return None

    def deliver_duplicate(self, src, dst, fault, reader, rec):
        """The risky duplicate: the PREVIOUS round's payload (already at
        ``dst`` before this relay) arrives again *after* the fresh delivery
        and clobbers it — the out-of-order stale copy the manifest CRC
        cross-check exists to catch.  The repair restores the fresh
        delivery.  First-ever delivery of a file (nothing stale to
        duplicate) degrades to a harmless double copy."""
        stale = None
        if os.path.exists(dst):
            with open(dst, "rb") as f:
                stale = f.read()
        transport.atomic_copy(src, dst)  # the fresh delivery lands first
        if stale is None:
            transport.atomic_copy(src, dst)
            return
        with open(dst, "wb") as f:  # dinulint: disable=wire-atomic-commit
            f.write(stale)  # the late duplicate overwrites it

        def repair(src=src, dst=dst):
            transport.atomic_copy(src, dst)

        self._repairs[os.path.abspath(dst)] = [repair, fault, 0, reader]

    def register_dropped_relay(self, src, dst, fault, reader=None):
        """A dropped relay's repair is performing the copy; ``reader`` is
        the destination site consuming ``dst``."""

        def repair(src=src, dst=dst):
            transport.atomic_copy(src, dst)

        self._repairs[os.path.abspath(dst)] = [repair, fault, 0, reader]

    # ------------------------------------------------------------------ heal
    def _heal(self, key, rec):
        repair, fault, _, _ = self._repairs.pop(key)
        repair()
        if rec is not None:
            rec.event(
                "chaos:heal", cat="chaos", fault=fault.kind,
                file=os.path.basename(key),
            )

    def on_load_failure(self, path, attempt, exc):
        """Transport load-failure hook (in-process readers): repair the
        damaged payload once ``heal_after`` failed attempts accumulated —
        the deterministic 'relay completed' moment.

        The damage blocking a load is not always ON the loaded file: a
        dropped/duplicated ``.wire_manifest.json`` fails the PAYLOAD's
        manifest-CRC cross-check, so the failing path and the damaged file
        differ.  Before the tier-4 model checker surfaced it
        (``proto-model-unrecoverable``), that repair was keyed only by the
        damaged path and could never fire — a single transient relay fault
        on the manifest killed the reader's node despite the wire-retry
        contract.  The heal now also covers damage registered on the
        failing file's directory manifest."""
        key = os.path.abspath(str(path))
        entry = self._repairs.get(key)
        if entry is None:
            key = os.path.abspath(os.path.join(
                os.path.dirname(str(path)), transport.MANIFEST_NAME
            ))
            entry = self._repairs.get(key)
        if entry is None:
            return False
        entry[2] += 1
        if entry[2] < entry[1].heal_after:
            return False
        self._heal(key, self._rec)
        return True

    def heal_for_retry(self, rec=None, target=None):
        """Repair pending damage blocking ``target``'s reads (engine-side,
        between invocation retry attempts — the heal path for fresh-process
        nodes whose loads run outside this process).  ``target=None`` heals
        everything; damage registered for a DIFFERENT reader is left in
        place, so retrying one node never cancels a fault aimed at another.
        Returns how many payloads were repaired."""
        keys = [
            k for k, entry in self._repairs.items()
            if target is None or entry[3] is None or entry[3] == str(target)
        ]
        for key in keys:
            self._heal(key, rec if rec is not None else self._rec)
        return len(keys)

    # -------------------------------------------------------------- lifetime
    @contextlib.contextmanager
    def activate(self, rec):
        """Scope the session over an engine round: registers the transport
        load-failure hook (in-process heal) and pins the telemetry lane."""
        self._rec = rec
        transport.add_load_failure_hook(self.on_load_failure)
        try:
            yield self
        finally:
            transport.remove_load_failure_hook(self.on_load_failure)
            self._rec = None
