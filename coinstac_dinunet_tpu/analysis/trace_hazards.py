"""trace-hazards: host code that breaks (or silently de-optimizes) inside a
traced JAX computation.

A "traced context" here is any function that JAX will trace to jaxpr:

- decorated with ``jit``/``jax.jit``/``partial(jax.jit, ...)`` (same for
  ``pmap``/``vmap``/``grad``/``value_and_grad``/``checkpoint``/``remat``/
  ``custom_vjp``),
- passed as the first argument to a ``jit(...)``/``shard_map(...)``/
  ``pallas_call(...)`` call or to ``lax.scan``/``lax.cond``/
  ``lax.while_loop``,
- or named like a step builder's inner function (``_build_*_step`` style:
  any ``def`` whose name ends with ``_step``/``_kernel`` defined inside a
  function whose name starts with ``_build_``).

Within such functions the rules flag:

- ``trace-host-sync`` — ``.item()``, ``float()``/``int()``/``bool()`` on
  non-literal arguments, ``np.asarray``/``np.array`` on traced values: each
  forces a device→host readback (or a ConcretizationTypeError under jit).
- ``trace-impure`` — ``time.time()``/``perf_counter()``, ``datetime.now()``,
  ``np.random.*``/``random.*``/``os.urandom``: baked in as compile-time
  constants, so every call after the first reuses the first call's value.
- ``trace-py-control`` — Python ``if``/``while`` on an expression derived
  from the traced function's array arguments (shape/dtype/``is None``/
  ``isinstance`` tests are static and exempt).
- ``trace-set-iter`` — iterating a ``set`` literal/call to build a
  list/dict/pytree: set order varies across processes, so the resulting
  pytree structure (and therefore the compiled program) diverges between
  hosts of the same multi-controller run.
- ``trace-telemetry`` — telemetry/Recorder/PhaseTimer calls: telemetry is
  host-side wall-clock + file I/O; under tracing a span measures trace
  time once and then vanishes from the compiled program (silently wrong
  data), and any record emission is dead code at best.  Record around the
  compiled call, never inside it.
"""
import ast

from .core import Finding, Rule, dotted_name, register_rule

_TRACER_NAMES = {
    "jit", "pmap", "vmap", "grad", "value_and_grad", "checkpoint", "remat",
    "custom_vjp", "custom_jvp", "shard_map", "pallas_call",
}
_CALLABLE_TAKING = {
    "jit", "pmap", "vmap", "grad", "value_and_grad", "checkpoint", "remat",
    "shard_map", "pallas_call", "scan", "cond", "while_loop", "fori_loop",
    "custom_vjp", "custom_jvp", "map", "associative_scan",
}
_IMPURE_CALLS = {
    "time.time", "time.perf_counter", "time.monotonic",
    "datetime.now", "datetime.datetime.now", "datetime.utcnow",
    "os.urandom", "random.random", "random.randint", "random.uniform",
    "random.choice", "random.shuffle", "random.seed",
}
_IMPURE_PREFIXES = ("np.random.", "numpy.random.")
_HOST_CASTS = {"float", "int", "bool"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}

# telemetry surface (trace-telemetry rule): name segments that identify the
# subsystem itself, recorder method names, and the variable-name convention
# instrumented code uses for a bound recorder.  Roots are EXACT names —
# a prefix match would flag e.g. `record.count(...)` (a plain list method
# on an unlucky variable name) and fail the CI lint gate on clean code.
_TELEMETRY_SEGMENTS = {"telemetry", "phasetimer", "get_active", "for_node"}
_RECORDER_METHODS = {"span", "event", "wire", "count", "set_context", "flush"}
_RECORDER_ROOTS = {"rec", "recorder", "telemetry", "tracer"}


def _callable_name(node):
    """Dotted-ish display name of a Call's func; '' when unresolvable."""
    return dotted_name(node, require_name_root=False)


def _is_tracer_call(call):
    """True for jit(...)/jax.jit(...)/partial(jax.jit, ...) etc."""
    name = _callable_name(call.func)
    last = name.rsplit(".", 1)[-1] if name else ""
    if last in _TRACER_NAMES:
        return True
    if last == "partial" and call.args:
        inner = _callable_name(call.args[0])
        if inner and inner.rsplit(".", 1)[-1] in _TRACER_NAMES:
            return True
    return False


def _positional_params(fn):
    return [a.arg for a in list(fn.args.posonlyargs) + list(fn.args.args)]


def _static_param_names(fn, call):
    """Parameters pinned static via ``static_argnames``/``static_argnums`` on
    a tracer call (``@partial(jax.jit, static_argnames=...)`` or
    ``jit(fn, static_argnums=...)``) — those stay Python values, never
    tracers, so hazards keyed on them are false alarms."""
    names = set()
    if not isinstance(call, ast.Call):
        return names
    pos = _positional_params(fn)
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for sub in ast.walk(kw.value):
                if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                    names.add(sub.value)
        elif kw.arg == "static_argnums":
            for sub in ast.walk(kw.value):
                if isinstance(sub, ast.Constant) and isinstance(sub.value, int):
                    if 0 <= sub.value < len(pos):
                        names.add(pos[sub.value])
    return names


def _collect_traced_functions(tree):
    """All FunctionDef nodes JAX will trace → (reason, static param names)."""
    traced = {}  # node -> (reason, static_names)

    # decorators
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            name = _callable_name(dec.func if isinstance(dec, ast.Call) else dec)
            last = name.rsplit(".", 1)[-1] if name else ""
            if last in _TRACER_NAMES or (
                isinstance(dec, ast.Call) and _is_tracer_call(dec)
            ):
                traced[node] = (
                    f"@{name or last}", _static_param_names(node, dec)
                )

    # defs referenced as the traced callee of jit/shard_map/scan/... calls
    local_defs = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            local_defs.setdefault(node.name, node)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _callable_name(node.func)
        last = name.rsplit(".", 1)[-1] if name else ""
        if last not in _CALLABLE_TAKING:
            continue
        # jax.tree_util.map etc. are not tracers; require lax.map/jax-ish root
        if last == "map" and not name.startswith(("lax.", "jax.lax.")):
            continue
        for arg in node.args[:1]:
            target = None
            if isinstance(arg, ast.Name):
                target = local_defs.get(arg.id)
            elif isinstance(arg, ast.Call) and _callable_name(arg.func).endswith("partial"):
                if arg.args and isinstance(arg.args[0], ast.Name):
                    target = local_defs.get(arg.args[0].id)
            if target is not None and target not in traced:
                traced[target] = (
                    f"passed to {name}()", _static_param_names(target, node)
                )

    # step-builder idiom: inner *_step/*_kernel defs of a _build_* function
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
            node.name.startswith("_build_") or node.name.endswith("_kernel")
        ):
            for inner in ast.walk(node):
                if (
                    isinstance(inner, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and inner is not node
                    and (inner.name.endswith("_step") or inner.name.endswith("_kernel"))
                    and inner not in traced
                ):
                    traced[inner] = (f"inner step of {node.name}", set())
            if node.name.endswith("_kernel") and node not in traced:
                traced[node] = ("kernel naming convention", set())
    return traced


def _array_params(fn):
    """Parameter names likely bound to traced arrays (all of them, minus
    obvious non-array conventions)."""
    skip = {"self", "cls"}
    names = set()
    args = fn.args
    for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
        if a.arg not in skip:
            names.add(a.arg)
    return names


def _test_is_static(test):
    """True for tests that stay Python-static under tracing.  Boolean
    combinations are static only when EVERY operand is — ``x is None or
    x.sum() > 0`` still concretizes the traced half."""
    if isinstance(test, ast.BoolOp):
        return all(_test_is_static(v) for v in test.values)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _test_is_static(test.operand)
    for node in ast.walk(test):
        if isinstance(node, ast.Compare):
            comps = [node.left] + list(node.comparators)
            if any(isinstance(c, ast.Constant) and c.value is None for c in comps):
                return True
        if isinstance(node, ast.Call):
            name = _callable_name(node.func)
            if name.rsplit(".", 1)[-1] in {"isinstance", "len", "hasattr", "callable"}:
                return True
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return True
    return False


def _telemetry_call_name(node):
    """Display name when ``node`` (a Call) hits the telemetry surface;
    None otherwise.  Catches the module/class spellings
    (``telemetry.get_active()``, ``Recorder.for_node(...)``,
    ``PhaseTimer(cache)``), recorder-method calls on conventionally named
    bindings (``rec.span(...)``, ``recorder.event(...)``) and chained calls
    rooted at a factory (``get_active().count(...)``)."""
    name = _callable_name(node.func)
    segs = [s for s in (name or "").split(".") if s]
    low = [s.lower() for s in segs]
    if any(s in _TELEMETRY_SEGMENTS for s in low):
        return name
    if segs and low[-1] in _RECORDER_METHODS:
        if low[0] in _RECORDER_ROOTS:
            return name
        inner = node.func.value if isinstance(node.func, ast.Attribute) else None
        if isinstance(inner, ast.Call):
            iname = _callable_name(inner.func) or ""
            ilow = iname.lower()
            if (
                ilow.rsplit(".", 1)[-1] in _TELEMETRY_SEGMENTS
                or "telemetry" in ilow
            ):
                return f"{iname}().{segs[-1]}"
    return None


class _TracedBodyChecker(ast.NodeVisitor):
    def __init__(self, rule_host, rule_impure, rule_ctl, rule_set,
                 module, fn, reason, static_names=(), rule_tel=None):
        self.rh, self.ri, self.rc, self.rs = (
            rule_host, rule_impure, rule_ctl, rule_set
        )
        self.rt = rule_tel
        self.module = module
        self.fn = fn
        self.reason = reason
        self.params = _array_params(fn) - set(static_names)
        self.findings = []

    def _emit(self, rule, node, message):
        if rule is None:
            return
        self.findings.append(Finding(
            rule=rule, path=self.module.path, line=node.lineno,
            col=node.col_offset,
            message=f"{message} (inside traced `{self.fn.name}`, {self.reason})",
        ))

    # nested defs are their own traced (or host) contexts — don't descend
    def visit_FunctionDef(self, node):
        if node is self.fn:
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node):
        if self.rt is not None:
            tel = _telemetry_call_name(node)
            if tel:
                self._emit(
                    self.rt, node,
                    f"`{tel}()` is telemetry (host wall-clock + file I/O) — "
                    "it is traced away in compiled code; record around the "
                    "compiled call instead",
                )
        name = _callable_name(node.func)
        last = name.rsplit(".", 1)[-1] if name else ""
        if isinstance(node.func, ast.Attribute) and node.func.attr == "item":
            self._emit(self.rh, node, "`.item()` forces a device->host sync")
        elif last in _HOST_CASTS and node.args and not isinstance(
            node.args[0], ast.Constant
        ) and isinstance(node.func, ast.Name):
            self._emit(
                self.rh, node,
                f"`{last}()` on a traced value concretizes it on host",
            )
        elif name in ("np.asarray", "np.array", "numpy.asarray", "numpy.array"):
            self._emit(
                self.rh, node,
                f"`{name}()` pulls a traced value to host memory",
            )
        elif name in _IMPURE_CALLS or name.startswith(_IMPURE_PREFIXES):
            self._emit(
                self.ri, node,
                f"`{name}()` is baked in as a compile-time constant under "
                "tracing (stale on every later call)",
            )
        self.generic_visit(node)

    def _check_test(self, node, kind):
        if _test_is_static(node.test):
            return
        names = {
            n.id for n in ast.walk(node.test) if isinstance(n, ast.Name)
        }
        hit = sorted(names & self.params)
        if hit:
            self._emit(
                self.rc, node,
                f"Python `{kind}` on `{', '.join(hit)}` — traced values make "
                "this a ConcretizationTypeError; use lax.cond/lax.while_loop "
                "or jnp.where",
            )

    def visit_If(self, node):
        self._check_test(node, "if")
        self.generic_visit(node)

    def visit_While(self, node):
        self._check_test(node, "while")
        self.generic_visit(node)

    def _check_iter(self, iter_node):
        is_set = isinstance(iter_node, ast.Set) or (
            isinstance(iter_node, ast.Call)
            and _callable_name(iter_node.func).rsplit(".", 1)[-1] == "set"
        )
        if is_set:
            self._emit(
                self.rs, iter_node,
                "iterating a `set` under tracing: ordering varies across "
                "processes, so pytree/program structure diverges between "
                "hosts — sort it first",
            )

    def visit_For(self, node):
        self._check_iter(node.iter)
        self.generic_visit(node)

    def visit_comprehension(self, node):
        # comprehension generators (list/set/dict/genexp) land here via
        # generic_visit; the node itself carries no position — use the iter's
        self._check_iter(node.iter)
        self.generic_visit(node)


class _TraceHazardBase(Rule):
    """Shared machinery; subclasses pick which finding family they own."""

    family = None  # 'host' | 'impure' | 'ctl' | 'set' | 'telemetry'

    def visit_module(self, module):
        findings = []
        traced = _collect_traced_functions(module.tree)
        for fn, (reason, static_names) in traced.items():
            checker = _TracedBodyChecker(
                rule_host=self.id if self.family == "host" else None,
                rule_impure=self.id if self.family == "impure" else None,
                rule_ctl=self.id if self.family == "ctl" else None,
                rule_set=self.id if self.family == "set" else None,
                rule_tel=self.id if self.family == "telemetry" else None,
                module=module, fn=fn, reason=reason,
                static_names=static_names,
            )
            checker.visit(fn)
            findings.extend(checker.findings)
        return findings


@register_rule
class HostSyncRule(_TraceHazardBase):
    id = "trace-host-sync"
    doc = (".item()/float()/np.asarray on traced values — device->host "
           "syncs or ConcretizationTypeErrors inside jit/shard_map bodies.")
    family = "host"


@register_rule
class ImpureCallRule(_TraceHazardBase):
    id = "trace-impure"
    doc = ("time.time()/fresh-PRNG calls inside traced functions are frozen "
           "at compile time.")
    family = "impure"


@register_rule
class PyControlFlowRule(_TraceHazardBase):
    id = "trace-py-control"
    doc = ("Python if/while on traced array arguments inside jit/shard_map "
           "bodies.")
    family = "ctl"


@register_rule
class SetIterationRule(_TraceHazardBase):
    id = "trace-set-iter"
    doc = ("set iteration feeding pytree construction under tracing — "
           "cross-host nondeterminism.")
    family = "set"


@register_rule
class TelemetryInTraceRule(_TraceHazardBase):
    id = "trace-telemetry"
    doc = ("telemetry/Recorder/PhaseTimer calls inside jit/shard_map bodies "
           "— host-side I/O is traced away (spans measure trace time once, "
           "records never emit); instrument around the compiled call.")
    family = "telemetry"
