"""Protocol transition-system IR — the tier-4 model checker's input.

Tier-3's :mod:`~.protocol_flow` extracts phase-attributed wire/cache events
from the AST of ``nodes/local.py`` / ``nodes/remote.py`` and judges each
node in isolation.  This module lifts that extraction into an **explicit
transition-system IR** the whole-federation model checker
(:mod:`~.model_check`) executes:

- :class:`PhaseBlock` — one dispatch block of a node's ``compute``: the
  wire keys it produces/consumes, its cache reads/writes, and the phase
  values it writes into the round output (``outgoing``).  Per-node state in
  the composed model is *phase × cache-key set*; actions are
  invoke/relay/fault.
- :class:`NodeIR` — a node's ordered blocks plus the phases its dispatch
  tests, **including** the wire events of the modules it delegates to
  (``parallel/learner.py`` ships the gradients a site's COMPUTATION block
  never touches directly; ``parallel/reducer.py`` consumes them on the
  aggregator) and the cache writes of its constructor path.
- :class:`SemanticFacts` — behaviors the composed model needs that live in
  statement *order*, not in any single event: whether the aggregator's
  quorum filter runs before the reducer's input snapshot (the
  reappearing-stale-site hazard), whether a mixed-phase round fails loudly
  (the silent-INIT_RUNS-reset hazard), and whether the chaos heal bridges
  damage on a directory manifest to the payload load that fails because of
  it (the relay clobber-window hazard).  Extraction is by AST only — the
  recognized guard methods are the canonical ``_check_quorum`` /
  ``_check_lockstep_phases`` names (the same convention tier-3 uses for
  ``PHASE_TRANSITIONS``); the chaos heal marker is a real behavioral
  reference (``MANIFEST_NAME`` inside ``on_load_failure``).

Pure stdlib ``ast`` — the IR builds everywhere tier-3's proto pass runs,
no JAX required.
"""
import ast
import dataclasses
import os

from .core import Module
from .protocol import _resolve_key, load_vocabulary
from .protocol_flow import (
    _WILDCARD,
    _NodeModel,
    _contains_input,
    _package_root,
    _read_source,
    load_phase_transitions,
    load_volatile_keys,
)

#: delegate modules whose wire events execute inside a node's COMPUTATION
#: block (repo-relative path -> node role they belong to).
#: ``federation/membership.py`` is the aggregator's elastic-membership
#: round processing (ISSUE 15): it consumes the ``leaving`` flag and the
#: ``roster_epoch`` echo off each site's payload on the remote side.
DELEGATE_FILES = {
    "parallel/learner.py": "local",
    "parallel/reducer.py": "remote",
    "federation/membership.py": "remote",
}

#: methods whose ``return {literal: ...}`` dicts are wire payloads in the
#: delegate modules (mirrors analysis/protocol.py::PRODUCER_METHODS)
_PRODUCER_METHODS = {
    "step", "to_reduce", "backward", "reduce", "train_serializable",
    "validation_distributed", "test_distributed",
}


@dataclasses.dataclass(frozen=True)
class IREvent:
    """One wire/cache event inside a block (kind: produce | consume |
    write | wildcard | hard | soft)."""

    key: str
    kind: str
    line: int


@dataclasses.dataclass(frozen=True)
class PhaseBlock:
    """All events of one dispatch phase (``phase=None`` = unguarded code
    that runs every invocation); ``guard`` is what the dispatch tests —
    ``"out"`` (the local if/elif chain over the rewritten out-phase, so a
    block it rewrites to CHAINS within the same invocation) or ``"input"``
    (the remote ``check(all, ...)`` style, no chaining)."""

    phase: str
    guard: str
    produces: tuple
    consumes: tuple
    cache_reads: tuple
    cache_writes: tuple
    outgoing: tuple


@dataclasses.dataclass
class NodeIR:
    """One node's transition-system view."""

    role: str
    path: str
    blocks: dict              # phase (or None) -> PhaseBlock
    tested_phases: frozenset
    init_writes: tuple        # cache writes on the constructor path
    phase_fallthrough: str    # phase echoed when no dispatch block fires

    def block(self, phase):
        return self.blocks.get(phase)

    def static_cache_writers(self):
        """Keys with at least one non-wildcard compute-tree write — the
        set the read-before-write check may judge (origin known).  Cached:
        the IR is immutable and the explorer calls this once per simulated
        invocation in its hot loop."""
        cached = getattr(self, "_writers", None)
        if cached is None:
            cached = frozenset(
                e.key for b in self.blocks.values()
                for e in b.cache_writes if e.key != _WILDCARD
            )
            self._writers = cached
        return cached


@dataclasses.dataclass
class SemanticFacts:
    """Order/behavior facts the per-event IR cannot carry (see module
    docstring); ``anchors`` maps fact names to (path, line) for finding
    attribution."""

    quorum_checked: bool = True
    quorum_filters_reappeared: bool = True
    quorum_before_reduce_input: bool = True
    # elastic membership (ISSUE 15): the aggregator runs a membership
    # round step (canonical ``_check_membership`` name, the same
    # convention as ``_check_quorum``) before the reducer/trainer input
    # snapshot, the membership filter refuses payloads by ROSTER EPOCH
    # (an echo older than the site's current admission), and the local
    # join entry is exactly-once (the admission block is guarded by a
    # negated cache sentinel, so a retry after a completed join skips)
    membership_checked: bool = True
    membership_before_reduce_input: bool = True
    roster_epoch_refusal: bool = True
    admission_exactly_once: bool = True
    lockstep_phase_guard: bool = True
    round_lockstep_guard: bool = True
    # the round-stamp guard honors the async staleness WINDOW
    # (Federation.ASYNC_STALENESS): an echo lagging by at most k is
    # accepted instead of refused — the relaxation the staleness_k model
    # action exercises (ISSUE 12)
    round_lockstep_window: bool = True
    # the window additionally honors the run-ahead pipelining depth
    # (Federation.RUN_AHEAD): a FRESH contribution's echo may lag by up
    # to k + d — the widening the run_ahead model action exercises
    # (ISSUE 14)
    round_lockstep_run_ahead: bool = True
    heal_bridges_manifest: bool = True
    anchors: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ProtocolIR:
    """The composed model's complete input."""

    local: NodeIR
    remote: NodeIR
    transitions: dict
    volatile: frozenset
    facts: SemanticFacts
    phase_values: frozenset


# ------------------------------------------------------------- node lifting
def _guard_kinds(module, enum_map, phase_values, phase_key="phase"):
    """phase value -> "out" | "input": what the dispatch guard tests."""
    model = _NodeModel.__new__(_NodeModel)
    model.enum_map = enum_map
    model.phase_key = phase_key
    kinds = {}
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.If):
            continue
        phase = model._phase_of_test(node.test)
        if phase is None or phase in kinds:
            continue
        kind = "input"
        for sub in ast.walk(node.test):
            if isinstance(sub, ast.Compare):
                for side in (sub.left, *sub.comparators):
                    if isinstance(side, ast.Subscript) and (
                        (isinstance(side.value, ast.Name)
                         and side.value.id == "out")
                        or (isinstance(side.value, ast.Attribute)
                            and side.value.attr == "out")
                    ):
                        kind = "out"
        kinds[phase] = kind
    return kinds


def _constructor_writes(module, enum_map):
    """Cache writes on the constructor path (``__init__`` + the self-methods
    it calls), as IREvents — tier-3 ignores them entirely; the executing
    model must not fabricate read-before-write findings for keys the
    constructor populates every invocation."""
    model = _NodeModel.__new__(_NodeModel)
    model.module = module
    model.enum_map = enum_map
    model.phase_key = "phase"
    model.produced, model.consumed = [], []
    model.outgoing, model.cache_writes, model.cache_reads = {}, [], []
    model.tested_phases = set()
    model.methods, model.class_name = {}, None
    model._find_class()
    if model.class_name is None or "__init__" not in model.methods:
        return ()
    model._visit_region(model.methods["__init__"].body, "__init__", set())
    return tuple(
        IREvent(e.key, e.kind if e.kind == "wildcard" else "write", e.line)
        for e in model.cache_writes
    )


def _delegate_events(role, enum_map, extra_modules=None):
    """Wire produce/consume events of the delegate modules for ``role``
    (attached to the node's COMPUTATION block by the model)."""
    produces, consumes = [], []
    mods = []
    root = _package_root()
    for rel, r in DELEGATE_FILES.items():
        if r != role:
            continue
        path = os.path.join(root, *rel.split("/"))
        if os.path.exists(path):
            try:
                mods.append(Module.parse(path, "coinstac_dinunet_tpu/" + rel))
            except (SyntaxError, OSError, ValueError):
                continue
    for mod in extra_modules or ():
        mods.append(mod)
    for mod in mods:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Subscript) and not isinstance(
                node.ctx, ast.Load
            ):
                base = node.value
                if (isinstance(base, ast.Name) and base.id == "out") or (
                    isinstance(base, ast.Attribute) and base.attr == "out"
                ):
                    key = _resolve_key(node.slice, enum_map)
                    if key:
                        produces.append(IREvent(key, "produce", node.lineno))
            elif isinstance(node, ast.FunctionDef) and (
                node.name in _PRODUCER_METHODS
            ):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Return) and isinstance(
                        sub.value, ast.Dict
                    ):
                        for k_node in sub.value.keys:
                            key = k_node and _resolve_key(k_node, enum_map)
                            if key:
                                produces.append(
                                    IREvent(key, "produce", sub.lineno)
                                )
            elif isinstance(node, ast.Call):
                fn = node.func
                name = fn.attr if isinstance(fn, ast.Attribute) else None
                if name == "get" and node.args and isinstance(
                    fn, ast.Attribute
                ):
                    # any input-rooted base counts, incl. the reducer's
                    # per-site view ``self.input[s].get(K)`` — and the
                    # per-site payload iteration variables the membership
                    # delegate walks (``for site, site_vars in
                    # input_dict.items()``), the same convention
                    # _NodeModel._consume_anywhere honors
                    if _contains_input(fn.value) or (
                        isinstance(fn.value, ast.Name)
                        and fn.value.id in ("site", "site_vars")
                    ):
                        key = _resolve_key(node.args[0], enum_map)
                        if key:
                            consumes.append(
                                IREvent(key, "consume", node.lineno)
                            )
                elif name == "_load" and node.args:
                    key = _resolve_key(node.args[0], enum_map)
                    if key:
                        consumes.append(IREvent(key, "consume", node.lineno))
    return tuple(produces), tuple(consumes)


#: the synthetic pseudo-phase holding the local join entry's events
#: (ISSUE 15): the ``_join*`` method a joiner's FIRST invocation executes
#: under the admission guard.  It is not a dispatch phase — the model
#: checker executes it exactly once per admitted joiner, never on a
#: steady-state re-invocation, so its one-shot cache writes (fold
#: assignment, frozen args) do not trip the volatile-key rule.
JOIN_BLOCK = "join"


def _carve_join_block(module, blocks):
    """Re-attribute the events of the local join-entry method (canonical
    ``_join*`` name, the same convention as ``_check_quorum``) from
    whatever dispatch region its call site sits in to the synthetic
    :data:`JOIN_BLOCK`.  Returns (blocks, join_method_node or None)."""
    methods = _find_class_methods(module.tree)
    join_fn = next(
        (fn for name, fn in sorted(methods.items())
         if name.startswith("_join")), None
    )
    if join_fn is None:
        return blocks, None
    lo = join_fn.lineno
    hi = getattr(join_fn, "end_lineno", lo) or lo
    carved = {"produces": [], "consumes": [],
              "cache_reads": [], "cache_writes": []}
    new_blocks = {}
    for phase, block in blocks.items():
        kept = {}
        for field in carved:
            kept[field] = []
            for e in getattr(block, field):
                (carved if lo <= e.line <= hi else kept)[field].append(e)
        new_blocks[phase] = dataclasses.replace(
            block, **{f: tuple(v) for f, v in kept.items()}
        )
    if any(carved.values()):
        new_blocks[JOIN_BLOCK] = PhaseBlock(
            phase=JOIN_BLOCK, guard="input",
            produces=tuple(carved["produces"]),
            consumes=tuple(carved["consumes"]),
            cache_reads=tuple(carved["cache_reads"]),
            cache_writes=tuple(carved["cache_writes"]),
            outgoing=(),
        )
    return new_blocks, join_fn


def build_node_ir(module, role, enum_map=None, extra_delegates=None,
                  delegates=True):
    """Lift one node module into a :class:`NodeIR`; ``delegates=False``
    skips the package delegate modules (fixture pairs)."""
    if enum_map is None:
        enum_map, _, _, _ = load_vocabulary()
    phase_values = {v for (cls, _), v in enum_map.items() if cls == "Phase"}
    model = _NodeModel(module, enum_map)
    kinds = _guard_kinds(module, enum_map, phase_values)

    phases = set()
    for ev in (model.produced + model.consumed
               + model.cache_writes + model.cache_reads):
        phases.add(ev.phase)
    phases |= set(model.outgoing)
    blocks = {}
    for phase in phases:
        blocks[phase] = PhaseBlock(
            phase=phase,
            guard=kinds.get(phase, "input"),
            produces=tuple(
                IREvent(e.key, "produce", e.line)
                for e in model.produced if e.phase == phase
            ),
            consumes=tuple(
                IREvent(e.key, "consume", e.line)
                for e in model.consumed if e.phase == phase
            ),
            cache_reads=tuple(
                IREvent(e.key, e.kind, e.line)
                for e in model.cache_reads if e.phase == phase
            ),
            cache_writes=tuple(
                IREvent(e.key, e.kind if e.kind == "wildcard" else "write",
                        e.line)
                for e in model.cache_writes if e.phase == phase
            ),
            outgoing=tuple(sorted(model.outgoing.get(phase, ()))),
        )
    # the local join entry is its own synthetic block (ISSUE 15) — carved
    # BEFORE the delegate merge, whose events carry other files' lines
    if role == "local":
        blocks, _ = _carve_join_block(module, blocks)
    # delegate wire events execute inside the COMPUTATION block
    d_prod, d_cons = ((), ())
    if delegates or extra_delegates:
        d_prod, d_cons = _delegate_events(
            role if delegates else "<none>", enum_map,
            extra_modules=extra_delegates,
        )
    comp = blocks.get("computation")
    if comp is None and (d_prod or d_cons):
        comp = PhaseBlock("computation", kinds.get("computation", "input"),
                          (), (), (), (), ())
    if comp is not None:
        blocks["computation"] = dataclasses.replace(
            comp,
            produces=comp.produces + d_prod,
            consumes=comp.consumes + d_cons,
        )

    # the phase echoed when no dispatch fires: the unguarded
    # ``out[PHASE] = input.get(PHASE, <default>)`` default, if resolvable
    fallthrough = "echo"
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        fn = node.value.func
        if not (isinstance(fn, ast.Attribute) and fn.attr == "get"):
            continue
        targets_phase = any(
            isinstance(t, ast.Subscript)
            and _resolve_key(t.slice, enum_map) == "phase"
            for t in node.targets
        )
        if targets_phase and len(node.value.args) >= 2:
            default = _resolve_key(node.value.args[1], enum_map)
            if default in phase_values:
                fallthrough = default
                break
    return NodeIR(
        role=role,
        path=module.path,
        blocks=blocks,
        tested_phases=frozenset(model.tested_phases),
        init_writes=_constructor_writes(module, enum_map),
        phase_fallthrough=fallthrough,
    )


# ------------------------------------------------------------ semantic facts
def _find_class_methods(tree):
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            methods = {
                n.name: n for n in node.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            if "compute" in methods:
                return methods
    return {}


def _self_calls_in_order(fn):
    """(name, line) of every ``self.<name>(...)`` call and every
    ``<target> = <call>(... input=self.input ...)`` snapshot assignment in
    ``fn``, in source order."""
    events = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self"):
                events.append(("call", f.attr, node.lineno))
            for kw in node.keywords:
                if kw.arg == "input" and isinstance(kw.value, ast.Attribute) \
                        and kw.value.attr == "input":
                    events.append(("input_snapshot", "", node.lineno))
    return sorted(events, key=lambda e: e[2])


def extract_remote_facts(remote_module, facts):
    """Quorum/lockstep ordering facts from the aggregator module."""
    methods = _find_class_methods(remote_module.tree)
    compute = methods.get("compute")
    if compute is None:
        facts.quorum_checked = False
        return facts
    events = _self_calls_in_order(compute)
    quorum_line = next(
        (ln for kind, name, ln in events
         if kind == "call" and name == "_check_quorum"), None
    )
    snapshot_line = next(
        (ln for kind, _, ln in events if kind == "input_snapshot"), None
    )
    facts.quorum_checked = quorum_line is not None
    lockstep_names = [
        name for kind, name, _ in events
        if kind == "call" and "lockstep" in name
    ]
    facts.lockstep_phase_guard = bool(lockstep_names)
    # the stale-in-steady-state defense: the lockstep guard also compares
    # the echoed round stamp (LocalWire.ROUND / the "wire_round" value),
    # and — since ISSUE 12 — may relax the exact-stamp comparison to the
    # async staleness WINDOW (a reference to Federation.ASYNC_STALENESS /
    # "async_staleness" inside the guard method is the marker)
    facts.round_lockstep_guard = False
    facts.round_lockstep_window = False
    facts.round_lockstep_run_ahead = False
    for name in lockstep_names:
        body = methods.get(name)
        if body is None:
            continue
        for sub in ast.walk(body):
            marker = None
            if isinstance(sub, ast.Attribute):
                marker = sub.attr
            elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                marker = sub.value
            if marker in ("ROUND", "wire_round"):
                facts.round_lockstep_guard = True
            if marker in ("ASYNC_STALENESS", "async_staleness"):
                facts.round_lockstep_window = True
            if marker in ("RUN_AHEAD", "run_ahead"):
                facts.round_lockstep_run_ahead = True
    # elastic membership (ISSUE 15): the membership round step (admission
    # processing + the roster-epoch payload filter) must ALSO precede the
    # reducer/trainer input snapshot — the same stale-contribution hazard
    # the quorum ordering fact patrols, for rejoined incarnations
    membership_line = next(
        (ln for kind, name, ln in events
         if kind == "call" and "membership" in name), None
    )
    facts.membership_checked = membership_line is not None
    facts.membership_before_reduce_input = (
        membership_line is not None
        and (snapshot_line is None or membership_line < snapshot_line)
    )
    if membership_line is not None:
        facts.anchors["membership"] = (remote_module.path, membership_line)
    if snapshot_line is not None:
        facts.anchors["reduce_input"] = (remote_module.path, snapshot_line)
    if quorum_line is not None:
        facts.anchors["quorum"] = (remote_module.path, quorum_line)
    lockstep_line = next(
        (ln for kind, name, ln in events
         if kind == "call" and "lockstep" in name), None
    )
    if lockstep_line is not None:
        facts.anchors["lockstep"] = (remote_module.path, lockstep_line)
    facts.quorum_before_reduce_input = (
        quorum_line is not None
        and (snapshot_line is None or quorum_line < snapshot_line)
    )
    # does _check_quorum actually filter reappeared sites (rebind self.input)?
    cq = methods.get("_check_quorum")
    facts.quorum_filters_reappeared = bool(cq) and any(
        isinstance(n, ast.Assign) and any(
            isinstance(t, ast.Attribute) and t.attr == "input"
            for t in n.targets
        )
        for n in ast.walk(cq)
    ) if cq is not None else False
    return facts


def extract_local_facts(local_module, facts):
    """Local-side elastic-membership facts (ISSUE 15): is the join entry
    exactly-once?  Marker: the ``_join*`` call site's enclosing ``if``
    guard includes a NEGATED cache sentinel (``not self.cache.get(...)``)
    — a retry after a completed join, or a re-broadcast admission record,
    skips the entry instead of resetting the site's fold state."""
    methods = _find_class_methods(local_module.tree)
    join_fn = next(
        (fn for name, fn in sorted(methods.items())
         if name.startswith("_join")), None
    )
    if join_fn is None:
        facts.admission_exactly_once = False
        return facts
    compute = methods.get("compute")
    facts.admission_exactly_once = False
    for node in ast.walk(compute) if compute is not None else ():
        if not isinstance(node, ast.If):
            continue
        calls_join = any(
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr == join_fn.name
            for sub in ast.walk(node)
        )
        if not calls_join:
            continue
        facts.anchors["admission"] = (local_module.path, node.lineno)
        for sub in ast.walk(node.test):
            if isinstance(sub, ast.UnaryOp) and isinstance(
                sub.op, ast.Not
            ) and any(
                isinstance(s, ast.Attribute) and s.attr == "cache"
                for s in ast.walk(sub.operand)
            ):
                facts.admission_exactly_once = True
    return facts


def extract_membership_facts(membership_source, facts, membership_path=None):
    """Does the aggregator's membership filter refuse payloads BY ROSTER
    EPOCH?  Marker: a function named ``refuses`` or ``filter_membership``
    references the ``roster_epoch`` wire key (the ``ROSTER_EPOCH`` enum
    member or its value) — the only witness that a rejoined site's fresh
    contribution is distinguishable from a redelivery out of its previous,
    dead incarnation."""
    try:
        tree = ast.parse(membership_source)
    except SyntaxError:
        facts.roster_epoch_refusal = False
        return facts
    facts.roster_epoch_refusal = False
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name in (
            "refuses", "filter_membership"
        ):
            facts.anchors.setdefault("membership_filter", (
                membership_path
                or "coinstac_dinunet_tpu/federation/membership.py",
                node.lineno,
            ))
            for sub in ast.walk(node):
                marker = None
                if isinstance(sub, ast.Attribute):
                    marker = sub.attr
                elif isinstance(sub, ast.Constant) and isinstance(
                    sub.value, str
                ):
                    marker = sub.value
                if marker in ("ROSTER_EPOCH", "roster_epoch",
                              "admitted_epoch"):
                    facts.roster_epoch_refusal = True
    return facts


def extract_chaos_facts(chaos_source, facts, chaos_path=None):
    """Does the chaos heal bridge manifest damage to the payload load that
    fails because of it?  Marker: ``on_load_failure`` references
    ``MANIFEST_NAME`` (a behavioral reference, not a declaration)."""
    try:
        tree = ast.parse(chaos_source)
    except SyntaxError:
        facts.heal_bridges_manifest = False
        return facts
    facts.heal_bridges_manifest = False
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and (
            node.name == "on_load_failure"
        ):
            facts.anchors["heal"] = (
                chaos_path or "coinstac_dinunet_tpu/resilience/chaos.py",
                node.lineno,
            )
            for sub in ast.walk(node):
                name = None
                if isinstance(sub, ast.Name):
                    name = sub.id
                elif isinstance(sub, ast.Attribute):
                    name = sub.attr
                if name == "MANIFEST_NAME":
                    facts.heal_bridges_manifest = True
    return facts


def extract_engine_facts(engine_source, facts,
                         engine_path="coinstac_dinunet_tpu/engine.py"):
    """Anchor the relay clobber window: the ``duplicate_delivery`` branch
    of the engine's broadcast relay (finding attribution only)."""
    try:
        tree = ast.parse(engine_source)
    except SyntaxError:
        return facts
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and (
            node.name == "_relay_broadcast"
        ):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Constant) and (
                    sub.value == "duplicate_delivery"
                ):
                    facts.anchors.setdefault(
                        "relay_duplicate", (engine_path, sub.lineno)
                    )
    return facts


# -------------------------------------------------------------- entry point
def build_protocol_ir(local_module=None, remote_module=None,
                      keys_source=None, chaos_source=None,
                      volatile_keys=None, facts=None, delegates=None):
    """Build the composed-model IR for the real package (default) or an
    explicit fixture pair (tier-4's unit tests seed protocol bugs into
    synthetic node modules exactly like tier-3's; delegates are merged
    only for the real pair unless overridden)."""
    root = _package_root()
    if delegates is None:
        delegates = local_module is None and remote_module is None

    def _mod(path, rel):
        return Module.parse(path, "coinstac_dinunet_tpu/" + rel)

    if local_module is None:
        local_module = _mod(
            os.path.join(root, "nodes", "local.py"), "nodes/local.py"
        )
    if remote_module is None:
        remote_module = _mod(
            os.path.join(root, "nodes", "remote.py"), "nodes/remote.py"
        )
    enum_map, _, _, _ = load_vocabulary(keys_source)
    phase_values = frozenset(
        v for (cls, _), v in enum_map.items() if cls == "Phase"
    )
    if chaos_source is None:
        chaos_path = os.path.join(root, "resilience", "chaos.py")
        chaos_source = _read_source(chaos_path) if os.path.exists(
            chaos_path
        ) else ""
    if facts is None:
        facts = SemanticFacts()
        extract_remote_facts(remote_module, facts)
        extract_local_facts(local_module, facts)
        extract_chaos_facts(chaos_source, facts)
        membership_path = os.path.join(root, "federation", "membership.py")
        if os.path.exists(membership_path):
            extract_membership_facts(_read_source(membership_path), facts)
        else:
            facts.roster_epoch_refusal = False
        engine_path = os.path.join(root, "engine.py")
        if os.path.exists(engine_path):
            extract_engine_facts(_read_source(engine_path), facts)
    volatile = frozenset(
        volatile_keys if volatile_keys is not None
        else load_volatile_keys(enum_map=enum_map)
    )
    return ProtocolIR(
        local=build_node_ir(local_module, "local", enum_map,
                            delegates=delegates),
        remote=build_node_ir(remote_module, "remote", enum_map,
                             delegates=delegates),
        transitions=load_phase_transitions(keys_source),
        volatile=volatile,
        facts=facts,
        phase_values=phase_values,
    )
