"""sharding-*: mesh/axis/PartitionSpec conformance across the parallel layer.

Axis names ("site", "sp", "pp", …) and PartitionSpecs are the package's
second wire protocol: a mesh defines the vocabulary on one line of
``parallel/*.py`` and dozens of consumers — ``PartitionSpec`` literals,
collective ``axis_name`` arguments, ``shard_map`` ``in_specs``/``out_specs``
— must agree with it.  Nothing at runtime checks the agreement early: a
typo'd axis or a collective outside its ``shard_map`` only surfaces at trace
time on a multi-device mesh, often only on the real pod.  These rules make
the whole class a millisecond-scale static finding:

- ``sharding-unknown-axis`` — an axis name (in a mesh definition, spec,
  collective, or ``*_axis``/``axis_name`` kwarg) that the
  :class:`~coinstac_dinunet_tpu.config.keys.MeshAxis` vocabulary does not
  declare: the typo case.
- ``sharding-mesh-arity`` — a ``Mesh(arr.reshape(a, b), names)`` whose
  axis-name tuple length differs from the device-array rank, or a mesh
  naming the same axis twice.
- ``sharding-spec-arity`` — a ``PartitionSpec`` that uses one axis twice
  (JAX rejects it at trace time), or combines axes that no mesh defined
  anywhere in the scanned project defines together (a spec that can never
  match its constructing mesh).
- ``sharding-collective-scope`` — a collective (``psum``/``all_gather``/
  ``ppermute``/…) over a *statically known* axis name inside a function
  that is not (module-locally) connected to any ``shard_map``/``pmap``:
  outside a binding context the axis is unbound and the call raises at
  trace time.
- ``sharding-axis-literal`` — a bare string literal in an axis position
  where the :class:`MeshAxis` constant exists: the vocabulary is only a
  single source of truth if call sites actually go through it.

Axis resolution understands both spellings — ``"site"`` (a *bare* literal)
and ``MeshAxis.SITE`` (the sanctioned constant, resolved by *parsing*
``config/keys.py``, never importing it) — so the conformance rules keep
checking migrated call sites while the literal rule enforces the migration.

Scope analysis for ``sharding-collective-scope`` is deliberately
conservative (module-local, so no import graph is needed):

- functions passed to ``shard_map``/``pmap``/``pallas_call`` (directly,
  via ``functools.partial``, or as a decorator) are *connected*;
- connectivity propagates through module-local name references — a helper
  a connected function mentions (``_site_mean``, ``self._site_weight``)
  is connected too, as is anything nested inside a connected body;
- a function *returned* by its enclosing function escapes local analysis
  (the hook-factory idiom: ``_intra_grad_reduce`` returning
  ``sp_grad_reduce``) and is never flagged;
- collectives whose axis is dynamic (a parameter, ``self.tp_axis``) are
  never flagged — the binding obligation is the caller's.
"""
import ast
import os

from .core import Finding, ProjectRule, register_rule, dotted_name

MESH_AXIS_CLASS = "MeshAxis"

#: callables that *define* a mesh: last dotted component -> index of the
#: axis-names argument.
_MESH_CTORS = {"Mesh": 1, "make_mesh": 1}

#: callables that *consume* axis names as their n-th positional argument
#: (kwarg spelling: ``axis_name``).
_COLLECTIVES = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "all_gather": 1,
    "ppermute": 1, "all_to_all": 1, "psum_scatter": 1, "pshuffle": 1,
    "pswapaxes": 1, "axis_index": 0, "axis_size": 0,
}

_SPEC_CTORS = {"P", "PartitionSpec"}
_TRACER_SEEDS = {"shard_map", "pmap", "pallas_call"}
_AXIS_KWARGS = {"axis_name", "axis_names"}


def _keys_module_path():
    return os.path.normpath(
        os.path.join(os.path.dirname(__file__), "..", "config", "keys.py")
    )


def load_mesh_axes(keys_source=None):
    """Parse ``config/keys.py`` (source text or the package's own copy) into
    the ``{member: value}`` map of the :class:`MeshAxis` vocabulary."""
    if keys_source is None:
        with open(_keys_module_path(), "r", encoding="utf-8") as f:
            keys_source = f.read()
    tree = ast.parse(keys_source)
    axes = {}
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == MESH_AXIS_CLASS:
            for stmt in node.body:
                if (
                    isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, str)
                ):
                    axes[stmt.targets[0].id] = stmt.value.value
    return axes


def _last(name):
    return name.rsplit(".", 1)[-1] if name else ""


class _AxisUse:
    """One statically-resolved axis name at a source position."""

    __slots__ = ("value", "node", "kind", "bare")

    def __init__(self, value, node, kind, bare):
        self.value = value    # resolved axis string
        self.node = node      # ast node carrying the position
        self.kind = kind      # 'mesh' | 'spec' | 'collective' | 'axis-kwarg'
        self.bare = bare      # True for a raw string literal


class _MeshDef:
    __slots__ = ("axes", "node", "ctor_rank")

    def __init__(self, axes, node, ctor_rank):
        self.axes = axes          # tuple of resolved names (None = dynamic)
        self.node = node
        self.ctor_rank = ctor_rank  # device-array rank when inferable


class _SpecUse:
    __slots__ = ("entries", "node", "partial")

    def __init__(self, entries, node, partial):
        self.entries = entries    # flat list of resolved axis names
        self.node = node
        self.partial = partial    # True when some entries were dynamic

    def axes(self):
        return [e for e in self.entries if e is not None]


class _CollectiveUse:
    __slots__ = ("axes", "node", "fn_chain")

    def __init__(self, axes, node, fn_chain):
        self.axes = axes          # resolved axis names (may be empty)
        self.node = node
        self.fn_chain = fn_chain  # enclosing FunctionDef nodes, innermost first


class _ModuleShardingInfo:
    def __init__(self):
        self.uses = []            # [_AxisUse]
        self.meshes = []          # [_MeshDef]
        self.specs = []           # [_SpecUse]
        self.collectives = []     # [_CollectiveUse]
        self.connected = set()    # FunctionDef nodes reachable from shard_map
        self.escaped = set()      # FunctionDef nodes returned by their parent


def _resolve_axis(node, axis_members):
    """expr -> (axis string, bare) or None when dynamic/unresolvable.

    Accepts a raw string literal or a ``MeshAxis.X`` attribute chain (any
    prefix: ``keys.MeshAxis.X`` resolves too).
    """
    if isinstance(node, ast.Constant):
        if isinstance(node.value, str):
            return node.value, True
        return None
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    parts.reverse()
    if len(parts) >= 2 and parts[-2] == MESH_AXIS_CLASS:
        value = axis_members.get(parts[-1])
        if value is not None:
            return value, False
    return None


def _resolve_axis_seq(node, axis_members):
    """Tuple/List of axis exprs -> (entries, fully_resolved).

    Entries are resolved ``_resolve_axis`` results (``(value, bare)``) or
    ``None`` placeholders for ``None`` constants; dynamic entries flip
    ``fully_resolved`` off but keep the resolvable neighbors.
    """
    entries, full = [], True
    for elt in node.elts:
        if isinstance(elt, ast.Constant) and elt.value is None:
            entries.append(None)
            continue
        if isinstance(elt, (ast.Tuple, ast.List)):
            sub, sub_full = _resolve_axis_seq(elt, axis_members)
            entries.extend(sub)
            full = full and sub_full
            continue
        hit = _resolve_axis(elt, axis_members)
        if hit is None:
            full = False
            entries.append(None)
        else:
            entries.append(hit)
    return entries, full


class _Collector(ast.NodeVisitor):
    def __init__(self, axis_members):
        self.axis_members = axis_members
        self.info = _ModuleShardingInfo()
        self.fn_stack = []
        self.defs_by_name = {}

    # ------------------------------------------------------------- structure
    def visit_FunctionDef(self, node):
        self.defs_by_name.setdefault(node.name, []).append(node)
        self.fn_stack.append(node)
        self.generic_visit(node)
        self.fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Return(self, node):
        # the hook-factory escape: a def whose name is returned by its
        # enclosing function leaves local analysis
        if node.value is not None and self.fn_stack:
            parent = self.fn_stack[-1]
            names = {
                n.id for n in ast.walk(node.value) if isinstance(n, ast.Name)
            }
            for name in names:
                for d in self.defs_by_name.get(name, []):
                    if d is not parent:
                        self.info.escaped.add(d)
        self.generic_visit(node)

    # ----------------------------------------------------------------- calls
    def _record_use(self, resolved, node, kind):
        value, bare = resolved
        self.info.uses.append(_AxisUse(value, node, kind, bare))

    def _record_axis_arg(self, arg, kind):
        """One axis argument: a single name or a tuple of names; uses anchor
        to the innermost resolvable node.  Returns the resolved axis
        strings."""
        out = []
        if isinstance(arg, (ast.Tuple, ast.List)):
            for elt in arg.elts:
                hit = _resolve_axis(elt, self.axis_members)
                if hit is not None:
                    self._record_use(hit, elt, kind)
                    out.append(hit[0])
        else:
            hit = _resolve_axis(arg, self.axis_members)
            if hit is not None:
                self._record_use(hit, arg, kind)
                out.append(hit[0])
        return out

    def _handle_mesh(self, call, axes_ix):
        axes_arg = None
        if len(call.args) > axes_ix:
            axes_arg = call.args[axes_ix]
        else:
            for kw in call.keywords:
                if kw.arg == "axis_names":
                    axes_arg = kw.value
        if axes_arg is None:
            return
        if isinstance(axes_arg, (ast.Tuple, ast.List)):
            axes = []
            for elt in axes_arg.elts:
                hit = _resolve_axis(elt, self.axis_members)
                if hit is None:
                    axes.append(None)
                else:
                    self._record_use(hit, elt, "mesh")
                    axes.append(hit[0])
            self.info.meshes.append(
                _MeshDef(tuple(axes), call, self._ctor_rank(call))
            )
        else:
            hit = _resolve_axis(axes_arg, self.axis_members)
            if hit is not None:  # Mesh(devs, "x") single-axis spelling
                self._record_use(hit, axes_arg, "mesh")
                self.info.meshes.append(
                    _MeshDef((hit[0],), call, self._ctor_rank(call))
                )

    @staticmethod
    def _ctor_rank(call):
        """Rank of the device array when the first argument is a visible
        ``....reshape(a, b, ...)`` call (or ``make_mesh``'s shape tuple)."""
        if not call.args:
            return None
        first = call.args[0]
        if _last(dotted_name(call.func, require_name_root=False)) == "make_mesh":
            if isinstance(first, (ast.Tuple, ast.List)):
                return len(first.elts)
            return None
        if (
            isinstance(first, ast.Call)
            and isinstance(first.func, ast.Attribute)
            and first.func.attr == "reshape"
        ):
            args = first.args
            if len(args) == 1 and isinstance(args[0], (ast.Tuple, ast.List)):
                return len(args[0].elts)
            if args and all(not isinstance(a, ast.Starred) for a in args):
                return len(args)
        return None

    def _handle_spec(self, call):
        entries, full = [], True
        for arg in call.args:
            if isinstance(arg, ast.Starred):
                # P(*dynamic): record whatever axis tokens are visible, skip
                # the structural checks
                for sub in ast.walk(arg):
                    hit = _resolve_axis(sub, self.axis_members)
                    if hit is not None:
                        self._record_use(hit, sub, "spec")
                full = False
                continue
            if isinstance(arg, ast.Constant) and arg.value is None:
                entries.append(None)
                continue
            if isinstance(arg, (ast.Tuple, ast.List)):
                sub, sub_full = _resolve_axis_seq(arg, self.axis_members)
                for hit in sub:
                    if hit is not None:
                        self._record_use(hit, arg, "spec")
                        entries.append(hit[0])
                full = full and sub_full
                continue
            hit = _resolve_axis(arg, self.axis_members)
            if hit is None:
                entries.append(None)
                full = False
            else:
                self._record_use(hit, arg, "spec")
                entries.append(hit[0])
        self.info.specs.append(_SpecUse(entries, call, not full))

    def _handle_collective(self, call, axis_ix):
        arg = None
        if len(call.args) > axis_ix:
            arg = call.args[axis_ix]
        else:
            for kw in call.keywords:
                if kw.arg == "axis_name":
                    arg = kw.value
        if arg is None:
            return
        axes = self._record_axis_arg(arg, "collective")
        self.info.collectives.append(
            _CollectiveUse(axes, call, tuple(reversed(self.fn_stack)))
        )

    def visit_Call(self, call):
        name = dotted_name(call.func, require_name_root=False)
        last = _last(name)
        consumed = set()  # kwargs the dedicated handlers already recorded
        if last in _MESH_CTORS:
            self._handle_mesh(call, _MESH_CTORS[last])
            consumed.add("axis_names")
        elif last in _SPEC_CTORS:
            self._handle_spec(call)
        elif last in _COLLECTIVES:
            self._handle_collective(call, _COLLECTIVES[last])
            consumed.add("axis_name")
        for kw in call.keywords:
            if kw.arg in consumed:
                continue
            if kw.arg and (kw.arg in _AXIS_KWARGS or kw.arg.endswith("_axis")):
                if isinstance(kw.value, (ast.Constant, ast.Tuple, ast.List)) \
                        or isinstance(kw.value, ast.Attribute):
                    # ints (jnp axis=0) resolve to None and are ignored
                    self._record_axis_arg(kw.value, "axis-kwarg")
        self.generic_visit(call)


def _connected_defs(tree, defs_by_name):
    """FunctionDef nodes (module-locally) connected to a shard_map/pmap."""
    seeds = set()

    def add_target(expr):
        if isinstance(expr, ast.Name):
            seeds.update(defs_by_name.get(expr.id, []))
        elif isinstance(expr, ast.Call) and _last(
            dotted_name(expr.func, require_name_root=False)
        ) == "partial":
            if expr.args and isinstance(expr.args[0], ast.Name):
                seeds.update(defs_by_name.get(expr.args[0].id, []))

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            last = _last(dotted_name(node.func, require_name_root=False))
            if last in _TRACER_SEEDS and node.args:
                add_target(node.args[0])
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                names = {
                    _last(dotted_name(d, require_name_root=False))
                    for d in ast.walk(dec)
                    if isinstance(d, (ast.Name, ast.Attribute))
                }
                if names & _TRACER_SEEDS:
                    seeds.add(node)

    connected = set()
    frontier = list(seeds)
    while frontier:
        fn = frontier.pop()
        if fn in connected:
            continue
        connected.add(fn)
        for sub in ast.walk(fn):
            ref = None
            if isinstance(sub, ast.Name):
                ref = sub.id
            elif isinstance(sub, ast.Attribute):
                ref = sub.attr
            if ref:
                for d in defs_by_name.get(ref, []):
                    if d not in connected:
                        frontier.append(d)
    return connected


def _collect(module, axis_members):
    """Per-module collection, cached on the Module object: all five
    sharding rules share one AST walk + connectivity analysis per file
    instead of each repeating it (the cache key carries the axis
    vocabulary, so fixture rules with a custom ``keys_source`` never see a
    stale vocabulary's info)."""
    key = tuple(sorted(axis_members.items()))
    cache = getattr(module, "_sharding_info_cache", None)
    if cache is None:
        cache = module._sharding_info_cache = {}
    info = cache.get(key)
    if info is not None:
        return info
    collector = _Collector(axis_members)
    collector.visit(module.tree)
    info = collector.info
    info.connected = _connected_defs(module.tree, collector.defs_by_name)
    cache[key] = info
    return info


class _ShardingBase(ProjectRule):
    """Shared collection; subclasses own one finding family each.

    ``keys_source`` overrides the ``config/keys.py`` source for fixture
    tests (mirroring :class:`~.protocol.ProtocolConformanceRule`).
    """

    def __init__(self, keys_source=None):
        self._keys_source = keys_source
        self._axis_members = None
        self._infos = []  # [(module, info)] in scan order

    def axis_members(self):
        if self._axis_members is None:
            self._axis_members = load_mesh_axes(self._keys_source)
        return self._axis_members

    def vocab(self):
        return set(self.axis_members().values())

    def member_for(self, value):
        for member, v in self.axis_members().items():
            if v == value:
                return member
        return None

    def visit_module(self, module):
        info = _collect(module, self.axis_members())
        self._infos.append((module, info))
        return self.module_findings(module, info)

    def module_findings(self, module, info):
        return []

    def finalize(self, modules):
        return []

    def _finding(self, module, node, message):
        return Finding(
            rule=self.id, path=module.path, line=node.lineno,
            col=node.col_offset, message=message,
        )


@register_rule
class UnknownAxisRule(_ShardingBase):
    id = "sharding-unknown-axis"
    doc = ("Axis names in mesh definitions, PartitionSpecs, collectives, or "
           "*_axis kwargs that the config/keys.py MeshAxis vocabulary does "
           "not declare (typos).")

    def module_findings(self, module, info):
        vocab = self.vocab()
        findings = []
        for use in info.uses:
            if use.value not in vocab:
                findings.append(self._finding(
                    module, use.node,
                    f"axis name '{use.value}' ({use.kind}) is not declared "
                    "in the config/keys.py MeshAxis vocabulary "
                    f"(known: {', '.join(sorted(vocab))})",
                ))
        return findings


@register_rule
class MeshArityRule(_ShardingBase):
    id = "sharding-mesh-arity"
    doc = ("Mesh definitions whose axis-name tuple cannot match the device "
           "array (reshape rank != axis count, or a duplicated axis name).")

    def module_findings(self, module, info):
        findings = []
        for mesh in info.meshes:
            named = [a for a in mesh.axes if a is not None]
            dupes = sorted({a for a in named if named.count(a) > 1})
            for a in dupes:
                findings.append(self._finding(
                    module, mesh.node,
                    f"mesh names axis '{a}' more than once",
                ))
            if mesh.ctor_rank is not None and mesh.ctor_rank != len(mesh.axes):
                findings.append(self._finding(
                    module, mesh.node,
                    f"mesh axis tuple has {len(mesh.axes)} name(s) but the "
                    f"device array is reshaped to rank {mesh.ctor_rank}",
                ))
        return findings


@register_rule
class SpecArityRule(_ShardingBase):
    id = "sharding-spec-arity"
    doc = ("PartitionSpecs that repeat an axis, or combine axes no mesh "
           "defined in the scanned project defines together.")

    def module_findings(self, module, info):
        findings = []
        for spec in info.specs:
            axes = spec.axes()
            dupes = sorted({a for a in axes if axes.count(a) > 1})
            for a in dupes:
                findings.append(self._finding(
                    module, spec.node,
                    f"PartitionSpec uses axis '{a}' more than once (JAX "
                    "rejects a spec that repeats a mesh axis)",
                ))
        return findings

    def finalize(self, modules):
        mesh_axis_sets = []
        for _, info in self._infos:
            for mesh in info.meshes:
                named = frozenset(a for a in mesh.axes if a is not None)
                if named and None not in mesh.axes:
                    mesh_axis_sets.append(named)
        if not mesh_axis_sets:
            # partial scan (no mesh in sight): a combo check would flood
            return []
        findings = []
        vocab = self.vocab()
        for module, info in self._infos:
            for spec in info.specs:
                axes = frozenset(spec.axes())
                if len(axes) < 2 or not axes <= vocab:
                    continue  # unknown axes are UnknownAxisRule's report
                if not any(axes <= m for m in mesh_axis_sets):
                    combos = ", ".join(
                        "(" + ", ".join(sorted(m)) + ")"
                        for m in sorted(mesh_axis_sets, key=sorted)
                    )
                    findings.append(self._finding(
                        module, spec.node,
                        "PartitionSpec combines axes "
                        f"({', '.join(sorted(axes))}) that no mesh defines "
                        f"together (meshes: {combos})",
                    ))
        return findings


@register_rule
class CollectiveScopeRule(_ShardingBase):
    id = "sharding-collective-scope"
    doc = ("Collectives over a statically-known axis name in functions not "
           "connected (module-locally) to any shard_map/pmap — the axis is "
           "unbound there and the call fails at trace time.")

    def module_findings(self, module, info):
        findings = []
        for use in info.collectives:
            if not use.axes:
                continue  # dynamic axis: the caller owns the binding
            chain = use.fn_chain
            if any(fn in info.connected for fn in chain):
                continue
            if any(fn in info.escaped for fn in chain):
                continue  # returned hook: escapes local analysis
            where = (f"`{chain[0].name}`" if chain else "module level")
            findings.append(self._finding(
                module, use.node,
                f"collective over axis '{', '.join(use.axes)}' at {where} "
                "is not connected to any shard_map/pmap in this module — "
                "the axis name is unbound outside a mapped context",
            ))
        return findings


@register_rule
class AxisLiteralRule(_ShardingBase):
    id = "sharding-axis-literal"
    doc = ("Bare mesh-axis string literals in axis positions — use the "
           "MeshAxis constants from config/keys.py (the single source of "
           "truth the sharding rules check against).")

    def module_findings(self, module, info):
        findings = []
        for use in info.uses:
            if use.bare and use.value in self.vocab():
                member = self.member_for(use.value)
                findings.append(self._finding(
                    module, use.node,
                    f"bare mesh-axis literal '{use.value}' ({use.kind}) — "
                    f"use MeshAxis.{member} from config/keys.py",
                ))
        return findings
