"""proto-num-parity: the tier-7 bit-parity prover (dynamic half).

The repo's acceptance strategy leans on CLAIMED equivalences — run-ahead
``d=0`` drains to the serial schedule (PR 14), async ``k=0`` + pool-1 is
the lockstep template (PR 12), mmap fan-in loads equal heap copies
(PR 14), the site-vectorized engine matches the file transport (PR 13),
and a factored codec at full rank is exact (PowerSGD, ROADMAP 1).  This
prover enumerates those contracts as scenarios and EXECUTES both arms —
the engine-backed ones through the real :class:`~..engine.InProcessEngine`
round loop with pure-numpy fedbench-shaped stubs under virtual time (the
tier-5 explorer's seams: an inline pool, no wall-clock grace), the
wire-backed one through the real COINNTW2 save/load path, the transport
and codec models as honest two-implementation numpy recurrences — and
compares the per-round tensor trajectories under a ULP-aware comparator.

Site gradients are magnitude-spread (×10³ per site) so floating-point
summation ORDER genuinely changes bits: an arm that reorders one fan-in
or substitutes one payload cannot pass.

On mismatch the prover bisects to the first diverging round (the arms
are deterministic recurrences — divergence persists once introduced),
scans that round for the first diverging tensor, and emits a
``proto-num-parity`` finding anchored at the engine seam whose contract
broke, plus a replayable parity plan JSON (``--parity-plans``) that
:func:`replay_parity` re-executes to the same violation — exactly like
tier-4 chaos plans and tier-5 schedules.  The ``_BREAK_*`` switches
(tests only) model one broken semantics per contract so every invariant
is provably checkable, not vacuous (``tests/test_analysis_tier7.py``).

Deterministic: seeded trajectories, fixed orders, no wall-clock — the
same verdicts on every run; the full five-contract sweep stays well
under a minute on CPU.
"""
import ast
import json
import math
import os
import tempfile

import numpy as np

from ..config.keys import Numerics

#: broken-semantics switches (tests only; the tier-4/5 idiom).  One per
#: contract: an off-schedule eps on the run-ahead arm's reduce, the async
#: arm re-using its round-0 derived key, a tainted mmap view, the
#: vectorized arm stacking its fan-in unsorted, and the codec silently
#: dropping a rank.
_BREAK_RUN_AHEAD_EPS = False
_BREAK_ASYNC_REUSED_KEY = False
_BREAK_MMAP_TAINT = False
_BREAK_UNSORTED_FAN_IN = False
_BREAK_RANK_DROP = False

#: the five claimed equivalence contracts, by scenario name
CONTRACTS = (
    "async-k0-pool1-vs-lockstep",
    "codec-full-rank-vs-dense",
    "mmap-vs-copy",
    "run-ahead-0-vs-serial",
    "vectorized-vs-file-transport",
)

PARITY_RULE_IDS = (
    Numerics.CONFIG,
    Numerics.PARITY,
)

_INVARIANTS = {
    "run-ahead-0-vs-serial":
        "run_ahead=0 drains to the serial round schedule bit-identically",
    "async-k0-pool1-vs-lockstep":
        "async k=0 + pool-1 is the lockstep template bit-identically",
    "mmap-vs-copy":
        "mmap fan-in views load bit-identically to heap copies",
    "vectorized-vs-file-transport":
        "the vectorized reduce equals the sequential file-transport reduce",
    "codec-full-rank-vs-dense":
        "the factored codec at full rank reconstructs the dense wire exactly",
}


class ParityConfig:
    """Prover bound: ``sites`` × ``rounds`` per scenario, and the base
    seed every arm's gradient stream derives from."""

    def __init__(self, sites=None, rounds=None, seed=7):
        self.sites = int(sites if sites is not None
                         else Numerics.DEFAULT_SITES)
        self.rounds = int(rounds if rounds is not None
                          else Numerics.DEFAULT_ROUNDS)
        self.seed = int(seed)

    def scenario(self):
        return {"sites": self.sites, "rounds": self.rounds,
                "seed": self.seed}


class ParityResult:
    def __init__(self, findings, plans, report):
        self.findings = findings
        self.plans = plans
        self.report = report


# ------------------------------------------------------ ULP-aware compare
def ulp_diff(a, b):
    """Elementwise ULP distance between two same-shape/same-dtype float
    arrays as exact Python ints (an object array), 0 == bit-identical.
    Any shape/dtype mismatch is ``inf`` — there is no meaningful ULP
    between different types.  Shared with ``tests/_parity.py``."""
    a, b = np.asarray(a), np.asarray(b)
    if a.shape != b.shape or a.dtype != b.dtype:
        return np.array(math.inf)
    if a.dtype.kind not in "f":
        eq = (a == b)
        return np.where(eq, 0, math.inf) if a.size else np.zeros(0, object)
    itype = {2: np.int16, 4: np.int32, 8: np.int64}[a.dtype.itemsize]
    min_int = int(np.iinfo(itype).min)
    # IEEE bit patterns → monotonically ordered ints (exact object math:
    # the distance between opposite-sign floats overflows fixed width)
    ia = a.view(itype).ravel().astype(object)
    ib = b.view(itype).ravel().astype(object)
    oa = np.where(ia >= 0, ia, min_int - ia)
    ob = np.where(ib >= 0, ib, min_int - ib)
    return np.abs(oa - ob).reshape(a.shape)


def max_ulp_diff(a, b):
    """The worst elementwise ULP distance (int, or ``math.inf`` on a
    shape/dtype mismatch); 0 means bit-identical."""
    d = ulp_diff(a, b)
    if not d.size:
        return 0
    m = d.max()
    return m if m == math.inf else int(m)


def tree_max_ulp(tree_a, tree_b):
    """Per-tensor worst ULP distance between two ``{name: array}`` dicts;
    a key present on one side only maps to ``inf``."""
    out = {}
    for name in sorted(set(tree_a) | set(tree_b)):
        if name not in tree_a or name not in tree_b:
            out[name] = math.inf
        else:
            out[name] = max_ulp_diff(tree_a[name], tree_b[name])
    return out


# ------------------------------------------------------- gradient streams
def _site_grad(seed, site, rnd, dim=8):
    """Site ``site``'s round-``rnd`` gradient: a seeded deterministic
    stream, magnitude-spread ×10³ per site so summation order changes
    bits (the float64 mantissa holds ~15.9 decimal digits — three sites
    span 10⁶, well inside exact representation but far outside
    order-invariant addition)."""
    gen = np.random.Generator(
        np.random.PCG64(seed * 100003 + site * 31 + rnd)
    )
    scale = 10.0 ** (3 * (site % 5))
    return scale * (1.0 + gen.random(dim))


# ------------------------------------------------------ engine-backed arms
class _InlinePool:
    """Submit-runs-inline executor: the async path's futures are complete
    before the collect phase ever looks — virtual time, zero wall-clock,
    and (with k=0) the exact lockstep delivery the contract claims."""

    def submit(self, fn, *args, **kwargs):
        from concurrent.futures import Future

        fut = Future()
        fut.set_running_or_notify_cancel()
        try:
            fut.set_result(fn(*args, **kwargs))
        except BaseException as exc:  # noqa: BLE001 — future contract
            fut.set_exception(exc)
        return fut

    def shutdown(self, wait=True, cancel_futures=False):
        pass


def _make_parity_engine(workdir, config, arm, **engine_kwargs):
    """A real :class:`~..engine.InProcessEngine` whose node invocations
    are pure-numpy stubs shipping REAL COINNTW2 payloads; the aggregator
    stub runs the sorted-site mean + weight update and records the
    per-round trajectory.  Deferred import so the static tier never pays
    the engine import."""
    from ..config.keys import Mode, Phase
    from ..engine import InProcessEngine
    from ..utils.tensorutils import load_arrays, save_arrays

    seed = config.seed

    class _ParityEngine(InProcessEngine):
        _ASYNC_POOL_CAP = None

        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            self._parity_pool = _InlinePool()
            self._parity_w = np.zeros(8, dtype=np.float64)
            self.trajectory = []

        def _ensure_async_pool(self, size):
            return self._parity_pool

        def _async_grace(self):
            return None  # virtual time: futures are already complete

        def _site_attempt(self, rnd, s, inp, rec):
            ix = int(s.rsplit("_", 1)[1])
            draw = rnd
            if _BREAK_ASYNC_REUSED_KEY and arm == "async":
                # broken semantics: the async arm re-uses its round-0
                # derived key — every round replays round 0's stream
                draw = 0
            with rec.span(f"invoke:{s}", cat="invoke", round=rnd):
                save_arrays(
                    os.path.join(
                        self.site_states[s]["transferDirectory"],
                        "grads.npy",
                    ),
                    [_site_grad(seed, ix, draw)],
                )
            return {
                "phase": Phase.COMPUTATION.value, "mode": Mode.TRAIN.value,
                "reduce": True, "grads_file": "grads.npy", "wire_round": rnd,
            }

        def _remote_attempt(self, rnd, site_outs, rec):
            with rec.span("invoke:remote", cat="invoke"):
                total, n = np.zeros(8, dtype=np.float64), 0
                for s in sorted(site_outs):
                    out = site_outs[s]
                    if not out.get("reduce"):
                        continue
                    arrays = load_arrays(os.path.join(
                        self.site_states[s]["transferDirectory"],
                        out["grads_file"],
                    ))
                    total = total + np.asarray(arrays[0], dtype=np.float64)
                    n += 1
                avg = total / max(n, 1)
                if _BREAK_RUN_AHEAD_EPS and arm == "run_ahead":
                    # broken semantics: the pipelined arm's reduce drifts
                    # by one eps — d=0 is no longer the serial schedule
                    avg = avg * (1.0 + 2.0 ** -48)
                self._parity_w = self._parity_w - 0.1 * avg
                self.trajectory.append({
                    "avg_grads": avg.copy(), "w": self._parity_w.copy(),
                })
                save_arrays(
                    os.path.join(self.remote_state["transferDirectory"],
                                 "avg_grads.npy"),
                    [avg],
                )
            return {"phase": Phase.COMPUTATION.value, "update": True,
                    "avg_grads_file": "avg_grads.npy"}

    return _ParityEngine(workdir, config.sites, telemetry=True,
                         **engine_kwargs)


def _engine_trajectory(workdir, config, arm, **engine_kwargs):
    eng = _make_parity_engine(workdir, config, arm, **engine_kwargs)
    try:
        for _ in range(config.rounds):
            eng.step_round()
        return list(eng.trajectory)
    finally:
        eng.close()


# --------------------------------------------------------- wire-backed arm
def _mmap_arm(workdir, config, mmap):
    """The real COINNTW2 save → ``load_arrays_many(mmap=...)`` → sorted
    fan-in sum, as a per-round recurrence."""
    from ..utils.tensorutils import load_arrays_many, save_arrays

    os.makedirs(workdir, exist_ok=True)
    w = np.zeros(8, dtype=np.float64)
    traj = []
    for rnd in range(1, config.rounds + 1):
        paths = []
        for i in range(config.sites):
            p = os.path.join(workdir, f"r{rnd}_site_{i}.npy")
            save_arrays(p, [_site_grad(config.seed, i, rnd)])
            paths.append(p)
        arrays = load_arrays_many(sorted(paths), mmap=mmap)
        vals = [np.array(a[0], dtype=np.float64, copy=True) for a in arrays]
        if _BREAK_MMAP_TAINT and mmap:
            # broken semantics: the mapped view is no longer the bytes on
            # disk — the LARGEST operand drifts by an eps (a drift on the
            # smallest would drown below one ulp of the spread-magnitude
            # total, which is the point of the spread)
            vals[-1] = vals[-1] * (1.0 + 2.0 ** -48)
        total = np.zeros(8, dtype=np.float64)
        for v in vals:
            total = total + v
        avg = total / max(config.sites, 1)
        w = w - 0.1 * avg
        traj.append({"avg_grads": avg, "w": w.copy()})
    return traj


# ------------------------------------------------------ transport/codec arms
def _transport_arm(config, vectorized):
    """File transport (sequential per-site loop, ascending site order) vs
    the vectorized engine's stacked reduce of the SAME recurrence."""
    w = np.zeros(8, dtype=np.float64)
    traj = []
    for rnd in range(1, config.rounds + 1):
        grads = [0.5 * w + _site_grad(config.seed, i, rnd)
                 for i in range(config.sites)]
        if vectorized:
            if _BREAK_UNSORTED_FAN_IN:
                # broken semantics: the stacked fan-in loses its sorted
                # site order — fp addition does not commute bitwise
                grads = grads[::-1]
            total = np.add.reduce(np.stack(grads), axis=0)
        else:
            total = np.zeros(8, dtype=np.float64)
            for g in grads:
                total = total + g
        avg = total / max(config.sites, 1)
        w = w - 0.1 * avg
        traj.append({"avg_grads": avg, "w": w.copy()})
    return traj


def _codec_arm(config, codec):
    """Dense wire vs the PowerSGD-shaped P/Q factorization at FULL rank:
    ``P`` is a seeded permutation basis (orthonormal, spans everything),
    so ``P @ (P.T @ G)`` must reproduce ``G`` bit-for-bit."""
    p_dim, q_dim = 6, 3
    gen = np.random.Generator(np.random.PCG64(config.seed * 7919))
    P = np.eye(p_dim, dtype=np.float64)[:, gen.permutation(p_dim)]
    if _BREAK_RANK_DROP and codec:
        # broken semantics: the codec silently drops one rank — full rank
        # is no longer full, the reconstruction zeroes a row
        P = P[:, :-1]
    w = np.zeros((p_dim, q_dim), dtype=np.float64)
    traj = []
    for rnd in range(1, config.rounds + 1):
        G = np.stack([
            _site_grad(config.seed, i, rnd, dim=q_dim)[:q_dim]
            for i in range(p_dim)
        ]) * (1.0 + rnd / 7.0)
        if codec:
            Q = P.T @ G           # wire: the factored payload
            G_recv = P @ Q        # receiver: reconstruct
        else:
            G_recv = G            # dense wire
        w = w - 0.1 * G_recv
        traj.append({"recon_grads": G_recv, "w": w.copy()})
    return traj


# -------------------------------------------------------------- contracts
def _run_contract(name, config, workdir):
    """Both arms of one contract → (trajectory_a, trajectory_b), the
    reference arm first."""
    if name == "run-ahead-0-vs-serial":
        a = _engine_trajectory(os.path.join(workdir, "serial"), config,
                               "serial")
        b = _engine_trajectory(os.path.join(workdir, "run_ahead"), config,
                               "run_ahead", run_ahead=0)
        return a, b
    if name == "async-k0-pool1-vs-lockstep":
        a = _engine_trajectory(os.path.join(workdir, "lockstep"), config,
                               "lockstep")
        b = _engine_trajectory(os.path.join(workdir, "async"), config,
                               "async", async_staleness=0,
                               async_invoke_pool=1)
        return a, b
    if name == "mmap-vs-copy":
        a = _mmap_arm(os.path.join(workdir, "copy"), config, mmap=False)
        b = _mmap_arm(os.path.join(workdir, "mmap"), config, mmap=True)
        return a, b
    if name == "vectorized-vs-file-transport":
        return (_transport_arm(config, vectorized=False),
                _transport_arm(config, vectorized=True))
    if name == "codec-full-rank-vs-dense":
        return (_codec_arm(config, codec=False),
                _codec_arm(config, codec=True))
    raise ValueError(f"unknown parity contract: {name!r}")


def _round_divergence(ta, tb, r, max_ulp):
    """The first diverging tensor of round ``r`` as (tensor, ulp), or
    None when the round matches within ``max_ulp``."""
    per = tree_max_ulp(ta[r], tb[r])
    for tensor in sorted(per):
        if per[tensor] > max_ulp:
            return tensor, per[tensor]
    return None


def _first_divergence(ta, tb, max_ulp=0):
    """Bisect to the FIRST diverging round (the arms are deterministic
    recurrences through the carried weight state — once diverged, every
    later round stays diverged), then scan that round for its first
    diverging tensor.  Returns ``{"round", "tensor", "ulp"}`` (1-based
    round), or None when the trajectories agree."""
    n = min(len(ta), len(tb))
    if n and _round_divergence(ta, tb, n - 1, max_ulp) is None:
        if len(ta) != len(tb):
            return {"round": n + 1, "tensor": "<trajectory>",
                    "ulp": math.inf}
        return None
    if not n:
        return ({"round": 1, "tensor": "<trajectory>", "ulp": math.inf}
                if len(ta) != len(tb) else None)
    lo, hi = 0, n - 1  # invariant: round hi diverges
    while lo < hi:
        mid = (lo + hi) // 2
        if _round_divergence(ta, tb, mid, max_ulp) is not None:
            hi = mid
        else:
            lo = mid + 1
    tensor, ulp = _round_divergence(ta, tb, lo, max_ulp)
    return {"round": lo + 1, "tensor": tensor, "ulp": ulp}


# ---------------------------------------------------------------- anchors
#: contract -> (module kind, class-or-None, function) the finding anchors
#: to — the real seam whose claimed equivalence broke.
def _anchor_for(contract):
    cls = None
    if contract == "run-ahead-0-vs-serial":
        from .. import engine as mod

        cls, func = "InProcessEngine", "_step_round_async"
    elif contract == "async-k0-pool1-vs-lockstep":
        from .. import engine as mod

        cls, func = "InProcessEngine", "_async_config"
    elif contract == "mmap-vs-copy":
        from ..utils import tensorutils as mod

        func = "load_arrays"
    elif contract == "vectorized-vs-file-transport":
        from ..federation import vector as mod

        func = "_build_step"
    else:
        from ..parallel import powersgd as mod

        func = "reconstruct"
    path = os.path.relpath(mod.__file__).replace(os.sep, "/")
    line = 1
    try:
        with open(mod.__file__, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read())
        fallback = None
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef) and node.name == func:
                fallback = fallback or node.lineno
        for node in tree.body:
            if cls and isinstance(node, ast.ClassDef) and node.name == cls:
                for sub in node.body:
                    if isinstance(sub, ast.FunctionDef) and sub.name == func:
                        return path, sub.lineno
            elif cls is None and isinstance(node, ast.FunctionDef) \
                    and node.name == func:
                return path, node.lineno
        if fallback:
            line = fallback
    except (OSError, SyntaxError, ValueError):
        pass
    return path, line


def _switch_states():
    return {
        "_BREAK_RUN_AHEAD_EPS": _BREAK_RUN_AHEAD_EPS,
        "_BREAK_ASYNC_REUSED_KEY": _BREAK_ASYNC_REUSED_KEY,
        "_BREAK_MMAP_TAINT": _BREAK_MMAP_TAINT,
        "_BREAK_UNSORTED_FAN_IN": _BREAK_UNSORTED_FAN_IN,
        "_BREAK_RANK_DROP": _BREAK_RANK_DROP,
    }


# -------------------------------------------------------------- the prover
def prove_contract(name, config=None, workdir=None, max_ulp=0):
    """Run one contract's two arms and compare.  Returns the divergence
    dict (``{"contract", "round", "tensor", "ulp"}``) or None when the
    contract holds."""
    config = config or ParityConfig()
    if workdir is not None:
        ta, tb = _run_contract(name, config, workdir)
    else:
        with tempfile.TemporaryDirectory(prefix="tier7-parity-") as wd:
            ta, tb = _run_contract(name, config, wd)
    div = _first_divergence(ta, tb, max_ulp=max_ulp)
    if div is None:
        return None
    div = dict(div, contract=name)
    return div


def replay_parity(plan, workdir=None):
    """Re-execute a parity plan JSON under ITS recorded switch states;
    returns the divergence dicts the replay produced (the regression-
    test contract: the same round + tensor diverge again — and a plan
    replayed against the fixed tree, switches off, comes back clean)."""
    import sys

    mod = sys.modules[__name__]
    scenario = dict(plan.get("scenario") or {})
    config = ParityConfig(sites=scenario.get("sites"),
                          rounds=scenario.get("rounds"),
                          seed=scenario.get("seed", 7))
    saved = _switch_states()
    try:
        for switch, value in (plan.get("switches") or {}).items():
            if switch in saved:
                setattr(mod, switch, bool(value))
        div = prove_contract(plan["contract"], config, workdir=workdir)
    finally:
        for switch, value in saved.items():
            setattr(mod, switch, value)
    return [div] if div else []


def run_parity_prover(config=None, plans_dir=None, contracts=None):
    """Prove every claimed equivalence contract; returns a
    :class:`ParityResult` whose findings flow through the same baseline
    machinery as tiers 1–6."""
    config = config or ParityConfig()
    report = {"contracts_run": 0, "violations": 0, "proved": []}
    findings, plans = [], []
    from .core import Finding

    try:
        with tempfile.TemporaryDirectory(prefix="dinulint-tier7-") as root:
            for name in (contracts or CONTRACTS):
                div = prove_contract(
                    name, config,
                    workdir=os.path.join(root, name.replace("/", "_")),
                )
                report["contracts_run"] += 1
                if div is None:
                    report["proved"].append(name)
                    continue
                report["violations"] += 1
                path, line = _anchor_for(name)
                ulp = div["ulp"]
                plan = {
                    "comment": (
                        "dinulint tier-7 parity counterexample — replay "
                        "with analysis.parity.replay_parity(<this file>) "
                        "(docs/ANALYSIS.md 'Tier 7')"
                    ),
                    "rule": Numerics.PARITY,
                    "contract": name,
                    "invariant": _INVARIANTS[name],
                    "scenario": config.scenario(),
                    "switches": _switch_states(),
                    "violation": {
                        "round": int(div["round"]),
                        "tensor": div["tensor"],
                        "ulp": ("inf" if ulp == math.inf else int(ulp)),
                    },
                }
                findings.append(Finding(
                    rule=Numerics.PARITY, path=path, line=line, col=0,
                    message=(
                        f"parity contract '{name}' "
                        f"({_INVARIANTS[name]}) diverged at round "
                        f"{div['round']}, tensor '{div['tensor']}' "
                        f"(max {plan['violation']['ulp']} ulp) under "
                        f"{config.sites} sites x {config.rounds} rounds "
                        "— replayable parity plan JSON via --parity-plans"
                    ),
                ))
                plans.append(plan)
    except Exception as exc:  # noqa: BLE001 — typed error channel
        f = Finding(
            rule=Numerics.CONFIG, path="coinstac_dinunet_tpu", line=1,
            col=0,
            message=(
                "the tier-7 parity prover could not run: "
                f"{type(exc).__name__}: {exc}"
            ),
        )
        return ParityResult([f], [None], report)

    order = sorted(range(len(findings)), key=lambda i: findings[i].rule)
    findings = [findings[i] for i in order]
    plans = [plans[i] for i in order]
    if plans_dir:
        os.makedirs(plans_dir, exist_ok=True)
        for n, (f, plan) in enumerate(zip(findings, plans)):
            if not plan:
                continue
            name = f"{plan['contract']}-{n:02d}.json"
            with open(os.path.join(plans_dir, name), "w",
                      encoding="utf-8") as fh:
                json.dump(plan, fh, indent=2, sort_keys=True)
                fh.write("\n")
    return ParityResult(findings, plans, report)
