"""``python -m coinstac_dinunet_tpu.analysis`` — the dinulint CLI."""
import argparse
import json
import os
import sys

from .core import (
    default_rules,
    filter_baselined,
    load_baseline,
    run_lint,
    write_baseline,
)
from .jax_api import JaxApiDriftRule

DEFAULT_BASELINE = "dinulint_baseline.json"


def build_parser():
    p = argparse.ArgumentParser(
        prog="python -m coinstac_dinunet_tpu.analysis",
        description="dinulint: JAX-hazard + federated-protocol static analysis",
    )
    p.add_argument("paths", nargs="*", default=["coinstac_dinunet_tpu"],
                   help="files or directories to lint (default: the package)")
    p.add_argument("--format", choices=("text", "json", "github"),
                   default="text",
                   help="'github' renders new findings as ::error workflow "
                        "annotations (plus the text summary)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: ./{DEFAULT_BASELINE} if present)")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline with the current findings and exit 0")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--jax-version", default=None,
                   help="pin the jax version for jax-api-drift "
                        "(default: installed jax metadata)")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--show-baselined", action="store_true",
                   help="also print findings matched by the baseline")
    p.add_argument("--deep", action="store_true",
                   help="also run the eval_shape deep-check pass over the "
                        "registered entry points on an 8-device virtual CPU "
                        "platform (imports JAX; see docs/ANALYSIS.md)")
    p.add_argument("--deep-entries", default=None,
                   help="comma-separated entry-point names for --deep "
                        "(default: all registered)")
    p.add_argument("--list-deep", action="store_true",
                   help="list the registered deep-check entry points")
    return p


def _github_escape(text):
    """Escape a message for a GitHub workflow-command data section."""
    return (
        text.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")
    )


def main(argv=None):
    args = build_parser().parse_args(argv)

    rules = default_rules()
    if args.jax_version:
        rules = [
            JaxApiDriftRule(jax_version=args.jax_version)
            if isinstance(r, JaxApiDriftRule) else r
            for r in rules
        ]
    if args.list_rules:
        for r in sorted(rules, key=lambda r: r.id):
            print(f"{r.id}: {r.doc}")
        return 0
    if args.list_deep:
        from .deepcheck import list_entry_points

        for name, path in list_entry_points().items():
            print(f"{name}: {path}")
        return 0
    if args.deep_entries and not args.deep:
        print("--deep-entries requires --deep", file=sys.stderr)
        return 2

    deep_names = None
    if args.deep_entries:
        # validate BEFORE the static scan runs — a typo'd entry name is a
        # usage error and shouldn't cost a whole-package lint first.
        # (importing deepcheck only loads the registry; the builders defer
        # their JAX imports until run_deepcheck calls them)
        from .deepcheck import list_entry_points

        deep_names = [n.strip() for n in args.deep_entries.split(",") if n.strip()]
        if not deep_names:
            # an empty list would fall through as falsy and run the FULL
            # registry — the opposite of the narrowing the user asked for
            print("--deep-entries given but no entry names parsed",
                  file=sys.stderr)
            return 2
        known = set(list_entry_points())
        unknown = sorted(set(deep_names) - known)
        if unknown:
            print(f"unknown deep entry point(s): {', '.join(unknown)} "
                  f"(known: {', '.join(sorted(known))})", file=sys.stderr)
            return 2
    if args.write_baseline and deep_names is not None:
        print("--write-baseline with --deep-entries would drop the other "
              "entry points' baselined deep findings; refresh over the full "
              "registry (--deep without --deep-entries) instead",
              file=sys.stderr)
        return 2

    rule_ids = args.rules.split(",") if args.rules else None
    if rule_ids:
        known = {r.id for r in rules}
        unknown = sorted(set(rule_ids) - known)
        if unknown:
            print(f"unknown rule id(s): {', '.join(unknown)} "
                  f"(known: {', '.join(sorted(known))})", file=sys.stderr)
            return 2
    if args.write_baseline and rule_ids:
        print("--write-baseline with --rules would drop every other rule's "
              "baselined findings; refresh over the full rule set instead",
              file=sys.stderr)
        return 2
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"no such path(s): {', '.join(missing)}", file=sys.stderr)
        return 2

    findings, errors = run_lint(args.paths, rules=rules, rule_ids=rule_ids)

    if args.deep:
        # lazy import: only --deep pays the JAX import (and it sets up the
        # 8-device virtual CPU platform itself when the backend is fresh)
        from .deepcheck import run_deepcheck

        findings = findings + run_deepcheck(deep_names)
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    baseline_path = args.baseline
    if baseline_path is None and os.path.exists(DEFAULT_BASELINE):
        baseline_path = DEFAULT_BASELINE

    if args.write_baseline:
        out = baseline_path or DEFAULT_BASELINE
        if args.deep and any(f.rule == "deep-config" for f in findings):
            # the deep tier never actually ran — writing now would drop its
            # accepted entries AND baseline the platform misconfiguration
            print("--write-baseline refused: the deep tier could not run "
                  "(deep-config: virtual device platform unavailable) — fix "
                  "XLA_FLAGS or refresh without --deep", file=sys.stderr)
            return 2
        extra = ()
        if not args.deep and os.path.exists(out):
            # the deep tier didn't run, so this refresh knows nothing about
            # its findings — carry the accepted deep-* entries over instead
            # of silently dropping them from the rewritten file
            with open(out, "r", encoding="utf-8") as f:
                old = json.load(f)
            extra = [e for e in old.get("findings", [])
                     if e.get("rule", "").startswith("deep-")]
        write_baseline(out, findings, extra_entries=extra)
        kept = f" (+{len(extra)} deep-* entr{'y' if len(extra) == 1 else 'ies'} kept)" if extra else ""
        print(f"wrote {len(findings)} finding(s) to {out}{kept}")
        return 0

    baseline_counts = {}
    if baseline_path and os.path.exists(baseline_path):
        baseline_counts = load_baseline(baseline_path)
    new, baselined = filter_baselined(findings, baseline_counts)

    if args.format == "json":
        payload = {
            "new": [f.to_dict() for f in new],
            "baselined": [f.to_dict() for f in baselined],
            "errors": [{"path": p, "error": e} for p, e in errors],
        }
        print(json.dumps(payload, indent=2))
    elif args.format == "github":
        # workflow annotations: GitHub surfaces these inline on the PR diff
        for f in new:
            print(f"::error file={f.path},line={f.line},col={f.col + 1},"
                  f"title=dinulint {f.rule}::{_github_escape(f.message)}")
        for path, err in errors:
            print(f"::error file={path},line=1,title=dinulint parse error::"
                  f"{_github_escape(err)}")
        summary = f"{len(new)} new finding(s), {len(baselined)} baselined"
        if errors:
            summary += f", {len(errors)} parse error(s)"
        print(summary)
    else:
        for f in new:
            print(f.render())
        if args.show_baselined:
            for f in baselined:
                print(f"{f.render()} [baselined]")
        for path, err in errors:
            print(f"{path}: parse error: {err}", file=sys.stderr)
        summary = f"{len(new)} new finding(s), {len(baselined)} baselined"
        if errors:
            summary += f", {len(errors)} parse error(s)"
        print(summary)

    return 1 if new or errors else 0


if __name__ == "__main__":
    sys.exit(main())
