"""The dinulint CLI — installed as the ``dinulint`` console script
(pyproject ``[project.scripts]``); ``python -m
coinstac_dinunet_tpu.analysis`` is the equivalent fallback spelling."""
import argparse
import json
import os
import sys

from .core import (
    default_rules,
    filter_baselined,
    load_baseline,
    run_lint,
    write_baseline,
)
from .jax_api import JaxApiDriftRule

DEFAULT_BASELINE = "dinulint_baseline.json"


def build_parser():
    p = argparse.ArgumentParser(
        prog="dinulint",
        description="dinulint: JAX-hazard + federated-protocol static analysis",
    )
    p.add_argument("paths", nargs="*", default=["coinstac_dinunet_tpu"],
                   help="files or directories to lint (default: the package)")
    p.add_argument("--format", choices=("text", "json", "github"),
                   default="text",
                   help="'github' renders new findings as ::error workflow "
                        "annotations (plus the text summary)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: ./{DEFAULT_BASELINE} if present)")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline with the current findings and exit 0")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--jax-version", default=None,
                   help="pin the jax version for jax-api-drift "
                        "(default: installed jax metadata)")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--show-baselined", action="store_true",
                   help="also print findings matched by the baseline")
    p.add_argument("--deep", action="store_true",
                   help="also run the eval_shape deep-check pass over the "
                        "registered entry points on an 8-device virtual CPU "
                        "platform (imports JAX; see docs/ANALYSIS.md)")
    p.add_argument("--deep-entries", default=None,
                   help="comma-separated entry-point names for --deep "
                        "(default: all registered)")
    p.add_argument("--list-deep", action="store_true",
                   help="list the registered deep-check entry points")
    p.add_argument("--tier3", action="store_true",
                   help="also run the tier-3 jaxpr dataflow pass: lower "
                        "every deep-check entry point and run the perf-* "
                        "rules (donation, dtype promotion, host sync, "
                        "constant capture) plus the proto-flow-*/"
                        "proto-cache-* phase-machine model (imports JAX "
                        "for the perf rules; composes with --deep, sharing "
                        "entry builds; see docs/ANALYSIS.md)")
    p.add_argument("--model", action="store_true",
                   help="also run the tier-4 federation protocol model "
                        "checker: exhaustively explore N site machines + "
                        "the aggregator + the relay channel under the "
                        "chaos fault alphabet and check the ModelCheck "
                        "global invariants; every proto-model-* violation "
                        "ships a replayable chaos fault plan (pure "
                        "Python, no JAX; see docs/ANALYSIS.md 'Tier 4')")
    p.add_argument("--model-sites", type=int, default=None,
                   help="site count of the explored model (default: "
                        "ModelCheck.DEFAULT_SITES)")
    p.add_argument("--model-rounds", type=int, default=None,
                   help="federated rounds inside the exploration bound "
                        "(default: ModelCheck.DEFAULT_ROUNDS)")
    p.add_argument("--model-faults", type=int, default=None,
                   help="fault budget per explored run — the "
                        "simultaneous-fault tolerance level verified "
                        "(default: ModelCheck.DEFAULT_FAULT_BUDGET)")
    p.add_argument("--model-staleness", type=int, default=None,
                   help="async staleness window k explored ALONGSIDE "
                        "lockstep: every scenario runs at k=0 (exact "
                        "wire_round stamp) and at this k (window-relaxed "
                        "stamp + the staleness_k action; 0 disables the "
                        "async dimension entirely; default: "
                        "ModelCheck.DEFAULT_STALENESS_K)")
    p.add_argument("--model-elastic", type=int, default=None,
                   choices=(0, 1),
                   help="explore the elastic-membership dimension (one "
                        "spare non-member slot + the join/leave/rejoin "
                        "roster transitions) ALONGSIDE the fixed roster; "
                        "0 disables it (default: "
                        "ModelCheck.DEFAULT_ELASTIC)")
    p.add_argument("--model-plans", default=None, metavar="DIR",
                   help="write each proto-model-* counterexample as an "
                        "executable resilience/chaos.py fault plan JSON "
                        "into DIR (the CI model-check job uploads these)")
    p.add_argument("--tier5", action="store_true",
                   help="also run the tier-5 concurrency auditor: the "
                        "static conc-* lock-discipline rules (pure AST) "
                        "plus the proto-conc-* deterministic interleaving "
                        "explorer driving the real async round loop under "
                        "virtual time; every explorer violation ships a "
                        "replayable schedule JSON (numpy only, no JAX; "
                        "see docs/ANALYSIS.md 'Tier 5')")
    p.add_argument("--schedules", default=None, metavar="DIR",
                   help="write each proto-conc-* counterexample as a "
                        "replayable schedule JSON into DIR (the CI lint "
                        "job uploads these in the lint-findings artifact)")
    p.add_argument("--schedule-bound", type=int, default=None,
                   help="post-warmup rounds the interleaving explorer "
                        "enumerates completion schedules over (default: "
                        "Concurrency.DEFAULT_ROUNDS)")
    p.add_argument("--wire", action="store_true",
                   help="also run the tier-6 wire-contract auditor: lift "
                        "every boundary-crossing artifact (handshake keys, "
                        "tensor dumps, daemon frames, cache deltas) into a "
                        "typed wire-schema IR and check the wire-orphan/"
                        "wire-unversioned/wire-dense rules plus wire-lock "
                        "drift against wire_schema.lock.json (pure stdlib "
                        "ast, no JAX; see docs/ANALYSIS.md 'Tier 6')")
    p.add_argument("--write-lock", action="store_true",
                   help="rewrite the wire-schema lockfile from the current "
                        "extraction (and regenerate the docs/FEDERATION.md "
                        "wire-contract table between its markers) instead "
                        "of reporting wire-lock drift")
    p.add_argument("--wire-lock", default=None, metavar="FILE",
                   help="wire-schema lockfile path (default: "
                        "./wire_schema.lock.json)")
    p.add_argument("--wire-ledger", default=None, metavar="FILE",
                   help="also write the static byte-cost ledger JSON "
                        "(params x dtype x per-round multiplicity per "
                        "tensor path; the CI lint job uploads it)")
    p.add_argument("--tier7", action="store_true",
                   help="also run the tier-7 numerics & determinism "
                        "auditor: the static num-* rules (pure AST: PRNG "
                        "key discipline, unordered fan-in reduction, codec "
                        "error accounting), the num-accum-narrow jaxpr "
                        "pass over the tier-3 lowering cache, and the "
                        "proto-num-parity bit-parity prover executing "
                        "every claimed equivalence contract two-armed "
                        "under virtual time (see docs/ANALYSIS.md "
                        "'Tier 7')")
    p.add_argument("--parity", action="store_true",
                   help="run ONLY the tier-7 bit-parity prover (skip the "
                        "static num-* scan and the jaxpr pass; numpy only, "
                        "no JAX)")
    p.add_argument("--parity-plans", default=None, metavar="DIR",
                   help="write each proto-num-parity counterexample as a "
                        "replayable parity plan JSON into DIR (the CI lint "
                        "job uploads these in the lint-findings artifact)")
    p.add_argument("--reconcile", default=None, metavar="DIR",
                   help="compare the static byte ledger against the "
                        "telemetry wire records under DIR (recursive "
                        "telemetry.*.jsonl scan); unaccounted observed "
                        "bytes report as wire-unmodeled")
    return p


#: rule-id prefixes owned by each opt-in tier — a --write-baseline refresh
#: that did not run a tier carries its accepted entries over verbatim
TIER_PREFIXES = {
    "deep": ("deep-",),
    "tier3": ("tier3-", "perf-", "proto-flow-", "proto-cache-"),
    "model": ("proto-model-",),
    "tier5": ("conc-", "proto-conc-"),
    # tier-6 is tracked by its EXACT rule ids, never the bare "wire-"
    # prefix: the default-tier rule wire-atomic-commit shares the spelling
    # and its baselined entries must not ride a tier-6 carry-over
    "wire": ("wire-orphan", "wire-unversioned", "wire-dense", "wire-lock",
             "wire-unmodeled", "wire-config"),
    "tier7": ("num-", "proto-num-"),
}


def _github_escape(text):
    """Escape a message for a GitHub workflow-command data section."""
    return (
        text.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")
    )


def main(argv=None):
    args = build_parser().parse_args(argv)

    rules = default_rules()
    if args.jax_version:
        rules = [
            JaxApiDriftRule(jax_version=args.jax_version)
            if isinstance(r, JaxApiDriftRule) else r
            for r in rules
        ]
    if args.list_rules:
        # every opt-in tier's rule families are enumerated here from their
        # id lists (no tier flag, no JAX import needed), each annotated
        # with the owning tier — a rule must never be invisible just
        # because the flag that RUNS it wasn't passed
        for r in sorted(rules, key=lambda r: r.id):
            print(f"{r.id}: {r.doc}")
        from .concurrency import TIER5_STATIC_RULE_IDS
        from .dataflow import TIER3_RULE_IDS
        from .deepcheck import DEEP_RULE_IDS
        from .model_check import MODEL_RULE_IDS
        from .schedule_explorer import EXPLORER_RULE_IDS
        from .wire_schema import WIRE_RULE_IDS

        for rid in DEEP_RULE_IDS:
            print(f"{rid}: (tier-2 deep checker, --deep; "
                  "see docs/ANALYSIS.md)")
        for rid in TIER3_RULE_IDS:
            print(f"{rid}: (tier-3, --tier3; see docs/ANALYSIS.md)")
        for rid in MODEL_RULE_IDS:
            print(f"{rid}: (tier-4 model checker, --model; "
                  "see docs/ANALYSIS.md)")
        for rid in TIER5_STATIC_RULE_IDS:
            print(f"{rid}: (tier-5 concurrency auditor, --tier5; "
                  "see docs/ANALYSIS.md)")
        for rid in EXPLORER_RULE_IDS:
            print(f"{rid}: (tier-5 interleaving explorer, --tier5; "
                  "see docs/ANALYSIS.md)")
        for rid in WIRE_RULE_IDS:
            print(f"{rid}: (tier-6 wire auditor, --wire; "
                  "see docs/ANALYSIS.md)")
        from ..config.keys import Numerics
        from .numerics import NUMERICS_STATIC_RULE_IDS

        for rid in NUMERICS_STATIC_RULE_IDS:
            print(f"{rid}: (tier-7 numerics auditor, --tier7; "
                  "see docs/ANALYSIS.md)")
        print(f"{Numerics.ACCUM_NARROW}: (tier-7 jaxpr pass, --tier7; "
              "see docs/ANALYSIS.md)")
        print(f"{Numerics.PARITY}: (tier-7 parity prover, "
              "--tier7/--parity; see docs/ANALYSIS.md)")
        return 0
    if args.list_deep:
        from .deepcheck import list_entry_points

        for name, path in list_entry_points().items():
            print(f"{name}: {path}")
        return 0
    if args.deep_entries and not args.deep:
        print("--deep-entries requires --deep", file=sys.stderr)
        return 2

    deep_names = None
    if args.deep_entries:
        # validate BEFORE the static scan runs — a typo'd entry name is a
        # usage error and shouldn't cost a whole-package lint first.
        # (importing deepcheck only loads the registry; the builders defer
        # their JAX imports until run_deepcheck calls them)
        from .deepcheck import list_entry_points

        deep_names = [n.strip() for n in args.deep_entries.split(",") if n.strip()]
        if not deep_names:
            # an empty list would fall through as falsy and run the FULL
            # registry — the opposite of the narrowing the user asked for
            print("--deep-entries given but no entry names parsed",
                  file=sys.stderr)
            return 2
        known = set(list_entry_points())
        unknown = sorted(set(deep_names) - known)
        if unknown:
            print(f"unknown deep entry point(s): {', '.join(unknown)} "
                  f"(known: {', '.join(sorted(known))})", file=sys.stderr)
            return 2
    if args.write_baseline and deep_names is not None:
        print("--write-baseline with --deep-entries would drop the other "
              "entry points' baselined deep findings; refresh over the full "
              "registry (--deep without --deep-entries) instead",
              file=sys.stderr)
        return 2

    if not args.model and any(
        v is not None for v in (args.model_sites, args.model_rounds,
                                args.model_faults, args.model_plans,
                                args.model_staleness, args.model_elastic)
    ):
        print("--model-sites/--model-rounds/--model-faults/--model-plans/"
              "--model-staleness/--model-elastic require --model",
              file=sys.stderr)
        return 2
    if args.model_sites is not None and args.model_sites < 1:
        print(f"--model-sites {args.model_sites}: need at least 1 site",
              file=sys.stderr)
        return 2
    if args.model_rounds is not None and args.model_rounds < 1:
        print(f"--model-rounds {args.model_rounds}: need at least 1 "
              "federated round (0/negative bounds make every invariant "
              "vacuous or falsely violated)", file=sys.stderr)
        return 2
    if args.model_faults is not None and args.model_faults < 0:
        print(f"--model-faults {args.model_faults}: the fault budget "
              "cannot be negative (0 = fault-free runs only)",
              file=sys.stderr)
        return 2
    if args.model_staleness is not None and args.model_staleness < 0:
        print(f"--model-staleness {args.model_staleness}: the async "
              "window cannot be negative (0 = lockstep only)",
              file=sys.stderr)
        return 2
    if not args.tier5 and (args.schedules is not None
                           or args.schedule_bound is not None):
        print("--schedules/--schedule-bound require --tier5",
              file=sys.stderr)
        return 2
    if args.schedule_bound is not None and args.schedule_bound < 1:
        print(f"--schedule-bound {args.schedule_bound}: the explorer "
              "needs at least 1 post-warmup round (0/negative bounds "
              "make every round-loop invariant vacuous)", file=sys.stderr)
        return 2
    if not args.wire and (args.write_lock or args.wire_lock is not None
                          or args.wire_ledger is not None
                          or args.reconcile is not None):
        print("--write-lock/--wire-lock/--wire-ledger/--reconcile require "
              "--wire", file=sys.stderr)
        return 2
    if args.parity_plans is not None and not (args.tier7 or args.parity):
        print("--parity-plans requires --tier7 or --parity",
              file=sys.stderr)
        return 2
    if args.write_baseline and args.parity and not args.tier7:
        print("--write-baseline with --parity (prover only) would drop the "
              "static num-* baselined findings; refresh with --tier7 "
              "instead", file=sys.stderr)
        return 2
    rule_ids = args.rules.split(",") if args.rules else None
    if rule_ids:
        from .concurrency import TIER5_STATIC_RULE_IDS
        from .dataflow import TIER3_RULE_IDS
        from .model_check import MODEL_RULE_IDS
        from .schedule_explorer import EXPLORER_RULE_IDS
        from ..config.keys import Numerics
        from .numerics import NUMERICS_STATIC_RULE_IDS
        from .wire_schema import WIRE_RULE_IDS

        tier5_ids = set(TIER5_STATIC_RULE_IDS) | set(EXPLORER_RULE_IDS)
        tier7_static_ids = set(NUMERICS_STATIC_RULE_IDS) | {
            Numerics.ACCUM_NARROW
        }
        # tier-3/4/5/6/7 ids are selectable too (their findings are
        # filtered after the tier runs below)
        known = {r.id for r in rules} | set(TIER3_RULE_IDS) | set(
            MODEL_RULE_IDS
        ) | tier5_ids | set(WIRE_RULE_IDS) | tier7_static_ids | {
            Numerics.PARITY
        }
        unknown = sorted(set(rule_ids) - known)
        if unknown:
            print(f"unknown rule id(s): {', '.join(unknown)} "
                  f"(known: {', '.join(sorted(known))})", file=sys.stderr)
            return 2
        tier3_selected = sorted(set(rule_ids) & set(TIER3_RULE_IDS))
        if tier3_selected and not args.tier3:
            # without the tier the selected rule would silently report
            # nothing — a false clean for whoever is reproducing a finding
            print(f"--rules {','.join(tier3_selected)} requires --tier3 "
                  "(tier-3 rules only run under --tier3)", file=sys.stderr)
            return 2
        model_selected = sorted(set(rule_ids) & set(MODEL_RULE_IDS))
        if model_selected and not args.model:
            print(f"--rules {','.join(model_selected)} requires --model "
                  "(tier-4 rules only run under --model)", file=sys.stderr)
            return 2
        tier5_selected = sorted(set(rule_ids) & tier5_ids)
        if tier5_selected and not args.tier5:
            print(f"--rules {','.join(tier5_selected)} requires --tier5 "
                  "(tier-5 rules only run under --tier5)", file=sys.stderr)
            return 2
        wire_selected = sorted(set(rule_ids) & set(WIRE_RULE_IDS))
        if wire_selected and not args.wire:
            print(f"--rules {','.join(wire_selected)} requires --wire "
                  "(tier-6 rules only run under --wire)", file=sys.stderr)
            return 2
        tier7_selected = sorted(set(rule_ids) & tier7_static_ids)
        if tier7_selected and not args.tier7:
            print(f"--rules {','.join(tier7_selected)} requires --tier7 "
                  "(the static tier-7 rules only run under --tier7)",
                  file=sys.stderr)
            return 2
        if Numerics.PARITY in set(rule_ids) and not (args.tier7
                                                     or args.parity):
            print(f"--rules {Numerics.PARITY} requires --tier7 or "
                  "--parity (the parity prover only runs under them)",
                  file=sys.stderr)
            return 2
    if args.write_baseline and rule_ids:
        print("--write-baseline with --rules would drop every other rule's "
              "baselined findings; refresh over the full rule set instead",
              file=sys.stderr)
        return 2
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"no such path(s): {', '.join(missing)}", file=sys.stderr)
        return 2

    findings, errors = run_lint(args.paths, rules=rules, rule_ids=rule_ids)

    if args.tier3:
        from .dataflow import TIER3_RULE_IDS

        # tier-3 FIRST: its entry builds are cached and handed to --deep
        # below, so a combined run constructs each entry once
        wanted = set(rule_ids) if rule_ids else None
        if wanted is not None and wanted.isdisjoint(TIER3_RULE_IDS):
            # --rules selected no tier-3 rule at all: nothing this tier
            # could produce would survive the filter — skip it entirely
            tier3_findings = []
        elif wanted is not None and not any(
            r.startswith(("perf-", "tier3-")) for r in wanted
        ):
            # only the pure-AST proto-* family selected: skip the JAX
            # import and the per-entry lowering entirely
            from .protocol_flow import run_protocol_flow

            tier3_findings = list(run_protocol_flow(paths=args.paths))
        else:
            from .dataflow import run_tier3

            tier3_findings = run_tier3(paths=args.paths)
        if wanted is not None:
            # the tier's own error channel must survive any filter:
            # dropping tier3-config/tier3-lower would turn "the tier never
            # actually ran" into a false-clean exit 0
            keep = wanted | {"tier3-config", "tier3-lower"}
            tier3_findings = [f for f in tier3_findings if f.rule in keep]
        findings = findings + tier3_findings
    if args.deep:
        # lazy import: only --deep pays the JAX import (and it sets up the
        # 8-device virtual CPU platform itself when the backend is fresh)
        from .deepcheck import run_deepcheck

        builds = None
        if args.tier3:
            from .dataflow import tier3_builds

            builds = tier3_builds()
        findings = findings + run_deepcheck(deep_names, builds=builds)
    if args.model:
        # tier-4: pure-Python bounded exploration (no JAX import)
        from .model_check import MODEL_RULE_IDS, ModelConfig, run_model_check

        defaults = ModelConfig()
        staleness = defaults.staleness
        if args.model_staleness is not None:
            staleness = (
                (0, args.model_staleness) if args.model_staleness else (0,)
            )
        elastic = defaults.elastic
        if args.model_elastic is not None:
            elastic = (False, True) if args.model_elastic else (False,)
        cfg = ModelConfig(
            sites=(args.model_sites if args.model_sites is not None
                   else defaults.sites),
            rounds=(args.model_rounds if args.model_rounds is not None
                    else defaults.rounds),
            max_faults=(args.model_faults if args.model_faults is not None
                        else defaults.max_faults),
            staleness=staleness,
            elastic=elastic,
        )
        result = run_model_check(config=cfg, plans_dir=args.model_plans)
        model_findings = result.findings
        wanted_model = set(rule_ids) if rule_ids else None
        if wanted_model is not None:
            # the tier's own error channel must survive any filter
            keep = wanted_model | {"proto-model-config"}
            model_findings = [f for f in model_findings if f.rule in keep]
        findings = findings + model_findings
        if args.tier3:
            # path-sensitive promotion: a syntactic read-before-write whose
            # read site the exhaustive exploration EXERCISED without ever
            # realizing a violation is a reachability false positive —
            # retire it.  Reads the bound never exercised are left alone
            # (coverage-based retirement would be unsound for them).
            confirmed = set(
                tuple(c) for c in result.report.get("confirmed_cache", [])
            )
            exercised = set(
                tuple(c) for c in result.report.get("exercised_reads", [])
            )
            retired = [
                f for f in findings
                if f.rule == "proto-cache-read-before-write"
                and (f.path, f.line) in exercised
                and (f.path, f.line) not in confirmed
            ]
            if retired:
                findings = [f for f in findings if f not in retired]
                print(
                    f"dinulint --model: retired {len(retired)} "
                    "proto-cache-read-before-write finding(s) whose read "
                    "site the explored model exercised without ever "
                    "violating", file=sys.stderr,
                )
    if args.tier5:
        # tier-5: the static lock-discipline rules (pure AST) + the
        # deterministic interleaving explorer (numpy only, no JAX)
        from ..config.keys import Concurrency
        from .concurrency import run_tier5_static
        from .schedule_explorer import (
            EXPLORER_RULE_IDS,
            ScheduleConfig,
            run_schedule_explorer,
        )

        wanted5 = set(rule_ids) if rule_ids else None
        tier5_findings = list(run_tier5_static(paths=args.paths))
        if wanted5 is None or not wanted5.isdisjoint(EXPLORER_RULE_IDS):
            # skip the explorer entirely when --rules selected none of
            # its ids: nothing it could produce would survive the filter
            cfg = ScheduleConfig(rounds=args.schedule_bound)
            result5 = run_schedule_explorer(
                config=cfg, schedules_dir=args.schedules,
            )
            tier5_findings += result5.findings
        if wanted5 is not None:
            # the tier's own error channel must survive any filter
            keep = wanted5 | {Concurrency.CONFIG}
            tier5_findings = [f for f in tier5_findings if f.rule in keep]
        findings = findings + tier5_findings
    if args.wire:
        # tier-6: the wire-contract auditor (pure stdlib ast, no JAX)
        from ..config.keys import WireContract
        from .wire_schema import DEFAULT_LOCK, run_wire

        wire_findings, wire_schema = run_wire(
            paths=args.paths,
            lock_path=args.wire_lock or DEFAULT_LOCK,
            write_lock_file=args.write_lock,
            reconcile_dir=args.reconcile,
            ledger_path=args.wire_ledger,
        )
        if wire_schema is None and not wire_findings:
            # partial scan (single-file lint): a one-sided lift would
            # flood every key of the missing side as an orphan — skip,
            # exactly like the default tier's protocol-conformance rule
            print("dinulint --wire: skipped — the scanned paths do not "
                  "cover the full wire boundary file set",
                  file=sys.stderr)
        wanted_wire = set(rule_ids) if rule_ids else None
        if wanted_wire is not None:
            # the tier's own error channel must survive any filter
            keep = wanted_wire | {WireContract.CONFIG}
            wire_findings = [f for f in wire_findings if f.rule in keep]
        findings = findings + wire_findings
        if args.write_lock and wire_schema is not None:
            print(f"wrote wire-schema lockfile to "
                  f"{args.wire_lock or DEFAULT_LOCK} "
                  f"({len(wire_schema.entries)} entries)")
    if args.tier7 or args.parity:
        # tier-7: the static num-* numerics rules (pure AST), the
        # accum-narrow jaxpr pass over the tier-3 lowering cache (imports
        # JAX), and the bit-parity prover (numpy only)
        from ..config.keys import Numerics
        from .numerics import run_accum_narrow, run_tier7_static
        from .parity import run_parity_prover

        wanted7 = set(rule_ids) if rule_ids else None
        tier7_findings = []
        if args.tier7:
            tier7_findings += list(run_tier7_static(paths=args.paths))
            if wanted7 is None or Numerics.ACCUM_NARROW in wanted7:
                # skip the JAX import (and every entry lowering) when
                # --rules selected no jaxpr-pass rule at all
                tier7_findings += list(run_accum_narrow())
        if wanted7 is None or Numerics.PARITY in wanted7:
            result7 = run_parity_prover(plans_dir=args.parity_plans)
            tier7_findings += result7.findings
        if wanted7 is not None:
            # the tier's own error channel must survive any filter
            keep = wanted7 | {Numerics.CONFIG}
            tier7_findings = [f for f in tier7_findings if f.rule in keep]
        findings = findings + tier7_findings
    if (args.deep or args.tier3 or args.model or args.tier5 or args.wire
            or args.tier7 or args.parity):
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    baseline_path = args.baseline
    if baseline_path is None and os.path.exists(DEFAULT_BASELINE):
        baseline_path = DEFAULT_BASELINE

    if args.write_baseline:
        out = baseline_path or DEFAULT_BASELINE
        broken = [f.rule for f in findings
                  if f.rule in ("deep-config", "tier3-config",
                                "proto-model-config", "proto-conc-config",
                                "wire-config", "num-config")]
        if broken:
            # an opt-in tier never actually ran (platform misconfig,
            # explorer failure, or a truncated bound) — writing now would
            # drop its accepted entries AND permanently baseline the
            # tier's own error channel, so every later run would absorb
            # it and exit clean with the tier never running
            print(f"--write-baseline refused: {broken[0]}: the tier could "
                  "not run to completion — fix the configuration "
                  "(XLA_FLAGS / explorer bound) or refresh without that "
                  "tier", file=sys.stderr)
            return 2
        extra = []
        missing = [t for t, ran in (("deep", args.deep),
                                    ("tier3", args.tier3),
                                    ("model", args.model),
                                    ("tier5", args.tier5),
                                    ("wire", args.wire),
                                    ("tier7", args.tier7)) if not ran]
        if missing and os.path.exists(out):
            # a tier that didn't run contributes nothing to this refresh —
            # carry its accepted entries over instead of silently dropping
            # them from the rewritten file
            prefixes = tuple(p for t in missing for p in TIER_PREFIXES[t])
            with open(out, "r", encoding="utf-8") as f:
                old = json.load(f)
            extra = [e for e in old.get("findings", [])
                     if e.get("rule", "").startswith(prefixes)]
        write_baseline(out, findings, extra_entries=extra)
        kept = (f" (+{len(extra)} entr{'y' if len(extra) == 1 else 'ies'} "
                f"kept from tiers not run: {', '.join(missing)})"
                if extra else "")
        print(f"wrote {len(findings)} finding(s) to {out}{kept}")
        return 0

    baseline_counts = {}
    if baseline_path and os.path.exists(baseline_path):
        baseline_counts = load_baseline(baseline_path)
    new, baselined = filter_baselined(findings, baseline_counts)

    if args.format == "json":
        payload = {
            "new": [f.to_dict() for f in new],
            "baselined": [f.to_dict() for f in baselined],
            "errors": [{"path": p, "error": e} for p, e in errors],
        }
        print(json.dumps(payload, indent=2))
    elif args.format == "github":
        # workflow annotations: GitHub surfaces these inline on the PR diff
        for f in new:
            print(f"::error file={f.path},line={f.line},col={f.col + 1},"
                  f"title=dinulint {f.rule}::{_github_escape(f.message)}")
        for path, err in errors:
            print(f"::error file={path},line=1,title=dinulint parse error::"
                  f"{_github_escape(err)}")
        summary = f"{len(new)} new finding(s), {len(baselined)} baselined"
        if errors:
            summary += f", {len(errors)} parse error(s)"
        print(summary)
    else:
        for f in new:
            print(f.render())
        if args.show_baselined:
            for f in baselined:
                print(f"{f.render()} [baselined]")
        for path, err in errors:
            print(f"{path}: parse error: {err}", file=sys.stderr)
        summary = f"{len(new)} new finding(s), {len(baselined)} baselined"
        if errors:
            summary += f", {len(errors)} parse error(s)"
        print(summary)

    return 1 if new or errors else 0


if __name__ == "__main__":
    sys.exit(main())
