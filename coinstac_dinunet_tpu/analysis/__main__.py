"""``python -m coinstac_dinunet_tpu.analysis`` — the dinulint CLI."""
import argparse
import json
import os
import sys

from .core import (
    default_rules,
    filter_baselined,
    load_baseline,
    run_lint,
    write_baseline,
)
from .jax_api import JaxApiDriftRule

DEFAULT_BASELINE = "dinulint_baseline.json"


def build_parser():
    p = argparse.ArgumentParser(
        prog="python -m coinstac_dinunet_tpu.analysis",
        description="dinulint: JAX-hazard + federated-protocol static analysis",
    )
    p.add_argument("paths", nargs="*", default=["coinstac_dinunet_tpu"],
                   help="files or directories to lint (default: the package)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: ./{DEFAULT_BASELINE} if present)")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline with the current findings and exit 0")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--jax-version", default=None,
                   help="pin the jax version for jax-api-drift "
                        "(default: installed jax metadata)")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--show-baselined", action="store_true",
                   help="also print findings matched by the baseline")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)

    rules = default_rules()
    if args.jax_version:
        rules = [
            JaxApiDriftRule(jax_version=args.jax_version)
            if isinstance(r, JaxApiDriftRule) else r
            for r in rules
        ]
    if args.list_rules:
        for r in sorted(rules, key=lambda r: r.id):
            print(f"{r.id}: {r.doc}")
        return 0

    rule_ids = args.rules.split(",") if args.rules else None
    if rule_ids:
        known = {r.id for r in rules}
        unknown = sorted(set(rule_ids) - known)
        if unknown:
            print(f"unknown rule id(s): {', '.join(unknown)} "
                  f"(known: {', '.join(sorted(known))})", file=sys.stderr)
            return 2
    if args.write_baseline and rule_ids:
        print("--write-baseline with --rules would drop every other rule's "
              "baselined findings; refresh over the full rule set instead",
              file=sys.stderr)
        return 2
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"no such path(s): {', '.join(missing)}", file=sys.stderr)
        return 2

    findings, errors = run_lint(args.paths, rules=rules, rule_ids=rule_ids)

    baseline_path = args.baseline
    if baseline_path is None and os.path.exists(DEFAULT_BASELINE):
        baseline_path = DEFAULT_BASELINE

    if args.write_baseline:
        out = baseline_path or DEFAULT_BASELINE
        write_baseline(out, findings)
        print(f"wrote {len(findings)} finding(s) to {out}")
        return 0

    baseline_counts = {}
    if baseline_path and os.path.exists(baseline_path):
        baseline_counts = load_baseline(baseline_path)
    new, baselined = filter_baselined(findings, baseline_counts)

    if args.format == "json":
        payload = {
            "new": [f.to_dict() for f in new],
            "baselined": [f.to_dict() for f in baselined],
            "errors": [{"path": p, "error": e} for p, e in errors],
        }
        print(json.dumps(payload, indent=2))
    else:
        for f in new:
            print(f.render())
        if args.show_baselined:
            for f in baselined:
                print(f"{f.render()} [baselined]")
        for path, err in errors:
            print(f"{path}: parse error: {err}", file=sys.stderr)
        summary = f"{len(new)} new finding(s), {len(baselined)} baselined"
        if errors:
            summary += f", {len(errors)} parse error(s)"
        print(summary)

    return 1 if new or errors else 0


if __name__ == "__main__":
    sys.exit(main())
