"""conc-*: the tier-5 static concurrency auditor (lock-discipline analysis).

PRs 11–12 made the engine genuinely concurrent — a bounded async
invocation pool (``engine.py::_step_round_async``), lock-supervised
daemon workers (``federation/daemon.py``), the background wire committer
(``resilience/transport.py``), and shared recorder/live-state locks — and
none of tiers 1–4 can see a data race.  This pass infers each module's
**lock discipline** from the AST and flags the shapes that break it:

- **Guard inference** — a lock is any ``self._x = threading.Lock()`` /
  ``RLock()`` (or module-global equivalent); a *write* is any assignment,
  augmented assignment, subscript store or mutating method call
  (``append``/``update``/``add``/…) on a ``self._*`` attribute or
  module-global; the *held set* at a write is the stack of enclosing
  ``with <lock>:`` blocks, propagated through same-module calls.  An
  attribute's **inferred guard** is the lock set common to every write
  outside constructors; threaded contexts are the transitive closure of
  ``threading.Thread(target=...)`` targets and ``.submit(...)`` callables
  over the module's call graph.
- ``conc-unguarded-shared-write`` — a threaded-context write with an
  empty held set while every other write site holds the inferred guard.
- ``conc-lock-order`` — lock A is acquired while B is held on one path
  and B while A is held on another (the ABBA inversion), including
  acquisitions reached through same-module calls.
- ``conc-escape`` — a name handed into ``pool.submit(fn, name, ...)`` is
  container-mutated by the submitting function before the returned
  future's ``.result()`` — the closure and the parent race on it.
- ``conc-fs-race`` — a transfer-directory payload written outside the
  ``resilience/transport.py`` atomic-commit helpers *from a threaded
  context*: ``wire-atomic-commit``'s one-hop taint extended across the
  thread boundary (the submitted closure captures the tainted path).

Pure stdlib ``ast`` — no JAX, no engine import; a whole-package run stays
in the tens of milliseconds.  Constructors (``__init__``/module level)
define attributes but are excluded from discipline judgement: they run
before any thread exists.  The dynamic half of tier 5 lives in
:mod:`.schedule_explorer`.
"""
import ast

from ..config.keys import Concurrency
from .core import Finding, Module, dotted_name, iter_python_files
from .wire_atomic import (
    _EXEMPT_SUFFIX,
    _NP_ROOTS,
    _mentions_transfer,
    _open_write_mode,
    _tainted_names,
)

TIER5_STATIC_RULE_IDS = (
    Concurrency.ESCAPE,
    Concurrency.FS_RACE,
    Concurrency.LOCK_ORDER,
    Concurrency.UNGUARDED,
)

#: method calls that mutate their receiver in place (dict/list/set/deque
#: vocabulary).  Deliberately excludes the thread-safe ``queue.Queue``
#: verbs (``put``/``get``/``task_done``) — a queue IS the sanctioned
#: cross-thread channel.
_MUTATORS = frozenset({
    "append", "appendleft", "add", "clear", "discard", "extend", "insert",
    "pop", "popitem", "popleft", "remove", "setdefault", "update",
})

#: lock constructors recognised for guard inference
_LOCK_CTORS = ("Lock", "RLock")

#: constructor-like functions whose writes define attributes but never
#: race (they run before any thread exists)
_CTOR_NAMES = ("__init__", "__new__", "__post_init__")


def _is_lock_ctor(value):
    if not isinstance(value, ast.Call):
        return False
    name = dotted_name(value.func, require_name_root=False) or ""
    leaf = name.rsplit(".", 1)[-1]
    return leaf in _LOCK_CTORS


def _attr_token(node):
    """``self.<attr>`` → ``"self.<attr>"``; bare module-global Name →
    its id; anything deeper (``self.a.b``) is out of scope (None)."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return f"self.{node.attr}"
    if isinstance(node, ast.Name):
        return node.id
    return None


def _callable_ref(node):
    """Same-module name of a callable reference (``self.m`` → ``m``,
    bare ``f`` → ``f``; anything else → None)."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _callee_token(call):
    """Same-module callee name of a call: ``self.m(...)`` → ``m``,
    ``f(...)`` → ``f``; anything else → None."""
    return _callable_ref(call.func)


class _Write:
    __slots__ = ("attr", "line", "col", "held", "func", "kind")

    def __init__(self, attr, line, col, held, func, kind="rebind"):
        self.attr, self.line, self.col = attr, line, col
        self.held, self.func = frozenset(held), func
        # "mutate" = in-place container mutation (subscript store,
        # augmented assign, mutator method call); "rebind" = a plain name
        # rebinding, which never touches the object a closure captured
        self.kind = kind


class _FuncFacts:
    """Per-function facts gathered in one AST walk."""

    def __init__(self, name):
        self.name = name
        self.writes = []          # [_Write]
        self.acquires = []        # [(lock, held-before, line)]
        self.calls = []           # [(callee, held, line)]
        self.submits = []         # [(futures-var|None, [arg names], line)]
        self.submit_targets = []  # callables handed to submit/Thread
        self.result_lines = {}    # futures-var -> first .result() line
        self.fs_writes = []       # [(how, target-node, line, col)]
        # Python scoping facts: a bare name plain-assigned anywhere in a
        # function is LOCAL there (unless declared global) — its writes
        # are not shared state no matter what module global it shadows
        self.local_names = set()
        self.global_decls = set()


class _ModuleAudit:
    """One module's lock-discipline analysis."""

    def __init__(self, module):
        self.module = module
        self.locks = set()
        self.funcs = {}           # name -> [_FuncFacts] (overloads share)
        self.nested_parent = {}   # nested def name -> enclosing facts
        # bare-name tokens that really ARE module globals (module-level
        # assignment targets + `global` declarations).  Function-local
        # names that merely shadow a guarded global must never feed the
        # shared-write judgement — no scope tracking means false
        # positives, and this rule's contract is precision over recall.
        self.module_globals = self._find_module_globals(module.tree)

    @staticmethod
    def _find_module_globals(tree):
        names = set()
        for node in tree.body:
            targets = ()
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets = (node.target,)
            for t in targets:
                for elt in (t.elts if isinstance(t, (ast.Tuple, ast.List))
                            else (t,)):
                    if isinstance(elt, ast.Name):
                        names.add(elt.id)
        for node in ast.walk(tree):
            if isinstance(node, ast.Global):
                names.update(node.names)
        return names

    # ------------------------------------------------------------- gathering
    def run(self):
        self._find_locks(self.module.tree)
        for node in self.module.tree.body:
            self._gather_scope(node)
        findings = []
        findings += self._unguarded_findings()
        findings += self._lock_order_findings()
        findings += self._escape_findings()
        findings += self._fs_race_findings()
        return findings

    def _find_locks(self, tree):
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for t in node.targets:
                    tok = _attr_token(t)
                    if tok:
                        self.locks.add(tok)

    def _gather_scope(self, node, class_name=None):
        if isinstance(node, ast.ClassDef):
            for sub in node.body:
                self._gather_scope(sub, class_name=node.name)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            facts = _FuncFacts(node.name)
            self.funcs.setdefault(node.name, []).append(facts)
            self._walk_body(node.body, facts, held=())

    def _lock_of(self, expr):
        """The lock token a with-item acquires, or None.  ``with a, b:``
        items are handled by the caller; ``lock.acquire()`` calls are out
        of scope (the codebase idiom is the with-block)."""
        tok = _attr_token(expr)
        return tok if tok in self.locks else None

    def _walk_body(self, body, facts, held):
        for stmt in body:
            self._walk_stmt(stmt, facts, held)

    def _walk_stmt(self, stmt, facts, held):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: a fresh scope whose facts ride with the module
            # (submit targets resolve to it by name); the parent's held
            # set does NOT transfer — the closure runs later
            nested = _FuncFacts(stmt.name)
            self.funcs.setdefault(stmt.name, []).append(nested)
            self.nested_parent[stmt.name] = facts
            self._walk_body(stmt.body, nested, held=())
            return
        if isinstance(stmt, ast.With):
            acquired = list(held)
            for item in stmt.items:
                lock = self._lock_of(item.context_expr)
                if lock is not None:
                    facts.acquires.append((lock, tuple(acquired),
                                           item.context_expr.lineno))
                    acquired.append(lock)
                self._scan_expr(item.context_expr, facts, tuple(acquired))
            self._walk_body(stmt.body, facts, tuple(acquired))
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._scan_expr(stmt.test, facts, held)
            self._walk_body(stmt.body, facts, held)
            self._walk_body(stmt.orelse, facts, held)
            return
        if isinstance(stmt, ast.For):
            self._scan_expr(stmt.iter, facts, held)
            self._record_store(stmt.target, facts, held)
            self._walk_body(stmt.body, facts, held)
            self._walk_body(stmt.orelse, facts, held)
            return
        if isinstance(stmt, ast.Try):
            self._walk_body(stmt.body, facts, held)
            for h in stmt.handlers:
                self._walk_body(h.body, facts, held)
            self._walk_body(stmt.orelse, facts, held)
            self._walk_body(stmt.finalbody, facts, held)
            return
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                self._record_store(t, facts, held)
            self._scan_expr(stmt.value, facts, held,
                            assign_targets=stmt.targets)
            return
        if isinstance(stmt, ast.AugAssign):
            self._record_store(stmt.target, facts, held, kind="mutate")
            self._scan_expr(stmt.value, facts, held)
            return
        if isinstance(stmt, ast.Expr):
            self._scan_expr(stmt.value, facts, held)
            return
        if isinstance(stmt, ast.Global):
            facts.global_decls.update(stmt.names)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._scan_expr(child, facts, held)
            elif isinstance(child, ast.stmt):
                self._walk_stmt(child, facts, held)

    def _record_store(self, target, facts, held, kind="rebind"):
        """An assignment target: plain attr/global rebinding and subscript
        stores both count as writes of the base token."""
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._record_store(elt, facts, held, kind)
            return
        base = target
        if isinstance(target, ast.Subscript):
            base = target.value
            kind = "mutate"
        elif isinstance(target, ast.Name):
            # a plain bare-name assignment binds the name LOCALLY in this
            # function (Python scoping) — recorded so the shared-write
            # judgement can exclude shadowing locals
            facts.local_names.add(target.id)
        tok = _attr_token(base)
        if tok and tok not in self.locks:
            facts.writes.append(_Write(tok, target.lineno, target.col_offset,
                                       held, facts, kind))

    def _scan_expr(self, expr, facts, held, assign_targets=()):
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            # mutating method call on a tracked token (one chained hop:
            # ``self.d.setdefault(k, deque()).append(v)`` mutates self.d)
            if isinstance(func, ast.Attribute) and func.attr in _MUTATORS:
                base = func.value
                if isinstance(base, ast.Call) and isinstance(
                        base.func, ast.Attribute):
                    base = base.func.value
                tok = _attr_token(base)
                if tok and tok not in self.locks:
                    facts.writes.append(_Write(
                        tok, node.lineno, node.col_offset, held, facts,
                        kind="mutate"))
            # pool.submit(fn, args...) / Thread(target=fn)
            if isinstance(func, ast.Attribute) and func.attr == "submit" \
                    and node.args:
                callee = _callable_ref(node.args[0])
                if callee:
                    facts.submit_targets.append(callee)
                fut = None
                for t in assign_targets:
                    if isinstance(t, ast.Name):
                        fut = t.id
                arg_names = [a.id for a in node.args[1:]
                             if isinstance(a, ast.Name) and a.id != "self"]
                facts.submits.append((fut, arg_names, node.lineno))
            name = dotted_name(func, require_name_root=False) or ""
            if name.rsplit(".", 1)[-1] == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        callee = _callable_ref(kw.value)
                        if callee:
                            facts.submit_targets.append(callee)
            # .result() bookkeeping for the escape window
            if isinstance(func, ast.Attribute) and func.attr == "result" \
                    and isinstance(func.value, ast.Name):
                facts.result_lines.setdefault(func.value.id, node.lineno)
            # same-module call for held-lock propagation / threaded closure
            callee = _callee_token(node)
            if callee and callee != facts.name:
                facts.calls.append((callee, frozenset(held), node.lineno))
            # direct payload writes, judged later against threaded contexts
            how = None
            target = None
            if isinstance(func, ast.Name) and func.id == "open":
                mode = _open_write_mode(node)
                if mode and node.args:
                    target = node.args[0]
                    how = f"open(..., {mode!r})"
            else:
                fn = dotted_name(func, require_name_root=False) or ""
                if fn.rsplit(".", 1)[-1] == "save" and \
                        fn.split(".")[0] in _NP_ROOTS and node.args:
                    target = node.args[0]
                    how = f"{fn}(...)"
            if how is not None:
                facts.fs_writes.append((how, target, node.lineno,
                                        node.col_offset))

    # ----------------------------------------------------- derived relations
    def _threaded_funcs(self):
        """Transitive closure of thread entry points over same-module
        calls (and nested defs declared inside threaded functions)."""
        threaded = set()
        frontier = []
        for flist in self.funcs.values():
            for f in flist:
                frontier.extend(f.submit_targets)
        while frontier:
            name = frontier.pop()
            if name in threaded or name not in self.funcs:
                continue
            threaded.add(name)
            for f in self.funcs[name]:
                for callee, _held, _line in f.calls:
                    if callee not in threaded:
                        frontier.append(callee)
            for nested, parent in self.nested_parent.items():
                if parent.name == name and nested not in threaded:
                    frontier.append(nested)
        return threaded

    def _all_writes(self):
        for flist in self.funcs.values():
            for f in flist:
                yield from f.writes

    # ----------------------------------------------------------------- rules
    def _unguarded_findings(self):
        threaded = self._threaded_funcs()
        by_attr = {}
        for w in self._all_writes():
            if w.func.name in _CTOR_NAMES:
                continue  # constructors run before any thread exists
            if not w.attr.startswith("self."):
                if w.attr not in self.module_globals:
                    continue  # never a module global anywhere
                if w.attr in w.func.local_names and (
                    w.attr not in w.func.global_decls
                ):
                    continue  # a local shadowing the global in this scope
            by_attr.setdefault(w.attr, []).append(w)
        findings = []
        for attr, writes in sorted(by_attr.items()):
            unguarded = [w for w in writes
                         if w.func.name in threaded and not w.held]
            others = [w for w in writes if w not in unguarded]
            if not unguarded or not others:
                continue
            common = None
            for w in others:
                common = w.held if common is None else (common & w.held)
            if not common:
                continue  # no consistent discipline inferred: out of scope
            guard = sorted(common)[0]
            for w in unguarded:
                findings.append(Finding(
                    rule=Concurrency.UNGUARDED, path=self.module.path,
                    line=w.line, col=w.col,
                    message=(
                        f"'{attr}' is written from the threaded context "
                        f"'{w.func.name}' (a Thread target / pool-submitted "
                        f"callable) without '{guard}', the lock every other "
                        "write site of it holds — an unsynchronized shared "
                        "write the inferred lock discipline forbids"
                    ),
                ))
        return findings

    def _lock_order_findings(self):
        # locks transitively acquired inside each function (fixed point)
        trans = {name: set() for name in self.funcs}
        for name, flist in self.funcs.items():
            for f in flist:
                trans[name].update(lock for lock, _h, _l in f.acquires)
        changed = True
        while changed:
            changed = False
            for name, flist in self.funcs.items():
                for f in flist:
                    for callee, _held, _line in f.calls:
                        if callee in trans and not (
                                trans[callee] <= trans[name]):
                            trans[name] |= trans[callee]
                            changed = True
        edges = {}  # (outer, inner) -> (line)
        for flist in self.funcs.values():
            for f in flist:
                for lock, held, line in f.acquires:
                    for outer in held:
                        if outer != lock:
                            edges.setdefault((outer, lock), line)
                for callee, held, line in f.calls:
                    if callee not in trans:
                        continue
                    for inner in trans[callee]:
                        for outer in held:
                            if outer != inner:
                                edges.setdefault((outer, inner), line)
        findings = []
        seen = set()
        for (a, b), line in sorted(edges.items(), key=lambda kv: kv[1]):
            if (b, a) in edges and frozenset((a, b)) not in seen:
                seen.add(frozenset((a, b)))
                other = edges[(b, a)]
                first, second = sorted([(line, a, b), (other, b, a)])
                findings.append(Finding(
                    rule=Concurrency.LOCK_ORDER, path=self.module.path,
                    line=second[0], col=0,
                    message=(
                        f"inconsistent lock order: '{second[1]}' is acquired "
                        f"while '{second[2]}' is held here, but line "
                        f"{first[0]} acquires '{first[1]}' while "
                        f"'{first[2]}' is held — two threads taking the "
                        "opposite orders deadlock (ABBA)"
                    ),
                ))
        return findings

    def _escape_findings(self):
        findings = []
        for flist in self.funcs.values():
            for f in flist:
                for fut, arg_names, submit_line in f.submits:
                    if not arg_names:
                        continue
                    window_end = f.result_lines.get(fut, float("inf"))
                    for w in f.writes:
                        if w.kind == "mutate" and w.attr in arg_names and \
                                submit_line < w.line < window_end:
                            findings.append(Finding(
                                rule=Concurrency.ESCAPE,
                                path=self.module.path,
                                line=w.line, col=w.col,
                                message=(
                                    f"'{w.attr}' was handed into a "
                                    f"pool.submit closure on line "
                                    f"{submit_line} and is mutated here "
                                    "before the future's .result() — the "
                                    "submitted callable and this thread "
                                    "race on the shared object; snapshot "
                                    "it before submitting"
                                ),
                            ))
        return findings

    def _fs_race_findings(self):
        if self.module.path.replace("\\", "/").endswith(_EXEMPT_SUFFIX):
            return []
        threaded = self._threaded_funcs()
        if not threaded:
            return []
        tainted = _tainted_names(self.module.tree)
        findings = []
        for name in sorted(threaded):
            for f in self.funcs.get(name, ()):
                for how, target, line, col in f.fs_writes:
                    if not _mentions_transfer(target, tainted):
                        continue
                    findings.append(Finding(
                        rule=Concurrency.FS_RACE, path=self.module.path,
                        line=line, col=col,
                        message=(
                            f"{how} writes a transfer-directory payload "
                            f"from the threaded context '{name}' outside "
                            "the resilience/transport.py atomic-commit "
                            "helpers — a concurrent reader (or the relay) "
                            "can observe a partial payload; this extends "
                            "wire-atomic-commit's taint across the thread "
                            "boundary"
                        ),
                    ))
        return findings


def analyze_module(module):
    """All tier-5 static findings for one parsed :class:`~.core.Module`."""
    return _ModuleAudit(module).run()


def run_tier5_static(paths=None):
    """The tier-5 static half over ``paths`` (files or directories).
    Parse failures are skipped silently — the base static scan already
    reports them through its own error channel."""
    import os

    paths = list(paths) if paths else ["coinstac_dinunet_tpu"]
    findings = []
    for path in iter_python_files(paths):
        display = os.path.relpath(path).replace(os.sep, "/")
        try:
            mod = Module.parse(path, display)
        except (SyntaxError, UnicodeDecodeError, OSError, ValueError):
            continue
        findings.extend(analyze_module(mod))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
