"""proto-model-*: the tier-4 federation protocol model checker.

Tier-3 audits each node's phase machine in isolation and syntactically.
This pass composes the whole federation — N site machines + the aggregator
+ the engine's relay channel, all lifted from the AST by
:mod:`~.proto_ir` — and **exhaustively explores** the bounded execution
space under the :mod:`~..resilience.chaos` fault vocabulary:

- per-site invoke faults: ``crash``, ``hang`` (retry exhaustion),
  ``stale`` (a delayed duplicate of the previous site message delivered in
  place of the fresh one), ``reappear`` (death now, stale redelivery one
  round later), ``truncate_payload`` / ``corrupt_payload`` (detectable
  payload damage on the site→aggregator leg);
- per-site relay faults on the broadcast leg: ``drop_relay`` /
  ``duplicate_delivery``, each targeting the payload file or its
  ``.wire_manifest.json`` sidecar (the only witness that an intact,
  self-validating payload is STALE).

Exploration is BFS over hashed global states with a configurable bound
(default: :class:`~..config.keys.ModelCheck` — 2 sites × 3 federated
rounds × the full alphabet at fault budget 1), across both quorum
configurations (all-site lockstep and ``site_quorum=1``) and both
NEXT_RUN dispatch branches (pretrain on/off).  The checked invariants are
the :class:`~..config.keys.ModelCheck` vocabulary: deadlock-freedom,
no lifecycle reset, quorum soundness, exactly-once gradient contributions
and broadcast updates, single-transient-fault recoverability, and the
path-sensitive promotions of the tier-3 cache rules.

Every violation is emitted as a ``proto-model-*`` finding through the
same baseline/ratchet machinery as tiers 1–3 AND as an executable chaos
fault plan (``--model-plans``) whose replay through a real
:class:`~..engine.InProcessEngine` reproduces the counterexample
(``tests/test_model_check.py``).

Deterministic and pure-Python: no JAX, no clocks, no randomness — the
same tree, bound and findings on every run.
"""
import collections
import dataclasses
import itertools
import json
import os

from ..config.keys import ModelCheck
from .core import Finding
from .proto_ir import build_protocol_ir

#: the fault alphabet the explorer schedules (ISSUE 9 bound; ``slow`` is
#: protocol-invisible in a lockstep engine and is deliberately absent).
#: ``worker_crash``/``worker_restart`` (ISSUE 11) are the DAEMON
#: supervision actions: the site's long-lived worker process is killed
#: mid-invocation (crash) or between rounds (restart), the supervisor
#: restarts it and the invocation re-runs against the engine's
#: round-tripped cache — so unlike ``crash``/``hang`` the SITE stays
#: alive, and the invariants verify the supervision contract: a restarted
#: worker contributes to the round's reduce exactly once (the crashed
#: attempt's output is discarded by the engine and its payload files are
#: atomically overwritten by the re-invocation), and a restart during the
#: relay never wedges the next round.  Counterexamples replay as
#: ``worker_kill`` chaos plans through a real ``DaemonEngine``
#: (``tests/test_daemon.py``).
FAULT_ALPHABET = (
    "crash", "hang", "stale", "reappear",
    "truncate_payload", "corrupt_payload",
    "drop_relay", "duplicate_delivery",
    "worker_crash", "worker_restart",
    # ISSUE 12: the async round engine's stand-in — the site is not
    # invoked this round and its LAST output (with its lagging wire_round
    # echo) is delivered instead.  Mechanically a delayed duplicate like
    # ``stale``, but scheduled only in scenarios whose staleness window k
    # is positive: the aggregator must ACCEPT it while the echo lags by at
    # most k (the reducer down-weights it — not modeled) and must still
    # refuse anything older.  Repeated firings age the stand-in past the
    # window, which is how the seeded k-violation reaches the boundary.
    "staleness_k",
    # ISSUE 14: run-ahead pipelining — the site delivers a FRESH
    # contribution computed against the broadcast its PREVIOUS output
    # consumed (the engine re-submitted it while the reduce tail was
    # still in flight on the reducer worker).  The wire_round echo lags
    # by the pipeline depth and ages one more round per consecutive
    # firing; the aggregator must ACCEPT it while the lag is at most
    # k + d (the reducer's gamma**lag discount covers it — not modeled)
    # and refuse anything deeper.  Scheduled only in scenarios whose
    # run-ahead depth d is positive.
    "run_ahead",
)

#: model action -> replayable chaos fault-plan kind (worker actions map to
#: the daemon engine's worker_kill fault with the matching kill point;
#: staleness_k replays as the engines' ``stale`` replay fault)
_WORKER_ACTIONS = {"worker_crash": "invoke", "worker_restart": "idle"}
_STALE_ACTIONS = ("stale", "staleness_k")

#: broken-supervisor semantics switch (tests only): a mis-implemented
#: daemon supervisor might REDELIVER the crashed worker's previous output
#: instead of re-invoking the node — exactly the stale-delivery class the
#: round-stamp invariant exists to catch.  ``tests/test_model_check.py``
#: flips this to prove the worker_crash action is checkable, not vacuous:
#: with the ``wire_round`` stamp intact the protocol refuses the
#: redelivery loudly; with the stamp fact flipped, STALE_CONTRIBUTION
#: fires with a worker_kill counterexample plan.
_RESTART_REDELIVERS_LAST_OUTPUT = False

#: broken-window semantics switch (tests only): a mis-implemented async
#: window check might accept a contribution OLDER than the staleness bound
#: k into the reduce — the exact boundary the window-relaxed
#: STALE_CONTRIBUTION invariant patrols.  ``tests/test_async.py`` flips
#: this to prove the staleness_k action is checkable, not vacuous: with
#: the real window semantics a beyond-k echo is refused loudly (clean);
#: with the flip, STALE_CONTRIBUTION fires with a replayable ``stale``
#: chaos plan.
_WINDOW_ACCEPTS_BEYOND_K = False

#: broken-horizon semantics switch (tests only): a mis-implemented
#: run-ahead window might accept a FRESH contribution whose broadcast lag
#: exceeds ``k + d`` into the reduce — the broadcast-lag boundary the
#: widened window patrols (``nodes/remote.py`` reads
#: ``Federation.RUN_AHEAD`` into the window).  ``tests/test_async.py``
#: flips this to prove the ``run_ahead`` action is checkable, not
#: vacuous: with the real window a beyond-``k+d`` echo is refused loudly
#: (clean); with the flip, STALE_CONTRIBUTION fires with a replayable
#: ``stale`` chaos plan.
_WINDOW_ACCEPTS_BEYOND_RUN_AHEAD = False

#: broadcast-channel components a relay fault can target
_COMPONENTS = ("payload", "manifest")

MODEL_RULE_IDS = (
    ModelCheck.CACHE, ModelCheck.CONFIG, ModelCheck.DEADLOCK,
    ModelCheck.LOST_CONTRIBUTION, ModelCheck.LOST_UPDATE,
    ModelCheck.PHASE_RESET, ModelCheck.QUORUM,
    ModelCheck.STALE_CONTRIBUTION, ModelCheck.UNRECOVERABLE,
    ModelCheck.VOLATILE, ModelCheck.WIRE,
)

#: hard ceiling on explored states per scenario — a runaway bound must
#: degrade to a typed finding, never a hung CI job
MAX_STATES = 250_000


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Exploration bound (defaults = the CI gate's contract).

    ``staleness`` is a scenario dimension like ``quorums``: every bound is
    explored at each listed async window k.  k=0 is today's exact-stamp
    lockstep (every stale delivery refused); k>0 relaxes the stamp to the
    window and schedules the ``staleness_k`` action."""

    sites: int = ModelCheck.DEFAULT_SITES
    rounds: int = ModelCheck.DEFAULT_ROUNDS
    max_faults: int = ModelCheck.DEFAULT_FAULT_BUDGET
    kinds: tuple = FAULT_ALPHABET
    quorums: tuple = (None, 1)
    pretrain: tuple = (False, True)
    staleness: tuple = (0, ModelCheck.DEFAULT_STALENESS_K)
    #: run-ahead depth dimension (ISSUE 14): d=0 is the blocking wire
    #: tail; d>0 widens the window to k + d and schedules ``run_ahead``
    run_ahead: tuple = (0, ModelCheck.DEFAULT_RUN_AHEAD)

    @property
    def engine_rounds(self):
        """Engine invocation bound: INIT round + the federated rounds."""
        return int(self.rounds) + 1


@dataclasses.dataclass
class ModelResult:
    findings: list
    plans: list          # one plan dict per finding, same order
    report: dict


# --------------------------------------------------------------- state model
# All state is plain hashable tuples.
#
# site:   (alive, redeliver_rnd, applied_tag, cache_keys, any_write,
#          had_comp, last_out)
#         last_out = (phase, keys, contrib, echo_ok, made_rnd) — made_rnd
#         is the engine round the output was produced in, so a stale
#         delivery's echo lag (rnd - made_rnd) is judged against the
#         scenario's staleness window
# chan:   (payload_tag, manifest_tag, repairs)   repairs ⊆ {components}
# remote: (cache_keys, any_write, dropped)
# bcast:  (phase, keys, update_tag)
# state:  (rnd, budget, sites, chans, remote, bcast, reduces)
# scenario: (site_quorum, pretrain, staleness_k)

_FRESH_SITE = (True, 0, 0, frozenset(), False, False, None)
_FRESH_CHAN = (0, 0, frozenset())


def _initial_state(config):
    n = int(config.sites)
    return (
        1, int(config.max_faults),
        tuple(_FRESH_SITE for _ in range(n)),
        tuple(_FRESH_CHAN for _ in range(n)),
        (frozenset(), False, frozenset()),
        None,
        0,
    )


class _Trace:
    """Immutable fault-schedule trace riding alongside a state."""

    __slots__ = ("entries",)

    def __init__(self, entries=()):
        self.entries = tuple(entries)

    def extend(self, rnd, actions):
        return _Trace(
            self.entries + tuple((rnd,) + a for a in actions)
        )

    def describe(self):
        if not self.entries:
            return "no faults"
        parts = []
        for e in self.entries:
            rnd, kind, site = e[0], e[1], e[2]
            comp = e[3] if len(e) > 3 else None
            suffix = f"/{comp}" if comp else ""
            parts.append(f"{kind}@r{rnd}/site_{site}{suffix}")
        return ", ".join(parts)


def _plan_faults(trace, avg_file, manifest_file):
    """Trace → resilience/chaos.py fault-plan entries."""
    faults = []
    for e in trace.entries:
        rnd, kind, site = e[0], e[1], e[2]
        comp = e[3] if len(e) > 3 else None
        entry = {"kind": kind, "round": int(rnd), "site": f"site_{site}"}
        if kind in _WORKER_ACTIONS:
            # the executable counterpart is the daemon engine's
            # worker_kill fault at the matching kill point
            entry["kind"] = "worker_kill"
            entry["when"] = _WORKER_ACTIONS[kind]
        elif kind in ("staleness_k", "run_ahead"):
            # the executable counterpart is the engines' stale replay
            # fault: skip the invocation, redeliver the previous output
            # (for run_ahead it approximates the lagging echo — the
            # delayed-broadcast class the window refuses either way)
            entry["kind"] = "stale"
        elif kind in ("truncate_payload", "corrupt_payload"):
            entry["file"] = "grads.npy"
        elif comp is not None:
            entry["file"] = manifest_file if comp == "manifest" else avg_file
        faults.append(entry)
    return faults


# ---------------------------------------------------------------- IR helpers
def _block_events(node_ir, phases):
    """Ordered executed blocks for an invocation: the unguarded block plus
    each phase in ``phases`` that has a block."""
    blocks = []
    if None in node_ir.blocks:
        blocks.append(node_ir.blocks[None])
    for p in phases:
        b = node_ir.blocks.get(p)
        if b is not None:
            blocks.append(b)
    return blocks


#: within-invocation dispatch rewrites the model follows (phase → the
#: successors reachable without a mode barrier; NEXT_RUN_WAITING/TEST are
#: gated on the test mode, which a bounded steady-state run never reaches)
_CHAIN_GATE = {
    "next_run": ("computation", "pre_computation"),
    "pre_computation": ("computation",),
}


def _local_dispatch(node_ir, incoming, pretrain):
    """(executed phases, out_phase) of a site invocation."""
    if incoming not in node_ir.tested_phases:
        # unhandled broadcast phase: only unguarded code runs; phase echoes
        return [], incoming
    executed, cur = [], incoming
    for _ in range(4):
        block = node_ir.blocks.get(cur)
        if cur in executed or block is None:
            break
        executed.append(cur)
        allowed = [
            p for p in block.outgoing if p in _CHAIN_GATE.get(cur, ())
        ]
        if not allowed:
            break
        if cur == "next_run":
            nxt = ("pre_computation"
                   if pretrain and "pre_computation" in allowed
                   else "computation")
            if nxt not in allowed and allowed:
                nxt = allowed[0]
        else:
            nxt = allowed[0]
        # PRE_COMPUTATION is an *output* phase (the next round dispatches
        # it); COMPUTATION chains into its block within this invocation
        if nxt == "pre_computation":
            return executed, "pre_computation"
        cur = nxt
    return executed, cur


# ------------------------------------------------------------------ explorer
class _Explorer:
    def __init__(self, ir, config):
        self.ir = ir
        self.config = config
        self.findings = {}       # (rule, path, line) -> (Finding, plan)
        self.report = {
            "produced": set(), "consumed": set(),
            "states": 0, "runs": 0,
            "confirmed_cache": set(),
            "exercised_reads": set(),
            "phases_covered": set(),
            "terminal_loud": 0, "terminal_success": 0,
        }

    # ------------------------------------------------------------- findings
    def _emit(self, rule, anchor, message, scenario, trace, invariant):
        path, line = anchor
        key = (rule, path, line)
        if key in self.findings:
            return
        plan = {
            "comment": (
                "dinulint tier-4 counterexample — replay with "
                "InProcessEngine(..., fault_plan=<this file>) "
                "(docs/ANALYSIS.md 'Tier 4')"
            ),
            "rule": rule,
            "invariant": invariant,
            "scenario": {
                "n_sites": self.config.sites,
                "site_quorum": scenario[0],
                "pretrain": bool(scenario[1]),
                "staleness_k": int(scenario[2]) if len(scenario) > 2 else 0,
                "run_ahead": int(scenario[3]) if len(scenario) > 3 else 0,
                "engine_rounds": self.config.engine_rounds,
            },
            "faults": _plan_faults(trace, "avg_grads.npy",
                                   ".wire_manifest.json"),
        }
        quorum = scenario[0]
        k = int(scenario[2]) if len(scenario) > 2 else 0
        d_ra = int(scenario[3]) if len(scenario) > 3 else 0
        msg = (
            f"{message} — counterexample: site_quorum={quorum}, "
            f"pretrain={bool(scenario[1])}, staleness_k={k}, "
            f"run_ahead={d_ra}, "
            f"faults=[{trace.describe()}] "
            f"(bound: {self.config.sites} sites x {self.config.rounds} "
            f"rounds, budget {self.config.max_faults}); replayable chaos "
            "plan via --model-plans"
        )
        self.findings[key] = (
            Finding(rule=rule, path=path, line=line, col=0, message=msg),
            plan,
        )

    def _anchor(self, name, default_side=None):
        a = self.ir.facts.anchors.get(name)
        if a:
            return a
        side = default_side or self.ir.remote
        return (side.path, 1)

    def _remote_phase_anchor(self):
        b = self.ir.remote.blocks.get(None)
        if b:
            for e in b.produces:
                if e.key == "phase":
                    return (self.ir.remote.path, e.line)
        return (self.ir.remote.path, 1)

    # ------------------------------------------------------------ execution
    def _exec_events(self, node_ir, state_site, executed_phases, incoming,
                     msg_keys, steady, scenario, trace):
        """Run a node invocation's IR events: cache lifecycle checks, wire
        bookkeeping.  Returns (produced keys, new cache, new any_write)."""
        alive, redeliver, applied, cache, any_w, had_comp, last = state_site
        produced = set()
        writers = node_ir.static_cache_writers()
        cache = set(cache)
        blocks = _block_events(node_ir, executed_phases)
        for block in blocks:
            self.report["phases_covered"].add((node_ir.role, block.phase))
            block_writes = {
                e.key for e in block.cache_writes if e.key != "*"
            }
            block_wild = any(e.key == "*" for e in block.cache_writes)
            for e in block.cache_reads:
                if e.kind != "hard" or e.key.startswith("_"):
                    continue
                if e.key not in writers:
                    continue  # written outside this node: origin unknown
                self.report["exercised_reads"].add((node_ir.path, e.line))
                if any_w or block_wild or e.key in cache or (
                    e.key in block_writes
                ):
                    continue
                self.report["confirmed_cache"].add((node_ir.path, e.line))
                self._emit(
                    ModelCheck.CACHE, (node_ir.path, e.line),
                    f"cache['{e.key}'] is read in the "
                    f"{block.phase or 'unguarded'} block before any write "
                    "of it has executed on this explored path "
                    "(path-sensitive promotion of "
                    "proto-cache-read-before-write)",
                    scenario, trace, "cache write-before-read",
                )
            for e in block.cache_writes:
                if e.key == "*":
                    any_w = True
                    continue
                cache.add(e.key)
                if steady and not e.key.startswith("_") and (
                    e.key not in self.ir.volatile
                ):
                    self._emit(
                        ModelCheck.VOLATILE, (node_ir.path, e.line),
                        f"cache['{e.key}'] is written on an executed "
                        "steady-state round (a COMPUTATION re-invocation) "
                        "but is not in _VOLATILE_CACHE_KEYS — the shared "
                        "compiled-step bucket key churns and the round "
                        "recompiles",
                        scenario, trace, "volatile-key hygiene",
                    )
            for e in block.produces:
                produced.add(e.key)
                self.report["produced"].add((node_ir.role, e.key, e.line))
            for e in block.consumes:
                if e.key in msg_keys:
                    self.report["consumed"].add((node_ir.role, e.key))
        return produced, frozenset(cache), any_w

    def _site_round(self, i, site, chan, bcast, faults, scenario, trace,
                    rnd, quorum):
        """One site's turn.  Returns (site', chan', out or None,
        loud or None, violations already emitted)."""
        alive, redeliver, applied, cache, any_w, had_comp, last = site
        my_faults = {a[0] for a in faults if a[1] == i}
        if not alive:
            return site, chan, None, None
        if my_faults & {"crash", "hang", "reappear"}:
            if quorum is None:
                return site, chan, None, "site failure without quorum"
            redeliver_rnd = rnd + 1 if "reappear" in my_faults else 0
            return ((False, redeliver_rnd, applied, cache, any_w, had_comp,
                     last), chan, None, None)
        if (my_faults & set(_STALE_ACTIONS)) and last is not None:
            # delayed duplicate / async stand-in: previous output
            # redelivered, cache frozen — the echo lag (rnd - made_rnd)
            # grows with every repeated firing
            phase, keys, contrib, _, made = last
            return site, chan, (phase, keys, contrib, False, made), None

        incoming = bcast[0] if bcast else "init_runs"
        executed, out_phase = _local_dispatch(
            self.ir.local, incoming, scenario[1]
        )
        msg_keys = bcast[1] if bcast else frozenset()
        steady = had_comp and incoming == "computation"
        # worker_crash / worker_restart (ISSUE 11): the site's DAEMON
        # WORKER dies (mid-invocation / between rounds) but the SITE does
        # not.  worker_crash: the crashed attempt half-ran — its cache/
        # wire events executed against the engine's round-tripped cache —
        # then the supervisor restarts the worker and RE-INVOKES, so the
        # invocation's events run a SECOND time below and only the
        # re-invocation's output is delivered (exactly once into the
        # reduce).  worker_restart (between rounds) loses only worker-
        # local state; the protocol state lives entirely in the engine's
        # round-trip, so the next invocation proceeds unchanged.  The
        # invariant sweep judges every path carrying these actions
        # (exactly-once contributions/updates, deadlock freedom), and the
        # broken-supervisor switch above pins the check as non-vacuous.
        if "worker_crash" in my_faults:
            if _RESTART_REDELIVERS_LAST_OUTPUT:
                if last is not None:
                    phase, keys, contrib, _, made = last
                    return site, chan, (phase, keys, contrib, False,
                                        made), None
            else:
                _, cache_crash, anyw_crash = self._exec_events(
                    self.ir.local, site, executed, incoming, msg_keys,
                    steady, scenario, trace,
                )
                site = (alive, redeliver, applied, cache_crash, anyw_crash,
                        had_comp, last)
        produced, cache, any_w = self._exec_events(
            self.ir.local, site, executed, incoming, msg_keys, steady,
            scenario, trace,
        )

        # broadcast update application (learner.step semantics)
        update_tag = bcast[2] if bcast else 0
        if update_tag and "update" in msg_keys and (
            "update" in {
                e.key for b in _block_events(self.ir.local, executed)
                for e in b.consumes
            }
        ):
            payload, manifest, repairs = chan
            detected = (payload != manifest) or payload == 0
            if detected:
                healed = set()
                for comp in repairs:
                    if comp == "payload" or (
                        comp == "manifest"
                        and self.ir.facts.heal_bridges_manifest
                    ):
                        healed.add(comp)
                if "payload" in healed:
                    payload = update_tag
                if "manifest" in healed:
                    manifest = update_tag
                repairs = frozenset(repairs - healed)
                chan = (payload, manifest, repairs)
                if (payload != manifest) or payload == 0:
                    # retries exhaust: the transient killed the node
                    self._emit(
                        ModelCheck.UNRECOVERABLE, self._anchor("heal"),
                        "a single transient relay fault on the broadcast "
                        "leg exhausts the wire retries and kills the "
                        "reading site: the chaos repair is registered on "
                        "the damaged file but the failing load is on the "
                        "payload whose manifest it is — the heal never "
                        "fires (the engine relay clobber window)",
                        scenario, trace, "single-fault recoverability",
                    )
                    if quorum is None:
                        return site, chan, None, "wire failure without quorum"
                    return ((False, 0, applied, cache, any_w, had_comp,
                             last), chan, None, None)
            if payload < update_tag:
                self._emit(
                    ModelCheck.LOST_UPDATE, self._anchor(
                        "relay_duplicate", self.ir.local
                    ),
                    f"a stale broadcast payload (round {payload}) is "
                    f"applied in place of the fresh round-{update_tag} "
                    "update and passes every integrity check (payload and "
                    "manifest are both stale and mutually consistent) — "
                    "the update is silently lost on this site",
                    scenario, trace, "exactly-once update application",
                )
            applied = payload
            chan = (payload, manifest, chan[2])

        had_comp = had_comp or "computation" in executed
        contrib = rnd if "reduce" in produced else 0
        if "run_ahead" in my_faults and last is not None:
            # run-ahead pipelining (ISSUE 14): the invocation ran in full
            # and its contribution is FRESH (contrib == rnd), but it was
            # computed against the broadcast the PREVIOUS output consumed
            # — the echo stays pinned at the previous made-round and ages
            # one more round per consecutive firing, which is how the
            # seeded trace reaches the k + d boundary
            out = (out_phase, frozenset(produced), contrib, False, last[4])
        else:
            out = (out_phase, frozenset(produced), contrib, True, rnd)
        site = (alive, redeliver, applied, cache, any_w, had_comp, out)
        return site, chan, out, None

    def _remote_round(self, state, site_outs, stale_flags, scenario, trace):
        """The aggregator's turn: quorum, lockstep guards, dispatch,
        reduce bookkeeping.  Returns (remote', bcast or None, loud,
        reduced)."""
        rnd, budget, sites, chans, remote, bcast, reduces = state
        quorum = scenario[0]
        r_cache, r_any, dropped = remote
        facts = self.ir.facts
        roster = set(range(self.config.sites))

        filtered = dict(site_outs)
        if facts.quorum_checked:
            returned = dropped & set(site_outs)
            if returned and facts.quorum_filters_reappeared:
                for i in returned:
                    filtered.pop(i, None)
            missing = (roster - set(site_outs)) | dropped
            new_drops = missing - dropped
            if new_drops:
                if not quorum:
                    return remote, None, "sites dropped without policy", False
                if len(filtered) < max(int(quorum), 1):
                    return remote, None, "quorum unmet", False
                dropped = frozenset(dropped | new_drops)
        reducer_input = (
            filtered if (facts.quorum_checked
                         and facts.quorum_before_reduce_input)
            else dict(site_outs)
        )

        phases = {out[0] for out in filtered.values()}
        if len(phases) > 1:
            if facts.lockstep_phase_guard:
                return remote, None, "mixed phases refused", False
            self._emit(
                ModelCheck.PHASE_RESET, self._remote_phase_anchor(),
                "a mixed-phase round (a stale site message next to fresh "
                "ones) falls through every dispatch branch and the "
                "aggregator echoes the INIT_RUNS default — every site "
                "re-initializes and the run silently resets mid-training",
                scenario, trace, "no lifecycle reset",
            )
            return remote, None, None, False
        phase = next(iter(phases)) if phases else "init_runs"
        # stale same-phase message: only the echoed round stamp catches it.
        # Under the async window (scenario staleness_k > 0 AND the guard
        # implements the cache-keyed window — facts.round_lockstep_window)
        # an echo lagging by at most k is ACCEPTED: the async engine's
        # stand-in for a straggler, down-weighted by the reducer (not
        # modeled).  Anything older must still be refused loudly; the
        # test-only _WINDOW_ACCEPTS_BEYOND_K switch models a broken window
        # check that lets it through, which the window-relaxed
        # STALE_CONTRIBUTION invariant below must catch.
        window = (
            int(scenario[2])
            if facts.round_lockstep_guard and facts.round_lockstep_window
            else 0
        )
        if (facts.round_lockstep_guard and facts.round_lockstep_window
                and facts.round_lockstep_run_ahead and len(scenario) > 3):
            # run-ahead pipelining widens the window to k + d — the
            # broadcast-lag allowance the real guard reads from
            # Federation.RUN_AHEAD (nodes/remote.py)
            window += int(scenario[3])
        stale_in = {i for i in filtered if stale_flags.get(i)}
        if stale_in and facts.round_lockstep_guard:
            beyond = {
                i for i in stale_in
                if rnd - (filtered[i][4] if len(filtered[i]) > 4 else rnd)
                > window
            }
            # a FRESH contribution with a lagging echo (contrib == rnd)
            # is a run-ahead delivery; a stale redelivery carries an older
            # contrib — each has its own broken-window test switch
            ra_beyond = {i for i in beyond if filtered[i][2] == rnd}
            stale_beyond = beyond - ra_beyond
            if stale_beyond and not _WINDOW_ACCEPTS_BEYOND_K:
                return remote, None, "stale round echo refused", False
            if ra_beyond and not _WINDOW_ACCEPTS_BEYOND_RUN_AHEAD:
                return remote, None, "stale round echo refused", False
            if ra_beyond and _WINDOW_ACCEPTS_BEYOND_RUN_AHEAD:
                for i in sorted(ra_beyond):
                    lag = rnd - filtered[i][4]
                    self._emit(
                        ModelCheck.STALE_CONTRIBUTION,
                        self._anchor("lockstep", self.ir.remote),
                        f"the reduce consumes site_{i}'s fresh round-{rnd} "
                        f"contribution computed {lag} broadcasts behind the "
                        f"stamp — beyond the combined k + d window: the "
                        "run-ahead horizon is unbounded and the staleness "
                        "discount no longer covers the broadcast lag",
                        scenario, trace, "bounded broadcast lag",
                    )

        if phase not in self.ir.remote.tested_phases:
            fallthrough = self.ir.remote.phase_fallthrough
            if rnd > 1 and fallthrough == "init_runs":
                self._emit(
                    ModelCheck.PHASE_RESET, self._remote_phase_anchor(),
                    f"phase '{phase}' reaches the aggregator but its "
                    "dispatch never tests it: the round falls through and "
                    "the echoed INIT_RUNS default silently resets the run",
                    scenario, trace, "no lifecycle reset",
                )
                return remote, None, None, False
            executed = []
        else:
            executed = [phase]

        msg_keys = set()
        for out in filtered.values():
            msg_keys |= out[1]
        shell = (True, 0, 0, r_cache, r_any, False, None)
        steady = phase == "computation" and reduces > 0
        produced, r_cache, r_any = self._exec_events(
            self.ir.remote, shell, executed, phase, msg_keys, steady,
            scenario, trace,
        )

        reduced = False
        if phase == "computation" and filtered and all(
            "reduce" in out[1] for out in filtered.values()
        ):
            reduced = True
            if facts.quorum_checked and quorum:
                pass  # unmet quorum already aborted above
            elif not facts.quorum_checked and len(site_outs) < len(roster):
                self._emit(
                    ModelCheck.QUORUM, self._anchor("reduce_input"),
                    f"the reduce proceeds with {len(site_outs)} of "
                    f"{len(roster)} sites and no quorum policy was ever "
                    "evaluated (the quorum check is missing from the "
                    "aggregator's round path)",
                    scenario, trace, "quorum soundness",
                )
            for i, out in sorted(reducer_input.items()):
                contrib = out[2]
                if "reduce" not in out[1]:
                    continue
                if contrib and contrib < rnd:
                    if i not in dropped and rnd - contrib <= window:
                        # in-window stand-in of a LIVE site: the window-
                        # relaxed exactly-once contract accepts it (the
                        # reducer's staleness discount weights it down) —
                        # only contributions OLDER than the window, or a
                        # dropped site's redelivery, violate
                        continue
                    if i in dropped:
                        anchor, why = self._anchor("reduce_input"), (
                            "the reducer's input snapshot is taken before "
                            "the quorum filtering runs, so a dropped "
                            "site's redelivered output is silently "
                            "double-counted into the global average"
                        )
                    else:
                        anchor, why = self._anchor(
                            "lockstep", self.ir.remote
                        ), (
                            "a delayed duplicate of an earlier message "
                            "from a live site carries the same phase as a "
                            "fresh one — only a round stamp echoed on the "
                            "wire (LocalWire.ROUND) can reject it"
                        )
                    self._emit(
                        ModelCheck.STALE_CONTRIBUTION, anchor,
                        f"the reduce consumes a stale round-{contrib} "
                        f"gradient payload from site_{i} in round {rnd}: "
                        + why,
                        scenario, trace, "exactly-once contributions",
                    )
            for i, out in sorted(filtered.items()):
                if out[2] == rnd and "reduce" in out[1] and (
                    i not in reducer_input
                ):
                    self._emit(
                        ModelCheck.LOST_CONTRIBUTION,
                        self._anchor("reduce_input"),
                        f"site_{i}'s fresh round-{rnd} gradient "
                        "contribution is dropped from the reduce while "
                        "the site is alive and participating",
                        scenario, trace, "exactly-once contributions",
                    )

        # broadcast phase per the executed dispatch
        if executed:
            block = self.ir.remote.blocks.get(phase)
            outgoing = [p for p in (block.outgoing if block else ())]
            out_phase = outgoing[0] if len(outgoing) >= 1 else phase
            if phase == "computation":
                out_phase = "computation"
        else:
            out_phase = phase  # covered: non-reset fallthrough (round 1)
        update_tag = rnd if reduced else (bcast[2] if bcast else 0)
        keys = frozenset(produced)
        remote = (r_cache, r_any, dropped)
        return remote, (out_phase, keys, update_tag, reduced), None, reduced

    # ---------------------------------------------------------------- rounds
    def _round_actions(self, state, scenario):
        """Every single-fault action available this round, sorted.  The
        ``staleness_k`` action only exists in scenarios whose window is
        positive — at k=0 the async engine never stands a site in."""
        rnd, budget, sites, chans, remote, bcast, reduces = state
        if budget <= 0:
            return []
        actions = []
        for i, site in enumerate(sites):
            if not site[0]:
                continue
            for kind in self.config.kinds:
                if kind in ("drop_relay", "duplicate_delivery"):
                    for comp in _COMPONENTS:
                        actions.append((kind, i, comp))
                elif kind in _STALE_ACTIONS:
                    if site[6] is None:
                        continue
                    if kind == "staleness_k" and not scenario[2]:
                        continue
                    actions.append((kind, i))
                elif kind == "run_ahead":
                    # only in scenarios with a positive pipeline depth,
                    # and only once the site has an output whose consumed
                    # broadcast the echo can stay pinned at
                    if site[6] is None:
                        continue
                    if len(scenario) < 4 or not scenario[3]:
                        continue
                    actions.append((kind, i))
                else:
                    actions.append((kind, i))
        return sorted(actions)

    def _step(self, state, actions, scenario, trace):
        """Execute one engine round under ``actions``.  Returns the new
        state, or None when the trace terminated (loudly or at bound)."""
        rnd, budget, sites, chans, remote, bcast, reduces = state
        quorum = scenario[0]
        trace = trace.extend(rnd, actions)
        budget -= len(actions)

        site_outs, stale_flags = {}, {}
        new_sites, new_chans = list(sites), list(chans)
        for i in range(len(sites)):
            site, chan, out, loud = self._site_round(
                i, sites[i], chans[i], bcast, actions, scenario, trace,
                rnd, quorum,
            )
            new_sites[i], new_chans[i] = site, chan
            if loud:
                self.report["terminal_loud"] += 1
                return None
            if out is not None:
                site_outs[i] = out
                stale_flags[i] = not out[3]

        # reappear redeliveries (death fired one round earlier)
        for i, site in enumerate(new_sites):
            if not site[0] and site[1] == rnd and site[6] is not None:
                phase, keys, contrib, _, made = site[6]
                site_outs[i] = (phase, keys, contrib, False, made)
                stale_flags[i] = True
                new_sites[i] = site[:1] + (0,) + site[2:]

        if not site_outs:
            self.report["terminal_loud"] += 1
            return None

        remote, new_bcast, loud, reduced = self._remote_round(
            (rnd, budget, tuple(new_sites), tuple(new_chans), remote,
             bcast, reduces),
            site_outs, stale_flags, scenario, trace,
        )
        if loud:
            self.report["terminal_loud"] += 1
            return None
        if new_bcast is None:
            # a violating fall-through already emitted; stop the trace
            return None
        out_phase, keys, update_tag, reduced = new_bcast
        if out_phase == "success":
            self.report["terminal_success"] += 1
            return None

        # relay the broadcast files (the avg payload + its manifest)
        for i, site in enumerate(new_sites):
            if not site[0]:
                continue
            payload, manifest, repairs = new_chans[i]
            if reduced:
                # a fresh relay recopies EVERY file, so damage from earlier
                # rounds that nothing loaded in between heals naturally;
                # only faults fired THIS round leave stale components
                fresh = rnd
                repairs = {
                    a[2] for a in actions
                    if a[0] in ("drop_relay", "duplicate_delivery")
                    and a[1] == i
                }
                if "payload" not in repairs:
                    payload = fresh
                if "manifest" not in repairs:
                    manifest = fresh
                new_chans[i] = (payload, manifest, frozenset(repairs))
        return (
            rnd + 1, budget, tuple(new_sites), tuple(new_chans), remote,
            (out_phase, keys, update_tag), reduces + (1 if reduced else 0),
        )

    # ------------------------------------------------------------ exploration
    def explore(self):
        for quorum in self.config.quorums:
            for pretrain in self.config.pretrain:
                for k in self.config.staleness:
                    for d_ra in self.config.run_ahead:
                        self._explore_scenario(
                            (quorum, pretrain, int(k), int(d_ra))
                        )
        findings = [f for f, _ in self.findings.values()]
        plans = [p for _, p in self.findings.values()]
        order = sorted(
            range(len(findings)),
            key=lambda ix: (findings[ix].path, findings[ix].line,
                            findings[ix].rule),
        )
        return [findings[ix] for ix in order], [plans[ix] for ix in order]

    def _explore_scenario(self, scenario):
        frontier = collections.deque([(_initial_state(self.config), _Trace())])
        visited = set()
        bound = self.config.engine_rounds
        while frontier:
            state, trace = frontier.popleft()
            if state in visited:
                continue
            visited.add(state)
            self.report["states"] += 1
            if self.report["states"] > MAX_STATES:
                self._emit(
                    ModelCheck.CONFIG, (self.ir.remote.path, 1),
                    f"state-space ceiling ({MAX_STATES}) exceeded — shrink "
                    "--model-sites/--model-rounds/--model-faults",
                    scenario, trace, "bounded exploration",
                )
                return
            rnd = state[0]
            if rnd > bound:
                self.report["runs"] += 1
                if state[6] == 0:
                    self._emit(
                        ModelCheck.DEADLOCK, self._remote_phase_anchor(),
                        f"the bounded run finishes {bound} engine rounds "
                        "with ZERO reduces and no loud failure — the "
                        "federation is silently wedged (a dispatch "
                        "transition is missing or a barrier never "
                        "releases)",
                        scenario, trace, "deadlock freedom",
                    )
                continue
            singles = self._round_actions(state, scenario)
            subsets = [()]
            # the whole remaining budget may be spent in ONE round: the
            # --model-faults contract is the simultaneous-fault tolerance
            # level verified, so no silent per-round cap
            for k in range(1, state[1] + 1):
                subsets.extend(itertools.combinations(singles, k))
            for actions in subsets:
                nxt = self._step(state, actions, scenario, trace)
                if nxt is not None:
                    frontier.append(
                        (nxt, trace.extend(state[0], actions))
                    )


# ------------------------------------------------------------- wire property
def _wire_findings(ir, explorer, config):
    """Every wire key produced on an explored path must be consumed (with
    the payload actually present) on some explored path of the peer."""
    findings, plans = [], []
    consumed = {(role, key) for role, key in explorer.report["consumed"]}
    peers = {"local": "remote", "remote": "local"}
    seen = set()
    for role, key, line in sorted(explorer.report["produced"]):
        if key in ("phase",) or (role, key) in seen:
            continue
        seen.add((role, key))
        if (peers[role], key) not in consumed:
            node = ir.local if role == "local" else ir.remote
            findings.append(Finding(
                rule=ModelCheck.WIRE, path=node.path, line=line, col=0,
                message=(
                    f"wire key '{key}' is produced on an explored path "
                    f"({role} side) but the peer never consumes it on ANY "
                    f"reachable execution of the bounded model "
                    f"({config.sites} sites x {config.rounds} rounds) — "
                    "the payload is computed and shipped into a round "
                    "that cannot see it"
                ),
            ))
            plans.append({
                "rule": ModelCheck.WIRE, "invariant": "wire reachability",
                "scenario": {"n_sites": config.sites,
                             "engine_rounds": config.engine_rounds},
                "faults": [],
            })
    return findings, plans


# --------------------------------------------------------------- entry point
def run_model_check(config=None, ir=None, plans_dir=None):
    """Explore the bounded model; returns a :class:`ModelResult` whose
    findings flow through the same baseline machinery as tiers 1–3."""
    config = config or ModelConfig()
    try:
        if ir is None:
            ir = build_protocol_ir()
    except (OSError, SyntaxError, ValueError) as exc:
        f = Finding(
            rule=ModelCheck.CONFIG, path="coinstac_dinunet_tpu", line=1,
            col=0, message=f"protocol IR extraction failed: {exc}",
        )
        return ModelResult([f], [None], {})
    explorer = _Explorer(ir, config)
    findings, plans = explorer.explore()
    wf, wp = _wire_findings(ir, explorer, config)
    findings, plans = findings + wf, plans + wp
    order = sorted(
        range(len(findings)),
        key=lambda ix: (findings[ix].path, findings[ix].line,
                        findings[ix].rule),
    )
    findings = [findings[ix] for ix in order]
    plans = [plans[ix] for ix in order]
    if plans_dir:
        os.makedirs(plans_dir, exist_ok=True)
        for n, (f, plan) in enumerate(zip(findings, plans)):
            if not plan:
                continue
            name = f"{f.rule}-{n:02d}.json"
            with open(os.path.join(plans_dir, name), "w",
                      encoding="utf-8") as fh:
                json.dump(plan, fh, indent=2, sort_keys=True)
                fh.write("\n")
    report = dict(explorer.report)
    report["produced"] = sorted(report["produced"])
    report["consumed"] = sorted(report["consumed"])
    report["confirmed_cache"] = sorted(report["confirmed_cache"])
    report["exercised_reads"] = sorted(report["exercised_reads"])
    report["phases_covered"] = sorted(
        (r, p or "<unguarded>") for r, p in report["phases_covered"]
    )
    return ModelResult(findings, plans, report)
