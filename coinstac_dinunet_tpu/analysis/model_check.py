"""proto-model-*: the tier-4 federation protocol model checker.

Tier-3 audits each node's phase machine in isolation and syntactically.
This pass composes the whole federation — N site machines + the aggregator
+ the engine's relay channel, all lifted from the AST by
:mod:`~.proto_ir` — and **exhaustively explores** the bounded execution
space under the :mod:`~..resilience.chaos` fault vocabulary:

- per-site invoke faults: ``crash``, ``hang`` (retry exhaustion),
  ``stale`` (a delayed duplicate of the previous site message delivered in
  place of the fresh one), ``reappear`` (death now, stale redelivery one
  round later), ``truncate_payload`` / ``corrupt_payload`` (detectable
  payload damage on the site→aggregator leg);
- per-site relay faults on the broadcast leg: ``drop_relay`` /
  ``duplicate_delivery``, each targeting the payload file or its
  ``.wire_manifest.json`` sidecar (the only witness that an intact,
  self-validating payload is STALE).

Exploration is BFS over hashed global states with a configurable bound
(default: :class:`~..config.keys.ModelCheck` — 2 sites × 3 federated
rounds × the full alphabet at fault budget 1), across both quorum
configurations (all-site lockstep and ``site_quorum=1``) and both
NEXT_RUN dispatch branches (pretrain on/off).  The checked invariants are
the :class:`~..config.keys.ModelCheck` vocabulary: deadlock-freedom,
no lifecycle reset, quorum soundness, exactly-once gradient contributions
and broadcast updates, single-transient-fault recoverability, and the
path-sensitive promotions of the tier-3 cache rules.

Every violation is emitted as a ``proto-model-*`` finding through the
same baseline/ratchet machinery as tiers 1–3 AND as an executable chaos
fault plan (``--model-plans``) whose replay through a real
:class:`~..engine.InProcessEngine` reproduces the counterexample
(``tests/test_model_check.py``).

Deterministic and pure-Python: no JAX, no clocks, no randomness — the
same tree, bound and findings on every run.
"""
import collections
import dataclasses
import itertools
import json
import os

from ..config.keys import ModelCheck
from .core import Finding
from .proto_ir import build_protocol_ir

#: the fault alphabet the explorer schedules (ISSUE 9 bound; ``slow`` is
#: protocol-invisible in a lockstep engine and is deliberately absent).
#: ``worker_crash``/``worker_restart`` (ISSUE 11) are the DAEMON
#: supervision actions: the site's long-lived worker process is killed
#: mid-invocation (crash) or between rounds (restart), the supervisor
#: restarts it and the invocation re-runs against the engine's
#: round-tripped cache — so unlike ``crash``/``hang`` the SITE stays
#: alive, and the invariants verify the supervision contract: a restarted
#: worker contributes to the round's reduce exactly once (the crashed
#: attempt's output is discarded by the engine and its payload files are
#: atomically overwritten by the re-invocation), and a restart during the
#: relay never wedges the next round.  Counterexamples replay as
#: ``worker_kill`` chaos plans through a real ``DaemonEngine``
#: (``tests/test_daemon.py``).
FAULT_ALPHABET = (
    "crash", "hang", "stale", "reappear",
    "truncate_payload", "corrupt_payload",
    "drop_relay", "duplicate_delivery",
    "worker_crash", "worker_restart",
    # ISSUE 12: the async round engine's stand-in — the site is not
    # invoked this round and its LAST output (with its lagging wire_round
    # echo) is delivered instead.  Mechanically a delayed duplicate like
    # ``stale``, but scheduled only in scenarios whose staleness window k
    # is positive: the aggregator must ACCEPT it while the echo lags by at
    # most k (the reducer down-weights it — not modeled) and must still
    # refuse anything older.  Repeated firings age the stand-in past the
    # window, which is how the seeded k-violation reaches the boundary.
    "staleness_k",
    # ISSUE 14: run-ahead pipelining — the site delivers a FRESH
    # contribution computed against the broadcast its PREVIOUS output
    # consumed (the engine re-submitted it while the reduce tail was
    # still in flight on the reducer worker).  The wire_round echo lags
    # by the pipeline depth and ages one more round per consecutive
    # firing; the aggregator must ACCEPT it while the lag is at most
    # k + d (the reducer's gamma**lag discount covers it — not modeled)
    # and refuse anything deeper.  Scheduled only in scenarios whose
    # run-ahead depth d is positive.
    "run_ahead",
    # ISSUE 15: elastic membership — deterministic ROSTER transitions,
    # not faults (they spend the separate membership budget): ``join``
    # admits the scenario's spare non-member slot mid-run (the joiner's
    # first invocation executes the local join entry and its first
    # contribution is due the FOLLOWING round, exactly once), ``leave``
    # retires a member gracefully (its flagged final contribution still
    # counts, then the roster shrinks — never a dropped site), and
    # ``rejoin`` re-admits a dead or left site with a fresh incarnation
    # whose epoch bump is the refusal boundary for any payload out of the
    # previous life.  Scheduled only in elastic scenarios on trees whose
    # aggregator runs the membership round step (facts.membership_checked).
    "join", "leave", "rejoin",
)

#: elastic-membership roster transitions (ISSUE 15): scheduled from the
#: separate ``max_membership`` budget so churn composes with the fault
#: alphabet at the default fault budget (a death + a rejoin must fit in
#: one trace)
_MEMBERSHIP_ACTIONS = ("join", "leave", "rejoin")

#: model action -> replayable chaos fault-plan kind (worker actions map to
#: the daemon engine's worker_kill fault with the matching kill point;
#: staleness_k replays as the engines' ``stale`` replay fault)
_WORKER_ACTIONS = {"worker_crash": "invoke", "worker_restart": "idle"}
_STALE_ACTIONS = ("stale", "staleness_k")

#: broken-supervisor semantics switch (tests only): a mis-implemented
#: daemon supervisor might REDELIVER the crashed worker's previous output
#: instead of re-invoking the node — exactly the stale-delivery class the
#: round-stamp invariant exists to catch.  ``tests/test_model_check.py``
#: flips this to prove the worker_crash action is checkable, not vacuous:
#: with the ``wire_round`` stamp intact the protocol refuses the
#: redelivery loudly; with the stamp fact flipped, STALE_CONTRIBUTION
#: fires with a worker_kill counterexample plan.
_RESTART_REDELIVERS_LAST_OUTPUT = False

#: broken-window semantics switch (tests only): a mis-implemented async
#: window check might accept a contribution OLDER than the staleness bound
#: k into the reduce — the exact boundary the window-relaxed
#: STALE_CONTRIBUTION invariant patrols.  ``tests/test_async.py`` flips
#: this to prove the staleness_k action is checkable, not vacuous: with
#: the real window semantics a beyond-k echo is refused loudly (clean);
#: with the flip, STALE_CONTRIBUTION fires with a replayable ``stale``
#: chaos plan.
_WINDOW_ACCEPTS_BEYOND_K = False

#: broken-horizon semantics switch (tests only): a mis-implemented
#: run-ahead window might accept a FRESH contribution whose broadcast lag
#: exceeds ``k + d`` into the reduce — the broadcast-lag boundary the
#: widened window patrols (``nodes/remote.py`` reads
#: ``Federation.RUN_AHEAD`` into the window).  ``tests/test_async.py``
#: flips this to prove the ``run_ahead`` action is checkable, not
#: vacuous: with the real window a beyond-``k+d`` echo is refused loudly
#: (clean); with the flip, STALE_CONTRIBUTION fires with a replayable
#: ``stale`` chaos plan.
_WINDOW_ACCEPTS_BEYOND_RUN_AHEAD = False

#: broken-roster semantics switches (tests only, ISSUE 15) — each pins one
#: elastic-membership invariant as checkable, not vacuous:
#:
#: - ``_ROSTER_ACCEPTS_STALE_EPOCH``: a mis-implemented membership filter
#:   accepts a payload from a NON-MEMBER (a gracefully left site's late
#:   duplicate) or one whose roster-epoch echo predates the site's current
#:   admission — the redelivery-out-of-a-dead-incarnation class only the
#:   epoch refusal can catch (quorum's reappeared-site filter never sees a
#:   graceful leaver: it was never dropped).  Flipping it makes
#:   ROSTER fire with a replayable leave+stale churn plan.
#: - ``_QUORUM_AGAINST_INIT_ROSTER``: quorum judged against the frozen
#:   founding roster instead of the live member list — a graceful
#:   retirement then counts as a death and erodes (or falsely fails) the
#:   quorum.  Flipping it makes ROSTER fire with a replayable leave plan.
#: - ``_JOIN_CONTRIBUTES_IN_ADMISSION_ROUND``: a mis-implemented engine
#:   invokes the joiner in the SAME round its admission is processed — the
#:   contribution lands twice around the r/r+1 boundary instead of exactly
#:   once at r+1.  Flipping it makes ADMISSION fire with a replayable join
#:   plan.
_ROSTER_ACCEPTS_STALE_EPOCH = False
_QUORUM_AGAINST_INIT_ROSTER = False
_JOIN_CONTRIBUTES_IN_ADMISSION_ROUND = False

#: broadcast-channel components a relay fault can target
_COMPONENTS = ("payload", "manifest")

MODEL_RULE_IDS = (
    ModelCheck.ADMISSION, ModelCheck.CACHE, ModelCheck.CONFIG,
    ModelCheck.DEADLOCK,
    ModelCheck.LOST_CONTRIBUTION, ModelCheck.LOST_UPDATE,
    ModelCheck.PHASE_RESET, ModelCheck.QUORUM, ModelCheck.ROSTER,
    ModelCheck.STALE_CONTRIBUTION, ModelCheck.UNRECOVERABLE,
    ModelCheck.VOLATILE, ModelCheck.WIRE,
)

#: hard ceiling on explored states per scenario — a runaway bound must
#: degrade to a typed finding, never a hung CI job
MAX_STATES = 250_000


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Exploration bound (defaults = the CI gate's contract).

    ``staleness`` is a scenario dimension like ``quorums``: every bound is
    explored at each listed async window k.  k=0 is today's exact-stamp
    lockstep (every stale delivery refused); k>0 relaxes the stamp to the
    window and schedules the ``staleness_k`` action."""

    sites: int = ModelCheck.DEFAULT_SITES
    rounds: int = ModelCheck.DEFAULT_ROUNDS
    max_faults: int = ModelCheck.DEFAULT_FAULT_BUDGET
    kinds: tuple = FAULT_ALPHABET
    quorums: tuple = (None, 1)
    pretrain: tuple = (False, True)
    staleness: tuple = (0, ModelCheck.DEFAULT_STALENESS_K)
    #: run-ahead depth dimension (ISSUE 14): d=0 is the blocking wire
    #: tail; d>0 widens the window to k + d and schedules ``run_ahead``
    run_ahead: tuple = (0, ModelCheck.DEFAULT_RUN_AHEAD)
    #: elastic-membership dimension (ISSUE 15, ModelCheck.DEFAULT_ELASTIC):
    #: elastic scenarios grow one spare non-member slot and schedule the
    #: join/leave/rejoin roster transitions from ``max_membership``
    elastic: tuple = (False, True)
    #: roster-transition budget per trace, separate from ``max_faults`` —
    #: a death (1 fault) + a rejoin (1 transition) must fit in one trace
    max_membership: int = 2

    @property
    def engine_rounds(self):
        """Engine invocation bound: INIT round + the federated rounds."""
        return int(self.rounds) + 1


@dataclasses.dataclass
class ModelResult:
    findings: list
    plans: list          # one plan dict per finding, same order
    report: dict


# --------------------------------------------------------------- state model
# All state is plain hashable tuples.
#
# site:   (alive, redeliver_rnd, applied_tag, cache_keys, any_write,
#          had_comp, last_out, adm, first_rnd)
#         last_out = (phase, keys, contrib, echo_ok, made_rnd, epoch_echo)
#         — made_rnd is the engine round the output was produced in, so a
#         stale delivery's echo lag (rnd - made_rnd) is judged against the
#         scenario's staleness window; epoch_echo is the roster epoch the
#         consumed broadcast carried (the membership filter's refusal
#         basis, ISSUE 15).  adm is the roster epoch the site was (last)
#         admitted at, or None while it is not a member (never admitted,
#         or gracefully retired); first_rnd is the round a mid-run
#         joiner's first contribution is due (0 for founding members).
# chan:   (payload_tag, manifest_tag, repairs)   repairs ⊆ {components}
# remote: (cache_keys, any_write, dropped, roster_epoch)
# bcast:  (phase, keys, update_tag, roster_epoch)
# state:  (rnd, budget, sites, chans, remote, bcast, reduces, mem_budget)
# scenario: (site_quorum, pretrain, staleness_k, run_ahead, elastic)

_FRESH_SITE = (True, 0, 0, frozenset(), False, False, None, 1, 0)
#: the elastic scenarios' spare slot: alive but never admitted — only the
#: ``join`` action can make it a member
_SPARE_SITE = (True, 0, 0, frozenset(), False, False, None, None, 0)
_FRESH_CHAN = (0, 0, frozenset())


def _initial_state(config, elastic=False):
    n = int(config.sites) + (1 if elastic else 0)
    sites = tuple(
        _SPARE_SITE if elastic and i == int(config.sites) else _FRESH_SITE
        for i in range(n)
    )
    return (
        1, int(config.max_faults),
        sites,
        tuple(_FRESH_CHAN for _ in range(n)),
        (frozenset(), False, frozenset(), 1),
        None,
        0,
        int(config.max_membership) if elastic else 0,
    )


class _Trace:
    """Immutable fault-schedule trace riding alongside a state."""

    __slots__ = ("entries",)

    def __init__(self, entries=()):
        self.entries = tuple(entries)

    def extend(self, rnd, actions):
        return _Trace(
            self.entries + tuple((rnd,) + a for a in actions)
        )

    def describe(self):
        if not self.entries:
            return "no faults"
        parts = []
        for e in self.entries:
            rnd, kind, site = e[0], e[1], e[2]
            comp = e[3] if len(e) > 3 else None
            suffix = f"/{comp}" if comp else ""
            parts.append(f"{kind}@r{rnd}/site_{site}{suffix}")
        return ", ".join(parts)


def _plan_faults(trace, avg_file, manifest_file):
    """Trace → resilience/chaos.py fault-plan entries."""
    faults = []
    for e in trace.entries:
        rnd, kind, site = e[0], e[1], e[2]
        comp = e[3] if len(e) > 3 else None
        entry = {"kind": kind, "round": int(rnd), "site": f"site_{site}"}
        if kind in _WORKER_ACTIONS:
            # the executable counterpart is the daemon engine's
            # worker_kill fault at the matching kill point
            entry["kind"] = "worker_kill"
            entry["when"] = _WORKER_ACTIONS[kind]
        elif kind in ("staleness_k", "run_ahead"):
            # the executable counterpart is the engines' stale replay
            # fault: skip the invocation, redeliver the previous output
            # (for run_ahead it approximates the lagging echo — the
            # delayed-broadcast class the window refuses either way)
            entry["kind"] = "stale"
        elif kind in ("truncate_payload", "corrupt_payload"):
            entry["file"] = "grads.npy"
        elif comp is not None:
            entry["file"] = manifest_file if comp == "manifest" else avg_file
        faults.append(entry)
    return faults


# ---------------------------------------------------------------- IR helpers
def _block_events(node_ir, phases):
    """Ordered executed blocks for an invocation: the unguarded block plus
    each phase in ``phases`` that has a block."""
    blocks = []
    if None in node_ir.blocks:
        blocks.append(node_ir.blocks[None])
    for p in phases:
        b = node_ir.blocks.get(p)
        if b is not None:
            blocks.append(b)
    return blocks


#: within-invocation dispatch rewrites the model follows (phase → the
#: successors reachable without a mode barrier; NEXT_RUN_WAITING/TEST are
#: gated on the test mode, which a bounded steady-state run never reaches)
_CHAIN_GATE = {
    "next_run": ("computation", "pre_computation"),
    "pre_computation": ("computation",),
}


def _local_dispatch(node_ir, incoming, pretrain):
    """(executed phases, out_phase) of a site invocation."""
    if incoming not in node_ir.tested_phases:
        # unhandled broadcast phase: only unguarded code runs; phase echoes
        return [], incoming
    executed, cur = [], incoming
    for _ in range(4):
        block = node_ir.blocks.get(cur)
        if cur in executed or block is None:
            break
        executed.append(cur)
        allowed = [
            p for p in block.outgoing if p in _CHAIN_GATE.get(cur, ())
        ]
        if not allowed:
            break
        if cur == "next_run":
            nxt = ("pre_computation"
                   if pretrain and "pre_computation" in allowed
                   else "computation")
            if nxt not in allowed and allowed:
                nxt = allowed[0]
        else:
            nxt = allowed[0]
        # PRE_COMPUTATION is an *output* phase (the next round dispatches
        # it); COMPUTATION chains into its block within this invocation
        if nxt == "pre_computation":
            return executed, "pre_computation"
        cur = nxt
    return executed, cur


# ------------------------------------------------------------------ explorer
class _Explorer:
    def __init__(self, ir, config):
        self.ir = ir
        self.config = config
        self.findings = {}       # (rule, path, line) -> (Finding, plan)
        self.report = {
            "produced": set(), "consumed": set(),
            "states": 0, "runs": 0,
            "confirmed_cache": set(),
            "exercised_reads": set(),
            "phases_covered": set(),
            "terminal_loud": 0, "terminal_success": 0,
        }

    # ------------------------------------------------------------- findings
    def _emit(self, rule, anchor, message, scenario, trace, invariant):
        path, line = anchor
        key = (rule, path, line)
        if key in self.findings:
            return
        plan = {
            "comment": (
                "dinulint tier-4 counterexample — replay with "
                "InProcessEngine(..., fault_plan=<this file>) "
                "(docs/ANALYSIS.md 'Tier 4')"
            ),
            "rule": rule,
            "invariant": invariant,
            "scenario": {
                "n_sites": self.config.sites,
                "site_quorum": scenario[0],
                "pretrain": bool(scenario[1]),
                "staleness_k": int(scenario[2]) if len(scenario) > 2 else 0,
                "run_ahead": int(scenario[3]) if len(scenario) > 3 else 0,
                "elastic": bool(scenario[4]) if len(scenario) > 4 else False,
                "engine_rounds": self.config.engine_rounds,
            },
            "faults": _plan_faults(trace, "avg_grads.npy",
                                   ".wire_manifest.json"),
        }
        quorum = scenario[0]
        k = int(scenario[2]) if len(scenario) > 2 else 0
        d_ra = int(scenario[3]) if len(scenario) > 3 else 0
        elastic = bool(scenario[4]) if len(scenario) > 4 else False
        msg = (
            f"{message} — counterexample: site_quorum={quorum}, "
            f"pretrain={bool(scenario[1])}, staleness_k={k}, "
            f"run_ahead={d_ra}, elastic={elastic}, "
            f"faults=[{trace.describe()}] "
            f"(bound: {self.config.sites} sites x {self.config.rounds} "
            f"rounds, budget {self.config.max_faults}); replayable chaos "
            "plan via --model-plans"
        )
        self.findings[key] = (
            Finding(rule=rule, path=path, line=line, col=0, message=msg),
            plan,
        )

    def _anchor(self, name, default_side=None):
        a = self.ir.facts.anchors.get(name)
        if a:
            return a
        side = default_side or self.ir.remote
        return (side.path, 1)

    def _remote_phase_anchor(self):
        b = self.ir.remote.blocks.get(None)
        if b:
            for e in b.produces:
                if e.key == "phase":
                    return (self.ir.remote.path, e.line)
        return (self.ir.remote.path, 1)

    # ------------------------------------------------------------ execution
    def _exec_events(self, node_ir, state_site, executed_phases, incoming,
                     msg_keys, steady, scenario, trace):
        """Run a node invocation's IR events: cache lifecycle checks, wire
        bookkeeping.  Returns (produced keys, new cache, new any_write)."""
        alive, redeliver, applied, cache, any_w, had_comp, last = \
            state_site[:7]
        produced = set()
        writers = node_ir.static_cache_writers()
        cache = set(cache)
        blocks = _block_events(node_ir, executed_phases)
        for block in blocks:
            self.report["phases_covered"].add((node_ir.role, block.phase))
            block_writes = {
                e.key for e in block.cache_writes if e.key != "*"
            }
            block_wild = any(e.key == "*" for e in block.cache_writes)
            for e in block.cache_reads:
                if e.kind != "hard" or e.key.startswith("_"):
                    continue
                if e.key not in writers:
                    continue  # written outside this node: origin unknown
                self.report["exercised_reads"].add((node_ir.path, e.line))
                if any_w or block_wild or e.key in cache or (
                    e.key in block_writes
                ):
                    continue
                self.report["confirmed_cache"].add((node_ir.path, e.line))
                self._emit(
                    ModelCheck.CACHE, (node_ir.path, e.line),
                    f"cache['{e.key}'] is read in the "
                    f"{block.phase or 'unguarded'} block before any write "
                    "of it has executed on this explored path "
                    "(path-sensitive promotion of "
                    "proto-cache-read-before-write)",
                    scenario, trace, "cache write-before-read",
                )
            for e in block.cache_writes:
                if e.key == "*":
                    any_w = True
                    continue
                cache.add(e.key)
                if steady and not e.key.startswith("_") and (
                    e.key not in self.ir.volatile
                ):
                    self._emit(
                        ModelCheck.VOLATILE, (node_ir.path, e.line),
                        f"cache['{e.key}'] is written on an executed "
                        "steady-state round (a COMPUTATION re-invocation) "
                        "but is not in _VOLATILE_CACHE_KEYS — the shared "
                        "compiled-step bucket key churns and the round "
                        "recompiles",
                        scenario, trace, "volatile-key hygiene",
                    )
            for e in block.produces:
                produced.add(e.key)
                self.report["produced"].add((node_ir.role, e.key, e.line))
            for e in block.consumes:
                if e.key in msg_keys:
                    self.report["consumed"].add((node_ir.role, e.key))
        return produced, frozenset(cache), any_w

    def _site_round(self, i, site, chan, bcast, faults, scenario, trace,
                    rnd, quorum):
        """One site's turn.  Returns (site', chan', out or None,
        loud or None, violations already emitted)."""
        alive, redeliver, applied, cache, any_w, had_comp, last = site[:7]
        adm, first_rnd = site[7], site[8]
        tail = site[7:]
        my_faults = {a[0] for a in faults if a[1] == i}
        if not alive:
            return site, chan, None, None
        if adm is None:
            # not a roster member (ISSUE 15): a retired or never-admitted
            # site is not invoked — only a late duplicate of a LEFT
            # site's final payload can still arrive (the stale action),
            # which the aggregator's membership filter must refuse as a
            # non-member contribution
            if (my_faults & set(_STALE_ACTIONS)) and last is not None:
                phase, keys, contrib, _, made = last[:5]
                echo_e = last[5] if len(last) > 5 else None
                return site, chan, (phase, keys, contrib, False, made,
                                    echo_e), None
            return site, chan, None, None
        if first_rnd > rnd:
            # admitted this very round: the joiner's first invocation
            # (and first contribution) is due NEXT round — its absence
            # from this round's input is the joining grace, not a drop
            return site, chan, None, None
        if my_faults & {"crash", "hang", "reappear"}:
            if quorum is None:
                return site, chan, None, "site failure without quorum"
            redeliver_rnd = rnd + 1 if "reappear" in my_faults else 0
            return ((False, redeliver_rnd, applied, cache, any_w, had_comp,
                     last) + tail, chan, None, None)
        if (my_faults & set(_STALE_ACTIONS)) and last is not None:
            # delayed duplicate / async stand-in: previous output
            # redelivered, cache frozen — the echo lag (rnd - made_rnd)
            # grows with every repeated firing
            phase, keys, contrib, _, made = last[:5]
            echo_e = last[5] if len(last) > 5 else None
            return site, chan, (phase, keys, contrib, False, made,
                                echo_e), None

        incoming = bcast[0] if bcast else "init_runs"
        executed, out_phase = _local_dispatch(
            self.ir.local, incoming, scenario[1]
        )
        if first_rnd == rnd and "join" in self.ir.local.blocks:
            # a mid-run joiner's FIRST invocation: the local join entry
            # (the carved JOIN_BLOCK — fold adoption, warm start) executes
            # before the steady-state dispatch, exactly once
            executed = ["join"] + list(executed)
        msg_keys = bcast[1] if bcast else frozenset()
        steady = had_comp and incoming == "computation"
        # worker_crash / worker_restart (ISSUE 11): the site's DAEMON
        # WORKER dies (mid-invocation / between rounds) but the SITE does
        # not.  worker_crash: the crashed attempt half-ran — its cache/
        # wire events executed against the engine's round-tripped cache —
        # then the supervisor restarts the worker and RE-INVOKES, so the
        # invocation's events run a SECOND time below and only the
        # re-invocation's output is delivered (exactly once into the
        # reduce).  worker_restart (between rounds) loses only worker-
        # local state; the protocol state lives entirely in the engine's
        # round-trip, so the next invocation proceeds unchanged.  The
        # invariant sweep judges every path carrying these actions
        # (exactly-once contributions/updates, deadlock freedom), and the
        # broken-supervisor switch above pins the check as non-vacuous.
        if "worker_crash" in my_faults:
            if _RESTART_REDELIVERS_LAST_OUTPUT:
                if last is not None:
                    phase, keys, contrib, _, made = last[:5]
                    echo_e = last[5] if len(last) > 5 else None
                    return site, chan, (phase, keys, contrib, False,
                                        made, echo_e), None
            else:
                _, cache_crash, anyw_crash = self._exec_events(
                    self.ir.local, site, executed, incoming, msg_keys,
                    steady, scenario, trace,
                )
                site = (alive, redeliver, applied, cache_crash, anyw_crash,
                        had_comp, last) + tail
        produced, cache, any_w = self._exec_events(
            self.ir.local, site, executed, incoming, msg_keys, steady,
            scenario, trace,
        )

        # broadcast update application (learner.step semantics)
        update_tag = bcast[2] if bcast else 0
        if update_tag and "update" in msg_keys and (
            "update" in {
                e.key for b in _block_events(self.ir.local, executed)
                for e in b.consumes
            }
        ):
            payload, manifest, repairs = chan
            detected = (payload != manifest) or payload == 0
            if detected:
                healed = set()
                for comp in repairs:
                    if comp == "payload" or (
                        comp == "manifest"
                        and self.ir.facts.heal_bridges_manifest
                    ):
                        healed.add(comp)
                if "payload" in healed:
                    payload = update_tag
                if "manifest" in healed:
                    manifest = update_tag
                repairs = frozenset(repairs - healed)
                chan = (payload, manifest, repairs)
                if (payload != manifest) or payload == 0:
                    # retries exhaust: the transient killed the node
                    self._emit(
                        ModelCheck.UNRECOVERABLE, self._anchor("heal"),
                        "a single transient relay fault on the broadcast "
                        "leg exhausts the wire retries and kills the "
                        "reading site: the chaos repair is registered on "
                        "the damaged file but the failing load is on the "
                        "payload whose manifest it is — the heal never "
                        "fires (the engine relay clobber window)",
                        scenario, trace, "single-fault recoverability",
                    )
                    if quorum is None:
                        return site, chan, None, "wire failure without quorum"
                    return ((False, 0, applied, cache, any_w, had_comp,
                             last) + tail, chan, None, None)
            if payload < update_tag:
                self._emit(
                    ModelCheck.LOST_UPDATE, self._anchor(
                        "relay_duplicate", self.ir.local
                    ),
                    f"a stale broadcast payload (round {payload}) is "
                    f"applied in place of the fresh round-{update_tag} "
                    "update and passes every integrity check (payload and "
                    "manifest are both stale and mutually consistent) — "
                    "the update is silently lost on this site",
                    scenario, trace, "exactly-once update application",
                )
            applied = payload
            chan = (payload, manifest, chan[2])

        had_comp = had_comp or "computation" in executed
        contrib = rnd if "reduce" in produced else 0
        epoch_now = bcast[3] if bcast and len(bcast) > 3 else 1
        if "run_ahead" in my_faults and last is not None:
            # run-ahead pipelining (ISSUE 14): the invocation ran in full
            # and its contribution is FRESH (contrib == rnd), but it was
            # computed against the broadcast the PREVIOUS output consumed
            # — the echo stays pinned at the previous made-round and ages
            # one more round per consecutive firing, which is how the
            # seeded trace reaches the k + d boundary
            out = (out_phase, frozenset(produced), contrib, False, last[4],
                   last[5] if len(last) > 5 else None)
        else:
            out = (out_phase, frozenset(produced), contrib, True, rnd,
                   epoch_now)
        site = (alive, redeliver, applied, cache, any_w, had_comp,
                out) + tail
        return site, chan, out, None

    def _remote_round(self, state, site_outs, stale_flags, scenario, trace):
        """The aggregator's turn: membership filter, quorum, lockstep
        guards, dispatch, reduce bookkeeping.  Returns (remote', bcast or
        None, loud, reduced)."""
        rnd, budget, sites, chans, remote, bcast, reduces = state[:7]
        quorum = scenario[0]
        r_cache, r_any, dropped = remote[:3]
        epoch = remote[3] if len(remote) > 3 else 1
        facts = self.ir.facts
        elastic = len(scenario) > 4 and bool(scenario[4])

        # elastic membership (ISSUE 15): this round's roster transitions
        # (trace entries carry them), the per-site ADMISSION epoch as the
        # aggregator sees it after processing admissions at the TOP of its
        # round (process_admissions runs before the payload filter), and
        # the broadcast epoch (admission bumps ride this round's
        # broadcast; graceful-leave bumps land after it)
        mem_now = [(e[1], e[2]) for e in trace.entries
                   if e[0] == rnd and e[1] in _MEMBERSHIP_ACTIONS]
        adm_eff = {i: s[7] for i, s in enumerate(sites)}
        pos = 0
        for kind, i in mem_now:
            if kind in ("join", "rejoin"):
                pos += 1
                adm_eff[i] = epoch + pos
        epoch_out = epoch + pos
        admitted_now = {i for kind, i in mem_now if kind != "leave"}

        filtered = dict(site_outs)
        # ---- the membership filter (federation/membership.py): refuse
        # payloads BY ROSTER EPOCH before quorum/lockstep/reduce see them
        # — non-member outputs (a left site's late duplicate) and echoes
        # older than the site's current admission (a redelivery out of a
        # previous, dead incarnation racing its rejoin)
        mem_refused = set()
        if elastic and facts.membership_checked:
            for i, out in sorted(site_outs.items()):
                adm_i = adm_eff.get(i)
                echo = out[5] if len(out) > 5 else None
                nonmember = adm_i is None
                stale_epoch = (
                    adm_i is not None and echo is not None
                    and int(echo) < int(adm_i)
                )
                if not (nonmember or stale_epoch):
                    continue
                refuses = (
                    not _ROSTER_ACCEPTS_STALE_EPOCH
                    and (nonmember or facts.roster_epoch_refusal)
                )
                if refuses:
                    mem_refused.add(i)
                    filtered.pop(i, None)

        # quorum is judged against the LIVE roster: a gracefully retired
        # site is gone from it (never a death), a never-admitted spare
        # was never in it.  _QUORUM_AGAINST_INIT_ROSTER (tests only)
        # models the grow-only bug: the founding roster stays frozen and
        # a retirement erodes quorum like a death.
        members = {i for i, s in enumerate(sites) if s[7] is not None}
        retired = {
            i for i, s in enumerate(sites)
            if s[7] is None and s[0] and (s[5] or s[6] is not None)
        }
        roster = (
            set(range(self.config.sites)) if _QUORUM_AGAINST_INIT_ROSTER
            else set(members)
        )
        if facts.quorum_checked:
            returned = dropped & set(site_outs)
            if returned and facts.quorum_filters_reappeared:
                for i in returned:
                    filtered.pop(i, None)
            delivered = set(site_outs) - {
                i for i in mem_refused if adm_eff.get(i) is not None
            }
            missing = (roster - delivered) | dropped
            if quorum and (missing & retired):
                self._emit(
                    ModelCheck.ROSTER,
                    self._anchor("membership", self.ir.remote),
                    "quorum is computed against a stale roster: a "
                    "gracefully retired site is still counted as a "
                    "missing member, so its leave erodes quorum exactly "
                    "like a death (the roster the quorum policy reads "
                    "was frozen at INIT)",
                    scenario, trace, "quorum against the live roster",
                )
            new_drops = missing - dropped
            if new_drops:
                if not quorum:
                    return remote, None, "sites dropped without policy", False
                if len(filtered) < max(int(quorum), 1):
                    return remote, None, "quorum unmet", False
                dropped = frozenset(dropped | new_drops)
        # the reducer/trainer input snapshot: membership refusals reach it
        # only when the membership step ran BEFORE the snapshot (its own
        # ordering fact, independent of the quorum one) — otherwise the
        # refused payloads still reach the reduce (the
        # proto-model-stale-contribution ordering hazard, roster flavor)
        base_input = (
            {i: o for i, o in site_outs.items() if i not in mem_refused}
            if facts.membership_before_reduce_input else dict(site_outs)
        )
        reducer_input = (
            filtered if (facts.quorum_checked
                         and facts.quorum_before_reduce_input)
            else base_input
        )

        phases = {out[0] for out in filtered.values()}
        if len(phases) > 1:
            if facts.lockstep_phase_guard:
                return remote, None, "mixed phases refused", False
            self._emit(
                ModelCheck.PHASE_RESET, self._remote_phase_anchor(),
                "a mixed-phase round (a stale site message next to fresh "
                "ones) falls through every dispatch branch and the "
                "aggregator echoes the INIT_RUNS default — every site "
                "re-initializes and the run silently resets mid-training",
                scenario, trace, "no lifecycle reset",
            )
            return remote, None, None, False
        phase = next(iter(phases)) if phases else "init_runs"
        # stale same-phase message: only the echoed round stamp catches it.
        # Under the async window (scenario staleness_k > 0 AND the guard
        # implements the cache-keyed window — facts.round_lockstep_window)
        # an echo lagging by at most k is ACCEPTED: the async engine's
        # stand-in for a straggler, down-weighted by the reducer (not
        # modeled).  Anything older must still be refused loudly; the
        # test-only _WINDOW_ACCEPTS_BEYOND_K switch models a broken window
        # check that lets it through, which the window-relaxed
        # STALE_CONTRIBUTION invariant below must catch.
        window = (
            int(scenario[2])
            if facts.round_lockstep_guard and facts.round_lockstep_window
            else 0
        )
        if (facts.round_lockstep_guard and facts.round_lockstep_window
                and facts.round_lockstep_run_ahead and len(scenario) > 3):
            # run-ahead pipelining widens the window to k + d — the
            # broadcast-lag allowance the real guard reads from
            # Federation.RUN_AHEAD (nodes/remote.py)
            window += int(scenario[3])
        stale_in = {i for i in filtered if stale_flags.get(i)}
        if stale_in and facts.round_lockstep_guard:
            beyond = {
                i for i in stale_in
                if rnd - (filtered[i][4] if len(filtered[i]) > 4 else rnd)
                > window
            }
            # a FRESH contribution with a lagging echo (contrib == rnd)
            # is a run-ahead delivery; a stale redelivery carries an older
            # contrib — each has its own broken-window test switch
            ra_beyond = {i for i in beyond if filtered[i][2] == rnd}
            stale_beyond = beyond - ra_beyond
            if stale_beyond and not _WINDOW_ACCEPTS_BEYOND_K:
                return remote, None, "stale round echo refused", False
            if ra_beyond and not _WINDOW_ACCEPTS_BEYOND_RUN_AHEAD:
                return remote, None, "stale round echo refused", False
            if ra_beyond and _WINDOW_ACCEPTS_BEYOND_RUN_AHEAD:
                for i in sorted(ra_beyond):
                    lag = rnd - filtered[i][4]
                    self._emit(
                        ModelCheck.STALE_CONTRIBUTION,
                        self._anchor("lockstep", self.ir.remote),
                        f"the reduce consumes site_{i}'s fresh round-{rnd} "
                        f"contribution computed {lag} broadcasts behind the "
                        f"stamp — beyond the combined k + d window: the "
                        "run-ahead horizon is unbounded and the staleness "
                        "discount no longer covers the broadcast lag",
                        scenario, trace, "bounded broadcast lag",
                    )

        if phase not in self.ir.remote.tested_phases:
            fallthrough = self.ir.remote.phase_fallthrough
            if rnd > 1 and fallthrough == "init_runs":
                self._emit(
                    ModelCheck.PHASE_RESET, self._remote_phase_anchor(),
                    f"phase '{phase}' reaches the aggregator but its "
                    "dispatch never tests it: the round falls through and "
                    "the echoed INIT_RUNS default silently resets the run",
                    scenario, trace, "no lifecycle reset",
                )
                return remote, None, None, False
            executed = []
        else:
            executed = [phase]

        msg_keys = set()
        for out in filtered.values():
            msg_keys |= out[1]
        shell = (True, 0, 0, r_cache, r_any, False, None)
        steady = phase == "computation" and reduces > 0
        produced, r_cache, r_any = self._exec_events(
            self.ir.remote, shell, executed, phase, msg_keys, steady,
            scenario, trace,
        )

        reduced = False
        if phase == "computation" and filtered and all(
            "reduce" in out[1] for out in filtered.values()
        ):
            reduced = True
            if facts.quorum_checked and quorum:
                pass  # unmet quorum already aborted above
            elif not facts.quorum_checked and len(site_outs) < len(roster):
                self._emit(
                    ModelCheck.QUORUM, self._anchor("reduce_input"),
                    f"the reduce proceeds with {len(site_outs)} of "
                    f"{len(roster)} sites and no quorum policy was ever "
                    "evaluated (the quorum check is missing from the "
                    "aggregator's round path)",
                    scenario, trace, "quorum soundness",
                )
            for i, out in sorted(reducer_input.items()):
                contrib = out[2]
                if "reduce" not in out[1]:
                    continue
                if contrib and contrib < rnd:
                    if i not in dropped and rnd - contrib <= window:
                        # in-window stand-in of a LIVE site: the window-
                        # relaxed exactly-once contract accepts it (the
                        # reducer's staleness discount weights it down) —
                        # only contributions OLDER than the window, or a
                        # dropped site's redelivery, violate
                        continue
                    if i in dropped:
                        anchor, why = self._anchor("reduce_input"), (
                            "the reducer's input snapshot is taken before "
                            "the quorum filtering runs, so a dropped "
                            "site's redelivered output is silently "
                            "double-counted into the global average"
                        )
                    else:
                        anchor, why = self._anchor(
                            "lockstep", self.ir.remote
                        ), (
                            "a delayed duplicate of an earlier message "
                            "from a live site carries the same phase as a "
                            "fresh one — only a round stamp echoed on the "
                            "wire (LocalWire.ROUND) can reject it"
                        )
                    self._emit(
                        ModelCheck.STALE_CONTRIBUTION, anchor,
                        f"the reduce consumes a stale round-{contrib} "
                        f"gradient payload from site_{i} in round {rnd}: "
                        + why,
                        scenario, trace, "exactly-once contributions",
                    )
            for i, out in sorted(filtered.items()):
                if out[2] == rnd and "reduce" in out[1] and (
                    i not in reducer_input
                ):
                    self._emit(
                        ModelCheck.LOST_CONTRIBUTION,
                        self._anchor("reduce_input"),
                        f"site_{i}'s fresh round-{rnd} gradient "
                        "contribution is dropped from the reduce while "
                        "the site is alive and participating",
                        scenario, trace, "exactly-once contributions",
                    )
            # ---- roster soundness (ISSUE 15): nothing from a non-member
            # epoch may enter the reduce
            if elastic:
                for i, out in sorted(reducer_input.items()):
                    if "reduce" not in out[1]:
                        continue
                    adm_i = adm_eff.get(i)
                    echo = out[5] if len(out) > 5 else None
                    if adm_i is None:
                        self._emit(
                            ModelCheck.ROSTER,
                            self._anchor("membership_filter",
                                         self.ir.remote),
                            f"the reduce consumes a payload from site_{i}, "
                            "which is NOT a roster member this round (a "
                            "gracefully retired site's late duplicate) — "
                            "only the membership filter can refuse it: the "
                            "quorum machinery never dropped the site, so "
                            "the reappeared-site filtering does not apply",
                            scenario, trace, "no non-member contributions",
                        )
                    elif echo is not None and int(echo) < int(adm_i):
                        self._emit(
                            ModelCheck.ROSTER,
                            self._anchor("membership_filter",
                                         self.ir.remote),
                            f"the reduce consumes a payload from site_{i} "
                            f"whose roster-epoch echo ({int(echo)}) "
                            "predates the site's current admission "
                            f"(epoch {int(adm_i)}) — a redelivery out of "
                            "its previous, dead incarnation double-counts "
                            "against the fresh one",
                            scenario, trace, "epoch-refused incarnations",
                        )
                # a joiner admitted at round r contributes to round r+1's
                # reduce exactly once — never to round r's
                for i in sorted(admitted_now & set(reducer_input)):
                    if reducer_input[i][2] == rnd:
                        self._emit(
                            ModelCheck.ADMISSION,
                            self._anchor("admission", self.ir.local),
                            f"site_{i} is admitted and contributes to the "
                            f"SAME round-{rnd} reduce: the admission "
                            "handshake requires the joiner's first "
                            "contribution in round r+1 (exactly once) — "
                            "an admission-round contribution lands twice "
                            "around the r/r+1 boundary",
                            scenario, trace, "joiner exactly-once",
                        )
                due = {
                    i for i, s in enumerate(sites)
                    if s[7] is not None and s[0] and s[8] == rnd
                }
                targeted = {e[2] for e in trace.entries if e[0] == rnd}
                for i in sorted(due - set(site_outs) - targeted):
                    self._emit(
                        ModelCheck.ADMISSION,
                        self._anchor("admission", self.ir.local),
                        f"site_{i} was admitted last round and its first "
                        f"contribution is due in round {rnd}, but the "
                        "engine never invoked it and no fault explains "
                        "the absence — the admission was lost",
                        scenario, trace, "joiner exactly-once",
                    )
                if admitted_now and not facts.admission_exactly_once:
                    self._emit(
                        ModelCheck.ADMISSION,
                        self._anchor("admission", self.ir.local),
                        "the local join entry is not exactly-once: no "
                        "negated cache sentinel guards the admission "
                        "block, so a retry (or a re-broadcast admission "
                        "record) re-runs the fold entry and resets the "
                        "joiner's training state mid-run",
                        scenario, trace, "joiner exactly-once",
                    )

        # broadcast phase per the executed dispatch
        if executed:
            block = self.ir.remote.blocks.get(phase)
            outgoing = [p for p in (block.outgoing if block else ())]
            out_phase = outgoing[0] if len(outgoing) >= 1 else phase
            if phase == "computation":
                out_phase = "computation"
        else:
            out_phase = phase  # covered: non-reset fallthrough (round 1)
        update_tag = rnd if reduced else (bcast[2] if bcast else 0)
        keys = frozenset(produced)
        # the broadcast carries the POST-admission epoch (the stamp every
        # site echoes back); graceful-leave bumps land in _step, after the
        # reduce counted the leaver's final contribution
        remote = (r_cache, r_any, dropped, epoch_out)
        return (remote, (out_phase, keys, update_tag, reduced, epoch_out),
                None, reduced)

    # ---------------------------------------------------------------- rounds
    def _round_actions(self, state, scenario):
        """(fault singles, membership singles) available this round, each
        sorted.  The ``staleness_k`` action only exists in scenarios whose
        window is positive — at k=0 the async engine never stands a site
        in.  Membership transitions spend the separate membership budget
        and are scheduled only in elastic scenarios, in the steady state
        (a COMPUTATION broadcast is out), on trees whose aggregator runs
        the membership round step."""
        rnd, budget, sites, chans, remote, bcast, reduces = state[:7]
        mem_budget = state[7] if len(state) > 7 else 0
        elastic = len(scenario) > 4 and bool(scenario[4])
        actions = []
        if budget > 0:
            for i, site in enumerate(sites):
                if not site[0]:
                    continue
                adm, first_rnd = site[7], site[8]
                if adm is None:
                    # a retired (left) site is never invoked again: only
                    # its late duplicate exists (the non-member refusal
                    # path the membership filter patrols)
                    if elastic and site[6] is not None and (
                        "stale" in self.config.kinds
                    ):
                        actions.append(("stale", i))
                    continue
                if first_rnd > rnd:
                    continue  # joining grace: not invocable yet
                for kind in self.config.kinds:
                    if kind in _MEMBERSHIP_ACTIONS:
                        continue  # scheduled from the membership budget
                    if kind in ("drop_relay", "duplicate_delivery"):
                        for comp in _COMPONENTS:
                            actions.append((kind, i, comp))
                    elif kind in _STALE_ACTIONS:
                        if site[6] is None:
                            continue
                        if kind == "staleness_k" and not scenario[2]:
                            continue
                        actions.append((kind, i))
                    elif kind == "run_ahead":
                        # only in scenarios with a positive pipeline depth,
                        # and only once the site has an output whose
                        # consumed broadcast the echo can stay pinned at
                        if site[6] is None:
                            continue
                        if len(scenario) < 4 or not scenario[3]:
                            continue
                        actions.append((kind, i))
                    else:
                        actions.append((kind, i))
        mems = []
        steady = bcast is not None and bcast[0] == "computation"
        if (elastic and mem_budget > 0 and steady
                and self.ir.facts.membership_checked):
            members_alive = [
                i for i, s in enumerate(sites) if s[7] is not None and s[0]
            ]
            for i, site in enumerate(sites):
                adm = site[7]
                if site[0] and adm is None:
                    # never admitted → join; gracefully left → rejoin
                    kind = ("join" if site[6] is None and not site[5]
                            else "rejoin")
                    if kind in self.config.kinds:
                        mems.append((kind, i))
                elif not site[0] and adm is not None:
                    # dead member: re-admission with a fresh incarnation
                    if "rejoin" in self.config.kinds:
                        mems.append(("rejoin", i))
                elif (adm is not None and site[8] <= rnd
                        and len(members_alive) > 1
                        and "leave" in self.config.kinds):
                    mems.append(("leave", i))
        return sorted(actions), sorted(mems)

    def _step(self, state, actions, scenario, trace):
        """Execute one engine round under ``actions``.  Returns the new
        state, or None when the trace terminated (loudly or at bound)."""
        rnd, budget, sites, chans, remote, bcast, reduces = state[:7]
        mem_budget = state[7] if len(state) > 7 else 0
        epoch_prev = remote[3] if len(remote) > 3 else 1
        quorum = scenario[0]
        trace = trace.extend(rnd, actions)
        mem_actions = [a for a in actions if a[0] in _MEMBERSHIP_ACTIONS]
        budget -= len(actions) - len(mem_actions)
        mem_budget -= len(mem_actions)

        site_outs, stale_flags = {}, {}
        new_sites, new_chans = list(sites), list(chans)
        for i in range(len(sites)):
            site, chan, out, loud = self._site_round(
                i, sites[i], chans[i], bcast, actions, scenario, trace,
                rnd, quorum,
            )
            new_sites[i], new_chans[i] = site, chan
            if loud:
                self.report["terminal_loud"] += 1
                return None
            if out is not None:
                site_outs[i] = out
                stale_flags[i] = not out[3]

        # reappear redeliveries (death fired one round earlier)
        for i, site in enumerate(new_sites):
            if not site[0] and site[1] == rnd and site[6] is not None:
                phase, keys, contrib, _, made = site[6][:5]
                echo_e = site[6][5] if len(site[6]) > 5 else None
                site_outs[i] = (phase, keys, contrib, False, made, echo_e)
                stale_flags[i] = True
                new_sites[i] = site[:1] + (0,) + site[2:]

        # a rejoin of a DEAD site races its old incarnation's redelivery
        # into the very round the re-admission is processed — the
        # worst-case interleaving the roster epoch exists to refuse
        for kind, i in mem_actions:
            if (kind == "rejoin" and not new_sites[i][0]
                    and new_sites[i][6] is not None and i not in site_outs):
                last = new_sites[i][6]
                phase, keys, contrib, _, made = last[:5]
                echo_e = last[5] if len(last) > 5 else None
                site_outs[i] = (phase, keys, contrib, False, made, echo_e)
                stale_flags[i] = True
        if _JOIN_CONTRIBUTES_IN_ADMISSION_ROUND:
            # broken-engine semantics (tests only): the joiner is invoked
            # in the round its admission is processed — one round early
            for kind, i in mem_actions:
                if kind in ("join", "rejoin") and i not in site_outs:
                    site_outs[i] = ("computation", frozenset({"reduce"}),
                                    rnd, True, rnd, None)
                    stale_flags[i] = False

        if not site_outs:
            self.report["terminal_loud"] += 1
            return None

        remote, new_bcast, loud, reduced = self._remote_round(
            (rnd, budget, tuple(new_sites), tuple(new_chans), remote,
             bcast, reduces),
            site_outs, stale_flags, scenario, trace,
        )
        if loud:
            self.report["terminal_loud"] += 1
            return None
        if new_bcast is None:
            # a violating fall-through already emitted; stop the trace
            return None
        out_phase, keys, update_tag, reduced, epoch_bcast = new_bcast
        if out_phase == "success":
            self.report["terminal_success"] += 1
            return None

        # ---- apply this round's roster transitions (ISSUE 15): the
        # aggregator admitted joiners at the top of its round (the epoch
        # bumps already ride the broadcast) and retires leavers AFTER the
        # reduce counted their flagged final contribution
        epoch_now = epoch_bcast
        dropped_set = set(remote[2])
        pos = 0
        for kind, i in mem_actions:
            if kind in ("join", "rejoin"):
                pos += 1
                s = new_sites[i]
                # a fresh incarnation: fresh cache, alive, first
                # contribution due next round; the old incarnation's
                # last output remains only as refusal fodder for late
                # duplicates, and its previous drop no longer applies
                new_sites[i] = (True, 0, 0, frozenset(), False, False,
                                s[6], epoch_prev + pos, rnd + 1)
                dropped_set.discard(i)
            elif kind == "leave" and i in site_outs and not stale_flags.get(
                i
            ):
                # the flagged final contribution was delivered fresh and
                # counted — retire the member (never a dropped site)
                s = new_sites[i]
                new_sites[i] = s[:7] + (None, 0)
                epoch_now += 1
        remote = (remote[0], remote[1], frozenset(dropped_set), epoch_now)

        # relay the broadcast files (the avg payload + its manifest)
        for i, site in enumerate(new_sites):
            if not site[0]:
                continue
            payload, manifest, repairs = new_chans[i]
            if reduced:
                # a fresh relay recopies EVERY file, so damage from earlier
                # rounds that nothing loaded in between heals naturally;
                # only faults fired THIS round leave stale components
                fresh = rnd
                repairs = {
                    a[2] for a in actions
                    if a[0] in ("drop_relay", "duplicate_delivery")
                    and a[1] == i
                }
                if "payload" not in repairs:
                    payload = fresh
                if "manifest" not in repairs:
                    manifest = fresh
                new_chans[i] = (payload, manifest, frozenset(repairs))
        return (
            rnd + 1, budget, tuple(new_sites), tuple(new_chans), remote,
            (out_phase, keys, update_tag, epoch_bcast),
            reduces + (1 if reduced else 0),
            mem_budget,
        )

    # ------------------------------------------------------------ exploration
    def explore(self):
        for quorum in self.config.quorums:
            for pretrain in self.config.pretrain:
                for k in self.config.staleness:
                    for d_ra in self.config.run_ahead:
                        for el in self.config.elastic:
                            if el and not (
                                ModelCheck.DEFAULT_ELASTIC
                                and self.ir.facts.membership_checked
                            ):
                                # the tree has no membership round step —
                                # there is no roster to churn
                                continue
                            self._explore_scenario(
                                (quorum, pretrain, int(k), int(d_ra),
                                 bool(el))
                            )
        findings = [f for f, _ in self.findings.values()]
        plans = [p for _, p in self.findings.values()]
        order = sorted(
            range(len(findings)),
            key=lambda ix: (findings[ix].path, findings[ix].line,
                            findings[ix].rule),
        )
        return [findings[ix] for ix in order], [plans[ix] for ix in order]

    def _explore_scenario(self, scenario):
        elastic = len(scenario) > 4 and bool(scenario[4])
        frontier = collections.deque(
            [(_initial_state(self.config, elastic), _Trace())]
        )
        visited = set()
        bound = self.config.engine_rounds
        while frontier:
            state, trace = frontier.popleft()
            if state in visited:
                continue
            visited.add(state)
            self.report["states"] += 1
            if self.report["states"] > MAX_STATES:
                self._emit(
                    ModelCheck.CONFIG, (self.ir.remote.path, 1),
                    f"state-space ceiling ({MAX_STATES}) exceeded — shrink "
                    "--model-sites/--model-rounds/--model-faults",
                    scenario, trace, "bounded exploration",
                )
                return
            rnd = state[0]
            if rnd > bound:
                self.report["runs"] += 1
                if state[6] == 0:
                    self._emit(
                        ModelCheck.DEADLOCK, self._remote_phase_anchor(),
                        f"the bounded run finishes {bound} engine rounds "
                        "with ZERO reduces and no loud failure — the "
                        "federation is silently wedged (a dispatch "
                        "transition is missing or a barrier never "
                        "releases)",
                        scenario, trace, "deadlock freedom",
                    )
                continue
            singles, mem_singles = self._round_actions(state, scenario)
            subsets = [()]
            # the whole remaining budget may be spent in ONE round: the
            # --model-faults contract is the simultaneous-fault tolerance
            # level verified, so no silent per-round cap
            for k in range(1, state[1] + 1):
                subsets.extend(itertools.combinations(singles, k))
            mem_subsets = [()]
            mem_budget = state[7] if len(state) > 7 else 0
            for k in range(1, mem_budget + 1):
                mem_subsets.extend(itertools.combinations(mem_singles, k))
            for fault_actions in subsets:
                for mem_actions in mem_subsets:
                    actions = fault_actions + mem_actions
                    nxt = self._step(state, actions, scenario, trace)
                    if nxt is not None:
                        frontier.append(
                            (nxt, trace.extend(state[0], actions))
                        )


# ------------------------------------------------------------- wire property
def _wire_findings(ir, explorer, config):
    """Every wire key produced on an explored path must be consumed (with
    the payload actually present) on some explored path of the peer."""
    findings, plans = [], []
    consumed = {(role, key) for role, key in explorer.report["consumed"]}
    peers = {"local": "remote", "remote": "local"}
    seen = set()
    for role, key, line in sorted(explorer.report["produced"]):
        if key in ("phase",) or (role, key) in seen:
            continue
        seen.add((role, key))
        if (peers[role], key) not in consumed:
            node = ir.local if role == "local" else ir.remote
            findings.append(Finding(
                rule=ModelCheck.WIRE, path=node.path, line=line, col=0,
                message=(
                    f"wire key '{key}' is produced on an explored path "
                    f"({role} side) but the peer never consumes it on ANY "
                    f"reachable execution of the bounded model "
                    f"({config.sites} sites x {config.rounds} rounds) — "
                    "the payload is computed and shipped into a round "
                    "that cannot see it"
                ),
            ))
            plans.append({
                "rule": ModelCheck.WIRE, "invariant": "wire reachability",
                "scenario": {"n_sites": config.sites,
                             "engine_rounds": config.engine_rounds},
                "faults": [],
            })
    return findings, plans


# --------------------------------------------------------------- entry point
def run_model_check(config=None, ir=None, plans_dir=None):
    """Explore the bounded model; returns a :class:`ModelResult` whose
    findings flow through the same baseline machinery as tiers 1–3."""
    config = config or ModelConfig()
    try:
        if ir is None:
            ir = build_protocol_ir()
    except (OSError, SyntaxError, ValueError) as exc:
        f = Finding(
            rule=ModelCheck.CONFIG, path="coinstac_dinunet_tpu", line=1,
            col=0, message=f"protocol IR extraction failed: {exc}",
        )
        return ModelResult([f], [None], {})
    explorer = _Explorer(ir, config)
    findings, plans = explorer.explore()
    wf, wp = _wire_findings(ir, explorer, config)
    findings, plans = findings + wf, plans + wp
    order = sorted(
        range(len(findings)),
        key=lambda ix: (findings[ix].path, findings[ix].line,
                        findings[ix].rule),
    )
    findings = [findings[ix] for ix in order]
    plans = [plans[ix] for ix in order]
    if plans_dir:
        os.makedirs(plans_dir, exist_ok=True)
        for n, (f, plan) in enumerate(zip(findings, plans)):
            if not plan:
                continue
            name = f"{f.rule}-{n:02d}.json"
            with open(os.path.join(plans_dir, name), "w",
                      encoding="utf-8") as fh:
                json.dump(plan, fh, indent=2, sort_keys=True)
                fh.write("\n")
    report = dict(explorer.report)
    report["produced"] = sorted(report["produced"])
    report["consumed"] = sorted(report["consumed"])
    report["confirmed_cache"] = sorted(report["confirmed_cache"])
    report["exercised_reads"] = sorted(report["exercised_reads"])
    report["phases_covered"] = sorted(
        (r, p or "<unguarded>") for r, p in report["phases_covered"]
    )
    return ModelResult(findings, plans, report)
