"""dinulint rule engine: findings, rule registry, baseline, suppressions.

Design (mirrors the shape of flake8/ruff internals at 1% of the size):

- A :class:`Rule` sees one parsed module at a time (``visit_module``) — the
  jax-api-drift and trace-hazard families live here.
- A :class:`ProjectRule` additionally gets a ``finalize`` pass over ALL
  scanned modules — protocol conformance needs the producer AND consumer
  files together before it can report an unmatched key.
- Findings are matched against a checked-in **baseline** by a line-number-free
  fingerprint ``(rule, path, message)`` so legacy findings never block CI
  while anything new does.  ``--write-baseline`` refreshes it.
- Inline escapes: a ``# dinulint: disable=rule-id[,rule-id...]`` comment
  suppresses on that source line; a ``# dinulint: disable-file=rule-id[,...]``
  comment anywhere in a file suppresses the rule(s) for the whole file.
  Only real comment tokens count — a docstring that merely *documents* the
  syntax (like this one) activates nothing.

The engine is pure stdlib ``ast`` — no JAX import, so a whole-package run
stays in the tens of milliseconds and is safe inside any CI container.
"""
import ast
import dataclasses
import io
import json
import os
import re
import tokenize

_SUPPRESS_LINE = re.compile(r"#\s*dinulint:\s*disable=([\w,\-]+)")
_SUPPRESS_FILE = re.compile(r"#\s*dinulint:\s*disable-file=([\w,\-]+)")


def dotted_name(node, require_name_root=True):
    """Attribute/Name chain → dotted string (shared by the rule families).

    With ``require_name_root`` (the default) returns None unless the chain
    bottoms out at a plain Name — the alias-resolution contract jax-api-drift
    needs.  Without it, joins whatever attribute tail is resolvable (``''``
    for none): the display-name contract the trace-hazard rules need for
    expressions like ``self.nn["net"].apply`` → ``"apply"``.
    """
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif require_name_root:
        return None
    return ".".join(reversed(parts))


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""
    rule: str
    path: str
    line: int
    col: int
    message: str

    def fingerprint(self):
        """Line-free identity used for baseline matching (survives edits
        elsewhere in the file)."""
        return (self.rule, self.path.replace(os.sep, "/"), self.message)

    def to_dict(self):
        return dataclasses.asdict(self)

    def render(self):
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"


class Module:
    """One parsed source file handed to rules."""

    def __init__(self, path, source, tree):
        self.path = path
        self.source = source
        self.tree = tree
        self.lines = source.splitlines()
        self._comments = None

    def comments(self):
        """line -> comment text, via ``tokenize`` so string literals that
        merely mention the suppression syntax cannot activate it."""
        if self._comments is None:
            found = {}
            try:
                tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
                for tok in tokens:
                    if tok.type == tokenize.COMMENT:
                        found[tok.start[0]] = tok.string
            except (tokenize.TokenError, IndentationError, SyntaxError):
                pass  # unparsable tail: keep the comments seen so far
            self._comments = found
        return self._comments

    @classmethod
    def parse(cls, path, display_path=None):
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
        return cls(display_path or path, source, tree)


class Rule:
    """Per-module rule.  Subclasses set ``id``/``doc`` and implement
    :meth:`visit_module` returning an iterable of findings."""

    id = "abstract"
    doc = ""

    def visit_module(self, module):  # pragma: no cover - interface
        return []


class ProjectRule(Rule):
    """Whole-project rule: ``visit_module`` collects, ``finalize`` reports."""

    def visit_module(self, module):
        return []

    def finalize(self, modules):  # pragma: no cover - interface
        return []


_REGISTRY = {}


def register_rule(cls):
    """Class decorator adding a rule to the default rule set."""
    _REGISTRY[cls.id] = cls
    return cls


def default_rules():
    """Fresh instances of every registered rule, importing the built-in rule
    modules on first use (registration happens at import)."""
    from . import (  # noqa: F401 (register)
        jax_api,
        protocol,
        sharding,
        telemetry_names,
        trace_hazards,
        wire_atomic,
    )

    return [cls() for _, cls in sorted(_REGISTRY.items())]


def _suppressed(finding, module_by_path):
    mod = module_by_path.get(finding.path)
    if mod is None:
        return False
    comments = mod.comments()
    for text in comments.values():
        m = _SUPPRESS_FILE.search(text)
        if m and finding.rule in m.group(1).split(","):
            return True
    m = _SUPPRESS_LINE.search(comments.get(finding.line, ""))
    if m and finding.rule in m.group(1).split(","):
        return True
    return False


def iter_python_files(paths):
    """Expand directories into their .py files (stable order, deduped).
    Explicitly listed files are always included regardless of extension —
    silently skipping one would report a clean exit for a path that was
    never scanned."""
    seen, out = set(), []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git", ".venv", "node_modules")
                )
                for name in sorted(files):
                    if name.endswith(".py"):
                        fp = os.path.join(root, name)
                        if fp not in seen:
                            seen.add(fp)
                            out.append(fp)
        elif p not in seen:
            seen.add(p)
            out.append(p)
    return out


def run_lint(paths, rules=None, rule_ids=None):
    """Lint ``paths`` (files or directories).

    Returns ``(findings, errors)`` — ``errors`` are files that failed to
    parse (reported, never crash the run).  ``rule_ids`` filters the default
    rule set by id.
    """
    rules = list(rules) if rules is not None else default_rules()
    if rule_ids:
        wanted = set(rule_ids)
        rules = [r for r in rules if r.id in wanted]
    files = iter_python_files(paths)
    modules, errors = [], []
    for path in files:
        display = os.path.relpath(path).replace(os.sep, "/")
        try:
            modules.append(Module.parse(path, display))
        except (SyntaxError, UnicodeDecodeError, OSError, ValueError) as exc:
            # ValueError: ast.parse on source with NUL bytes
            errors.append((display, f"{type(exc).__name__}: {exc}"))
    findings = []
    for mod in modules:
        for rule in rules:
            findings.extend(rule.visit_module(mod))
    for rule in rules:
        if isinstance(rule, ProjectRule):
            findings.extend(rule.finalize(modules))
    module_by_path = {m.path: m for m in modules}
    findings = [f for f in findings if not _suppressed(f, module_by_path)]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, errors


# ------------------------------------------------------------------ baseline
def load_baseline(path):
    """Baseline file → fingerprint → allowed count."""
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    counts = {}
    for entry in data.get("findings", []):
        fp = (entry["rule"], entry["path"], entry["message"])
        counts[fp] = counts.get(fp, 0) + int(entry.get("count", 1))
    return counts


def write_baseline(path, findings, extra_entries=()):
    """Write the baseline for ``findings``; ``extra_entries`` are existing
    baseline entry dicts to carry over verbatim — the CLI uses this to
    preserve a tier's accepted findings when a refresh didn't run that tier
    (a static-only ``--write-baseline`` must not drop ``deep-*`` entries)."""
    grouped = {}
    for f in findings:
        grouped[f.fingerprint()] = grouped.get(f.fingerprint(), 0) + 1
    for entry in extra_entries:
        fp = (entry["rule"], entry["path"], entry["message"])
        grouped[fp] = grouped.get(fp, 0) + int(entry.get("count", 1))
    entries = [
        {"rule": rule, "path": p, "message": msg, "count": n}
        for (rule, p, msg), n in sorted(grouped.items())
    ]
    payload = {
        "comment": (
            "dinulint baseline: legacy findings that do not fail CI.  "
            "Refresh with: dinulint <paths> --tier3 --deep --model --tier5 "
            "--write-baseline --baseline " + os.path.basename(path)
        ),
        "findings": entries,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")


def filter_baselined(findings, baseline_counts):
    """Split findings into (new, baselined) honoring per-fingerprint counts."""
    budget = dict(baseline_counts)
    new, baselined = [], []
    for f in findings:
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            baselined.append(f)
        else:
            new.append(f)
    return new, baselined
