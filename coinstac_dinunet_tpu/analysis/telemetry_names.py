"""telemetry-metric-name: health-series / anomaly names must come from the
``config/keys.py`` vocabulary.

Metric and anomaly names are the health layer's wire protocol: the watchdog
routes a sample to its detectors by STRING EQUALITY on the series name, and
the doctor's per-site attribution keys on the same strings.  A typo'd name
(``record_metric("gradnorm", ...)``) doesn't crash anything — it silently
produces a series no detector watches and no report aggregates, which is the
exact failure mode the :class:`~..config.keys.Metric`/:class:`~..config.keys.
Anomaly` vocabulary exists to prevent.  Same machinery as the ``sharding-*``
family: the vocabulary is *parsed* out of ``config/keys.py`` (never
imported), ``Metric.X``/``Anomaly.X`` attribute spellings resolve through
it, and fixture tests can substitute a ``keys_source``.

Checked call shapes (names resolvable statically only — dynamic names are
the caller's problem):

- ``record_metric(NAME, ...)`` / ``health.record_metric(NAME, ...)`` — NAME
  must be a declared **metric**.
- ``<recorder>.metric(NAME, ...)`` where ``<recorder>`` is a conventional
  recorder binding (``rec``/``recorder``/``tracer``/``telemetry``) or a
  chained factory call (``get_active().metric(...)``) — declared metric.
- ``register_detector(ANOMALY[, metric=METRIC])`` — ANOMALY must be a
  declared **anomaly**, METRIC (when present and not None) a declared
  metric.
- ``<watchdog>.observe(NAME, ...)`` on a ``Watchdog(...)`` chain or a
  ``wd``/``watchdog`` binding — declared metric.
- ``<recorder>.event(Live.X, ...)`` / any ``Live.X`` spelling — the member
  must exist in the :class:`~..config.keys.Live` vocabulary (event-name
  literals stay free-form; only member spellings are resolvable).

The rule additionally validates the VOCABULARY DEFINITION itself, in any
module that defines a ``Live`` class (in this repo: ``config/keys.py``):

- ``Live`` values must use the legal telemetry-name charset, and
  ``Live.HEARTBEAT`` must keep its load-bearing ``engine:`` prefix (the
  live tailer keys site liveness on it).
- ``Live.PROM_PREFIX``, every ``Live.VERDICT_*`` kind and every ``Metric``
  value must already be legal Prometheus metric-name material
  (``[a-z_][a-z0-9_]*``) — the exporter's name mapping
  (``telemetry/serve.py::prometheus_name``) must be the identity plus the
  prefix, never a mangling (a mangled name silently breaks every deployed
  dashboard that scraped the old spelling).
"""
import ast
import os
import re

from .core import Finding, Rule, dotted_name, register_rule

METRIC_CLASS = "Metric"
ANOMALY_CLASS = "Anomaly"
LIVE_CLASS = "Live"

#: legal Prometheus metric-name fragment — the exporter mapping must be the
#: identity on every declared series/verdict/prefix
_PROM_NAME_RE = re.compile(r"^[a-z_][a-z0-9_]*$")
#: legal telemetry event/cache-key charset (colon namespaces event names)
_TELEMETRY_NAME_RE = re.compile(r"^[a-z_][a-z0-9_:.]*$")
#: the heartbeat event's stable prefix (telemetry/live.py keys on it)
_HEARTBEAT_PREFIX = "engine:"

_RECORDER_ROOTS = {"rec", "recorder", "telemetry", "tracer"}
_WATCHDOG_ROOTS = {"wd", "watchdog"}
_FACTORY_SEGMENTS = {"get_active", "for_node"}


def _keys_module_path():
    return os.path.normpath(
        os.path.join(os.path.dirname(__file__), "..", "config", "keys.py")
    )


def load_name_vocab(keys_source=None):
    """Parse ``config/keys.py`` into ``{class_name: {member: value}}`` for
    the :class:`Metric`, :class:`Anomaly` and :class:`Live` vocabularies."""
    if keys_source is None:
        with open(_keys_module_path(), "r", encoding="utf-8") as f:
            keys_source = f.read()
    tree = ast.parse(keys_source)
    vocab = {METRIC_CLASS: {}, ANOMALY_CLASS: {}, LIVE_CLASS: {}}
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name in vocab:
            for stmt in node.body:
                if (
                    isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, str)
                ):
                    vocab[node.name][stmt.targets[0].id] = stmt.value.value
    return vocab


def _last(name):
    return name.rsplit(".", 1)[-1] if name else ""


def _resolve_name(node, vocab):
    """expr → ``(value_or_None, kind)``.

    kind: ``'literal'`` (string constant), ``'member'`` (``Metric.X`` /
    ``Anomaly.X`` — value None when the member does not exist), ``'none'``
    (the ``None`` constant), or ``'dynamic'`` (unresolvable)."""
    if isinstance(node, ast.Constant):
        if node.value is None:
            return None, "none"
        if isinstance(node.value, str):
            return node.value, "literal"
        return None, "dynamic"
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    parts.reverse()
    if len(parts) >= 2 and parts[-2] in (METRIC_CLASS, ANOMALY_CLASS,
                                         LIVE_CLASS):
        return vocab[parts[-2]].get(parts[-1]), "member"
    return None, "dynamic"


def _chain_root(node):
    """Innermost Name/Call of an attribute chain (``a.b.c`` → ``a``)."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node


def _is_recorder_expr(expr):
    """True when ``expr`` conventionally holds a recorder: a known binding
    name, anything mentioning telemetry, or a factory-call chain."""
    if isinstance(expr, ast.Name):
        return expr.id.lower() in _RECORDER_ROOTS or "telemetry" in expr.id.lower()
    if isinstance(expr, ast.Attribute):
        name = (dotted_name(expr, require_name_root=False) or "").lower()
        segs = name.split(".")
        return (
            "telemetry" in name
            or (segs and segs[0] in _RECORDER_ROOTS)
            or (segs and segs[-1] in _RECORDER_ROOTS)
        )
    if isinstance(expr, ast.Call):
        name = (dotted_name(expr.func, require_name_root=False) or "").lower()
        return _last(name) in _FACTORY_SEGMENTS or "telemetry" in name
    return False


def _is_watchdog_expr(expr):
    if isinstance(expr, ast.Name):
        return expr.id.lower() in _WATCHDOG_ROOTS
    if isinstance(expr, ast.Call):
        name = dotted_name(expr.func, require_name_root=False) or ""
        return _last(name) == "Watchdog"
    if isinstance(expr, ast.Attribute):
        name = (dotted_name(expr, require_name_root=False) or "").lower()
        return _last(name) in _WATCHDOG_ROOTS
    return False


@register_rule
class TelemetryMetricNameRule(Rule):
    id = "telemetry-metric-name"
    doc = ("Metric/anomaly names in record_metric()/Recorder.metric()/"
           "Watchdog.observe() calls and register_detector() registrations "
           "must come from the config/keys.py Metric/Anomaly vocabulary "
           "(typos make silently-unwatched series); Live vocabulary members "
           "must exist, keep the heartbeat's engine: prefix, and stay legal "
           "under the Prometheus metric-name mapping of telemetry/serve.py.")

    def __init__(self, keys_source=None):
        self._keys_source = keys_source
        self._vocab = None

    def vocab(self):
        if self._vocab is None:
            self._vocab = load_name_vocab(self._keys_source)
        return self._vocab

    # ----------------------------------------------------------------- checks
    def _check_name(self, module, node, which):
        """One name argument against vocabulary ``which``; returns the
        finding or None."""
        vocab = self.vocab()
        values = set(vocab[which].values())
        resolved, kind = _resolve_name(node, vocab)
        if kind in ("none", "dynamic"):
            return None
        if kind == "member" and resolved is None:
            dotted = dotted_name(node, require_name_root=False) or ""
            segs = dotted.split(".")
            cls = segs[-2] if len(segs) >= 2 else which
            return Finding(
                rule=self.id, path=module.path, line=node.lineno,
                col=node.col_offset,
                message=f"unknown {cls} member '{dotted}' — "
                        f"declare it in config/keys.py {cls}",
            )
        if resolved not in values:
            return Finding(
                rule=self.id, path=module.path, line=node.lineno,
                col=node.col_offset,
                message=f"{which.lower()} name '{resolved}' is not declared "
                        f"in the config/keys.py {which} vocabulary "
                        f"(known: {', '.join(sorted(values))})",
            )
        return None

    def _first_arg(self, call, kwarg=None):
        if call.args and not isinstance(call.args[0], ast.Starred):
            return call.args[0]
        if kwarg:
            for kw in call.keywords:
                if kw.arg == kwarg:
                    return kw.value
        return None

    # ------------------------------------------------- vocabulary definition
    def _definition_findings(self, module):
        """Validate the vocabulary DEFINITION in a module that defines a
        ``Live`` class (config/keys.py; fixture modules in tests): event
        prefix stability, telemetry-name charset, and the Prometheus
        mapping's identity requirement for ``Metric``/``PROM_PREFIX``/
        ``VERDICT_*`` values."""
        classes = {
            node.name: node for node in module.tree.body
            if isinstance(node, ast.ClassDef)
        }
        if LIVE_CLASS not in classes:
            return []
        findings = []

        def members(cls_node):
            for stmt in cls_node.body:
                if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                        and isinstance(stmt.value, ast.Constant)
                        and isinstance(stmt.value.value, str)):
                    yield stmt.targets[0].id, stmt.value.value, stmt

        def bad(stmt, message):
            findings.append(Finding(
                rule=self.id, path=module.path, line=stmt.lineno,
                col=stmt.col_offset, message=message,
            ))

        for member, value, stmt in members(classes[LIVE_CLASS]):
            if member == "HEARTBEAT" and not value.startswith(
                    _HEARTBEAT_PREFIX):
                bad(stmt, f"Live.HEARTBEAT '{value}' must keep the stable "
                          f"'{_HEARTBEAT_PREFIX}' prefix — the live tailer "
                          "keys site liveness on it")
            elif (member == "PROM_PREFIX" or member.startswith("VERDICT_")):
                if not _PROM_NAME_RE.match(value):
                    bad(stmt, f"Live.{member} '{value}' is not legal "
                              "Prometheus metric-name material "
                              "([a-z_][a-z0-9_]*) — the exporter mapping "
                              "(telemetry/serve.py) would mangle it")
            elif not _TELEMETRY_NAME_RE.match(value):
                bad(stmt, f"Live.{member} '{value}' uses characters outside "
                          "the telemetry name charset ([a-z_][a-z0-9_:.]*)")
        if METRIC_CLASS in classes:
            for member, value, stmt in members(classes[METRIC_CLASS]):
                if not _PROM_NAME_RE.match(value):
                    bad(stmt, f"Metric.{member} '{value}' is not a legal "
                              "Prometheus metric-name suffix "
                              "([a-z_][a-z0-9_]*) — the /metrics exporter "
                              "mapping must stay the identity plus the "
                              "prefix")
        return findings

    def visit_module(self, module):
        findings = self._definition_findings(module)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func, require_name_root=False) or ""
            last = _last(name)
            hit = None
            if last == "record_metric":
                arg = self._first_arg(node, kwarg="name")
                if arg is not None:
                    hit = self._check_name(module, arg, METRIC_CLASS)
            elif last == "register_detector":
                arg = self._first_arg(node, kwarg="anomaly")
                if arg is not None:
                    hit = self._check_name(module, arg, ANOMALY_CLASS)
                    if hit:
                        findings.append(hit)
                    hit = None
                metric = None
                if len(node.args) > 1 and not isinstance(node.args[1], ast.Starred):
                    metric = node.args[1]
                else:
                    for kw in node.keywords:
                        if kw.arg == "metric":
                            metric = kw.value
                if metric is not None:
                    hit = self._check_name(module, metric, METRIC_CLASS)
            elif last == "metric" and isinstance(node.func, ast.Attribute):
                if _is_recorder_expr(node.func.value):
                    arg = self._first_arg(node, kwarg="name")
                    if arg is not None:
                        hit = self._check_name(module, arg, METRIC_CLASS)
            elif last == "observe" and isinstance(node.func, ast.Attribute):
                if _is_watchdog_expr(node.func.value):
                    arg = self._first_arg(node, kwarg="name")
                    if arg is not None:
                        hit = self._check_name(module, arg, METRIC_CLASS)
            elif last == "event" and isinstance(node.func, ast.Attribute):
                # event-name LITERALS are free-form; only vocabulary-member
                # spellings are checked (an unknown Live/Metric/Anomaly
                # member is a typo that would emit a name no tailer watches)
                if _is_recorder_expr(node.func.value):
                    arg = self._first_arg(node, kwarg="name")
                    if arg is not None:
                        vocab = self.vocab()
                        resolved, kind = _resolve_name(arg, vocab)
                        if kind == "member" and resolved is None:
                            dotted = dotted_name(
                                arg, require_name_root=False
                            ) or ""
                            cls = dotted.split(".")[-2] if "." in dotted else "?"
                            hit = Finding(
                                rule=self.id, path=module.path,
                                line=arg.lineno, col=arg.col_offset,
                                message=f"unknown {cls} member '{dotted}' "
                                        "in recorder event name — declare "
                                        f"it in config/keys.py {cls}",
                            )
            if hit:
                findings.append(hit)
        return findings
