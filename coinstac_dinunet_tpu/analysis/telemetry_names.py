"""telemetry-metric-name: health-series / anomaly names must come from the
``config/keys.py`` vocabulary.

Metric and anomaly names are the health layer's wire protocol: the watchdog
routes a sample to its detectors by STRING EQUALITY on the series name, and
the doctor's per-site attribution keys on the same strings.  A typo'd name
(``record_metric("gradnorm", ...)``) doesn't crash anything — it silently
produces a series no detector watches and no report aggregates, which is the
exact failure mode the :class:`~..config.keys.Metric`/:class:`~..config.keys.
Anomaly` vocabulary exists to prevent.  Same machinery as the ``sharding-*``
family: the vocabulary is *parsed* out of ``config/keys.py`` (never
imported), ``Metric.X``/``Anomaly.X`` attribute spellings resolve through
it, and fixture tests can substitute a ``keys_source``.

Checked call shapes (names resolvable statically only — dynamic names are
the caller's problem):

- ``record_metric(NAME, ...)`` / ``health.record_metric(NAME, ...)`` — NAME
  must be a declared **metric**.
- ``<recorder>.metric(NAME, ...)`` where ``<recorder>`` is a conventional
  recorder binding (``rec``/``recorder``/``tracer``/``telemetry``) or a
  chained factory call (``get_active().metric(...)``) — declared metric.
- ``register_detector(ANOMALY[, metric=METRIC])`` — ANOMALY must be a
  declared **anomaly**, METRIC (when present and not None) a declared
  metric.
- ``<watchdog>.observe(NAME, ...)`` on a ``Watchdog(...)`` chain or a
  ``wd``/``watchdog`` binding — declared metric.
"""
import ast
import os

from .core import Finding, Rule, dotted_name, register_rule

METRIC_CLASS = "Metric"
ANOMALY_CLASS = "Anomaly"

_RECORDER_ROOTS = {"rec", "recorder", "telemetry", "tracer"}
_WATCHDOG_ROOTS = {"wd", "watchdog"}
_FACTORY_SEGMENTS = {"get_active", "for_node"}


def _keys_module_path():
    return os.path.normpath(
        os.path.join(os.path.dirname(__file__), "..", "config", "keys.py")
    )


def load_name_vocab(keys_source=None):
    """Parse ``config/keys.py`` into ``{class_name: {member: value}}`` for
    the :class:`Metric` and :class:`Anomaly` vocabularies."""
    if keys_source is None:
        with open(_keys_module_path(), "r", encoding="utf-8") as f:
            keys_source = f.read()
    tree = ast.parse(keys_source)
    vocab = {METRIC_CLASS: {}, ANOMALY_CLASS: {}}
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name in vocab:
            for stmt in node.body:
                if (
                    isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, str)
                ):
                    vocab[node.name][stmt.targets[0].id] = stmt.value.value
    return vocab


def _last(name):
    return name.rsplit(".", 1)[-1] if name else ""


def _resolve_name(node, vocab):
    """expr → ``(value_or_None, kind)``.

    kind: ``'literal'`` (string constant), ``'member'`` (``Metric.X`` /
    ``Anomaly.X`` — value None when the member does not exist), ``'none'``
    (the ``None`` constant), or ``'dynamic'`` (unresolvable)."""
    if isinstance(node, ast.Constant):
        if node.value is None:
            return None, "none"
        if isinstance(node.value, str):
            return node.value, "literal"
        return None, "dynamic"
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    parts.reverse()
    if len(parts) >= 2 and parts[-2] in (METRIC_CLASS, ANOMALY_CLASS):
        return vocab[parts[-2]].get(parts[-1]), "member"
    return None, "dynamic"


def _chain_root(node):
    """Innermost Name/Call of an attribute chain (``a.b.c`` → ``a``)."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node


def _is_recorder_expr(expr):
    """True when ``expr`` conventionally holds a recorder: a known binding
    name, anything mentioning telemetry, or a factory-call chain."""
    if isinstance(expr, ast.Name):
        return expr.id.lower() in _RECORDER_ROOTS or "telemetry" in expr.id.lower()
    if isinstance(expr, ast.Attribute):
        name = (dotted_name(expr, require_name_root=False) or "").lower()
        segs = name.split(".")
        return (
            "telemetry" in name
            or (segs and segs[0] in _RECORDER_ROOTS)
            or (segs and segs[-1] in _RECORDER_ROOTS)
        )
    if isinstance(expr, ast.Call):
        name = (dotted_name(expr.func, require_name_root=False) or "").lower()
        return _last(name) in _FACTORY_SEGMENTS or "telemetry" in name
    return False


def _is_watchdog_expr(expr):
    if isinstance(expr, ast.Name):
        return expr.id.lower() in _WATCHDOG_ROOTS
    if isinstance(expr, ast.Call):
        name = dotted_name(expr.func, require_name_root=False) or ""
        return _last(name) == "Watchdog"
    if isinstance(expr, ast.Attribute):
        name = (dotted_name(expr, require_name_root=False) or "").lower()
        return _last(name) in _WATCHDOG_ROOTS
    return False


@register_rule
class TelemetryMetricNameRule(Rule):
    id = "telemetry-metric-name"
    doc = ("Metric/anomaly names in record_metric()/Recorder.metric()/"
           "Watchdog.observe() calls and register_detector() registrations "
           "must come from the config/keys.py Metric/Anomaly vocabulary "
           "(typos make silently-unwatched series).")

    def __init__(self, keys_source=None):
        self._keys_source = keys_source
        self._vocab = None

    def vocab(self):
        if self._vocab is None:
            self._vocab = load_name_vocab(self._keys_source)
        return self._vocab

    # ----------------------------------------------------------------- checks
    def _check_name(self, module, node, which):
        """One name argument against vocabulary ``which``; returns the
        finding or None."""
        vocab = self.vocab()
        values = set(vocab[which].values())
        resolved, kind = _resolve_name(node, vocab)
        if kind in ("none", "dynamic"):
            return None
        if kind == "member" and resolved is None:
            cls = METRIC_CLASS if which == METRIC_CLASS else ANOMALY_CLASS
            return Finding(
                rule=self.id, path=module.path, line=node.lineno,
                col=node.col_offset,
                message=f"unknown {cls} member "
                        f"'{dotted_name(node, require_name_root=False)}' — "
                        f"declare it in config/keys.py {cls}",
            )
        if resolved not in values:
            return Finding(
                rule=self.id, path=module.path, line=node.lineno,
                col=node.col_offset,
                message=f"{which.lower()} name '{resolved}' is not declared "
                        f"in the config/keys.py {which} vocabulary "
                        f"(known: {', '.join(sorted(values))})",
            )
        return None

    def _first_arg(self, call, kwarg=None):
        if call.args and not isinstance(call.args[0], ast.Starred):
            return call.args[0]
        if kwarg:
            for kw in call.keywords:
                if kw.arg == kwarg:
                    return kw.value
        return None

    def visit_module(self, module):
        findings = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func, require_name_root=False) or ""
            last = _last(name)
            hit = None
            if last == "record_metric":
                arg = self._first_arg(node, kwarg="name")
                if arg is not None:
                    hit = self._check_name(module, arg, METRIC_CLASS)
            elif last == "register_detector":
                arg = self._first_arg(node, kwarg="anomaly")
                if arg is not None:
                    hit = self._check_name(module, arg, ANOMALY_CLASS)
                    if hit:
                        findings.append(hit)
                    hit = None
                metric = None
                if len(node.args) > 1 and not isinstance(node.args[1], ast.Starred):
                    metric = node.args[1]
                else:
                    for kw in node.keywords:
                        if kw.arg == "metric":
                            metric = kw.value
                if metric is not None:
                    hit = self._check_name(module, metric, METRIC_CLASS)
            elif last == "metric" and isinstance(node.func, ast.Attribute):
                if _is_recorder_expr(node.func.value):
                    arg = self._first_arg(node, kwarg="name")
                    if arg is not None:
                        hit = self._check_name(module, arg, METRIC_CLASS)
            elif last == "observe" and isinstance(node.func, ast.Attribute):
                if _is_watchdog_expr(node.func.value):
                    arg = self._first_arg(node, kwarg="name")
                    if arg is not None:
                        hit = self._check_name(module, arg, METRIC_CLASS)
            if hit:
                findings.append(hit)
        return findings
