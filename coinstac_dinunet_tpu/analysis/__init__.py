"""dinulint — AST-based static analyzer for JAX hazards and federated
protocol conformance.

Rule families (see ``docs/ANALYSIS.md``):

- ``jax-api-drift`` — references to JAX symbols absent/deprecated at the
  pinned JAX version (the class of bug that broke the seed's 57 tests).
- ``trace-host-sync`` / ``trace-impure`` / ``trace-py-control`` /
  ``trace-set-iter`` — host-sync, impurity, Python control flow, and
  nondeterministic-set hazards inside jit/shard_map-traced functions.
- ``protocol-conformance`` — producer/consumer agreement of the
  local↔remote wire keys against the ``config/keys.py`` vocabulary.
- ``sharding-unknown-axis`` / ``sharding-mesh-arity`` /
  ``sharding-spec-arity`` / ``sharding-collective-scope`` /
  ``sharding-axis-literal`` — mesh/axis/PartitionSpec conformance against
  the ``config/keys.py`` ``MeshAxis`` vocabulary.
- ``deep-*`` (opt-in ``--deep``) — an abstract-interpretation tier:
  ``jax.eval_shape`` traces of registered entry points on the 8-device
  virtual CPU platform (``analysis/deepcheck.py``; the only part of the
  analyzer that imports JAX, and only when asked).
- ``perf-*`` / ``proto-flow-*`` / ``proto-cache-*`` (opt-in ``--tier3``)
  — the jaxpr dataflow tier: registered entry points lowered via
  ``jax.make_jaxpr``/``.lower()`` and audited for missing buffer
  donation, dtype promotion, traced host syncs and captured constants
  (``analysis/{dataflow,perf_rules}.py``), plus the AST phase-machine /
  cache-lifecycle model of the round protocol
  (``analysis/protocol_flow.py``).

CLI (installed as the ``dinulint`` console script)::

    dinulint [paths...] \
        [--format text|json|github] [--baseline FILE] [--write-baseline] \
        [--rules id,id] [--jax-version X.Y.Z] [--list-rules] \
        [--deep] [--tier3]

Exit status: 0 when no *new* (non-baselined, non-suppressed) findings, 1
otherwise, 2 on usage errors.  The default tiers are pure stdlib ``ast``
— JAX is imported only under ``--deep``/``--tier3``.
"""
from .core import (  # noqa: F401
    Finding,
    Module,
    ProjectRule,
    Rule,
    default_rules,
    filter_baselined,
    load_baseline,
    register_rule,
    run_lint,
    write_baseline,
)
from .dataflow import TIER3_RULE_IDS  # noqa: F401  (JAX-free to import)
from .jax_api import JaxApiDriftRule, SYMBOL_TABLE, symbol_status  # noqa: F401
from .protocol import ProtocolConformanceRule, load_vocabulary  # noqa: F401
from .protocol_flow import ProtocolFlowAnalyzer, run_protocol_flow  # noqa: F401
from .sharding import (  # noqa: F401
    AxisLiteralRule,
    CollectiveScopeRule,
    MeshArityRule,
    SpecArityRule,
    UnknownAxisRule,
    load_mesh_axes,
)
from .trace_hazards import (  # noqa: F401
    HostSyncRule,
    ImpureCallRule,
    PyControlFlowRule,
    SetIterationRule,
)

__all__ = [
    "Finding", "Module", "Rule", "ProjectRule", "register_rule",
    "default_rules", "run_lint", "load_baseline", "write_baseline",
    "filter_baselined", "JaxApiDriftRule", "SYMBOL_TABLE", "symbol_status",
    "ProtocolConformanceRule", "load_vocabulary", "HostSyncRule",
    "ImpureCallRule", "PyControlFlowRule", "SetIterationRule",
    "UnknownAxisRule", "MeshArityRule", "SpecArityRule",
    "CollectiveScopeRule", "AxisLiteralRule", "load_mesh_axes",
    "TIER3_RULE_IDS", "ProtocolFlowAnalyzer", "run_protocol_flow",
]
