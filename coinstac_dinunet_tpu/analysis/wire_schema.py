"""dinulint tier-6 — the wire-contract auditor (``--wire``).

Everything that crosses a node boundary in this framework does so as one of
three payload kinds: **json** (the local↔remote output-dict handshake keys
and the daemon's framed-pipe fields), **tensor** (COINNTW2/``.npy`` payloads
committed through ``resilience/transport.py`` and named by ``*_file`` wire
keys), and **delta** (the daemon's dirty-key ``cache_delta``/``cache_patch``
lanes).  Nothing at runtime knows that contract as a whole — keys, frames
and dumps are ad-hoc dict writes spread across ``nodes/``, ``federation/``
and ``utils/tensorutils.py``.  This tier lifts all of it into one typed
**wire-schema IR** (:class:`WireEntry`: key, direction, producer/consumer
site-kind, payload kind, versioning echoes, applicable codec, static byte
cost) using the same pure-``ast`` extraction grammar as
``protocol.py``/``proto_ir.py`` — no JAX import — and checks four rule
families over it:

- ``wire-orphan`` — a key consumed on one side with no producer on the
  other (or produced and never consumed): silent schema drift.  Covers the
  daemon frame fields the ``protocol-conformance`` rule never sees.
- ``wire-unversioned`` — a boundary module (or daemon frame lane) that
  ships payloads without echoing the ``wire_round``/``roster_epoch``
  stamps the staleness window and roster machinery refuse deliveries by.
- ``wire-dense`` — a full-tensor path written outside the codec-capable
  ``save_wire`` choke point while a registered codec (``ops/quantize.py``
  int8, ``parallel/powersgd.py``, ``parallel/rankdad.py``) could apply;
  each finding carries the static byte-cost model (params × dtype width ×
  per-round multiplicity — the per-path denominator a ``--codec`` bench
  needs).
- ``wire-lock`` — the extracted schema drifted from the checked-in
  ``wire_schema.lock.json``: the same ratchet contract as
  ``dinulint_baseline.json`` (any contract change must be explicit in the
  diff; regenerate via ``dinulint --wire --write-lock``, which also
  regenerates the docs/FEDERATION.md contract table between its markers).

Runtime close-the-loop: ``--reconcile <telemetry dir>`` replays the PR 3
``wire`` counter records (and ``daemon:frame`` events) against the static
byte ledger, bucketing observed bytes by their ``payload_kind`` field and
reporting anything no schema entry accounts for as ``wire-unmodeled`` —
static analysis validated by observation.

``wire-config`` is the tier's own error channel; like
``proto-model-config`` it survives any ``--rules`` filter and blocks
``--write-baseline``.

NOTE: the default-tier rule ``wire-atomic-commit`` predates this tier and
shares the ``wire-`` spelling — tier ownership is tracked by the EXACT rule
ids in :data:`WIRE_RULE_IDS`, never by the bare ``wire-`` prefix.
"""
import ast
import json
import os

from ..config.keys import WireContract
from .core import Finding, Module, iter_python_files
from .protocol import (
    PROTOCOL_FILES,
    _Extractor,
    load_vocabulary,
)
from .wire_atomic import _mentions_transfer, _open_write_mode, _tainted_names
from .core import dotted_name

#: the tier's rule vocabulary — EXACT ids (``wire-atomic-commit`` is a
#: default-tier rule that happens to share the prefix; see module docstring)
WIRE_RULE_IDS = (
    WireContract.ORPHAN,
    WireContract.UNVERSIONED,
    WireContract.DENSE,
    WireContract.LOCK,
    WireContract.UNMODELED,
    WireContract.CONFIG,
)

#: boundary files the schema is lifted from, beyond PROTOCOL_FILES
DAEMON_SUFFIX = "federation/daemon.py"
TENSOR_SUFFIX = "utils/tensorutils.py"
TRANSPORT_SUFFIX = "resilience/transport.py"

#: repo-relative suffixes the full lift needs present (a partial scan —
#: single-file lint, editor integration — skips the tier rather than
#: flooding every key of the missing side as an orphan; the package-wide
#: run always has the full set)
WIRE_FILES = tuple(PROTOCOL_FILES) + (
    DAEMON_SUFFIX, TENSOR_SUFFIX, TRANSPORT_SUFFIX,
)

#: version-stamp wire keys (echoed verbatim; LocalWire/RemoteWire.ROUND and
#: ROSTER_EPOCH share these values) and the daemon frame stamp
VERSION_STAMPS = ("wire_round", "roster_epoch")
DAEMON_STAMP = "round"

#: registered wire codecs per tensor key (``None`` → the save_wire int8
#: hook, ``config.wire_codec``, is the applicable codec)
_CODEC_BY_KEY = {
    "powerSGD_P_file": "powerSGD",
    "powerSGD_Q_file": "powerSGD",
    "rank1_file": "powerSGD",
    "dad_data_file": "rankDAD",
    "dad_rest_file": "rankDAD",
}

#: enum members that name file payloads without a ``_FILE`` suffix
_FILE_MEMBERS = {"PRETRAINED_WEIGHTS", "RESULTS_ZIP"}

#: daemon frame fields riding the dirty-key delta lanes
_DELTA_FIELDS = {"cache_patch", "cache_delta", "set", "del"}

#: frame fields carried by the ``{cache, input, state}`` → ``{output,
#: cache}`` node contract itself (produced/consumed outside daemon.py —
#: by engine.py's payload builder and the node scripts); exempt from
#: orphan matching exactly like ENGINE_PROVIDED_KEYS in protocol.py
NODE_CONTRACT_FIELDS = {"cache", "input", "state", "output"}

# ---- daemon frame extraction grammar ---------------------------------------
_WORKER_FUNCS = {"worker_main"}
_ENGINE_CLASSES = {"_Worker", "DaemonEngine", "_FrameReader"}
#: calls whose second (write_frame) / first (.request) argument is a frame
_FRAME_SINKS = {"write_frame", "request"}
#: calls whose assigned result is a received frame (or carries one)
_FRAME_SEEDS = {"read_frame", "_read", "request", "run"}
_CONSUME_METHODS = {"get", "pop", "setdefault"}


class WireEntry:
    """One typed row of the wire-schema IR."""

    __slots__ = ("key", "direction", "producer", "consumer", "payload",
                 "versioned", "codec", "file", "source", "note")

    def __init__(self, key, direction, producer, consumer, payload,
                 versioned, codec=None, file=None, source="handshake",
                 note=None):
        self.key = key
        self.direction = direction
        self.producer = producer
        self.consumer = consumer
        self.payload = payload
        self.versioned = versioned
        self.codec = codec
        self.file = file
        self.source = source
        self.note = note

    def ident(self):
        return (self.direction, self.key)

    def to_dict(self):
        d = {
            "key": self.key, "direction": self.direction,
            "producer": self.producer, "consumer": self.consumer,
            "payload": self.payload, "versioned": self.versioned,
            "codec": self.codec, "file": self.file, "source": self.source,
        }
        if self.payload == "tensor":
            d["bytes_per_round"] = byte_cost_model(self)["formula"]
        if self.note:
            d["note"] = self.note
        return d


class WireSchema:
    """The lifted IR plus the evidence the rule passes anchor findings to."""

    def __init__(self):
        self.entries = []
        #: first (path, line, col) evidence per (direction, key)
        self.uses = {}
        #: {module display path: {"wire_round": bool, "roster_epoch": bool}}
        self.module_stamps = {}
        #: raw (non-choke-point) tensor writes: (path, line, col, how)
        self.raw_writes = []
        #: save_wire choke point carries the codec hook (config.wire_codec)
        # True until the choke point is actually in scope and disproves it:
        # a scan that never read utils/tensorutils.py must not report every
        # tensor entry dense on absence of evidence.
        self.choke_has_codec = True
        #: daemon lanes: {"engine->worker": fields, "worker->engine": fields}
        self.daemon_fields = {}

    def entry(self, direction, key):
        for e in self.entries:
            if e.direction == direction and e.key == key:
                return e
        return None


def byte_cost_model(entry, n_sites="n_sites", dtype_bytes=4):
    """The static byte-cost model of one tensor entry: params × dtype width
    × per-round multiplicity.  Site→agg payloads cross once per site per
    round; agg→site broadcasts are relayed to every site per round — the
    multiplicity is ``n_sites`` either way, which is exactly the
    denominator a codec bench divides observed bytes by."""
    codec = entry.codec
    model = {
        "dtype_bytes": dtype_bytes,
        "multiplicity": f"{n_sites}/round",
        "formula": f"params * {dtype_bytes} B * {n_sites} / round",
        "codec": codec,
    }
    if codec == "int8":
        model["codec_formula"] = (
            f"params * 1 B (+ f32 group scales) * {n_sites} / round"
        )
    elif codec in ("powerSGD", "rankDAD"):
        model["codec_formula"] = (
            f"rank * (rows + cols) * {dtype_bytes} B * {n_sites} / round"
        )
    return model


# --------------------------------------------------------------- daemon lift
class _DaemonLift:
    """Lift the daemon's framed-pipe vocabulary (top-level frame fields +
    the nested delta lanes) from ``federation/daemon.py``.

    Grammar, mirroring ``protocol._Extractor``'s conservatism (only
    statically-resolvable constant keys count):

    - produce: constant keys of dict literals flowing into a
      ``write_frame(stream, X)`` / ``worker.request(X, ...)`` call —
      directly, via a name later passed in, or via constant-key subscript
      stores on such a name (``resp["result"] = clean`` marks ``result``
      produced AND lets ``clean``'s stores contribute nested fields like
      ``cache_delta``/``set``/``del``).
    - consume: ``.get("k")``/``.pop("k")``/``.setdefault("k")`` calls and
      constant-subscript loads on names tainted by a frame-receiving call
      (``read_frame``/``_read``/``request``/``run``), iterated to a fixed
      point through plain assignments.

    Sides: ``worker_main`` is the worker; ``_Worker``/``DaemonEngine``/
    ``_FrameReader`` methods are the engine.
    """

    def __init__(self, module):
        self.module = module
        # side -> {field: (line, col)}
        self.produced = {"engine": {}, "worker": {}}
        self.consumed = {"engine": {}, "worker": {}}

    def run(self):
        for node in self.module.tree.body:
            if isinstance(node, ast.FunctionDef):
                if node.name in _WORKER_FUNCS:
                    self._lift_scope(node, "worker")
            elif isinstance(node, ast.ClassDef):
                if node.name in _ENGINE_CLASSES:
                    for item in node.body:
                        if isinstance(item, ast.FunctionDef):
                            self._lift_scope(item, "engine")
        return self

    @staticmethod
    def _call_name(call):
        f = call.func
        if isinstance(f, ast.Attribute):
            return f.attr
        if isinstance(f, ast.Name):
            return f.id
        return None

    def _record(self, table, side, key, node):
        if isinstance(key, str) and key:
            table[side].setdefault(key, (node.lineno, node.col_offset))

    def _record_dict(self, side, d, node):
        """Record a frame dict literal's constant keys; returns the bare
        names used as field VALUES — those objects ship inside the frame
        (``{"payload": req}`` makes ``req`` itself outbound), so callers
        fold them into the flow."""
        value_names = set()
        for k, v in zip(d.keys, d.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                self._record(self.produced, side, k.value, node)
            if isinstance(v, ast.Name):
                value_names.add(v.id)
        return value_names

    def _lift_scope(self, fn, side):
        # pass 1: frame objects flowing into a sink — dict literals record
        # their keys directly; only a WHOLE-argument name joins the flow (a
        # name merely interpolated inside an outbound dict, like msg in an
        # error f-string, is not itself an outbound frame)
        flowing = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = self._call_name(node)
            if name not in _FRAME_SINKS or not node.args:
                continue
            arg = node.args[1] if (name == "write_frame"
                                   and len(node.args) > 1) else node.args[0]
            if isinstance(arg, ast.Name):
                flowing.add(arg.id)
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Dict):
                    flowing |= self._record_dict(side, sub, sub)
        # pass 2: fixpoint — dict literals / subscript stores on flowing
        # names produce fields; an exact-name value stored into a flowing
        # slot aliases the frame (resp["result"] = clean → clean flows)
        assigns = [n for n in ast.walk(fn) if isinstance(n, ast.Assign)]
        changed = True
        while changed:
            changed = False
            for node in assigns:
                for target in node.targets:
                    if (isinstance(target, ast.Name)
                            and target.id in flowing):
                        joins = set()
                        if isinstance(node.value, ast.Dict):
                            joins |= self._record_dict(side, node.value,
                                                       node)
                        if isinstance(node.value, ast.Name):
                            joins.add(node.value.id)
                        if joins - flowing:
                            flowing |= joins
                            changed = True
                    elif (isinstance(target, ast.Subscript)
                          and isinstance(target.value, ast.Name)
                          and target.value.id in flowing):
                        key = target.slice
                        if (isinstance(key, ast.Constant)
                                and isinstance(key.value, str)):
                            self._record(self.produced, side, key.value,
                                         target)
                        joins = set()
                        if isinstance(node.value, ast.Dict):
                            joins |= self._record_dict(side, node.value,
                                                       node)
                        if isinstance(node.value, ast.Name):
                            joins.add(node.value.id)
                        if joins - flowing:
                            flowing |= joins
                            changed = True
        # pass 3: fixpoint — receiver taint for consumes
        tracked = set()
        changed = True
        while changed:
            changed = False
            for node in assigns:
                seed = False
                for sub in ast.walk(node.value):
                    if (isinstance(sub, ast.Call)
                            and self._call_name(sub) in _FRAME_SEEDS):
                        seed = True
                    if isinstance(sub, ast.Name) and sub.id in tracked:
                        seed = True
                if not seed:
                    continue
                for target in node.targets:
                    names = (target.elts if isinstance(target, ast.Tuple)
                             else [target])
                    for t in names:
                        if isinstance(t, ast.Name) and t.id not in tracked:
                            tracked.add(t.id)
                            changed = True
        # a name that flows into a sink holds an OUTBOUND frame: its reads
        # (the worker re-reading its own resp dict) are not wire consumes
        tracked -= flowing
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                f = node.func
                if (isinstance(f, ast.Attribute)
                        and f.attr in _CONSUME_METHODS
                        and isinstance(f.value, ast.Name)
                        and f.value.id in tracked
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    self._record(self.consumed, side, node.args[0].value,
                                 node)
            elif (isinstance(node, ast.Subscript)
                  and isinstance(node.ctx, ast.Load)
                  and isinstance(node.value, ast.Name)
                  and node.value.id in tracked
                  and isinstance(node.slice, ast.Constant)
                  and isinstance(node.slice.value, str)):
                self._record(self.consumed, side, node.slice.value, node)


# ------------------------------------------------------------- config parse
def _config_file_names(config_source=None):
    """Statically parse ``config/__init__.py``'s wire-filename defaults
    (``grads_file = "grads.npy"`` …) — the runtime basenames reconcile
    matches observed ``wire`` records against.  Never imports."""
    if config_source is None:
        path = os.path.normpath(os.path.join(
            os.path.dirname(__file__), "..", "config", "__init__.py"))
        with open(path, "r", encoding="utf-8") as f:
            config_source = f.read()
    names = {}
    for node in ast.parse(config_source).body:
        if (isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            names[node.targets[0].id] = node.value.value
    return names


def _norm(name):
    return "".join(c for c in name.lower() if c.isalnum())


def _file_for_key(key, config_names):
    """Default runtime basename of a ``*_file`` wire key, or None."""
    by_norm = {_norm(k): v for k, v in config_names.items()}
    hit = by_norm.get(_norm(key))
    if hit:
        return hit
    if key == "pretrained_weights" and "weights_file" in config_names:
        return f"pretrained_{config_names['weights_file']}"
    return None


# ----------------------------------------------------------------- extraction
def _find_package_files(paths):
    """{suffix: filesystem path} for every WIRE_FILES suffix under paths."""
    found = {}
    for path in iter_python_files(list(paths)):
        norm = path.replace(os.sep, "/")
        for suffix in WIRE_FILES:
            # component-boundary match: bare endswith would resolve the
            # "trainer.py" suffix to nn/basetrainer.py
            if norm == suffix or norm.endswith("/" + suffix):
                found[suffix] = path
    return found


def extract_schema(paths=None, files=None, keys_source=None,
                   config_source=None):
    """Lift the wire-schema IR.

    ``files`` (tests/fixtures): ``{suffix: source text}`` — used verbatim,
    no completeness requirement.  Otherwise the boundary modules are
    located under ``paths`` (default: the package) and a partial set
    returns ``None`` (the tier is skipped, mirroring
    ``protocol.ProtocolConformanceRule``'s partial-scan rule).
    """
    if files is None:
        located = _find_package_files(paths or ["coinstac_dinunet_tpu"])
        if set(located) != set(WIRE_FILES):
            return None
        sources = {}
        for suffix, path in located.items():
            with open(path, "r", encoding="utf-8") as f:
                sources[suffix] = (f.read(), path)
    else:
        sources = {
            suffix: (src, f"coinstac_dinunet_tpu/{suffix}")
            for suffix, src in files.items()
        }

    enum_map, local_vocab, remote_vocab, engine_provided = (
        load_vocabulary(keys_source)
    )
    config_names = _config_file_names(config_source)
    # member names per value, for payload-kind classification
    local_members = {v: m for (cls, m), v in enum_map.items()
                     if cls == "LocalWire"}
    remote_members = {v: m for (cls, m), v in enum_map.items()
                      if cls == "RemoteWire"}

    schema = WireSchema()
    produced = {"site": [], "agg": []}
    consumed = {"site": [], "agg": []}
    modules = {}
    for suffix, (src, display) in sources.items():
        try:
            modules[suffix] = Module(display, src, ast.parse(src))
        except SyntaxError as exc:
            raise ValueError(f"{display}: {exc}") from exc

    for suffix, default_side in PROTOCOL_FILES.items():
        mod = modules.get(suffix)
        if mod is None:
            continue
        ex = _Extractor(mod, default_side, enum_map)
        ex.visit(mod.tree)
        for side in ("site", "agg"):
            produced[side].extend(ex.produced[side])
            consumed[side].extend(ex.consumed[side])
        if suffix in ("nodes/local.py", "nodes/remote.py"):
            own_side = "site" if suffix == "nodes/local.py" else "agg"
            keys = {u.key for u in ex.produced[own_side]}
            schema.module_stamps[mod.path] = {
                stamp: stamp in keys for stamp in VERSION_STAMPS
            }

    def first_use(uses, key):
        hits = [u for u in uses if u.key == key]
        if not hits:
            return None
        u = min(hits, key=lambda u: (u.path, u.line))
        return (u.path, u.line, u.col)

    def classify(key, members):
        member = members.get(key, "")
        if member.endswith("_FILE") or member in _FILE_MEMBERS:
            return "tensor"
        return "json"

    def stamp_ok(suffix):
        mod = modules.get(suffix)
        if mod is None:
            return False
        stamps = schema.module_stamps.get(mod.path, {})
        return all(stamps.get(s) for s in VERSION_STAMPS)

    lanes = (
        ("site->agg", produced["site"], consumed["agg"], local_members,
         "site", "agg", stamp_ok("nodes/local.py")),
        ("agg->site", produced["agg"], consumed["site"], remote_members,
         "agg", "site", stamp_ok("nodes/remote.py")),
    )
    for (direction, prod, cons, members, prod_kind, cons_kind,
         versioned) in lanes:
        prod_keys = {u.key for u in prod}
        cons_keys = {u.key for u in cons}
        for key in sorted(prod_keys | cons_keys):
            payload = classify(key, members)
            is_engine = key in engine_provided
            codec = None
            file_name = None
            if payload == "tensor":
                codec = _CODEC_BY_KEY.get(key, "int8")
                file_name = _file_for_key(key, config_names)
                if key == "results_zip":
                    codec = None
            entry = WireEntry(
                key=key, direction=direction,
                producer=("engine" if is_engine
                          else prod_kind if key in prod_keys else None),
                consumer=cons_kind if key in cons_keys else None,
                payload=payload,
                versioned=bool(versioned) or key in VERSION_STAMPS
                or is_engine,
                codec=codec, file=file_name,
                source="handshake",
                note=("engine-provided (compspec injection)" if is_engine
                      else "version stamp (echoed verbatim)"
                      if key in VERSION_STAMPS else None),
            )
            schema.entries.append(entry)
            use = first_use(list(prod) + list(cons), key)
            if use:
                schema.uses[entry.ident()] = use

    # ---- daemon frames
    daemon_mod = modules.get(DAEMON_SUFFIX)
    if daemon_mod is not None:
        lift = _DaemonLift(daemon_mod).run()
        daemon_lanes = (
            ("engine->worker", lift.produced["engine"],
             lift.consumed["worker"], "daemon-engine", "daemon-worker"),
            ("worker->engine", lift.produced["worker"],
             lift.consumed["engine"], "daemon-worker", "daemon-engine"),
        )
        for direction, prod, cons, prod_kind, cons_kind in daemon_lanes:
            fields = sorted(set(prod) | set(cons))
            schema.daemon_fields[direction] = fields
            versioned = DAEMON_STAMP in prod
            for field in fields:
                entry = WireEntry(
                    key=field, direction=direction,
                    producer=prod_kind if field in prod else None,
                    consumer=cons_kind if field in cons else None,
                    payload=("delta" if field in _DELTA_FIELDS else "json"),
                    versioned=bool(versioned) or field == DAEMON_STAMP,
                    source="daemon",
                    note=("frame round stamp (echoed verbatim)"
                          if field == DAEMON_STAMP else None),
                )
                schema.entries.append(entry)
                at = prod.get(field) or cons.get(field)
                if at:
                    schema.uses[entry.ident()] = (
                        daemon_mod.path, at[0], at[1]
                    )

    # ---- dense-path evidence: raw tensor writes + the codec choke point
    tensor_mod = modules.get(TENSOR_SUFFIX)
    if tensor_mod is not None:
        schema.choke_has_codec = any(
            (isinstance(n, ast.Name) and n.id == "wire_codec")
            or (isinstance(n, ast.Attribute) and n.attr == "wire_codec")
            for n in ast.walk(tensor_mod.tree)
        )
    for suffix, mod in modules.items():
        if suffix in (TRANSPORT_SUFFIX,):
            continue  # the sanctioned writer itself
        tainted = _tainted_names(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            target = how = None
            func_name = dotted_name(node.func, require_name_root=False) or ""
            if isinstance(node.func, ast.Name) and node.func.id == "open":
                mode = _open_write_mode(node)
                if mode and node.args:
                    target, how = node.args[0], f"open(..., {mode!r})"
            elif (func_name.rsplit(".", 1)[-1] == "save"
                  and func_name.split(".")[0] in ("np", "numpy", "jnp")
                  and node.args):
                target, how = node.args[0], f"{func_name}(...)"
            if target is not None and _mentions_transfer(target, tainted):
                schema.raw_writes.append(
                    (mod.path, node.lineno, node.col_offset, how)
                )

    schema.entries.sort(key=lambda e: (e.direction, e.key))
    return schema


# ----------------------------------------------------------------- the rules
def _finding(rule, schema, ident, message, fallback_path):
    use = schema.uses.get(ident) if ident else None
    path, line, col = use if use else (fallback_path, 1, 0)
    return Finding(rule=rule, path=path, line=line, col=col, message=message)


def orphan_findings(schema, fallback_path="coinstac_dinunet_tpu"):
    out = []
    for e in schema.entries:
        if e.producer == "engine" or e.note and "engine-provided" in e.note:
            continue
        if e.source == "daemon" and e.key in NODE_CONTRACT_FIELDS:
            continue
        if e.producer is None:
            out.append(_finding(
                WireContract.ORPHAN, schema, e.ident(),
                f"{e.direction} {e.payload} key '{e.key}' is consumed but "
                "has no producer on the peer side — silent schema drift "
                "(the consumer reads a field nothing ever sends)",
                fallback_path,
            ))
        elif e.consumer is None:
            out.append(_finding(
                WireContract.ORPHAN, schema, e.ident(),
                f"{e.direction} {e.payload} key '{e.key}' is produced but "
                "never consumed by the peer — dead wire traffic (or the "
                "consumer's read was silently dropped)",
                fallback_path,
            ))
    return out


def unversioned_findings(schema, fallback_path="coinstac_dinunet_tpu"):
    out = []
    for path, stamps in sorted(schema.module_stamps.items()):
        for stamp in VERSION_STAMPS:
            if not stamps.get(stamp):
                out.append(Finding(
                    rule=WireContract.UNVERSIONED, path=path, line=1, col=0,
                    message=(
                        f"boundary module ships wire payloads without "
                        f"echoing the '{stamp}' version stamp — the "
                        "staleness window / roster-epoch machinery cannot "
                        "refuse stale or dead-incarnation deliveries of "
                        "these payloads"
                    ),
                ))
    for direction in sorted(schema.daemon_fields):
        fields = schema.daemon_fields[direction]
        producer_fields = [
            e.key for e in schema.entries
            if e.source == "daemon" and e.direction == direction
            and e.producer is not None
        ]
        if fields and DAEMON_STAMP not in producer_fields:
            ident = None
            for e in schema.entries:
                if e.source == "daemon" and e.direction == direction:
                    ident = e.ident()
                    break
            out.append(_finding(
                WireContract.UNVERSIONED, schema, ident,
                f"daemon {direction} frames carry no '{DAEMON_STAMP}' "
                "stamp — a response cannot be correlated to the round "
                "that requested it (redelivery/desync is undetectable "
                "on the frame lane)",
                fallback_path,
            ))
    return out


def dense_findings(schema, fallback_path="coinstac_dinunet_tpu"):
    out = []
    codecs = "int8 (ops/quantize via save_wire), powerSGD, rankDAD"
    for path, line, col, how in schema.raw_writes:
        out.append(Finding(
            rule=WireContract.DENSE, path=path, line=line, col=col,
            message=(
                f"{how} ships a full-tensor wire payload outside the "
                "codec-capable save_wire choke point — byte cost: params "
                "* 4 B * n_sites / round, with registered codecs "
                f"({codecs}) unable to apply"
            ),
        ))
    if not schema.choke_has_codec:
        for e in schema.entries:
            if e.payload != "tensor" or e.codec is None:
                continue
            model = byte_cost_model(e)
            out.append(_finding(
                WireContract.DENSE, schema, e.ident(),
                f"tensor key '{e.key}' rides a choke point with no codec "
                f"hook — byte cost {model['formula']}; registered codec "
                f"'{e.codec}' could apply",
                fallback_path,
            ))
    return out


# ------------------------------------------------------------------ lockfile
LOCK_COMMENT = (
    "dinulint --wire schema lockfile: the pinned wire contract every run "
    "is ratcheted against (same contract as dinulint_baseline.json — any "
    "wire-contract change must be explicit in this file's diff).  "
    "Regenerate: dinulint coinstac_dinunet_tpu --wire --write-lock"
)


def lock_payload(schema):
    return {
        "v": 1,
        "comment": LOCK_COMMENT,
        "entries": [e.to_dict() for e in schema.entries],
    }


def write_lock(path, schema):
    payload = lock_payload(schema)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    return payload


def load_lock(path):
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or not isinstance(
            data.get("entries"), list):
        raise ValueError(f"{path}: not a wire-schema lockfile")
    return data


def lock_findings(schema, lock_data, lock_path):
    """Drift between the extracted schema and the checked-in lockfile."""
    out = []
    current = {e.ident(): e.to_dict() for e in schema.entries}
    locked = {}
    for item in lock_data.get("entries", ()):
        if isinstance(item, dict) and "key" in item and "direction" in item:
            locked[(item["direction"], item["key"])] = item
    for ident in sorted(set(current) - set(locked)):
        out.append(_finding(
            WireContract.LOCK, schema, ident,
            f"wire entry '{ident[1]}' ({ident[0]}) is not in the schema "
            f"lockfile — a new boundary-crossing artifact; regenerate "
            f"{os.path.basename(lock_path)} via --write-lock to accept it",
            lock_path,
        ))
    for ident in sorted(set(locked) - set(current)):
        out.append(Finding(
            rule=WireContract.LOCK, path=lock_path, line=1, col=0,
            message=(
                f"wire entry '{ident[1]}' ({ident[0]}) is in the schema "
                "lockfile but no longer in the code — a removed contract "
                "entry; regenerate via --write-lock to accept the removal"
            ),
        ))
    for ident in sorted(set(locked) & set(current)):
        cur, old = current[ident], locked[ident]
        drifted = sorted(
            k for k in set(cur) | set(old)
            if k not in ("note",) and cur.get(k) != old.get(k)
        )
        if drifted:
            detail = "; ".join(
                f"{k}: {old.get(k)!r} -> {cur.get(k)!r}" for k in drifted
            )
            out.append(_finding(
                WireContract.LOCK, schema, ident,
                f"wire entry '{ident[1]}' ({ident[0]}) drifted from the "
                f"schema lockfile ({detail}); regenerate via --write-lock "
                "to accept the change",
                lock_path,
            ))
    return out


# -------------------------------------------------------------- byte ledger
def build_ledger(schema):
    """The static byte-cost ledger: one row per entry, with the cost model
    evaluated symbolically (params/n_sites are run-shaped) — the per-path
    denominator a codec bench divides observed bytes by."""
    rows = []
    for e in schema.entries:
        row = {"key": e.key, "direction": e.direction,
               "payload": e.payload, "source": e.source}
        if e.payload == "tensor":
            row.update(byte_cost_model(e))
            row["file"] = e.file
        rows.append(row)
    return {
        "v": 1,
        "comment": (
            "dinulint --wire static byte-cost ledger: params * dtype * "
            "per-round multiplicity per tensor path; json/delta lanes "
            "are accounted by --reconcile against observed telemetry"
        ),
        "entries": rows,
    }


def write_ledger(path, schema):
    payload = build_ledger(schema)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    return payload


# ---------------------------------------------------------------- reconcile
def _iter_telemetry_records(telemetry_dir):
    for root, _dirs, names in os.walk(telemetry_dir):
        for name in sorted(names):
            if not (name.startswith("telemetry.")
                    and name.endswith(".jsonl")):
                continue
            path = os.path.join(root, name)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            yield json.loads(line)
                        except ValueError:
                            continue
            except OSError:
                continue


def _match_tensor_record(rec, tensor_files, tensor_stems):
    base = str(rec.get("file", ""))
    if base in tensor_files:
        return True
    low = _norm(base)
    return any(stem and stem in low for stem in tensor_stems)


def reconcile_findings(schema, telemetry_dir,
                       fallback_path="coinstac_dinunet_tpu"):
    """Compare the static schema against observed PR 3 ``wire`` counter
    records (and ``daemon:frame`` events), bucketed by ``payload_kind``.
    Unaccounted observed bytes → ``wire-unmodeled``."""
    tensor_files = {e.file for e in schema.entries
                    if e.payload == "tensor" and e.file}
    tensor_stems = {
        _norm(e.key[: -len("_file")] if e.key.endswith("_file") else e.key)
        for e in schema.entries if e.payload == "tensor"
    }
    daemon_kinds = {e.payload for e in schema.entries
                    if e.source == "daemon"}
    unmodeled = {}  # payload_kind -> [bytes, examples]
    saw_any = False

    def miss(kind, nbytes, example):
        slot = unmodeled.setdefault(kind, [0, []])
        slot[0] += int(nbytes)
        if example and example not in slot[1] and len(slot[1]) < 5:
            slot[1].append(example)

    for rec in _iter_telemetry_records(telemetry_dir):
        kind = rec.get("kind")
        if kind == "wire":
            saw_any = True
            pk = rec.get("payload_kind")
            if pk is None:
                miss("(unlabeled)", rec.get("bytes", 0),
                     str(rec.get("file", "")))
            elif pk == "tensor":
                if not _match_tensor_record(rec, tensor_files,
                                            tensor_stems):
                    miss("tensor", rec.get("bytes", 0),
                         str(rec.get("file", "")))
            elif pk not in ("json", "delta"):
                miss(str(pk), rec.get("bytes", 0),
                     str(rec.get("file", "")))
        elif kind == "event" and rec.get("name") == "daemon:frame":
            saw_any = True
            pk = rec.get("payload_kind")
            nbytes = int(rec.get("tx_bytes", 0)) + int(
                rec.get("rx_bytes", 0))
            if pk is None:
                miss("(unlabeled)", nbytes, "daemon:frame")
            elif pk not in daemon_kinds:
                miss(str(pk), nbytes, "daemon:frame")

    out = []
    if not saw_any:
        out.append(Finding(
            rule=WireContract.CONFIG, path=fallback_path, line=1, col=0,
            message=(
                f"--reconcile {telemetry_dir}: no wire telemetry records "
                "found (telemetry.*.jsonl with kind=wire) — run with "
                "profile/telemetry enabled (e.g. scripts/telemetry_smoke"
                ".py) before reconciling"
            ),
        ))
    for kind in sorted(unmodeled):
        nbytes, examples = unmodeled[kind]
        out.append(Finding(
            rule=WireContract.UNMODELED, path=fallback_path, line=1, col=0,
            message=(
                f"{nbytes} observed wire bytes with payload_kind '{kind}' "
                "match no schema entry (examples: "
                f"{', '.join(examples) or 'n/a'}) — the static byte "
                "ledger under-models the live wire"
            ),
        ))
    return out


# ---------------------------------------------------------- docs generation
DOC_BEGIN = "<!-- wire-contract:begin (generated: dinulint --wire --write-lock) -->"
DOC_END = "<!-- wire-contract:end -->"


def render_contract_table(lock_data):
    """The docs/FEDERATION.md wire-contract table, generated from the
    lockfile so the doc can never drift from the code."""
    lines = [
        "| key | direction | producer | consumer | payload | versioned "
        "| codec | bytes/round |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for e in lock_data.get("entries", ()):
        lines.append(
            "| `{key}` | {direction} | {producer} | {consumer} | {payload}"
            " | {versioned} | {codec} | {cost} |".format(
                key=e.get("key"), direction=e.get("direction"),
                producer=e.get("producer") or "—",
                consumer=e.get("consumer") or "—",
                payload=e.get("payload"),
                versioned="yes" if e.get("versioned") else "**no**",
                codec=e.get("codec") or "—",
                cost=(f"`{e['bytes_per_round']}`"
                      if e.get("bytes_per_round") else "—"),
            )
        )
    return "\n".join(lines)


def update_federation_doc(lock_data, doc_path):
    """Regenerate the wire-contract table between the doc markers.
    Returns True when the doc changed, False when absent/unmarked."""
    try:
        with open(doc_path, "r", encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return False
    if DOC_BEGIN not in text or DOC_END not in text:
        return False
    head, rest = text.split(DOC_BEGIN, 1)
    _, tail = rest.split(DOC_END, 1)
    table = render_contract_table(lock_data)
    new = f"{head}{DOC_BEGIN}\n{table}\n{DOC_END}{tail}"
    if new != text:
        with open(doc_path, "w", encoding="utf-8") as f:
            f.write(new)
    return True


# -------------------------------------------------------------- tier driver
DEFAULT_LOCK = "wire_schema.lock.json"


def run_wire(paths=None, lock_path=None, write_lock_file=False,
             reconcile_dir=None, ledger_path=None, doc_path=None,
             files=None, keys_source=None, config_source=None):
    """The ``--wire`` tier entry point: lift, check, ratchet, reconcile.
    Returns (findings, schema); schema is None on a partial scan."""
    lock_path = lock_path or DEFAULT_LOCK
    try:
        schema = extract_schema(paths=paths, files=files,
                                keys_source=keys_source,
                                config_source=config_source)
    except (OSError, ValueError) as exc:
        return [Finding(
            rule=WireContract.CONFIG, path="coinstac_dinunet_tpu", line=1,
            col=0,
            message=f"wire-schema extraction failed: {exc}",
        )], None
    if schema is None:
        return [], None

    findings = []
    findings += orphan_findings(schema)
    findings += unversioned_findings(schema)
    findings += dense_findings(schema)

    if write_lock_file:
        lock_data = write_lock(lock_path, schema)
        if doc_path is None:
            doc_path = os.path.join("docs", "FEDERATION.md")
        update_federation_doc(lock_data, doc_path)
    elif os.path.exists(lock_path):
        try:
            lock_data = load_lock(lock_path)
            findings += lock_findings(schema, lock_data, lock_path)
        except (OSError, ValueError) as exc:
            findings.append(Finding(
                rule=WireContract.CONFIG, path=lock_path, line=1, col=0,
                message=f"unreadable wire-schema lockfile: {exc}",
            ))
    else:
        findings.append(Finding(
            rule=WireContract.CONFIG, path=lock_path, line=1, col=0,
            message=(
                f"wire-schema lockfile {lock_path} is missing — the "
                "contract ratchet cannot run; generate it with "
                "--write-lock and check it in"
            ),
        ))

    if ledger_path:
        write_ledger(ledger_path, schema)
    if reconcile_dir:
        findings += reconcile_findings(schema, reconcile_dir)

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, schema
