"""wire-atomic-commit: payloads bound for a transfer directory must commit
through ``resilience/transport.py``.

A direct ``open(path, "wb")`` or ``np.save`` aimed at a transfer directory
reintroduces the exact failure mode the resilience layer exists to close: a
reader (the aggregator, or a site consuming a broadcast) that opens the file
mid-write trains on a partial payload — silently when the payload predates
the checksummed wire format.  The transport's commit path (tmp + fsync +
atomic rename + directory manifest) is the single sanctioned writer, so this
rule flags any other write whose target expression implicates a transfer
directory:

- a string constant mentioning ``transferDirectory`` (the COINSTAC state
  key) or a ``transfer``/``xfer`` path fragment,
- a name/attribute whose spelling contains ``transfer`` or ``xfer``
  (``xfer``, ``transfer_dir``, ``self.transfer_path`` …),
- a call to a ``*transfer_path*`` helper (``self._transfer_path("g.npy")``),
- a local name assigned from any of the above (one-hop taint:
  ``p = os.path.join(state["transferDirectory"], f); open(p, "wb")``).

Checked write shapes: ``open(target, "wb"|"ab"|"xb"|"w+b"|...)`` (positional
or ``mode=`` keyword) and ``np.save(target, ...)`` / ``numpy.save`` /
``jnp.save``.  ``resilience/transport.py`` itself is exempt — it IS the
sanctioned writer.  Reads (``"rb"``) are never flagged.
"""
import ast

from .core import Finding, Rule, dotted_name, register_rule

#: the only module allowed to write transfer-directory payloads directly
_EXEMPT_SUFFIX = "resilience/transport.py"

_NP_ROOTS = {"np", "numpy", "jnp"}
_TRANSFER_MARKERS = ("transfer", "xfer")


def _mentions_transfer(node, tainted=()):
    """True when the path expression implicates a transfer directory
    (directly, or through a name in the ``tainted`` alias set)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            low = sub.value.lower()
            if any(m in low for m in _TRANSFER_MARKERS):
                return True
        elif isinstance(sub, ast.Name):
            low = sub.id.lower()
            if sub.id in tainted or any(m in low for m in _TRANSFER_MARKERS):
                return True
        elif isinstance(sub, ast.Attribute):
            low = sub.attr.lower()
            if any(m in low for m in _TRANSFER_MARKERS):
                return True
        elif isinstance(sub, ast.Call):
            name = (dotted_name(sub.func, require_name_root=False) or "").lower()
            if any(m in name for m in _TRANSFER_MARKERS):
                return True
    return False


def _tainted_names(tree):
    """Names assigned (anywhere in the module) from a transfer-mentioning
    expression — the ``p = join(transferDirectory, f)`` alias hop.  Iterated
    to a fixed point so a chain of plain-name aliases stays tainted."""
    tainted = set()
    assigns = []
    for node in ast.walk(tree):
        value = None
        if isinstance(node, ast.Assign):
            value = node.value
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            value = node.value
            targets = [node.target]
        else:
            continue
        names = [t.id for t in targets if isinstance(t, ast.Name)]
        if names:
            assigns.append((names, value))
    changed = True
    while changed:
        changed = False
        for names, value in assigns:
            if _mentions_transfer(value, tainted):
                for n in names:
                    if n not in tainted:
                        tainted.add(n)
                        changed = True
    return tainted


def _open_write_mode(call):
    """The write-mode string of an ``open`` call, or None (read/unknown)."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if not (isinstance(mode, ast.Constant) and isinstance(mode.value, str)):
        return None
    m = mode.value
    return m if ("b" in m and any(c in m for c in "wax+")) else None


@register_rule
class WireAtomicCommitRule(Rule):
    id = "wire-atomic-commit"
    doc = ("direct open(..., 'wb')/np.save writes targeting a transfer "
           "directory bypass the atomic, checksummed, manifest-recorded "
           "commit path in resilience/transport.py — a reader can observe "
           "a partial payload")

    def visit_module(self, module):
        if module.path.replace("\\", "/").endswith(_EXEMPT_SUFFIX):
            return []
        findings = []
        tainted = _tainted_names(module.tree)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            target = None
            how = None
            func_name = dotted_name(node.func, require_name_root=False) or ""
            if isinstance(node.func, ast.Name) and node.func.id == "open":
                mode = _open_write_mode(node)
                if mode and node.args:
                    target = node.args[0]
                    how = f"open(..., {mode!r})"
            elif (
                func_name.rsplit(".", 1)[-1] == "save"
                and func_name.split(".")[0] in _NP_ROOTS
                and node.args
            ):
                target = node.args[0]
                how = f"{func_name}(...)"
            if target is None or not _mentions_transfer(target, tainted):
                continue
            findings.append(Finding(
                rule=self.id, path=module.path, line=node.lineno,
                col=node.col_offset,
                message=(
                    f"{how} writes a transfer-directory payload directly — "
                    "commit it through resilience/transport.py "
                    "(tensorutils.save_wire/save_arrays or "
                    "transport.commit_bytes/atomic_copy) so readers can "
                    "never observe a partial payload"
                ),
            ))
        return findings
