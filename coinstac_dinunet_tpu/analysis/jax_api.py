"""jax-api-drift: references to JAX symbols that don't exist at the pinned
version.

The seed's defining breakage — 9 modules calling ``jax.shard_map`` against
JAX 0.4.37, where it still lives in ``jax.experimental.shard_map`` — is an
``AttributeError`` at *trace* time: import succeeds, tests collect, and the
failure only surfaces when a step function is first built.  This rule makes
that class of bug a millisecond-scale static finding instead.

The symbol table is declarative: each entry gives the version window in which
the dotted path exists (``added``/``removed``), an optional ``deprecated``
bound, and a replacement hint.  The pinned version is read from installed
package metadata (``importlib.metadata`` — no ``import jax``, keeping the
linter JAX-free and fast) and can be overridden with ``--jax-version``.

References inside the body of an ``if hasattr(jax, "shard_map"):`` (or any
guard whose test contains a matching ``hasattr``) are the *sanctioned*
version-portability idiom — ``utils/jax_compat.py`` is built from them — and
are never reported.  The guard's ``else:`` branch is exempt wholesale: it
only runs when the probe failed (the *other* version line), so every jax
reference there is a deliberate fallback.  The getattr-shim spelling the
hints recommend, ``getattr(pltpu, "CompilerParams", None) or
pltpu.TPUCompilerParams``, is likewise sanctioned — operands after a
jax-rooted ``getattr`` probe in an ``or`` chain only evaluate when the probe
returned None.
"""
import ast

from .core import Finding, Rule, dotted_name, register_rule

#: version -> status windows for drift-prone dotted paths.  ``added``: first
#: version the name exists at; ``removed``: first version it no longer
#: exists at; ``deprecated``: first version it warns at.  All bounds
#: optional.  Verified against 0.4.37 (the pinned toolchain) and the >=0.6
#: release notes the package targets.
SYMBOL_TABLE = {
    "jax.shard_map": {
        "added": "0.6.0",
        "hint": "use coinstac_dinunet_tpu.utils.jax_compat.shard_map "
                "(falls back to jax.experimental.shard_map.shard_map)",
    },
    "jax.P": {
        "added": "0.6.0",
        "hint": "use jax.sharding.PartitionSpec",
    },
    "jax.typeof": {"added": "0.6.0"},
    "jax.make_mesh": {"added": "0.4.35"},
    "jax.lax.axis_size": {
        "added": "0.4.38",
        "hint": "use coinstac_dinunet_tpu.utils.jax_compat.axis_size "
                "(lax.psum(1, axis_name) constant-folds to the static size)",
    },
    "jax.experimental.pallas.tpu.CompilerParams": {
        "added": "0.7.0",
        "hint": "getattr fallback to pltpu.TPUCompilerParams on 0.4.x",
    },
    "jax.experimental.pallas.tpu.InterpretParams": {
        "added": "0.7.0",
        "hint": "gate the TPU-flavored interpreter on "
                "hasattr(pltpu, 'InterpretParams')",
    },
    "jax.experimental.pallas.tpu.TPUCompilerParams": {
        "removed": "0.7.0",
        "hint": "renamed pltpu.CompilerParams in >=0.7; use a getattr shim",
    },
    "jax.experimental.shard_map": {
        "deprecated": "0.6.0",
        "removed": "0.8.0",
        "hint": "top-level jax.shard_map from 0.6 "
                "(coinstac_dinunet_tpu.utils.jax_compat bridges both)",
    },
    "jax.tree_map": {
        "deprecated": "0.4.25",
        "removed": "0.6.0",
        "hint": "use jax.tree_util.tree_map (any version) or jax.tree.map",
    },
    "jax.tree_leaves": {
        "deprecated": "0.4.25",
        "removed": "0.6.0",
        "hint": "use jax.tree_util.tree_leaves or jax.tree.leaves",
    },
    "jax.tree_structure": {
        "deprecated": "0.4.25",
        "removed": "0.6.0",
        "hint": "use jax.tree_util.tree_structure or jax.tree.structure",
    },
    "jax.tree_unflatten": {
        "deprecated": "0.4.25",
        "removed": "0.6.0",
        "hint": "use jax.tree_util.tree_unflatten or jax.tree.unflatten",
    },
    "jax.tree_transpose": {
        "deprecated": "0.4.25",
        "removed": "0.6.0",
        "hint": "use jax.tree_util.tree_transpose or jax.tree.transpose",
    },
    "jax.linear_util": {"removed": "0.4.16"},
    "jax.abstract_arrays": {"removed": "0.4.14"},
    "jax.random.KeyArray": {"removed": "0.4.24", "hint": "use jax.Array"},
    "jax.experimental.maps": {
        "removed": "0.4.14",
        "hint": "xmap/Mesh moved; use jax.sharding.Mesh + shard_map",
    },
    "jax.experimental.global_device_array": {
        "removed": "0.4.14",
        "hint": "use jax.Array",
    },
}

DEFAULT_JAX_VERSION = "0.4.37"  # pinned toolchain fallback


def parse_version(v):
    parts = []
    for tok in str(v).split("."):
        num = ""
        for ch in tok:
            if ch.isdigit():
                num += ch
            else:
                break
        parts.append(int(num or 0))
    return tuple((parts + [0, 0, 0])[:3])


def installed_jax_version():
    """Pinned jax version from package metadata (never imports jax)."""
    try:
        from importlib.metadata import version

        return version("jax")
    except Exception:  # noqa: BLE001 — metadata missing in odd installs
        return DEFAULT_JAX_VERSION


def symbol_status(dotted, version):
    """('missing'|'deprecated'|'ok', entry) for ``dotted`` at ``version``.

    Longest-prefix match, so ``jax.experimental.maps.Mesh`` resolves through
    the ``jax.experimental.maps`` entry.
    """
    v = parse_version(version)
    probe = dotted
    while probe:
        entry = SYMBOL_TABLE.get(probe)
        if entry is not None:
            added = entry.get("added")
            removed = entry.get("removed")
            deprecated = entry.get("deprecated")
            if added and v < parse_version(added):
                return "missing", probe, entry
            if removed and v >= parse_version(removed):
                return "missing", probe, entry
            if deprecated and v >= parse_version(deprecated):
                return "deprecated", probe, entry
            return "ok", probe, entry
        probe = probe.rpartition(".")[0]
    return "ok", dotted, None


@register_rule
class JaxApiDriftRule(Rule):
    id = "jax-api-drift"
    doc = ("References to JAX symbols absent (or deprecated) at the pinned "
           "JAX version, resolved through a version->symbol table.")

    def __init__(self, jax_version=None):
        self.jax_version = jax_version or installed_jax_version()

    def _module_aliases(self, tree):
        """name -> dotted module path for jax-rooted imports."""
        aliases = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax" or a.name.startswith("jax."):
                        aliases[a.asname or a.name.split(".")[0]] = (
                            a.name if a.asname else a.name.split(".")[0]
                        )
                        if a.asname:
                            aliases[a.asname] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                if node.module == "jax" or node.module.startswith("jax."):
                    for a in node.names:
                        aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    def _hasattr_guards(self, tree, aliases):
        """(guarded_dotted, first_line, last_line) exemption spans.

        Three sanctioned version-gating shapes (``guarded_dotted is None``
        exempts every symbol in the span, a dotted string only that symbol):

        - the body of ``if hasattr(jax, "x"):`` exempts ``jax.x``;
        - its ``else:`` branch exempts everything — it only runs when the
          probe failed, i.e. on the complement version line, so any jax
          reference there is a deliberate old/new-API fallback;
        - operands after a jax-rooted ``getattr(mod, "x", ...)`` probe in an
          ``or`` chain exempt everything — they only evaluate when the probe
          came back falsy.
        """
        guards = []

        def jax_base(expr):
            base = dotted_name(expr)
            if base is None:
                return None
            root = base.split(".", 1)[0]
            target = aliases.get(root)
            if target is not None:
                base = base.replace(root, target, 1)
            if base == "jax" or base.startswith("jax."):
                return base
            return None

        def span(nodes):
            last = nodes[-1]
            return nodes[0].lineno, getattr(last, "end_lineno", None) or last.lineno

        def probe(call, func_name):
            """base dotted path if ``call`` is hasattr/getattr(<jax>, "str")."""
            if not (
                isinstance(call, ast.Call)
                and isinstance(call.func, ast.Name)
                and call.func.id == func_name
                and len(call.args) >= 2
                and isinstance(call.args[1], ast.Constant)
                and isinstance(call.args[1].value, str)
            ):
                return None
            return jax_base(call.args[0])

        for node in ast.walk(tree):
            if isinstance(node, ast.If) and node.body:
                for call in ast.walk(node.test):
                    base = probe(call, "hasattr")
                    if base is None:
                        continue
                    guards.append((f"{base}.{call.args[1].value}",
                                   *span(node.body)))
                    if node.orelse:
                        guards.append((None, *span(node.orelse)))
            elif isinstance(node, ast.BoolOp) and isinstance(node.op, ast.Or):
                for i, value in enumerate(node.values[:-1]):
                    if probe(value, "getattr") is not None:
                        guards.append((None, *span(node.values[i + 1:])))
                        break
        return guards

    @staticmethod
    def _guarded(dotted, lineno, guards):
        return any(
            (g is None or dotted == g or dotted.startswith(g + "."))
            and start <= lineno <= end
            for g, start, end in guards
        )

    def _check(self, dotted, module, node, findings, seen, guards=()):
        status, sym, entry = symbol_status(dotted, self.jax_version)
        if status == "ok" or entry is None:
            return
        if self._guarded(dotted, node.lineno, guards):
            return
        key = (sym, node.lineno)
        if key in seen:
            return
        seen.add(key)
        hint = entry.get("hint")
        if status == "missing":
            msg = f"{sym} does not exist in jax {self.jax_version}"
        else:
            msg = f"{sym} is deprecated in jax {self.jax_version}"
        if hint:
            msg += f" — {hint}"
        findings.append(Finding(
            rule=self.id, path=module.path, line=node.lineno,
            col=node.col_offset, message=msg,
        ))

    def visit_module(self, module):
        findings, seen = [], set()
        aliases = self._module_aliases(module.tree)
        guards = self._hasattr_guards(module.tree, aliases)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom) and node.module and (
                node.module == "jax" or node.module.startswith("jax.")
            ):
                for a in node.names:
                    self._check(f"{node.module}.{a.name}", module, node,
                                findings, seen, guards)
                self._check(node.module, module, node, findings, seen, guards)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax" or a.name.startswith("jax."):
                        self._check(a.name, module, node, findings, seen,
                                    guards)
            elif isinstance(node, ast.Attribute):
                dotted = dotted_name(node)
                if dotted is None:
                    continue
                root = dotted.split(".", 1)[0]
                target = aliases.get(root)
                if target is None:
                    continue
                resolved = dotted.replace(root, target, 1)
                if resolved == "jax" or resolved.startswith("jax."):
                    self._check(resolved, module, node, findings, seen, guards)
        return findings
