"""perf-*: jaxpr-level performance-hazard rules (dinulint tier-3).

Each rule consumes one :class:`~.dataflow.LoweredEntry` and reports the
compiled-surface hazards docs/PERF.md measures by hand:

- ``perf-donation`` — a jit argument that is a multi-leaf state tree
  (params / opt-state shaped) whose exact successor comes back as an
  output, but which is not in ``donate_argnums``: every round keeps two
  generations of the tree live, doubling its HBM footprint (cross-replica
  sharded updates, arXiv:2004.13336, only pay off when donation actually
  frees the old buffers).
- ``perf-dtype-promotion`` — dtype traffic inside a reduced-precision
  step: a float32 *argument* cast down inside the jaxpr (the cast belongs
  at batch staging — the measured ~0.9 ms flagship lever), a large
  reduced-precision tensor upcast to f32 and fed straight into a
  matmul/conv (accidental f32 compute in a bf16 step), or the same tensor
  converted to the same dtype twice (pure bandwidth waste).
- ``perf-host-sync`` — callback primitives (``pure_callback`` /
  ``io_callback`` / ``debug_callback``) traced into the step: each one is
  a host round-trip inside the hot loop.  The AST tier flags host-sync
  *calls* it can see; this proves what actually reached the jaxpr.
- ``perf-constant-capture`` — a large closure-captured constant baked
  into the jaxpr: it is re-staged with every executable that closes over
  it and invisible to donation; pass it as an argument instead.

Thresholds are constructor parameters (fixture tests shrink them); the
defaults keep the rules quiet on the registry's miniature stand-in models
except where structure (not size) is the signal — donation is structural,
so it has no byte floor.
"""
import numpy as np

from .core import Finding
from .dataflow import entry_anchor_line, is_var, walk_jaxprs

#: dtype-promotion findings need the tensor to be worth a memory pass
DEFAULT_DTYPE_MIN_BYTES = 64 * 1024
#: constants below this are the normal embedded-iota/mask noise
DEFAULT_CONST_MIN_BYTES = 1024 * 1024

_REDUCED_FLOATS = ("bfloat16", "float16")
_CALLBACK_PRIMS = frozenset((
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call", "host_callback_call",
))
_COMPUTE_PRIMS = frozenset(("dot_general", "conv_general_dilated"))


def _nbytes(shape, dtype):
    return int(np.prod(shape, dtype=np.int64) * np.dtype(dtype).itemsize) \
        if len(shape) else int(np.dtype(dtype).itemsize)


def _human(nbytes):
    for unit in ("B", "KiB", "MiB", "GiB"):
        if nbytes < 1024 or unit == "GiB":
            return (f"{nbytes:.1f} {unit}" if unit != "B"
                    else f"{int(nbytes)} B")
        nbytes /= 1024


def default_perf_rules():
    return [
        DonationRule(),
        DtypePromotionRule(),
        HostSyncRule(),
        ConstantCaptureRule(),
    ]


# ---------------------------------------------------------------- donation
class DonationRule:
    """perf-donation: state-tree jit arguments returned updated but not
    donated.

    An argument is "state-tree shaped" when it is a container of
    ``min_leaves``+ array leaves (params and optimizer trees; bare q/k/v
    arrays are excluded — matching a lone output array by shape would be
    noise).  It is "returned updated" when some output — the whole output,
    or one element of a tuple output — has the identical treedef and the
    identical leaf (shape, dtype) sequence: the step hands back a
    successor of the argument, so the old buffers are dead the moment the
    call returns and donation would reuse them in place."""

    id = "perf-donation"
    doc = ("Large params/opt-state-shaped jit arguments returned updated "
           "by the step but missing from donate_argnums.")

    def __init__(self, min_leaves=2, min_bytes=0):
        self.min_leaves = int(min_leaves)
        self.min_bytes = int(min_bytes)

    @staticmethod
    def _signature(tree):
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(tree)
        sig = []
        for leaf in leaves:
            shape = tuple(getattr(leaf, "shape", ()))
            dtype = str(getattr(leaf, "dtype", ""))
            sig.append((shape, dtype))
        return treedef, tuple(sig), leaves

    def check(self, entry):
        if entry.args_info is None or entry.out_info is None:
            return []  # not a jit surface: no donation metadata to audit
        pos_args = entry.args_info[0] if isinstance(entry.args_info, tuple) \
            else entry.args_info
        # candidate successors: the whole output, or one tuple element
        out_candidates = [entry.out_info]
        if isinstance(entry.out_info, (tuple, list)):
            out_candidates.extend(entry.out_info)
        out_sigs = []
        for cand in out_candidates:
            treedef, sig, _ = self._signature(cand)
            out_sigs.append((treedef, sig))

        findings = []
        line = entry_anchor_line(entry.path)
        for i, arg in enumerate(pos_args):
            treedef, sig, leaves = self._signature(arg)
            if len(leaves) < self.min_leaves:
                continue
            donated = [bool(getattr(l, "donated", False)) for l in leaves]
            if all(donated):
                continue
            if (treedef, sig) not in out_sigs:
                continue
            total = sum(_nbytes(s, d) for s, d in sig if d)
            if total < self.min_bytes:
                continue
            label = (f" ({entry.arg_names[i]})"
                     if i < len(entry.arg_names) else "")
            findings.append(Finding(
                rule=self.id, path=entry.path, line=line, col=0,
                message=(
                    f"entry '{entry.name}': jit argument {i}{label} is a "
                    f"{len(leaves)}-leaf state tree ({_human(total)}) whose "
                    "exact successor is returned by the step, but it is not "
                    "in donate_argnums — both generations stay live every "
                    "round (HBM footprint doubles); donate it (see "
                    "utils/jax_compat.py::resolve_donate_argnums and "
                    "cache['donate_buffers'])"
                ),
            ))
        return findings


# --------------------------------------------------------- dtype promotion
class DtypePromotionRule:
    """perf-dtype-promotion: f32 upcasts / hoistable downcasts / repeated
    casts inside a reduced-precision step (see module docstring)."""

    id = "perf-dtype-promotion"
    doc = ("f32 upcasts feeding matmuls, staging-hoistable argument casts, "
           "and repeated casts inside a reduced-precision compiled step.")

    def __init__(self, min_bytes=DEFAULT_DTYPE_MIN_BYTES):
        self.min_bytes = int(min_bytes)

    @staticmethod
    def _is_reduced(dtype):
        return str(dtype) in _REDUCED_FLOATS

    def check(self, entry):
        closed = entry.closed_jaxpr
        if closed is None:
            return []
        levels = list(walk_jaxprs(closed))
        # "a reduced-precision step": some matmul/conv touches bf16/f16
        reduced_compute = any(
            any(self._is_reduced(v.aval.dtype)
                for v in eqn.invars if hasattr(v, "aval"))
            for jaxpr, _, _ in levels
            for eqn in jaxpr.eqns if eqn.primitive.name in _COMPUTE_PRIMS
        )
        findings = []
        line = entry_anchor_line(entry.path)

        def report(msg):
            findings.append(Finding(
                rule=self.id, path=entry.path, line=line, col=0,
                message=f"entry '{entry.name}': {msg}",
            ))

        for jaxpr, _, arg_vars in levels:
            compute_operands = set()
            for eqn in jaxpr.eqns:
                if eqn.primitive.name in _COMPUTE_PRIMS:
                    compute_operands.update(
                        v for v in eqn.invars if is_var(v)
                    )
            seen_casts = {}
            for eqn in jaxpr.eqns:
                if eqn.primitive.name != "convert_element_type":
                    continue
                src = eqn.invars[0]
                if not is_var(src):  # literal operand
                    continue
                src_dt = str(src.aval.dtype)
                dst_dt = str(eqn.params.get("new_dtype"))
                nbytes = _nbytes(tuple(src.aval.shape), src.aval.dtype)
                if nbytes < self.min_bytes:
                    continue
                shape = "x".join(map(str, src.aval.shape))
                # (a) a top-level argument cast down inside the step: the
                # cast belongs at batch staging (halves the argument's HBM
                # traffic AND the host->device transfer)
                if (src in arg_vars and src_dt in ("float32", "float64")
                        and self._is_reduced(dst_dt)):
                    report(
                        f"step argument ({shape} {src_dt}, {_human(nbytes)})"
                        f" is cast to {dst_dt} inside the compiled step — "
                        "hoist the cast to batch staging "
                        "(nn/basetrainer.py::_input_cast_dtype) so the step "
                        "consumes the compute dtype directly"
                    )
                # (b) reduced->f32 upcast feeding a matmul/conv in a step
                # that otherwise computes reduced: accidental f32 compute
                if (reduced_compute and self._is_reduced(src_dt)
                        and dst_dt == "float32"
                        and any(ov in compute_operands
                                for ov in eqn.outvars)):
                    report(
                        f"{shape} {src_dt} tensor ({_human(nbytes)}) is "
                        "upcast to float32 and fed into a matmul/conv — "
                        "accidental f32 compute inside a reduced-precision "
                        "step (2x the MXU time and bytes of the intended "
                        f"{src_dt} path)"
                    )
                # (c) the same tensor converted to the same dtype twice
                key = (id(src), dst_dt)
                if key in seen_casts:
                    report(
                        f"{shape} {src_dt} tensor ({_human(nbytes)}) is "
                        f"converted to {dst_dt} more than once in the same "
                        "scope — cast once and reuse (each repeat is a full "
                        "memory pass)"
                    )
                else:
                    seen_casts[key] = eqn
        return findings


# -------------------------------------------------------------- host sync
class HostSyncRule:
    """perf-host-sync: callback primitives traced into the compiled step."""

    id = "perf-host-sync"
    doc = ("Host callback primitives (pure_callback/io_callback/"
           "debug_callback) reachable inside a compiled step.")

    def check(self, entry):
        closed = entry.closed_jaxpr
        if closed is None:
            return []
        counts = {}
        for jaxpr, _, _ in walk_jaxprs(closed):
            for eqn in jaxpr.eqns:
                name = eqn.primitive.name
                if name in _CALLBACK_PRIMS:
                    counts[name] = counts.get(name, 0) + 1
        if not counts:
            return []
        detail = ", ".join(f"{n}x {p}" for p, n in sorted(counts.items()))
        return [Finding(
            rule=self.id, path=entry.path,
            line=entry_anchor_line(entry.path), col=0,
            message=(
                f"entry '{entry.name}': host callback(s) traced into the "
                f"compiled step ({detail}) — every execution pays a "
                "device->host round-trip inside the hot loop; move the "
                "callback outside the jit (telemetry is host-side by "
                "contract, see the trace-telemetry rule)"
            ),
        )]


# ------------------------------------------------------- constant capture
class ConstantCaptureRule:
    """perf-constant-capture: large closure-captured constants baked into
    the jaxpr."""

    id = "perf-constant-capture"
    doc = ("Large closure-captured constants embedded in the compiled "
           "step's jaxpr instead of passed as arguments.")

    def __init__(self, min_bytes=DEFAULT_CONST_MIN_BYTES):
        self.min_bytes = int(min_bytes)

    def check(self, entry):
        closed = entry.closed_jaxpr
        if closed is None:
            return []
        findings, seen = [], set()
        line = entry_anchor_line(entry.path)
        for _, consts, _ in walk_jaxprs(closed):
            for const in consts:
                if id(const) in seen:
                    continue
                seen.add(id(const))
                try:
                    arr = np.asarray(const)
                except Exception:  # noqa: BLE001 — non-array const
                    continue
                nbytes = int(arr.nbytes)
                if nbytes < self.min_bytes:
                    continue
                shape = "x".join(map(str, arr.shape)) or "scalar"
                findings.append(Finding(
                    rule=self.id, path=entry.path, line=line, col=0,
                    message=(
                        f"entry '{entry.name}': a {shape} {arr.dtype} "
                        f"constant ({_human(nbytes)}) is closure-captured "
                        "into the jaxpr — it is re-embedded in every "
                        "executable that closes over it and can never be "
                        "donated; pass it as an explicit argument"
                    ),
                ))
        return findings
