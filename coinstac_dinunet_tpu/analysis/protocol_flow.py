"""proto-flow-* / proto-cache-*: the static phase-machine model (tier-3).

The tier-1 ``protocol-conformance`` rule proves every wire key has a
producer and a consumer *somewhere*.  This module models **when**: the
invocation-per-round phase machine (INIT_RUNS → NEXT_RUN →
PRE_COMPUTATION → COMPUTATION… → NEXT_RUN_WAITING → SUCCESS, the
``PHASE_TRANSITIONS`` contract in ``config/keys.py``) is reconstructed
from the AST of ``nodes/local.py`` / ``nodes/remote.py`` — each ``if
out[PHASE] == Phase.X`` / ``check(all, PHASE, Phase.X, input)`` dispatch
block is one phase, self-method calls are followed transitively — and the
following are reported:

- ``proto-flow-phase`` — a phase value one side writes into its round
  output that the peer's dispatch never tests: the message arrives and
  falls through every branch (a silently idle round).
- ``proto-flow-unmatched`` — a wire key with no consumer on the peer at
  all, or whose producing block's outgoing phases never overlap the
  phases its only consumers are guarded on (the payload always arrives in
  a round that skips the consuming branch).
- ``proto-cache-read-before-write`` — a hard ``cache[k]`` read in a phase
  that no writing phase can precede under ``PHASE_TRANSITIONS`` (crashes
  the first time that branch runs).
- ``proto-cache-never-read`` — a cache key written by a node that nothing
  in the package ever reads (dead wire-round state).
- ``proto-cache-volatile`` — a non-``_``-prefixed cache key written
  mid-round (COMPUTATION-reachable code) that is missing from
  ``nn/basetrainer.py::_VOLATILE_CACHE_KEYS``: every write churns the
  shared compiled-step bucket key, so the steady-state federated round
  silently recompiles (the 32x regression the bucket exists to prevent).

Pure stdlib ``ast`` — this half of tier-3 runs even where JAX cannot.
Extraction is deliberately conservative: only statically-resolvable keys
and phase values participate; dynamic writes (``cache.update(**blob)``)
are wildcard events that satisfy, never trigger, the lifecycle checks.
"""
import ast
import os

from .core import Finding, Module
from .protocol import _resolve_key, load_vocabulary

#: phases whose cache writes are setup/teardown, not mid-round churn:
#: INIT_RUNS/NEXT_RUN/PRE_COMPUTATION each run at most once per run/fold
#: BEFORE the steady-state jit builds (a write there is config, exactly
#: what the compiled-bucket key should see), NEXT_RUN_WAITING once per
#: fold, SUCCESS once per run.  Only COMPUTATION-phase and unguarded
#: (every-invocation) writes can churn the steady-state bucket key.
_REINIT_PHASES = frozenset((
    "init_runs", "next_run", "pre_computation", "next_run_waiting",
    "success",
))

_WILDCARD = "*"


def _package_root():
    return os.path.normpath(
        os.path.join(os.path.dirname(__file__), "..")
    )


def _read_source(path):
    with open(path, "r", encoding="utf-8") as f:
        return f.read()


def load_phase_transitions(keys_source=None):
    """Parse ``config/keys.py``'s ``PHASE_TRANSITIONS`` dict into
    {phase value: (successor values...)}; falls back to the linear
    declaration order of ``Phase`` when the contract is absent."""
    if keys_source is None:
        keys_source = _read_source(
            os.path.join(_package_root(), "config", "keys.py")
        )
    tree = ast.parse(keys_source)
    enum_map, _, _, _ = load_vocabulary(keys_source)
    transitions = {}
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and any(isinstance(t, ast.Name) and t.id == "PHASE_TRANSITIONS"
                    for t in node.targets)
            and isinstance(node.value, ast.Dict)
        ):
            for k_node, v_node in zip(node.value.keys, node.value.values):
                key = _resolve_key(k_node, enum_map)
                if key is None or not isinstance(v_node, (ast.Tuple, ast.List)):
                    continue
                succ = tuple(
                    s for s in (
                        _resolve_key(elt, enum_map) for elt in v_node.elts
                    ) if s is not None
                )
                transitions[key] = succ
    if transitions:
        return transitions
    # fallback: Phase declaration order as a linear chain
    order = [v for (cls, _), v in enum_map.items() if cls == "Phase"]
    return {a: (b,) for a, b in zip(order, order[1:])} | (
        {order[-1]: ()} if order else {}
    )


def _reachability(transitions):
    """phase -> frozenset of phases reachable from it (reflexive)."""
    out = {}
    for start in transitions:
        seen = {start}
        frontier = [start]
        while frontier:
            for nxt in transitions.get(frontier.pop(), ()):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        out[start] = frozenset(seen)
    return out


def load_volatile_keys(basetrainer_source=None, enum_map=None):
    """The exact-name volatile set from ``nn/basetrainer.py``'s
    ``_VOLATILE_CACHE_KEYS`` frozenset literal (string constants plus
    ``Key.X.value`` references resolved against the vocabulary)."""
    if basetrainer_source is None:
        basetrainer_source = _read_source(
            os.path.join(_package_root(), "nn", "basetrainer.py")
        )
    if enum_map is None:
        enum_map, _, _, _ = load_vocabulary()
    keys = set()
    for node in ast.walk(ast.parse(basetrainer_source)):
        if not (
            isinstance(node, ast.Assign)
            and any(isinstance(t, ast.Name)
                    and t.id == "_VOLATILE_CACHE_KEYS" for t in node.targets)
        ):
            continue
        for sub in ast.walk(node.value):
            key = _resolve_key(sub, enum_map)
            if key is not None:
                keys.add(key)
    return keys


# ------------------------------------------------------------- AST events
class _Event:
    __slots__ = ("key", "phase", "line", "col", "kind")

    def __init__(self, key, phase, node, kind):
        self.key, self.phase, self.kind = key, phase, kind
        self.line, self.col = node.lineno, node.col_offset


def _is_cache_base(node):
    return (isinstance(node, ast.Name) and node.id == "cache") or (
        isinstance(node, ast.Attribute) and node.attr == "cache"
    )


def _is_out_base(node):
    return (isinstance(node, ast.Name) and node.id == "out") or (
        isinstance(node, ast.Attribute) and node.attr == "out"
    )


def _contains_input(node):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id == "input":
            return True
        if isinstance(sub, ast.Attribute) and sub.attr == "input":
            return True
    return False


class _NodeModel:
    """Phase-attributed wire/cache events of one node class (the class in
    the module that defines ``compute``)."""

    def __init__(self, module, enum_map, phase_key="phase"):
        self.module = module
        self.enum_map = enum_map
        self.phase_key = phase_key
        self.produced = []       # _Event (wire out[...] writes)
        self.consumed = []       # _Event (input reads)
        self.outgoing = {}       # phase -> set of PHASE values written there
        self.cache_writes = []   # _Event (kind: 'write'|'wildcard')
        self.cache_reads = []    # _Event (kind: 'hard'|'soft')
        self.tested_phases = set()  # phase values this side dispatches on
        self.methods = {}
        self.class_name = None
        self._find_class()
        if self.methods.get("compute") is not None:
            self._visit_region(self.methods["compute"].body, None, set())
        # phase guards anywhere in the file (e.g. the success check in
        # __call__) count as "this side handles that phase"
        self.tested_phases |= self._phases_tested_anywhere()
        # consumption OUTSIDE the compute tree (constructors adopting
        # shared_args, __call__ guards) still satisfies the matching —
        # recorded phase-less so it matches any arrival phase
        self._consume_anywhere()

    # ------------------------------------------------------------ structure
    def _find_class(self):
        for node in self.module.tree.body:
            if isinstance(node, ast.ClassDef):
                methods = {
                    n.name: n for n in node.body
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                }
                if "compute" in methods:
                    self.class_name = node.name
                    self.methods = methods
                    return

    def _phases_tested_anywhere(self):
        found = set()
        for node in ast.walk(self.module.tree):
            phase = self._phase_of_test(node)
            if phase is not None:
                found.add(phase)
        return found

    _SITE_VAR_NAMES = ("site", "site_vars")

    def _consume_anywhere(self):
        """File-wide input/site-payload reads, phase-less, deduped against
        the compute-tree events by source location."""
        seen = {(e.line, e.col) for e in self.consumed}

        def add(key, node):
            if key is not None and (node.lineno, node.col_offset) not in seen:
                self.consumed.append(_Event(key, None, node, "consume"))

        def peer_base(base):
            return _contains_input(base) or (
                isinstance(base, ast.Name)
                and base.id in self._SITE_VAR_NAMES
            )

        for node in ast.walk(self.module.tree):
            if isinstance(node, ast.Subscript) and isinstance(
                node.ctx, ast.Load
            ) and peer_base(node.value):
                add(_resolve_key(node.slice, self.enum_map), node)
            elif isinstance(node, ast.Call):
                fn = node.func
                name = fn.attr if isinstance(fn, ast.Attribute) else None
                if name == "get" and node.args and peer_base(fn.value):
                    add(_resolve_key(node.args[0], self.enum_map), node)
                elif name == "check" and len(node.args) >= 2:
                    add(_resolve_key(node.args[1], self.enum_map), node)
            elif isinstance(node, ast.Compare) and len(node.ops) == 1 and (
                isinstance(node.ops[0], (ast.In, ast.NotIn))
            ) and peer_base(node.comparators[0]):
                add(_resolve_key(node.left, self.enum_map), node)

    # ---------------------------------------------------------- phase guard
    def _phase_values(self):
        return {
            v for (cls, _), v in self.enum_map.items() if cls == "Phase"
        }

    def _phase_of_test(self, test):
        """Phase value a guard expression dispatches on, or None.

        Recognizes ``<out/input>[PHASE-key] == Phase.X`` compares and
        ``check(_, PHASE-key, Phase.X, ...)`` calls, anywhere inside the
        expression."""
        phase_values = self._phase_values()
        for node in ast.walk(test) if isinstance(test, ast.AST) else ():
            if isinstance(node, ast.Compare) and len(node.comparators) == 1:
                sides = (node.left, node.comparators[0])
                keys = [_resolve_key(s, self.enum_map) for s in sides]
                if any(
                    isinstance(s, ast.Subscript)
                    and _resolve_key(s.slice, self.enum_map) == self.phase_key
                    for s in sides
                ) and any(k in phase_values for k in keys if k):
                    return next(k for k in keys if k in phase_values)
            if isinstance(node, ast.Call) and len(node.args) >= 3:
                fn = node.func
                name = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else None
                )
                if name == "check":
                    key = _resolve_key(node.args[1], self.enum_map)
                    val = _resolve_key(node.args[2], self.enum_map)
                    if key == self.phase_key and val in phase_values:
                        return val
        return None

    # -------------------------------------------------------------- regions
    def _visit_region(self, stmts, phase, visiting):
        for stmt in stmts:
            if isinstance(stmt, ast.If):
                block_phase = self._phase_of_test(stmt.test)
                if block_phase is not None:
                    self.tested_phases.add(block_phase)
                self._record_expr(stmt.test, phase, visiting)
                self._visit_region(stmt.body, block_phase or phase, visiting)
                self._visit_region(stmt.orelse, phase, visiting)
            elif isinstance(stmt, (ast.For, ast.While, ast.With,
                                   ast.AsyncWith, ast.AsyncFor)):
                for attr in ("iter", "test"):
                    if hasattr(stmt, attr):
                        self._record_expr(getattr(stmt, attr), phase, visiting)
                if hasattr(stmt, "items"):
                    for item in stmt.items:
                        self._record_expr(item.context_expr, phase, visiting)
                self._visit_region(stmt.body, phase, visiting)
                self._visit_region(getattr(stmt, "orelse", []), phase, visiting)
            elif isinstance(stmt, ast.Try):
                self._visit_region(stmt.body, phase, visiting)
                for handler in stmt.handlers:
                    self._visit_region(handler.body, phase, visiting)
                self._visit_region(stmt.orelse, phase, visiting)
                self._visit_region(stmt.finalbody, phase, visiting)
            else:
                self._record_stmt(stmt, phase, visiting)

    # ------------------------------------------------------------ recording
    def _record_stmt(self, stmt, phase, visiting):
        if isinstance(stmt, ast.Assign):
            for target in stmt.targets:
                self._record_store(target, phase, value=stmt.value)
            self._record_expr(stmt.value, phase, visiting)
        elif isinstance(stmt, ast.AugAssign):
            self._record_store(stmt.target, phase)
            if isinstance(stmt.target, ast.Subscript):
                self._record_subscript_read(stmt.target, phase, hard=True)
            self._record_expr(stmt.value, phase, visiting)
        elif isinstance(stmt, (ast.Expr, ast.Return)) and stmt.value is not None:
            self._record_expr(stmt.value, phase, visiting)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            pass  # nested defs: out of the phase machine
        else:
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._record_expr(child, phase, visiting)

    def _record_store(self, target, phase, value=None):
        # `out = {K: V, ...}` dict-literal rebinds of an out-named local
        # (the learner-method producer idiom) produce every const key
        if (
            isinstance(target, (ast.Name, ast.Attribute))
            and _is_out_base(target)
            and isinstance(value, ast.Dict)
        ):
            for k_node, v_node in zip(value.keys, value.values):
                key = k_node and _resolve_key(k_node, self.enum_map)
                if key is None:
                    continue
                self.produced.append(_Event(key, phase, target, "produce"))
                if key == self.phase_key:
                    self._record_outgoing(v_node, phase)
            return
        if not isinstance(target, ast.Subscript):
            return
        base = target.value
        key = _resolve_key(target.slice, self.enum_map)
        if _is_out_base(base):
            if key is None:
                return
            self.produced.append(_Event(key, phase, target, "produce"))
            if key == self.phase_key:
                self._record_outgoing(value, phase)
        elif _is_cache_base(base):
            self.cache_writes.append(_Event(
                key if key is not None else _WILDCARD, phase, target, "write"
            ))

    def _record_outgoing(self, value_node, phase):
        """``out[PHASE] = <value>``: a resolvable Phase value is a phase
        transition of the current block."""
        if value_node is None:
            return
        val = _resolve_key(value_node, self.enum_map)
        if val in self._phase_values():
            self.outgoing.setdefault(phase, set()).add(val)

    def _record_subscript_read(self, node, phase, hard):
        base = node.value
        key = _resolve_key(node.slice, self.enum_map)
        if _is_cache_base(base) and key is not None:
            self.cache_reads.append(
                _Event(key, phase, node, "hard" if hard else "soft")
            )
        elif _contains_input(base) and key is not None:
            self.consumed.append(_Event(key, phase, node, "consume"))

    def _record_expr(self, expr, phase, visiting):
        if expr is None:
            return
        for node in ast.walk(expr):
            if isinstance(node, ast.Subscript) and isinstance(
                node.ctx, ast.Load
            ):
                self._record_subscript_read(node, phase, hard=True)
            elif isinstance(node, ast.Compare) and len(node.ops) == 1 and (
                isinstance(node.ops[0], (ast.In, ast.NotIn))
            ):
                key = _resolve_key(node.left, self.enum_map)
                target = node.comparators[0]
                if key is not None:
                    if _is_cache_base(target):
                        self.cache_reads.append(
                            _Event(key, phase, node, "soft")
                        )
                    elif _contains_input(target):
                        self.consumed.append(
                            _Event(key, phase, node, "consume")
                        )
            elif isinstance(node, ast.Call):
                self._record_call(node, phase, visiting)

    def _record_call(self, node, phase, visiting):
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None
        )
        if name in ("get", "pop", "setdefault") and isinstance(
            fn, ast.Attribute
        ) and node.args:
            key = _resolve_key(node.args[0], self.enum_map)
            base = fn.value
            if key is not None and _is_cache_base(base):
                self.cache_reads.append(_Event(key, phase, node, "soft"))
                if name == "setdefault":
                    self.cache_writes.append(
                        _Event(key, phase, node, "write")
                    )
            elif key is not None and _contains_input(base):
                self.consumed.append(_Event(key, phase, node, "consume"))
        elif name == "update" and isinstance(fn, ast.Attribute) and (
            _is_cache_base(fn.value)
        ):
            for kw in node.keywords:
                if kw.arg is None:
                    # **expr: a dict literal contributes its const keys,
                    # anything else is a wildcard write
                    if isinstance(kw.value, ast.Dict):
                        for k_node in kw.value.keys:
                            key = k_node and _resolve_key(
                                k_node, self.enum_map
                            )
                            if key is not None:
                                self.cache_writes.append(
                                    _Event(key, phase, node, "write")
                                )
                    else:
                        self.cache_writes.append(
                            _Event(_WILDCARD, phase, node, "wildcard")
                        )
                else:
                    self.cache_writes.append(
                        _Event(kw.arg, phase, node, "write")
                    )
            if node.args:
                self.cache_writes.append(
                    _Event(_WILDCARD, phase, node, "wildcard")
                )
        elif name == "check" and len(node.args) >= 2:
            key = _resolve_key(node.args[1], self.enum_map)
            if key is not None:
                self.consumed.append(_Event(key, phase, node, "consume"))
        elif (
            isinstance(fn, ast.Attribute)
            and isinstance(fn.value, ast.Name)
            and fn.value.id == "self"
            and name in self.methods
            and name not in visiting
        ):
            # follow self-method calls transitively (cycle-guarded): their
            # wire/cache events belong to the calling phase
            self._visit_region(
                self.methods[name].body, phase, visiting | {name}
            )


# ------------------------------------------------------------ whole check
class ProtocolFlowAnalyzer:
    """Run the phase-machine checks over a (local, remote) module pair.

    ``read_scan_modules`` (optional) is the wider module set whose cache
    reads keep ``proto-cache-never-read`` honest — pass the whole package
    for real runs, or just the pair for fixtures."""

    def __init__(self, local_module, remote_module, keys_source=None,
                 basetrainer_source=None, read_scan_modules=None,
                 volatile_keys=None):
        self.enum_map, self.local_vocab, self.remote_vocab, self.engine_keys \
            = load_vocabulary(keys_source)
        self.transitions = load_phase_transitions(keys_source)
        self.reach = _reachability(self.transitions)
        self.volatile = (
            set(volatile_keys) if volatile_keys is not None
            else load_volatile_keys(basetrainer_source, self.enum_map)
        )
        self.local = _NodeModel(local_module, self.enum_map)
        self.remote = _NodeModel(remote_module, self.enum_map)
        self.read_scan_modules = list(read_scan_modules or [])

    # ------------------------------------------------------------- helpers
    def _precedes(self, write_phase, read_phase):
        """True when some round ordering runs ``write_phase`` before (or
        at) ``read_phase`` — i.e. the read phase is reachable from the
        writing phase under PHASE_TRANSITIONS (reflexive)."""
        if write_phase is None or read_phase is None:
            return True  # unguarded code runs every invocation
        return read_phase in self.reach.get(write_phase, frozenset())

    # -------------------------------------------------------------- checks
    def run(self):
        findings = []
        findings += self._check_phase_dispatch()
        findings += self._check_wire_flow()
        for side in (self.local, self.remote):
            findings += self._check_cache_lifecycle(side)
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        return findings

    def _check_phase_dispatch(self):
        findings = []
        for sender, receiver, direction in (
            (self.local, self.remote, "site->aggregator"),
            (self.remote, self.local, "aggregator->site"),
        ):
            outgoing = set()
            for values in sender.outgoing.values():
                outgoing |= values
            for phase in sorted(outgoing - receiver.tested_phases):
                findings.append(Finding(
                    rule="proto-flow-phase", path=sender.module.path,
                    line=1, col=0,
                    message=(
                        f"{direction}: phase value '{phase}' is written "
                        "into the round output but the peer's dispatch "
                        "never tests it — the next invocation falls "
                        "through every phase branch"
                    ),
                ))
        return findings

    def _check_wire_flow(self):
        findings = []
        for sender, receiver, direction in (
            (self.local, self.remote, "LocalWire"),
            (self.remote, self.local, "RemoteWire"),
        ):
            outgoing_by_phase = sender.outgoing
            consumed_keys = {e.key for e in receiver.consumed}
            consumer_phases = {}
            for e in receiver.consumed:
                consumer_phases.setdefault(e.key, set()).add(e.phase)
            for e in sender.produced:
                if e.key == sender.phase_key:
                    continue
                if e.key not in consumed_keys:
                    findings.append(Finding(
                        rule="proto-flow-unmatched",
                        path=sender.module.path, line=e.line, col=e.col,
                        message=(
                            f"{direction} key '{e.key}' is produced in the "
                            f"{e.phase or 'unguarded'} block but the peer "
                            "node never consumes it (phase-flow model)"
                        ),
                    ))
                    continue
                # phase overlap: the payload arrives with the producing
                # block's outgoing PHASE (or, when no branch fires, the
                # echoed incoming phase — the block's own phase)
                arrival = set(outgoing_by_phase.get(e.phase) or ())
                if e.phase is not None:
                    arrival.add(e.phase)
                guards = consumer_phases.get(e.key, set())
                if not arrival or None in guards:
                    continue  # unknown arrival phase / unguarded consumer
                if arrival.isdisjoint(guards):
                    findings.append(Finding(
                        rule="proto-flow-unmatched",
                        path=sender.module.path, line=e.line, col=e.col,
                        message=(
                            f"{direction} key '{e.key}' arrives with phase "
                            f"{sorted(arrival)} but the peer only consumes "
                            f"it under phase {sorted(g for g in guards)} — "
                            "the consuming branch can never see the payload"
                        ),
                    ))
        return findings

    def _check_cache_lifecycle(self, side):
        findings = []
        writes_by_key = {}
        wildcard_phases = set()
        for e in side.cache_writes:
            if e.key == _WILDCARD:
                wildcard_phases.add(e.phase)
            else:
                writes_by_key.setdefault(e.key, []).append(e)

        # read-before-write: hard reads no write phase can precede
        for e in side.cache_reads:
            if e.kind != "hard" or e.key.startswith("_"):
                continue
            writers = writes_by_key.get(e.key)
            if not writers:
                continue  # written outside this node: origin unknown
            ok = any(
                self._precedes(w.phase, e.phase) for w in writers
            ) or any(self._precedes(p, e.phase) for p in wildcard_phases)
            if not ok:
                findings.append(Finding(
                    rule="proto-cache-read-before-write",
                    path=side.module.path, line=e.line, col=e.col,
                    message=(
                        f"cache['{e.key}'] is read in the "
                        f"{e.phase or 'unguarded'} block but only written "
                        f"in {sorted({w.phase for w in writers})} — no "
                        "phase ordering under PHASE_TRANSITIONS runs a "
                        "write first (KeyError on the first round that "
                        "takes this branch)"
                    ),
                ))

        # never-read: written here, read nowhere in the scanned package
        read_anywhere = self._package_read_keys()
        reported = set()
        for key, writers in sorted(writes_by_key.items()):
            if key.startswith("_") or key in reported:
                continue
            if key in read_anywhere:
                continue
            if any(e.key == key for e in side.cache_reads):
                continue
            reported.add(key)
            w = min(writers, key=lambda e: e.line)
            findings.append(Finding(
                rule="proto-cache-never-read", path=side.module.path,
                line=w.line, col=w.col,
                message=(
                    f"cache['{key}'] is written by this node but never "
                    "read anywhere in the scanned package — dead per-round "
                    "state (it still bloats every persisted cache snapshot)"
                ),
            ))

        # volatile: mid-round writes of keys the compiled-bucket key
        # machinery would treat as config
        seen = set()
        for e in side.cache_writes:
            if e.key in (_WILDCARD,) or e.key.startswith("_"):
                continue
            if e.phase in _REINIT_PHASES:
                continue
            if e.key in self.volatile or (e.key, e.line) in seen:
                continue
            seen.add((e.key, e.line))
            findings.append(Finding(
                rule="proto-cache-volatile", path=side.module.path,
                line=e.line, col=e.col,
                message=(
                    f"cache['{e.key}'] is written mid-round (the "
                    f"{e.phase or 'unguarded'} path) but is not in "
                    "nn/basetrainer.py::_VOLATILE_CACHE_KEYS — every write "
                    "churns the shared compiled-step bucket key and the "
                    "steady-state round recompiles; add it to the volatile "
                    "list (host-side keys only) or prefix it with '_'"
                ),
            ))
        return findings

    def _package_read_keys(self):
        """Every key-shaped string the scanned modules mention OUTSIDE a
        store-subscript target — the consumer index for never-read.
        Deliberately over-approximates reads (any literal or resolvable
        enum reference counts): never-read must only fire for keys with no
        conceivable consumer anywhere."""
        if getattr(self, "_read_keys", None) is not None:
            return self._read_keys
        keys = set()
        for mod in self.read_scan_modules:
            if mod.path.replace(os.sep, "/").endswith("config/keys.py"):
                continue  # declarations are not reads
            # the write sites themselves (`x[K] = ...` slices) must not
            # self-satisfy the check: collect their slice subtrees first
            write_nodes = set()
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Subscript) and not isinstance(
                    node.ctx, ast.Load
                ):
                    for sub in ast.walk(node.slice):
                        write_nodes.add(id(sub))
            for node in ast.walk(mod.tree):
                if id(node) in write_nodes:
                    continue
                if isinstance(node, ast.Constant) and isinstance(
                    node.value, str
                ):
                    keys.add(node.value)
                elif isinstance(node, ast.Attribute):
                    key = _resolve_key(node, self.enum_map)
                    if key is not None:
                        keys.add(key)
        self._read_keys = keys
        return keys


def run_protocol_flow(paths=None, local_path=None, remote_path=None):
    """Analyze the real package's node pair (or an explicit pair).

    The never-read consumer scan ALWAYS covers the whole installed
    package (plus any extra ``paths``): a scoped single-file lint must
    not shrink the consumer index and fabricate proto-cache-never-read
    findings whose real readers live outside the scanned path.  Missing
    node files make this a silent no-op (a partial checkout is not a
    protocol bug)."""
    root = _package_root()
    local_path = local_path or os.path.join(root, "nodes", "local.py")
    remote_path = remote_path or os.path.join(root, "nodes", "remote.py")
    if not (os.path.exists(local_path) and os.path.exists(remote_path)):
        return []
    from .core import iter_python_files

    def _mod(path):
        rel = os.path.relpath(path).replace(os.sep, "/")
        if "coinstac_dinunet_tpu" in rel:
            rel = "coinstac_dinunet_tpu/" + rel.split(
                "coinstac_dinunet_tpu/", 1
            )[-1]
        return Module.parse(path, rel)

    scan_files = iter_python_files(list(paths or []) + [root])
    scan_modules, seen_real = [], set()
    for path in scan_files:
        # relative CLI paths + the absolute package root defeat
        # iter_python_files' string dedup — realpath keeps each module
        # parsed once
        real = os.path.realpath(path)
        if real in seen_real:
            continue
        seen_real.add(real)
        try:
            scan_modules.append(_mod(path))
        except (SyntaxError, OSError, UnicodeDecodeError, ValueError):
            continue
    analyzer = ProtocolFlowAnalyzer(
        _mod(local_path), _mod(remote_path),
        read_scan_modules=scan_modules,
    )
    # the node pair consumes its own writes too
    return analyzer.run()
