"""deep-*: the opt-in abstract-interpretation tier (``--deep``).

Pure AST can prove an axis name exists — it cannot prove that the trainer's
compiled step actually *traces*: that every matmul's shapes agree, that the
``shard_map`` specs divide the arrays they shard, that a reducer's factor
shapes survive the collective round-trip.  This tier closes that gap without
ever running a real computation: each registered **entry point** builds one
of the package's compiled functions and traces it with
:func:`jax.eval_shape` against :class:`jax.ShapeDtypeStruct` inputs on the
same 8-device virtual CPU platform tier-1 uses
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``) — milliseconds of
abstract interpretation instead of minutes of compilation, and no TPU.

Reported findings (ordinary :class:`~.core.Finding` objects, so they flow
through the same baseline/suppression machinery as the static tiers):

- ``deep-entry-build`` — the entry point's builder raised: the public
  constructor path itself is broken (import error, bad config plumbing).
- ``deep-eval-shape`` — ``jax.eval_shape`` raised: a shape/dtype/sharding
  error somewhere in the traced computation.
- ``deep-recompile`` — tracing the SAME abstract inputs twice produced
  different output structures: the function bakes mutable host state into
  its trace, so every real call under jit is a cache miss (recompilation)
  — and under multi-controller, a cross-host program divergence.
- ``deep-config`` — the platform could not provide the required virtual
  device count (reported, never crashes the run).

This is the only module in ``analysis/`` that imports JAX, and it is only
imported when ``--deep`` is requested — the static tiers stay JAX-free and
millisecond-fast.

Registering an entry point::

    from coinstac_dinunet_tpu.analysis.deepcheck import register_entry_point

    @register_entry_point("my-step", "coinstac_dinunet_tpu/foo/bar.py")
    def _entry_my_step():
        fn = build_my_step(...)                 # the callable under test
        args = (jax.ShapeDtypeStruct(...), ...)  # abstract inputs
        return fn, args

The builder runs lazily inside ``run_deepcheck``; raising is itself a
finding, not a crash.
"""
import dataclasses

from .core import Finding

#: virtual devices the registry's meshes assume (= the tier-1 test platform)
REQUIRED_DEVICES = 8


@dataclasses.dataclass(frozen=True)
class EntryPoint:
    """One registered deep-check target."""

    name: str
    path: str      # repo-relative source path findings anchor to
    build: object  # () -> (fn, args) with args abstract ShapeDtypeStructs
    #: positional-argument labels for tier-3 findings ("argument 1
    #: (site_state)") — optional, display only
    arg_names: tuple = ()


#: every rule id this tier can emit (``--list-rules`` enumerates opt-in
#: tiers from these lists without importing JAX)
DEEP_RULE_IDS = (
    "deep-config", "deep-entry-build", "deep-eval-shape", "deep-recompile",
)

DEEP_REGISTRY = {}


def register_entry_point(name, path, arg_names=()):
    """Decorator registering ``build`` under ``name``; findings anchor to
    ``path`` (the module whose compiled artifact the entry exercises)."""

    def deco(build):
        DEEP_REGISTRY[name] = EntryPoint(name, path, build, tuple(arg_names))
        return build

    return deco


def ensure_virtual_devices(n=REQUIRED_DEVICES):
    """Force the n-device virtual CPU platform (same stand-in tier-1 uses).

    Effective only while the JAX backend is still uninitialized —
    ``XLA_FLAGS`` is read at backend creation, not at import — so the CLI
    can set it up itself; under pytest the conftest has already done both.
    Returns the live device count.
    """
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    import jax

    try:
        # the pinned container force-registers a TPU plugin via
        # sitecustomize; re-pin to pure CPU (no-op if already initialized)
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — backend already up: verify below
        pass
    return len(jax.devices())


def _first_line(exc, limit=240):
    text = f"{type(exc).__name__}: {exc}"
    line = text.splitlines()[0] if text.splitlines() else text
    return line[:limit] + ("…" if len(line) > limit else "")


def _structure_signature(tree):
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return (
        str(treedef),
        tuple((tuple(l.shape), str(l.dtype)) for l in leaves),
    )


def run_deepcheck(names=None, builds=None):
    """eval_shape-trace the registered entry points; returns findings.

    ``names`` filters the registry (None = all).  Every failure mode is a
    finding — the runner itself never raises.  ``builds`` (optional) is a
    prebuilt ``{name: ("ok", fn, args) | ("error", msg)}`` cache — the
    tier-3 pass hands its own over (``dataflow.tier3_builds()``) so a
    combined ``--tier3 --deep`` run constructs each entry's
    trainer/mesh/federation exactly once.
    """
    _register_builtin_entries()
    findings = []
    have = ensure_virtual_devices()
    if have < REQUIRED_DEVICES:
        findings.append(Finding(
            rule="deep-config", path="coinstac_dinunet_tpu/analysis/deepcheck.py",
            line=1, col=0,
            message=f"deep check needs {REQUIRED_DEVICES} virtual devices but "
                    f"the initialized JAX backend has {have} — set XLA_FLAGS="
                    "--xla_force_host_platform_device_count=8 before anything "
                    "imports jax",
        ))
        return findings
    import jax

    # the live jit wrapper type (version-portable: compare against what the
    # installed jax.jit actually returns)
    jit_type = type(jax.jit(lambda: None))

    def unjit(fn):
        """Peel TOP-LEVEL jit wrappers so both traces are real: a jit
        object's own trace cache (keyed on the jit object + avals) would
        serve the second trace from the first, hiding host-state
        dependence.  Only actual jit objects are peeled — shard_map-wrapped
        functions also carry ``__wrapped__``, and peeling those would trace
        the unsharded body with its collectives unbound."""
        while isinstance(fn, jit_type) and hasattr(fn, "__wrapped__"):
            fn = fn.__wrapped__
        return fn

    wanted = set(names) if names else None
    for name in sorted(DEEP_REGISTRY):
        if wanted is not None and name not in wanted:
            continue
        ep = DEEP_REGISTRY[name]
        prebuilt = builds.get(name) if builds else None
        if prebuilt is not None:
            if prebuilt[0] == "error":
                findings.append(Finding(
                    rule="deep-entry-build", path=ep.path, line=1, col=0,
                    message=f"entry '{name}': builder raised {prebuilt[1]}",
                ))
                continue
            fn, args = prebuilt[1], prebuilt[2]
        else:
            try:
                fn, args = ep.build()
            except Exception as exc:  # noqa: BLE001 — any build failure is a finding
                findings.append(Finding(
                    rule="deep-entry-build", path=ep.path, line=1, col=0,
                    message=f"entry '{name}': builder raised {_first_line(exc)}",
                ))
                continue
        fn = unjit(fn)
        # each trace goes through a FRESH wrapper: eval_shape rides the jit
        # trace cache (keyed on function identity), so tracing the same fn
        # object twice would silently reuse the first trace and hide any
        # host-state dependence the second trace is meant to expose
        try:
            out = jax.eval_shape(lambda *a: fn(*a), *args)
        except Exception as exc:  # noqa: BLE001 — trace errors are the product
            findings.append(Finding(
                rule="deep-eval-shape", path=ep.path, line=1, col=0,
                message=f"entry '{name}': eval_shape failed with "
                        f"{_first_line(exc)}",
            ))
            continue
        try:
            out2 = jax.eval_shape(lambda *a: fn(*a), *args)
        except Exception as exc:  # noqa: BLE001
            findings.append(Finding(
                rule="deep-recompile", path=ep.path, line=1, col=0,
                message=f"entry '{name}': second trace of identical inputs "
                        f"raised {_first_line(exc)} — the function consumes "
                        "host state across traces",
            ))
            continue
        if _structure_signature(out) != _structure_signature(out2):
            findings.append(Finding(
                rule="deep-recompile", path=ep.path, line=1, col=0,
                message=f"entry '{name}': two traces of identical inputs "
                        "produced different output structures — every jit "
                        "call will miss the cache (and multi-host programs "
                        "diverge)",
            ))
    return findings


def list_entry_points():
    """name -> path of every registered entry (builtin registration forced)."""
    _register_builtin_entries()
    return {name: ep.path for name, ep in sorted(DEEP_REGISTRY.items())}


# --------------------------------------------------------------------------
# Built-in registry: the package's compiled surfaces.
# --------------------------------------------------------------------------
_BUILTINS_DONE = False


def _sds(shape, dtype):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype))


def _abstract_tree(tree):
    """Concrete pytree -> matching ShapeDtypeStruct pytree."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x)), tree
    )


def _make_deep_trainer():
    """Minimal concrete NNTrainer (MLP classifier, no data handle) — enough
    state for the step builders to close over."""
    import flax.linen as fnn
    import jax.numpy as jnp

    from ..metrics import cross_entropy
    from ..nn import NNTrainer

    class _MLP(fnn.Module):
        @fnn.compact
        def __call__(self, x):
            x = fnn.relu(fnn.Dense(8)(x))
            return fnn.Dense(2)(x)

    class _DeepTrainer(NNTrainer):
        def _init_nn_model(self):
            self.nn["net"] = _MLP()

        def iteration(self, params, batch, rng=None):
            logits = self.nn["net"].apply(params["net"], batch["inputs"])
            mask = batch.get("_mask")
            loss = cross_entropy(logits, batch["labels"], mask=mask)
            pred = jnp.argmax(logits, axis=-1)
            return {"loss": loss, "pred": pred, "true": batch["labels"]}

    trainer = _DeepTrainer(cache={
        "input_shape": (4,), "learning_rate": 1e-2, "seed": 0,
        # donation ON: the registry models the production (accelerator)
        # configuration — tier-3 lowers these entries under
        # jax_compat.force_donation, so the perf-donation rule audits the
        # real donate_argnums intent (eval_shape is unaffected either way)
        "donate_buffers": True, "local_data_parallel": False,
    })
    trainer.init_nn()
    return trainer


def _register_builtin_entries():
    global _BUILTINS_DONE
    if _BUILTINS_DONE:
        return
    _BUILTINS_DONE = True

    @register_entry_point(
        "trainer-train-step", "coinstac_dinunet_tpu/nn/basetrainer.py"
    )
    def _entry_trainer_train():
        trainer = _make_deep_trainer()
        metrics_shell, averages_shell = trainer._metrics_shell()

        def step(ts, stacked):
            grads, aux = trainer._grads_uncompiled(
                ts, stacked, metrics_shell, averages_shell
            )
            ts = trainer._apply_updates(ts, grads)
            return ts, aux

        ts = _abstract_tree(trainer.train_state)
        stacked = {  # k=2 micro-batches exercises the grad-accumulation scan
            "inputs": _sds((2, 4, 4), "float32"),
            "labels": _sds((2, 4), "int32"),
        }
        return step, (ts, stacked)

    @register_entry_point(
        "trainer-eval-step", "coinstac_dinunet_tpu/nn/basetrainer.py"
    )
    def _entry_trainer_eval():
        trainer = _make_deep_trainer()
        metrics_shell, averages_shell = trainer._metrics_shell()

        def ev(ts, batch):
            it = trainer.iteration(ts.params, batch, None)
            return trainer._step_outputs(it, batch, metrics_shell, averages_shell)

        ts = _abstract_tree(trainer.train_state)
        batch = {
            "inputs": _sds((4, 4), "float32"),
            "labels": _sds((4,), "int32"),
        }
        return ev, (ts, batch)

    @register_entry_point(
        "trainer-train-jit", "coinstac_dinunet_tpu/nn/basetrainer.py",
        arg_names=("train_state", "stacked"),
    )
    def _entry_trainer_train_jit():
        # the REAL single-device hot-path jit (donation as production
        # resolves it) — the tier-3 donation/dtype audit target
        trainer = _make_deep_trainer()
        step = trainer._build_train_step()
        ts = _abstract_tree(trainer.train_state)
        stacked = {
            "inputs": _sds((2, 4, 4), "float32"),
            "labels": _sds((2, 4), "int32"),
        }
        return step, (ts, stacked)

    @register_entry_point(
        "trainer-dp-train-step", "coinstac_dinunet_tpu/nn/basetrainer.py",
        arg_names=("train_state", "stacked"),
    )
    def _entry_trainer_dp():
        from ..utils.jax_compat import resolve_donate_argnums

        trainer = _make_deep_trainer()
        step = trainer._build_dp_step(
            REQUIRED_DEVICES, apply_updates=True,
            donate=resolve_donate_argnums(trainer.cache, (0,)),
        )
        ts = _abstract_tree(trainer.train_state)
        stacked = {  # batch dim shards over the 8-device axis
            "inputs": _sds((1, 8, 4), "float32"),
            "labels": _sds((1, 8), "int32"),
        }
        return step, (ts, stacked)

    @register_entry_point(
        "mesh-federation-dsgd-step", "coinstac_dinunet_tpu/parallel/mesh.py",
        arg_names=("train_state", "stacked", "comm_state"),
    )
    def _entry_mesh_dsgd():
        import jax

        from ..parallel.mesh import MeshFederation

        trainer = _make_deep_trainer()
        fed = MeshFederation(
            trainer, n_sites=REQUIRED_DEVICES,
            devices=jax.devices()[:REQUIRED_DEVICES],
        )
        step = fed._build_step()
        ts = _abstract_tree(trainer.train_state)
        stacked = {  # (site, k, B, F)
            "inputs": _sds((8, 1, 4, 4), "float32"),
            "labels": _sds((8, 1, 4), "int32"),
        }
        return step, (ts, stacked, {})

    def _fed_vector_entry(n_sites):
        import jax

        from ..federation.vector import SiteVectorizedFederation

        trainer = _make_deep_trainer()
        fed = SiteVectorizedFederation(
            trainer, n_sites=n_sites,
            devices=jax.devices()[:REQUIRED_DEVICES],
        )
        step = fed._build_step()
        params = _abstract_tree(trainer.train_state.params)
        site_state = _abstract_tree(fed._stacked_site_state())
        site_ix = _sds((n_sites,), "int32")
        stacked = {  # (site, k, B, F)
            "inputs": _sds((n_sites, 1, 4, 4), "float32"),
            "labels": _sds((n_sites, 1, 4), "int32"),
        }
        return step, (params, site_state, site_ix, stacked)

    @register_entry_point(
        "fed-vector-step", "coinstac_dinunet_tpu/federation/vector.py",
        arg_names=("params", "site_state", "site_ix", "stacked"),
    )
    def _entry_fed_vector():
        # the mega-federation one-jit round, SITE axis sharded over the 8
        # virtual devices (shard_map path) — the ISSUE-8 donation target
        return _fed_vector_entry(REQUIRED_DEVICES)

    @register_entry_point(
        "fed-vector-step-vmap", "coinstac_dinunet_tpu/federation/vector.py",
        arg_names=("params", "site_state", "site_ix", "stacked"),
    )
    def _entry_fed_vector_vmap():
        # indivisible site count -> shards=1: the pure-vmap jit build
        return _fed_vector_entry(REQUIRED_DEVICES - 3)

    @register_entry_point(
        "powersgd-reducer", "coinstac_dinunet_tpu/parallel/powersgd.py"
    )
    def _entry_powersgd():
        from ..ops import orthogonalize
        from ..parallel.powersgd import compress_P, compress_Q, reconstruct

        def round_trip(M, Q):
            phat = orthogonalize(compress_P(M, Q))
            qn = compress_Q(M, phat)
            return reconstruct(phat, qn)

        return round_trip, (_sds((64, 32), "float32"), _sds((32, 4), "float32"))

    @register_entry_point(
        "rankdad-reducer", "coinstac_dinunet_tpu/parallel/rankdad.py"
    )
    def _entry_rankdad():
        import functools

        from ..ops import power_iteration_BC

        fn = functools.partial(power_iteration_BC, rank=4, iterations=3)
        return fn, (
            _sds((32, 16), "float32"),
            _sds((32, 8), "float32"),
            _sds((2,), "uint32"),  # PRNG key
        )

    @register_entry_point(
        "ring-attention", "coinstac_dinunet_tpu/parallel/ring_attention.py"
    )
    def _entry_ring():
        import functools

        import jax
        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P

        from ..config.keys import MeshAxis
        from ..parallel.ring_attention import ring_attention
        from ..utils.jax_compat import shard_map

        mesh = Mesh(
            np.array(jax.devices()[:REQUIRED_DEVICES]), (MeshAxis.SP,)
        )
        spec = P(None, None, MeshAxis.SP, None)
        fn = shard_map(
            functools.partial(
                ring_attention, axis_name=MeshAxis.SP, causal=True
            ),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        )
        q = _sds((2, 4, 64, 8), "float32")
        return fn, (q, q, q)

    @register_entry_point(
        "ulysses-attention", "coinstac_dinunet_tpu/parallel/ring_attention.py"
    )
    def _entry_ulysses():
        import functools

        import jax
        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P

        from ..config.keys import MeshAxis
        from ..parallel.ring_attention import ulysses_attention
        from ..utils.jax_compat import shard_map

        mesh = Mesh(
            np.array(jax.devices()[:REQUIRED_DEVICES]), (MeshAxis.SP,)
        )
        spec = P(None, None, MeshAxis.SP, None)
        fn = shard_map(
            functools.partial(ulysses_attention, axis_name=MeshAxis.SP),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        )
        q = _sds((2, 8, 64, 8), "float32")  # heads divisible by the 8 ranks
        return fn, (q, q, q)

    @register_entry_point(
        "pipeline-train-step", "coinstac_dinunet_tpu/parallel/pipeline.py"
    )
    def _entry_pipeline():
        from ..parallel.pipeline import build_pp_mesh, make_pp_train_step
        from ..parallel.sequence import TSPConfig, init_tsp_params
        import jax

        from ..parallel.pipeline import stack_layers

        cfg = TSPConfig(num_features=4, num_classes=2, d_model=16,
                        num_heads=4, num_layers=4, max_len=64)
        mesh = build_pp_mesh(pp=4, dp=2)
        step = make_pp_train_step(cfg, mesh)
        params = _abstract_tree(jax.eval_shape(
            lambda k: stack_layers(init_tsp_params(k, cfg)),
            jax.random.PRNGKey(0),
        ))
        # per-dp-rank batch 4 divides the 4 microbatches exactly
        return step, (params, _sds((8, 16, 4), "float32"), _sds((8,), "int32"))

    @register_entry_point(
        "tsp-train-step", "coinstac_dinunet_tpu/parallel/sequence.py"
    )
    def _entry_tsp():
        import jax

        from ..parallel.sequence import (
            TSPConfig, build_tsp_mesh, init_tsp_params, make_tsp_train_step,
        )

        cfg = TSPConfig(num_features=4, num_classes=2, d_model=16,
                        num_heads=4, num_layers=2, max_len=64)
        mesh = build_tsp_mesh(dp=2, tp=2, sp=2, ep=1)
        step = make_tsp_train_step(cfg, mesh)
        params = _abstract_tree(
            jax.eval_shape(lambda k: init_tsp_params(k, cfg),
                           jax.random.PRNGKey(0))
        )
        return step, (params, _sds((4, 16, 4), "float32"), _sds((4,), "int32"))

    @register_entry_point(
        "tsp-moe-train-step", "coinstac_dinunet_tpu/parallel/sequence.py"
    )
    def _entry_tsp_moe():
        import jax

        from ..parallel.sequence import (
            TSPConfig, build_tsp_mesh, init_tsp_params, make_tsp_train_step,
        )

        cfg = TSPConfig(num_features=4, num_classes=2, d_model=16,
                        num_heads=4, num_layers=1, max_len=64, num_experts=2)
        mesh = build_tsp_mesh(dp=2, tp=1, sp=2, ep=2)
        step = make_tsp_train_step(cfg, mesh)
        params = _abstract_tree(
            jax.eval_shape(lambda k: init_tsp_params(k, cfg),
                           jax.random.PRNGKey(0))
        )
        return step, (params, _sds((4, 16, 4), "float32"), _sds((4,), "int32"))
