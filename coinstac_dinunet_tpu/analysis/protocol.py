"""protocol-conformance: static producer/consumer matching of the
local↔remote JSON handshake.

Control flows between :class:`~coinstac_dinunet_tpu.nodes.COINNLocal` and
:class:`~coinstac_dinunet_tpu.nodes.COINNRemote` as bare string keys inside
the round ``output`` dicts.  Nothing at runtime checks that a key one side
writes is ever read by the other — a typo'd key silently degrades the
protocol (a site that writes ``"grad_file"`` never reduces; an aggregator
that reads ``"weights"`` never broadcasts).  This rule extracts both sides'
key sets from the AST and reports:

- ``produced but never consumed`` — side A writes a key side B never reads,
- ``consumed but never produced`` — side B reads a key side A never writes,
- ``not declared in config/keys.py`` — a wire key missing from the
  :class:`LocalWire`/:class:`RemoteWire` vocabulary (the single source of
  truth),
- ``declared but never used`` — a vocabulary entry no code references.

Extraction grammar (deliberately conservative — only statically-resolvable
string keys count; dynamic keys are ignored):

- produce: ``out[K] = ...`` / ``self.out[K] = ...`` anywhere in a side's
  class, and ``return {K: ...}`` dict literals inside well-known producer
  methods (``reduce``/``to_reduce``/``step``/``*_distributed``/...).
- consume: ``...input...[K]`` / ``...input....get(K)``, ``site_vars.get(K)``
  / ``site[K]`` / ``K in site``, ``check(_, K, ...)``,
  ``gather([K, ...], <expr involving input>)``, and ``self._load(K)``.

``K`` may be a string literal or a ``Key``/``LocalWire``/``RemoteWire``/
``Phase``/``Mode`` enum reference (``LocalWire.PHASE.value``), resolved by
parsing ``config/keys.py`` — never by importing it.

Sides are assigned per class: ``*Learner``/``*Trainer``/``COINNLocal`` are
site code, ``*Reducer``/``COINNRemote`` aggregator code; module-level code
follows its file (``nodes/local.py`` → site, ``nodes/remote.py`` → agg).
Keys listed in ``ENGINE_PROVIDED_KEYS`` are injected by the engine/compspec
on the first invocation and are exempt from producer matching.
"""
import ast
import os

from .core import Finding, ProjectRule, register_rule

#: repo-relative path suffixes taking part in the handshake, with the side
#: of their module-level (non-class) code.
PROTOCOL_FILES = {
    "nodes/local.py": "site",
    "nodes/remote.py": "agg",
    "parallel/learner.py": "site",
    "parallel/powersgd.py": None,  # Learner + Reducer classes, split per class
    "parallel/rankdad.py": None,
    "parallel/reducer.py": "agg",
    # elastic membership (ISSUE 15): the aggregator's roster rounds
    # consume the ``leaving`` flag + ``roster_epoch`` echo per site
    "federation/membership.py": "agg",
    "trainer.py": "site",
}

#: methods whose ``return {literal: ...}`` dicts are wire payloads
PRODUCER_METHODS = {
    "compute", "step", "to_reduce", "backward", "reduce",
    "validation_distributed", "test_distributed", "train_serializable",
    "_init_runs", "_next_run", "_pretrain_local", "_pre_compute",
    "_send_global_scores",
}

#: classes whose ``input`` reads are NOT peer messages (COINNTrainer's
#: ``input`` is the engine compspec view, not the aggregator broadcast)
CONSUME_EXEMPT_CLASSES = {"COINNTrainer"}

_ENUM_CLASSES = ("Key", "LocalWire", "RemoteWire", "Phase", "Mode",
                 "AggEngine", "GatherMode")
_SITE_VAR_NAMES = {"site", "site_vars"}


def _class_side(name):
    if name.endswith("Learner") or name.endswith("Trainer") or name == "COINNLocal":
        return "site"
    if name.endswith("Reducer") or name == "COINNRemote":
        return "agg"
    return None


def _keys_module_path():
    return os.path.normpath(
        os.path.join(os.path.dirname(__file__), "..", "config", "keys.py")
    )


def load_vocabulary(keys_source=None):
    """Parse config/keys.py (source text or the package's own copy) into
    ``(enum_map, local_vocab, remote_vocab, engine_provided)``."""
    if keys_source is None:
        with open(_keys_module_path(), "r", encoding="utf-8") as f:
            keys_source = f.read()
    tree = ast.parse(keys_source)
    enum_map, local_vocab, remote_vocab = {}, set(), set()
    engine_provided = set()
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            for stmt in node.body:
                if (
                    isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, str)
                ):
                    member = stmt.targets[0].id
                    value = stmt.value.value
                    enum_map[(node.name, member)] = value
                    if node.name == "LocalWire":
                        local_vocab.add(value)
                    elif node.name == "RemoteWire":
                        remote_vocab.add(value)
        elif isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if "ENGINE_PROVIDED_KEYS" in targets and isinstance(
                node.value, (ast.Tuple, ast.List)
            ):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                        engine_provided.add(elt.value)
    return enum_map, local_vocab, remote_vocab, engine_provided


def _resolve_key(node, enum_map):
    """AST expr → wire-key string, or None if dynamic/unresolvable."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    # Key.X / Key.X.value (possibly through a module alias prefix)
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        if parts and parts[-1] == "value":
            parts = parts[:-1]
        for i in range(len(parts) - 1):
            hit = enum_map.get((parts[i], parts[i + 1]))
            if hit is not None:
                return hit
    return None


def _contains_input(node):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id == "input":
            return True
        if isinstance(sub, ast.Attribute) and sub.attr == "input":
            return True
    return False


class _Use:
    __slots__ = ("key", "path", "line", "col")

    def __init__(self, key, path, line, col):
        self.key, self.path, self.line, self.col = key, path, line, col


class _Extractor(ast.NodeVisitor):
    """Collects produced/consumed keys from one protocol module."""

    def __init__(self, module, default_side, enum_map):
        self.module = module
        self.enum_map = enum_map
        self.side_stack = [default_side]
        self.class_stack = []
        self.fn_stack = []
        # {'site': [...], 'agg': [...]} of _Use
        self.produced = {"site": [], "agg": []}
        self.consumed = {"site": [], "agg": []}

    # ------------------------------------------------------------- structure
    def visit_ClassDef(self, node):
        side = _class_side(node.name) or self.side_stack[-1]
        self.side_stack.append(side)
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()
        self.side_stack.pop()

    def visit_FunctionDef(self, node):
        self.fn_stack.append(node.name)
        self.generic_visit(node)
        self.fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    # ------------------------------------------------------------- recording
    def _side(self):
        return self.side_stack[-1]

    def _record(self, table, key_node, at=None):
        side = self._side()
        if side is None:
            return
        key = _resolve_key(key_node, self.enum_map)
        if key is None:
            return
        at = at or key_node
        table[side].append(
            _Use(key, self.module.path, at.lineno, at.col_offset)
        )

    def _consume_exempt(self):
        return bool(self.class_stack) and (
            self.class_stack[-1] in CONSUME_EXEMPT_CLASSES
        )

    # --------------------------------------------------------------- produce
    def visit_Assign(self, node):
        for target in node.targets:
            if isinstance(target, ast.Subscript):
                base = target.value
                is_out = (
                    isinstance(base, ast.Name) and base.id == "out"
                ) or (
                    isinstance(base, ast.Attribute) and base.attr == "out"
                )
                if is_out:
                    self._record(self.produced, target.slice, at=target)
        self.generic_visit(node)

    def visit_Return(self, node):
        if (
            isinstance(node.value, ast.Dict)
            and self.fn_stack
            and self.fn_stack[-1] in PRODUCER_METHODS
        ):
            for key_node in node.value.keys:
                if key_node is not None:
                    self._record(self.produced, key_node, at=node)
        self.generic_visit(node)

    # --------------------------------------------------------------- consume
    def visit_Subscript(self, node):
        if isinstance(node.ctx, ast.Load) and not self._consume_exempt():
            base = node.value
            if _contains_input(base) or (
                isinstance(base, ast.Name) and base.id in _SITE_VAR_NAMES
            ):
                self._record(self.consumed, node.slice, at=node)
        self.generic_visit(node)

    def visit_Compare(self, node):
        # "key" in site / "key" in site_vars
        if (
            len(node.ops) == 1
            and isinstance(node.ops[0], ast.In)
            and not self._consume_exempt()
        ):
            target = node.comparators[0]
            if _contains_input(target) or (
                isinstance(target, ast.Name) and target.id in _SITE_VAR_NAMES
            ):
                self._record(self.consumed, node.left, at=node)
        self.generic_visit(node)

    def visit_Call(self, node):
        func = node.func
        fname = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None
        )
        if self._consume_exempt():
            self.generic_visit(node)
            return
        if fname == "get" and isinstance(func, ast.Attribute) and node.args:
            base = func.value
            if _contains_input(base) or (
                isinstance(base, ast.Name) and base.id in _SITE_VAR_NAMES
            ):
                self._record(self.consumed, node.args[0])
        elif fname == "check" and len(node.args) >= 2:
            self._record(self.consumed, node.args[1])
        elif (
            fname == "gather"
            and len(node.args) >= 2
            and isinstance(node.args[0], (ast.List, ast.Tuple))
            # only gathers over the round ``input`` read wire keys; gathers
            # over nested payloads (e.g. serialized {averages, metrics}
            # blobs) are local data-shuffling, not protocol consumption
            and _contains_input(node.args[1])
        ):
            for elt in node.args[0].elts:
                self._record(self.consumed, elt)
        elif fname == "_load" and node.args:
            self._record(self.consumed, node.args[0])
        self.generic_visit(node)


@register_rule
class ProtocolConformanceRule(ProjectRule):
    id = "protocol-conformance"
    doc = ("Producer/consumer agreement of the local<->remote wire keys, "
           "cross-checked against the LocalWire/RemoteWire vocabulary in "
           "config/keys.py.")

    def __init__(self, keys_source=None, protocol_files=None):
        self._keys_source = keys_source
        self._files = dict(protocol_files or PROTOCOL_FILES)

    def _file_role(self, path):
        norm = path.replace(os.sep, "/")
        for suffix, side in self._files.items():
            if norm.endswith(suffix):
                return suffix, side
        return None, None

    def finalize(self, modules):
        relevant, present = [], set()
        for mod in modules:
            suffix, side = self._file_role(mod.path)
            if suffix is not None:
                relevant.append((mod, side))
                present.add(suffix)
        # Producer/consumer matching is a whole-protocol property: with only
        # one side (or one learner) in scope every key on the missing side
        # would be reported unmatched.  Partial scans — single-file lints,
        # editor integration — are skipped rather than flooded with false
        # positives; the package-wide run (scripts/lint.sh, the tier-1
        # self-check) always has the full file set.
        if present != set(self._files):
            return []
        enum_map, local_vocab, remote_vocab, engine_provided = (
            load_vocabulary(self._keys_source)
        )
        produced = {"site": [], "agg": []}
        consumed = {"site": [], "agg": []}
        for mod, default_side in relevant:
            ex = _Extractor(mod, default_side, enum_map)
            ex.visit(mod.tree)
            for side in ("site", "agg"):
                produced[side].extend(ex.produced[side])
                consumed[side].extend(ex.consumed[side])

        findings = []

        def first(uses, key):
            return min(
                (u for u in uses if u.key == key),
                key=lambda u: (u.path, u.line),
            )

        def check_direction(direction, prod_uses, cons_uses, vocab):
            prod_keys = {u.key for u in prod_uses}
            cons_keys = {u.key for u in cons_uses}
            for key in sorted(prod_keys - cons_keys):
                u = first(prod_uses, key)
                findings.append(Finding(
                    rule=self.id, path=u.path, line=u.line, col=u.col,
                    message=f"{direction} key '{key}' is produced but never "
                            "consumed by the peer",
                ))
            for key in sorted(cons_keys - prod_keys - engine_provided):
                u = first(cons_uses, key)
                findings.append(Finding(
                    rule=self.id, path=u.path, line=u.line, col=u.col,
                    message=f"{direction} key '{key}' is consumed but never "
                            "produced by the peer",
                ))
            for key in sorted((prod_keys | cons_keys) - vocab - engine_provided):
                u = first(list(prod_uses) + list(cons_uses), key)
                findings.append(Finding(
                    rule=self.id, path=u.path, line=u.line, col=u.col,
                    message=f"{direction} key '{key}' is not declared in the "
                            f"config/keys.py {direction} vocabulary",
                ))
            for key in sorted(vocab - prod_keys - cons_keys):
                findings.append(Finding(
                    rule=self.id, path="coinstac_dinunet_tpu/config/keys.py",
                    line=1, col=0,
                    message=f"{direction} vocabulary key '{key}' is declared "
                            "but never produced or consumed",
                ))

        # site -> aggregator: sites produce, aggregator consumes
        check_direction("LocalWire", produced["site"], consumed["agg"],
                        local_vocab)
        # aggregator -> site
        check_direction("RemoteWire", produced["agg"], consumed["site"],
                        remote_vocab)
        return findings
