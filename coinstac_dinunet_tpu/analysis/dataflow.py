"""dinulint tier-3: the jaxpr dataflow tier (``--tier3``).

Tier 1 reads source text; tier 2 (``--deep``) proves the compiled surfaces
*trace*.  Neither can see what the traced program actually **does**: whether
the train step donates the state buffers it replaces, whether a bf16 step
quietly computes a matmul in f32, whether a host callback rides inside the
hot loop, whether a closure baked a half-gigabyte constant into the
executable.  Those are exactly the levers docs/PERF.md names and the ones
one-jit designs (Podracer, arXiv:2104.06272) live or die by — so this tier
lowers every registered entry point of :mod:`.deepcheck` to its jaxpr
(``jax.make_jaxpr``) and staged lowering (``fn.lower(*args)``) and runs the
dataflow rule families over the result:

- :mod:`.perf_rules` — ``perf-donation`` / ``perf-dtype-promotion`` /
  ``perf-host-sync`` / ``perf-constant-capture`` over the lowered entries;
- :mod:`.protocol_flow` — the static phase-machine + cache-lifecycle model
  of the invocation-per-round protocol (pure AST; runs even when the JAX
  platform is unavailable).

Findings are ordinary :class:`~.core.Finding` objects and flow through the
same baseline/suppression machinery as every other tier.

Build/lowering results are cached process-wide per entry name, and
``run_deepcheck`` accepts the same cache — so a CLI invocation combining
``--tier3 --deep`` builds each entry's trainer/mesh/federation exactly once
(the builders, not the traces, dominate the wall time).  Builders run under
:func:`~..utils.jax_compat.force_donation` so donation intent is resolved
as production (accelerator) builds would resolve it, not as the CPU
analysis platform would.
"""
import ast
import dataclasses
import functools
import os

from .core import Finding

#: every rule id this tier can emit (docs + ``--list-rules``)
TIER3_RULE_IDS = (
    "tier3-config",
    "tier3-lower",
    "perf-donation",
    "perf-dtype-promotion",
    "perf-host-sync",
    "perf-constant-capture",
    "proto-flow-phase",
    "proto-flow-unmatched",
    "proto-cache-read-before-write",
    "proto-cache-never-read",
    "proto-cache-volatile",
)


@dataclasses.dataclass
class LoweredEntry:
    """One deep-registry entry, lowered for dataflow analysis.

    ``closed_jaxpr`` is the ``jax.make_jaxpr`` result (None when lowering
    failed — ``error`` carries the reason).  ``args_info``/``out_info`` are
    the staged ``Lowered.args_info`` / ``Lowered.out_info`` pytrees for
    jit-wrapped entries (None for plain callables: only actual jit
    surfaces carry donation metadata)."""

    name: str
    path: str
    fn: object = None
    args: tuple = ()
    arg_names: tuple = ()
    closed_jaxpr: object = None
    args_info: object = None
    out_info: object = None
    error: str = None


# ------------------------------------------------------- shared build cache
# entry name -> ("ok", fn, args) | ("error", first-line message).  Shared
# with run_deepcheck (the ``builds=`` parameter) so --tier3 --deep builds
# each entry once per process.
_BUILD_CACHE = {}


def clear_build_cache():
    """Test hook: entries built under monkeypatched registries must not
    leak into later runs."""
    _BUILD_CACHE.clear()


def build_entry(name):
    """Build (or fetch the cached build of) one registry entry, under the
    production donation resolution.  Never raises."""
    from ..utils.jax_compat import force_donation
    from .deepcheck import DEEP_REGISTRY, _first_line

    if name not in _BUILD_CACHE:
        try:
            with force_donation():
                fn, args = DEEP_REGISTRY[name].build()
            _BUILD_CACHE[name] = ("ok", fn, args)
        except Exception as exc:  # noqa: BLE001 — failures become findings
            _BUILD_CACHE[name] = ("error", _first_line(exc))
    return _BUILD_CACHE[name]


@functools.lru_cache(maxsize=256)
def _jit_call_lines(entry_path):
    """Best-effort source anchor: the ``jax.jit(...)`` call lines in the
    entry's anchored module (so a donation finding points at the build
    site, e.g. ``federation/vector.py:230``), or [] when unresolvable.
    Memoized: every perf rule asks per entry, and re-parsing the same
    module ~50 times per run would dominate the rules' own cost."""
    root = os.path.normpath(
        os.path.join(os.path.dirname(__file__), "..", "..")
    )
    path = os.path.join(root, entry_path)
    if not os.path.exists(path):
        return []
    try:
        with open(path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (SyntaxError, OSError, UnicodeDecodeError, ValueError):
        return []
    lines = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None
            )
            if name == "jit":
                lines.append(node.lineno)
    return sorted(lines)


def entry_anchor_line(entry_path):
    """Line findings against a lowered entry anchor to: the module's first
    jit build site, else line 1."""
    lines = _jit_call_lines(entry_path)
    return lines[0] if lines else 1


def lower_entry(name):
    """Build + lower one registry entry.  Returns a :class:`LoweredEntry`
    whose ``error`` field (if set) is the typed failure; never raises."""
    import jax

    from .deepcheck import DEEP_REGISTRY, _first_line

    ep = DEEP_REGISTRY[name]
    arg_names = tuple(getattr(ep, "arg_names", ()) or ())
    status = build_entry(name)
    if status[0] == "error":
        return LoweredEntry(name=name, path=ep.path, arg_names=arg_names,
                            error=f"builder raised {status[1]}")
    fn, args = status[1], status[2]
    out = LoweredEntry(name=name, path=ep.path, fn=fn, args=args,
                       arg_names=arg_names)
    try:
        out.closed_jaxpr = jax.make_jaxpr(lambda *a: fn(*a))(*args)
    except Exception as exc:  # noqa: BLE001
        out.error = f"make_jaxpr failed with {_first_line(exc)}"
        return out
    if hasattr(fn, "lower"):
        try:
            lowered = fn.lower(*args)
            out.args_info = lowered.args_info
            out.out_info = lowered.out_info
        except Exception as exc:  # noqa: BLE001 — jaxpr rules still run
            out.error = f"lower failed with {_first_line(exc)}"
    return out


# ------------------------------------------------------- jaxpr walk helpers
def _jaxpr_types():
    try:
        from jax.extend import core as jex_core

        return jex_core.Jaxpr, jex_core.ClosedJaxpr
    except Exception:  # noqa: BLE001 — pre-extend versions
        from jax import core as jcore

        return jcore.Jaxpr, jcore.ClosedJaxpr


def sub_jaxprs(eqn):
    """Inner (Jaxpr, consts) pairs referenced by one equation's params —
    pjit bodies, scan/while/cond branches, custom_vjp jaxprs, shard_map
    bodies all live here."""
    Jaxpr, ClosedJaxpr = _jaxpr_types()
    out = []
    for value in eqn.params.values():
        items = value if isinstance(value, (list, tuple)) else (value,)
        for item in items:
            if isinstance(item, ClosedJaxpr):
                out.append((item.jaxpr, list(item.consts)))
            elif isinstance(item, Jaxpr):
                out.append((item, []))
    return out


#: call-like primitives whose eqn invars map positionally onto the inner
#: jaxpr's invars — safe to propagate "this var is a top-level argument"
#: through.  scan/while/cond re-pack their operands and are excluded
#: (conservative: arguments lose their identity there).
TRANSPARENT_CALL_PRIMS = frozenset((
    "pjit", "jit", "xla_call", "closed_call", "core_call", "custom_jvp_call",
    "custom_vjp_call", "custom_vjp_call_jaxpr", "remat", "remat2",
    "checkpoint", "shard_map",
))


def is_var(atom):
    """True for jaxpr Vars (hashable, def-use trackable); False for
    embedded Literals (which carry ``.val``)."""
    return not hasattr(atom, "val")


def walk_jaxprs(closed_jaxpr):
    """Yield ``(jaxpr, consts, arg_vars)`` for the top jaxpr and every
    nested jaxpr, depth-first.  ``arg_vars`` is the set of vars in that
    jaxpr known to alias a TOP-LEVEL entry argument (propagated through
    :data:`TRANSPARENT_CALL_PRIMS` only)."""
    top = closed_jaxpr.jaxpr
    stack = [(top, list(closed_jaxpr.consts), set(top.invars))]
    while stack:
        jaxpr, consts, arg_vars = stack.pop()
        yield jaxpr, consts, arg_vars
        for eqn in jaxpr.eqns:
            subs = sub_jaxprs(eqn)
            transparent = (
                eqn.primitive.name in TRANSPARENT_CALL_PRIMS
                and len(subs) == 1
            )
            for inner, inner_consts, in subs:
                inner_args = set()
                if transparent and len(inner.invars) == len(eqn.invars):
                    for outer_v, inner_v in zip(eqn.invars, inner.invars):
                        if (is_var(outer_v) and outer_v in arg_vars
                                and is_var(inner_v)):
                            inner_args.add(inner_v)
                stack.append((inner, inner_consts, inner_args))


# ----------------------------------------------------------------- tier run
def run_tier3(names=None, paths=None, perf_rules=None):
    """The ``--tier3`` pass: lower the deep registry and run the dataflow
    rule families.  ``names`` filters the registry; ``paths`` scopes the
    protocol-flow never-read scan (default: the installed package).
    Returns findings; never raises.
    """
    from . import deepcheck
    from .protocol_flow import run_protocol_flow

    findings = list(run_protocol_flow(paths=paths))

    deepcheck._register_builtin_entries()
    have = deepcheck.ensure_virtual_devices()
    if have < deepcheck.REQUIRED_DEVICES:
        findings.append(Finding(
            rule="tier3-config",
            path="coinstac_dinunet_tpu/analysis/dataflow.py", line=1, col=0,
            message=f"tier-3 lowering needs {deepcheck.REQUIRED_DEVICES} "
                    f"virtual devices but the initialized JAX backend has "
                    f"{have} — set XLA_FLAGS="
                    "--xla_force_host_platform_device_count=8 before "
                    "anything imports jax",
        ))
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        return findings

    if perf_rules is None:
        from .perf_rules import default_perf_rules

        perf_rules = default_perf_rules()

    wanted = set(names) if names else None
    for name in sorted(deepcheck.DEEP_REGISTRY):
        if wanted is not None and name not in wanted:
            continue
        entry = lower_entry(name)
        if entry.error is not None:
            findings.append(Finding(
                rule="tier3-lower", path=entry.path, line=1, col=0,
                message=f"entry '{name}': {entry.error}",
            ))
        if entry.closed_jaxpr is None:
            continue
        for rule in perf_rules:
            findings.extend(rule.check(entry))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def tier3_builds():
    """The build cache in run_deepcheck's ``builds=`` shape — pass this to
    ``run_deepcheck`` after :func:`run_tier3` so ``--deep`` reuses the
    tier-3 builders instead of reconstructing every trainer/mesh."""
    return dict(_BUILD_CACHE)
