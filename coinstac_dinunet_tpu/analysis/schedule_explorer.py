"""proto-conc-*: the tier-5 deterministic interleaving explorer.

The static half (:mod:`.concurrency`) audits lock discipline
syntactically; this half **executes** the real async round machinery —
``InProcessEngine._step_round_async``'s submit/collect/stand-in loop, the
``.stale`` alias snapshotting, the shared ``_last_site_outs`` replay
record, the engine-lane :class:`~..telemetry.recorder.Recorder`, and the
:class:`~..federation.daemon.DaemonEngine` close/restart supervision —
under a **deterministic schedule** in the spirit of tier-4's BFS.

The switch points are monkeypatched at the engine's own seams: the
bounded invocation pool is replaced by a virtual pool whose futures
complete exactly when the schedule says (before the collect, mid-round
during the aggregator's turn, or not at all — the engine's stand-in /
forced-block logic then runs for real), and the collect-phase grace
window runs under virtual time (no wall-clock waits).  Site and
aggregator invocations are pure-numpy stubs of the PR-12 fedbench task
shape (phase/mode/reduce + a CRC-checked wire payload tagged with the
submission round) so a bounded exploration finishes in seconds and needs
no JAX.

Checked round-loop invariants (each a ``proto-conc-*`` rule from
:class:`~..config.keys.Concurrency`):

- the reduce never observes a torn ``.stale`` alias pair — every
  stand-in's payload must load (manifest + CRC verified) to exactly the
  contribution its ``wire_round`` echo claims;
- ``_last_site_outs`` never loses a commit — every delivered output is
  in the replay record when the round ends;
- recorder JSONL lines stay whole — the engine telemetry lane has zero
  torn/undecodable lines after the bounded run
  (:func:`~..telemetry.collect.read_jsonl_segment`);
- ``close()`` never deadlocks against an in-flight supervised worker
  restart, and a worker spawned concurrently with ``close()`` never
  escapes shutdown (the daemon drill).

Every violation is emitted through the baseline machinery AND as a
**replayable schedule JSON** (``--schedules DIR``) that
:func:`replay_schedule` re-executes to the same violation — exactly like
tier-4's chaos counterexample plans.  The ``_SNAPSHOT_DISABLED`` /
``_DROP_COMMIT`` / ``_TORN_FLUSH`` / ``_DRILL_UNSERIALIZED_SPAWN``
switches (tests only) model the corresponding broken semantics so each
invariant is provably checkable, not vacuous
(``tests/test_analysis_tier5.py``).

Deterministic: fixed enumeration order, virtual completion decisions,
no randomness — the same findings on every run.
"""
import ast
import itertools
import json
import os
import tempfile
import threading
import time

from ..config.keys import Concurrency
from .core import Finding

#: broken-semantics switches (tests only; see the tier-4 idiom in
#: model_check.py).  Each models the bug class its invariant patrols:
#: stand-ins referencing LIVE payload names instead of frozen aliases,
#: the replay record dropping a delivered output, an unlocked concurrent
#: flush tearing a JSONL line, and a supervisor spawning workers outside
#: the engine lock so close() can miss one.
_SNAPSHOT_DISABLED = False
_DROP_COMMIT = False
_TORN_FLUSH = False
_DRILL_UNSERIALIZED_SPAWN = False

#: per-site completion choices a schedule assigns each post-warmup round:
#: ``fresh`` — the invocation completes before the collect phase;
#: ``defer`` — it stays in flight (the engine delivers a stand-in inside
#: the window, or blocks on it at the boundary — both real code paths);
#: ``mid`` — it completes DURING the aggregator's turn, exactly the
#: moment a straggler's next commit can race the in-flight reduce.
CHOICES = ("fresh", "defer", "mid")

EXPLORER_RULE_IDS = (
    Concurrency.CLOSE_DEADLOCK,
    Concurrency.CONFIG,
    Concurrency.LOST_COMMIT,
    Concurrency.TORN_JSONL,
    Concurrency.TORN_STALE,
)

#: hard ceiling on schedules per exploration — a runaway bound must
#: degrade to a typed proto-conc-config finding (tier-4's MAX_STATES
#: idiom), never a hung CI job or a silently-partial "clean" result.
#: Covers --schedule-bound 3 at the default 2 sites (9^3 = 729) with room.
MAX_SCHEDULES = 4096

_INVARIANTS = {
    Concurrency.TORN_STALE: "stand-in payloads match their frozen alias",
    Concurrency.LOST_COMMIT: "every delivered output is recorded for replay",
    Concurrency.TORN_JSONL: "telemetry JSONL lines stay whole",
    Concurrency.CLOSE_DEADLOCK: "close() wins against in-flight restarts",
}


class ScheduleConfig:
    """Exploration bound (defaults = the CI gate's contract): ``sites`` ×
    ``rounds`` post-warmup rounds (one all-fresh warmup round precedes
    them — stand-ins need a recorded contribution), window ``k``, pool
    width, and the schedule-count ceiling (a runaway bound degrades to a
    typed report entry, never a hung CI job)."""

    def __init__(self, sites=None, rounds=None, k=None, pool=None,
                 max_schedules=MAX_SCHEDULES):
        self.sites = int(sites if sites is not None
                         else Concurrency.DEFAULT_SITES)
        self.rounds = int(rounds if rounds is not None
                          else Concurrency.DEFAULT_ROUNDS)
        self.k = int(k if k is not None else Concurrency.DEFAULT_STALENESS_K)
        self.pool = int(pool if pool is not None
                        else Concurrency.DEFAULT_POOL)
        self.max_schedules = int(max_schedules)

    def scenario(self):
        return {"sites": self.sites, "rounds": self.rounds,
                "staleness_k": self.k, "pool": self.pool}


class ScheduleResult:
    def __init__(self, findings, plans, report):
        self.findings = findings
        self.plans = plans
        self.report = report


# ------------------------------------------------------------- virtual pool
class _VirtualPool:
    """Deterministic stand-in for the engine's ThreadPoolExecutor: a
    submitted invocation runs exactly when the schedule completes it (or
    inline, when the engine blocks on its future — the real forced-block
    path)."""

    def __init__(self):
        self.pending = {}
        self.complete_on_submit = set()
        self.mid_round = set()
        self.forced = []

    def submit(self, fn, *args, **kwargs):
        from concurrent.futures import Future

        site = args[2]  # the engine submits (policy, rnd, site, inp, rec)

        class _VirtualFuture(Future):
            def run(fut):
                if getattr(fut, "_ran", False) or fut.cancelled():
                    return
                fut._ran = True
                fut.set_running_or_notify_cancel()
                try:
                    fut.set_result(fn(*args, **kwargs))
                except BaseException as exc:  # noqa: BLE001 — future contract
                    fut.set_exception(exc)

            def result(fut, timeout=None):
                if not fut.done():
                    # the engine blocked on a straggler: the real serial
                    # fallback — run the invocation inline, note it
                    self.forced.append(site)
                    fut.run()
                return Future.result(fut, timeout)

        fut = _VirtualFuture()
        self.pending[site] = fut
        if site in self.complete_on_submit:
            fut.run()
        return fut

    def complete(self, site):
        fut = self.pending.get(site)
        if fut is not None and not fut.done():
            fut.run()

    def run_mid_round(self):
        for site in sorted(self.mid_round):
            self.complete(site)

    def shutdown(self, wait=True, cancel_futures=False):
        pass


# ------------------------------------------------------------- stub engine
def _make_engine(workdir, config):
    """A real :class:`~..engine.InProcessEngine` whose node invocations
    are pure-numpy protocol stubs and whose pool/grace switch points are
    under explorer control.  Deferred import so the static tier never
    pays the engine import."""
    import numpy as np

    from ..config.keys import Mode, Phase
    from ..engine import InProcessEngine
    from ..utils.tensorutils import WireError, load_arrays, save_arrays

    class _ExplorerEngine(InProcessEngine):
        #: the pool threads are virtual — no cap, the schedule decides
        _ASYNC_POOL_CAP = None

        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            self._explorer_pool = _VirtualPool()
            self.violations = []

        # ---- switch points -------------------------------------------
        def _ensure_async_pool(self, size):
            return self._explorer_pool

        def _async_grace(self):
            # virtual time: the schedule decides completion, wall-clock
            # grace is meaningless (and must never sleep)
            return None

        def _async_snapshot_payloads(self, s, out):
            if _SNAPSHOT_DISABLED:
                # broken semantics: the stand-in will reference the LIVE
                # payload names the straggler's next commit overwrites
                self._async_snapshots[s] = {}
                return
            super()._async_snapshot_payloads(s, out)

        def _finish_site_outputs(self, rnd, site_outs, rec, record=True):
            if _DROP_COMMIT:
                return  # broken semantics: the replay record loses them
            super()._finish_site_outputs(rnd, site_outs, rec, record=record)

        # ---- stub node invocations -----------------------------------
        def _site_attempt(self, rnd, s, inp, rec):
            ix = int(s.rsplit("_", 1)[1])
            with rec.span(f"invoke:{s}", cat="invoke", round=rnd):
                path = os.path.join(
                    self.site_states[s]["transferDirectory"], "grads.npy"
                )
                save_arrays(path, [np.array([ix, rnd], dtype=np.int64)])
            return {
                "phase": Phase.COMPUTATION.value, "mode": Mode.TRAIN.value,
                "reduce": True, "grads_file": "grads.npy", "wire_round": rnd,
            }

        def _remote_attempt(self, rnd, site_outs, rec):
            # mid-round completions fire here: the straggler's next commit
            # lands exactly while the reduce is consuming its stand-in
            self._explorer_pool.run_mid_round()
            with rec.span("invoke:remote", cat="invoke"):
                for s in sorted(site_outs):
                    out = site_outs[s]
                    if not out.get("reduce"):
                        continue
                    path = os.path.join(
                        self.site_states[s]["transferDirectory"],
                        out["grads_file"],
                    )
                    claimed = int(out.get("wire_round", rnd))
                    try:
                        arrays = load_arrays(path)
                        tag = int(arrays[0][1])
                    except (WireError, OSError, IndexError,
                            ValueError) as exc:
                        self.violations.append({
                            "rule": Concurrency.TORN_STALE, "round": rnd,
                            "detail": (
                                f"{s}'s payload failed to load during the "
                                f"reduce: {type(exc).__name__}: {exc}"
                            ),
                        })
                        continue
                    if tag != claimed:
                        self.violations.append({
                            "rule": Concurrency.TORN_STALE, "round": rnd,
                            "detail": (
                                f"the reduce consumed round-{tag} data "
                                f"through {s}'s round-{claimed} stand-in "
                                "reference — the stand-in raced the "
                                "straggler's next commit instead of "
                                "reading its frozen .stale alias"
                            ),
                        })
                save_arrays(
                    os.path.join(self.remote_state["transferDirectory"],
                                 "avg_grads.npy"),
                    [np.array([rnd], dtype=np.int64)],
                )
            return {"phase": Phase.COMPUTATION.value, "update": True,
                    "avg_grads_file": "avg_grads.npy"}

    return _ExplorerEngine(
        workdir, config.sites, telemetry=True,
        async_staleness=config.k, async_invoke_pool=config.pool,
    )


def _run_schedule(config, schedule, workdir, report=None):
    """Execute one schedule; returns the violation dicts it produced."""
    from ..telemetry.collect import read_jsonl_segment

    eng = _make_engine(workdir, config)
    rec = eng._recorder()
    if _TORN_FLUSH and rec.enabled:
        # broken semantics: a concurrent unlocked flush appends a torn
        # (unterminated) fragment the next append merges into garbage
        orig_flush = rec.flush

        def torn_flush():
            orig_flush()
            path = rec.path()
            if path:
                with open(path, "a", encoding="utf-8") as f:
                    f.write('{"v": 1, "kind": "ev')

        rec.flush = torn_flush
    violations = []
    try:
        warmup = {s: "fresh" for s in eng.site_ids}
        for decision in [warmup] + list(schedule):
            pool = eng._explorer_pool
            pool.complete_on_submit = {
                s for s, c in decision.items() if c == "fresh"
            }
            pool.mid_round = {s for s, c in decision.items() if c == "mid"}
            # futures still pending from earlier rounds complete now when
            # the schedule marks their site fresh
            for s in sorted(pool.complete_on_submit):
                pool.complete(s)
            site_outs, _remote_out = eng.step_round()
            rnd = eng.rounds
            for s in sorted(site_outs):
                out = site_outs[s]
                recorded = eng._last_site_outs.get(s)
                if recorded is None or (
                    recorded.get("wire_round") != out.get("wire_round")
                ):
                    violations.append({
                        "rule": Concurrency.LOST_COMMIT, "round": rnd,
                        "detail": (
                            f"{s}'s delivered round-"
                            f"{out.get('wire_round')} output is missing "
                            "from _last_site_outs when the round ends — "
                            "a later stand-in/replay would redeliver "
                            f"{'nothing' if recorded is None else 'round-' + str(recorded.get('wire_round'))}"
                        ),
                    })
        violations.extend(eng.violations)
        rec.flush()
        path = rec.path() if rec.enabled else None
        if path and os.path.exists(path):
            _records, _off, bad, partial = read_jsonl_segment(path)
            if bad or partial:
                violations.append({
                    "rule": Concurrency.TORN_JSONL, "round": eng.rounds,
                    "detail": (
                        f"the engine telemetry lane holds {bad} "
                        "undecodable line(s)"
                        + (" and a torn unterminated tail" if partial
                           else "")
                        + " after the bounded run — concurrent appends "
                        "interleaved mid-record"
                    ),
                })
    finally:
        if report is not None:
            report["forced_blocks"] += len(eng._explorer_pool.forced)
        eng.close()
    return violations


# --------------------------------------------------------------- close drill
def run_close_drill(workdir):
    """Deterministic DaemonEngine close-vs-restart interleaving: a
    supervised restart holds the engine's worker lock mid-spawn while
    ``close()`` runs.  The fixed contract — spawn under the lock, the
    closing flag checked first — means close() must block briefly, then
    shut the concurrently-registered worker down too.  Returns violation
    dicts (empty on the healthy tree)."""
    from ..federation import daemon as daemon_mod
    from ..telemetry.recorder import NULL_RECORDER

    spawn_entered = threading.Event()
    release_spawn = threading.Event()
    created = []

    class _FakeWorker:
        def __init__(self, target, script, env=None, log_path=None,
                     start_timeout=None):
            self.target = str(target)
            self.pid = 0
            self.warm_s = 0.0
            self.stopped = False
            created.append(self)
            spawn_entered.set()
            release_spawn.wait(timeout=5.0)

        def alive(self):
            return not self.stopped

        def shutdown(self, grace=3.0):
            self.stopped = True

        def kill(self):
            self.stopped = True

    eng = daemon_mod.DaemonEngine(
        workdir, 1, local_script="local.py", remote_script="remote.py",
    )
    orig_worker = daemon_mod._Worker
    daemon_mod._Worker = _FakeWorker
    violations = []
    try:
        if _DRILL_UNSERIALIZED_SPAWN:
            # the broken supervisor shape: the spawn happens OUTSIDE the
            # engine lock, so a close() racing it snapshots an empty
            # worker table and the late registration escapes shutdown
            def restart():
                with eng._worker_lock:
                    if eng._closing:
                        return
                w = _FakeWorker("site_0", "local.py")
                with eng._worker_lock:
                    eng._workers["site_0"] = w
        else:
            def restart():
                try:
                    eng._ensure_worker("site_0", "local.py", NULL_RECORDER)
                except RuntimeError:
                    pass  # a closing engine refuses the respawn: correct

        t = threading.Thread(target=restart, daemon=True,
                             name="tier5-drill-restart")
        t.start()
        spawn_entered.wait(timeout=5.0)

        closed = threading.Event()

        def do_close():
            eng.close()
            closed.set()

        c = threading.Thread(target=do_close, daemon=True,
                             name="tier5-drill-close")
        c.start()
        time.sleep(0.05)  # let close() reach the contended worker lock
        release_spawn.set()
        finished = closed.wait(timeout=10.0)
        t.join(timeout=5.0)
        c.join(timeout=1.0)
        if not finished:
            violations.append({
                "rule": Concurrency.CLOSE_DEADLOCK, "round": 0,
                "detail": (
                    "close() did not return within 10 s while a "
                    "supervised worker restart held the worker lock "
                    "mid-spawn — the shutdown path deadlocks against "
                    "the supervisor"
                ),
            })
        else:
            leaked = [w for w in created if w.alive()]
            if leaked:
                violations.append({
                    "rule": Concurrency.CLOSE_DEADLOCK, "round": 0,
                    "detail": (
                        f"{len(leaked)} worker(s) spawned concurrently "
                        "with close() escaped shutdown — the spawn ran "
                        "outside the worker lock, so close()'s snapshot "
                        "missed the late registration"
                    ),
                })
    finally:
        daemon_mod._Worker = orig_worker
        release_spawn.set()
    return violations


# ------------------------------------------------------------------ anchors
#: violation rule -> (module, class, method) its finding anchors to — the
#: real source location whose contract the invariant protects.  Resolution
#: is class-qualified: recorder.py also defines _NullRecorder.flush (a
#: no-op), and anchoring there would send the investigation to dead code.
def _anchor_for(rule):
    if rule == Concurrency.TORN_JSONL:
        from ..telemetry import recorder as mod

        cls, func = "Recorder", "flush"
    elif rule == Concurrency.CLOSE_DEADLOCK:
        from ..federation import daemon as mod

        cls, func = "DaemonEngine", "close"
    else:
        from .. import engine as mod

        cls = "InProcessEngine"
        func = ("_finish_site_outputs" if rule == Concurrency.LOST_COMMIT
                else "_async_snapshot_payloads")
    path = os.path.relpath(mod.__file__).replace(os.sep, "/")
    line = 1
    try:
        with open(mod.__file__, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read())
        fallback = None
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, ast.FunctionDef) and sub.name == func:
                        if node.name == cls:
                            return path, sub.lineno
                        fallback = fallback or sub.lineno
        if fallback:
            line = fallback
    except (OSError, SyntaxError, ValueError):
        pass
    return path, line


def _describe(schedule):
    if not schedule:
        return "warmup only"
    parts = []
    for i, decision in enumerate(schedule):
        non_fresh = [f"{s}={c}" for s, c in sorted(decision.items())
                     if c != "fresh"]
        parts.append(f"r{i + 2}:{','.join(non_fresh) or 'all-fresh'}")
    return " ".join(parts)


# -------------------------------------------------------------- exploration
def _enumerate_schedules(config):
    site_ids = [f"site_{i}" for i in range(config.sites)]
    per_round = list(itertools.product(CHOICES, repeat=len(site_ids)))
    for combo in itertools.product(per_round, repeat=config.rounds):
        yield [dict(zip(site_ids, c)) for c in combo]


def replay_schedule(plan, workdir=None):
    """Re-execute a violation's schedule JSON; returns the violation
    dicts the replay produced (the regression-test contract: the same
    rule fires again)."""
    scenario = dict(plan.get("scenario") or {})
    config = ScheduleConfig(
        sites=scenario.get("sites"), rounds=scenario.get("rounds"),
        k=scenario.get("staleness_k"), pool=scenario.get("pool"),
    )
    schedule = [dict(d) for d in plan.get("schedule") or []]
    if plan.get("rule") == Concurrency.CLOSE_DEADLOCK:
        if workdir is not None:
            return run_close_drill(workdir)
        with tempfile.TemporaryDirectory(prefix="tier5-replay-") as wd:
            return run_close_drill(wd)
    if workdir is not None:
        return _run_schedule(config, schedule, workdir)
    with tempfile.TemporaryDirectory(prefix="tier5-replay-") as wd:
        return _run_schedule(config, schedule, wd)


def run_schedule_explorer(config=None, schedules_dir=None):
    """Explore every schedule within the bound (plus the daemon close
    drill); returns a :class:`ScheduleResult` whose findings flow
    through the same baseline machinery as tiers 1–4."""
    config = config or ScheduleConfig()
    report = {"schedules_run": 0, "forced_blocks": 0, "violations": 0,
              "truncated": 0, "drill_run": False}
    found = {}  # rule -> (Finding, plan)

    def emit(violation, schedule):
        rule = violation["rule"]
        report["violations"] += 1
        if rule in found:
            return
        path, line = _anchor_for(rule)
        plan = {
            "comment": (
                "dinulint tier-5 counterexample — replay with "
                "analysis.schedule_explorer.replay_schedule(<this file>) "
                "(docs/ANALYSIS.md 'Tier 5')"
            ),
            "rule": rule,
            "invariant": _INVARIANTS[rule],
            "scenario": config.scenario(),
            "schedule": schedule,
            "violation_round": int(violation.get("round", 0)),
        }
        message = (
            f"{violation['detail']} — counterexample schedule: "
            f"[{_describe(schedule)}] (bound: {config.sites} sites x "
            f"{config.rounds} rounds + warmup, k={config.k}, "
            f"pool={config.pool}); replayable schedule JSON via "
            "--schedules"
        )
        found[rule] = (
            Finding(rule=rule, path=path, line=line, col=0, message=message),
            plan,
        )

    try:
        with tempfile.TemporaryDirectory(prefix="dinulint-tier5-") as root:
            total = (len(CHOICES) ** config.sites) ** config.rounds
            for i, schedule in enumerate(_enumerate_schedules(config)):
                if i >= config.max_schedules:
                    # no silent caps: a partially-explored bound must fail
                    # loudly, never read as "covered everything"
                    report["truncated"] = total - i
                    found.setdefault(Concurrency.CONFIG, (Finding(
                        rule=Concurrency.CONFIG,
                        path="coinstac_dinunet_tpu", line=1, col=0,
                        message=(
                            f"schedule ceiling ({config.max_schedules}) "
                            f"exceeded: the bound enumerates {total} "
                            f"schedules and {total - i} were NOT explored "
                            "— shrink --schedule-bound (or the site "
                            "count); a truncated exploration must not "
                            "pass as clean"
                        ),
                    ), None))
                    break
                wd = os.path.join(root, f"s{i:04d}")
                violations = _run_schedule(config, schedule, wd,
                                           report=report)
                report["schedules_run"] += 1
                for v in violations:
                    emit(v, schedule)
            for v in run_close_drill(os.path.join(root, "drill")):
                emit(v, [])
            report["drill_run"] = True
    except Exception as exc:  # noqa: BLE001 — typed error channel
        f = Finding(
            rule=Concurrency.CONFIG, path="coinstac_dinunet_tpu", line=1,
            col=0,
            message=(
                "the tier-5 schedule explorer could not run: "
                f"{type(exc).__name__}: {exc}"
            ),
        )
        return ScheduleResult([f], [None], report)

    order = sorted(found)
    findings = [found[r][0] for r in order]
    plans = [found[r][1] for r in order]
    if schedules_dir:
        os.makedirs(schedules_dir, exist_ok=True)
        for n, (f, plan) in enumerate(zip(findings, plans)):
            if not plan:
                continue  # the config error channel has no schedule
            name = f"{f.rule}-{n:02d}.json"
            with open(os.path.join(schedules_dir, name), "w",
                      encoding="utf-8") as fh:
                json.dump(plan, fh, indent=2, sort_keys=True)
                fh.write("\n")
    return ScheduleResult(findings, plans, report)
