"""num-*: the tier-7 numerics & determinism auditor (static half).

Every acceptance pin in this repo is a bit-parity claim — run-ahead d=0 ≡
serial, async k=0 + pool-1 ≡ lockstep, mmap ≡ copy loads, the vectorized
engine ≡ file transport — and ROADMAP item 1's compressed wire will bend
floating-point numerics on every one of those boundaries.  This pass
statically guards the properties the pins rest on:

- ``num-prng-reuse`` — a PRNGKey value consumed by two or more
  consuming calls (samplers, ``split``) without an intervening
  re-derivation, or consumed inside a loop without per-iteration
  re-derivation: both streams draw identical bits.  ``fold_in`` is the
  sanctioned indexed derivation and never counts as consumption.
- ``num-prng-discard`` — ``jax.random.split(...)`` immediately
  subscripted by a literal index: the sibling key is silently dropped,
  and the kept half can collide with a ``fold_in`` derivation of the
  same parent (``nn/basetrainer.py``'s dp step was the in-tree case).
- ``num-prng-constant`` — a literal-seeded key constructed inside a
  loop or a per-step/per-round function: every pass replays identical
  noise.
- ``num-unordered-reduce`` — a reduce fan-in (``load_arrays_many``,
  ``stack``/``concatenate``, ``sum``) whose operand order depends on
  dict/set iteration or an unsorted directory listing.  fp addition
  does not commute bitwise, so operand order IS the parity contract.
- ``num-codec-unbounded`` — a module defining wire-codec kernels
  (``compress*``/``quantize*``/…) that neither emits compression
  telemetry itself (``record_compression_health``, ``rec.event(...,
  cat="compress")``, ``rec.wire(..., codec=...)``) nor is called from a
  module that does: a lossy wire path would ship unaccounted.
- ``num-accum-narrow`` — a sum/mean/optimizer-moment accumulation whose
  jaxpr lowers in bf16/f16, audited over the tier-3 entry-build cache
  (:mod:`.dataflow` — trainer, reducer, powersgd, rankdad,
  ``federation/vector.py``; no JAX builds beyond ``--tier3``'s own).

All rules but ``num-accum-narrow`` are pure stdlib ``ast`` — no JAX, no
engine import; a whole-package run stays in the tens of milliseconds.
The dynamic half of tier 7 — the bit-parity prover over the engine's
claimed equivalence contracts — lives in :mod:`.parity`.
"""
import ast
import os

from ..config.keys import Numerics
from .core import Finding, Module, dotted_name, iter_python_files

NUMERICS_STATIC_RULE_IDS = (
    Numerics.CODEC_UNBOUNDED,
    Numerics.PRNG_CONSTANT,
    Numerics.PRNG_DISCARD,
    Numerics.PRNG_REUSE,
    Numerics.UNORDERED_REDUCE,
)

#: jax.random callables that CONSUME their key's bits: two calls on the
#: same key yield correlated (for split: identical) streams.  ``fold_in``
#: is deliberately absent — fold_in(key, i) with a varying i is the
#: sanctioned indexed derivation.
_CONSUMERS = frozenset({
    "ball", "bernoulli", "beta", "binomial", "bits", "categorical",
    "cauchy", "chisquare", "choice", "dirichlet", "double_sided_maxwell",
    "exponential", "gamma", "geometric", "gumbel", "laplace", "loggamma",
    "logistic", "lognormal", "maxwell", "multivariate_normal", "normal",
    "orthogonal", "pareto", "permutation", "poisson", "rademacher",
    "randint", "rayleigh", "shuffle", "split", "t", "triangular",
    "truncated_normal", "uniform", "wald", "weibull_min",
})

#: key constructors (num-prng-constant scope)
_KEY_CTORS = frozenset({"PRNGKey", "key"})

#: function-name fragments marking a per-step/per-round path …
_STEPPY = ("step", "round", "batch", "epoch", "iteration")
#: … and the one-time-construction fragments that exempt it
_STEPPY_EXEMPT = ("init", "seed", "make", "build", "setup", "example",
                  "test", "entry", "fixture")

#: fan-in sinks whose operand ORDER is the reduce's parity contract
_SINK_LEAVES = frozenset({
    "concatenate", "hstack", "load_arrays_many", "stack", "vstack",
})

#: codec-kernel def-name prefixes (num-codec-unbounded scope)
_CODEC_PREFIXES = ("compress", "decompress", "dequantize", "quantize",
                   "reconstruct", "encode_", "decode_")

#: keyword evidence that a ``rec.event(...)`` accounts a codec
_COMPRESS_EVIDENCE_KWARGS = frozenset({
    "compression_ratio", "error_norm", "factored_bytes", "full_bytes",
})


# --------------------------------------------------------- jax.random env
def _jr_env(tree):
    """(module-alias set, direct-name map) for jax.random in this module:
    aliases are names the ``jax.random`` MODULE is bound to (``random``,
    ``jr``); direct names map a bare imported callable to its leaf
    (``from jax.random import split as sp`` → ``{"sp": "split"}``)."""
    aliases, direct = set(), {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                # plain ``import jax.random`` binds ``jax`` — the dotted
                # ``jax.random.X`` spelling is matched structurally below
                if a.name == "jax.random" and a.asname:
                    aliases.add(a.asname)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for a in node.names:
                    if a.name == "random":
                        aliases.add(a.asname or "random")
            elif node.module == "jax.random":
                for a in node.names:
                    direct[a.asname or a.name] = a.name
    return aliases, direct


def _jr_leaf(call, env):
    """The jax.random leaf name of a call (``"split"``, ``"normal"``,
    ``"PRNGKey"``), or None when the call is not a jax.random call."""
    aliases, direct = env
    name = dotted_name(call.func, require_name_root=False) or ""
    parts = name.split(".")
    if len(parts) == 1:
        return direct.get(parts[0])
    if parts[-2] in aliases:
        return parts[-1]
    # structural jax.random.X — bare ``random.X`` without a jax import is
    # the STDLIB module (random.shuffle) and must not match
    if len(parts) >= 3 and parts[-2] == "random" and parts[-3] == "jax":
        return parts[-1]
    return None


def _key_arg_name(call):
    """The consumed key operand when it is a plain Name (precision over
    recall: attribute keys like ``ts.rng`` are out of scope)."""
    if call.args and isinstance(call.args[0], ast.Name):
        return call.args[0].id
    return None


def _target_names(target, out):
    if isinstance(target, ast.Name):
        out.add(target.id)
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            _target_names(elt, out)
    elif isinstance(target, ast.Starred):
        _target_names(target.value, out)


def _assigned_names(stmts):
    """Every plain name (re)bound anywhere under ``stmts`` — loop-carried
    re-derivation evidence for the loop-reuse check."""
    names = set()
    for stmt in stmts:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    _target_names(t, names)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign,
                                   ast.NamedExpr)):
                _target_names(node.target, names)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                _target_names(node.target, names)
            elif isinstance(node, ast.withitem) and node.optional_vars:
                _target_names(node.optional_vars, names)
    return names


def _stmt_calls(stmt):
    """Calls in one statement, nested defs/lambdas excluded (they are
    their own key scope), in source order."""
    out = []
    stack = [stmt]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not stmt:
            continue
        if isinstance(node, ast.Call):
            out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    out.sort(key=lambda c: (c.lineno, c.col_offset))
    return out


# --------------------------------------------------------------- PRNG scan
class _PrngScan:
    """num-prng-reuse over one function body: a linear walk tracking which
    key names have been consumed, cleansed on rebinding; branches walk on
    copies and merge by union (a key consumed on either branch is consumed
    after the join)."""

    def __init__(self, mod, env, findings):
        self.mod, self.env, self.findings = mod, env, findings

    def run(self, fn):
        self._block(fn.body, {}, frozenset())

    def _flag(self, call, message):
        self.findings.append(Finding(
            rule=Numerics.PRNG_REUSE, path=self.mod.path,
            line=call.lineno, col=call.col_offset, message=message,
        ))

    def _consume(self, stmt, consumed, loop_locals):
        for call in _stmt_calls(stmt):
            leaf = _jr_leaf(call, self.env)
            if leaf not in _CONSUMERS:
                continue
            name = _key_arg_name(call)
            if name is None:
                continue
            if loop_locals is not None and name not in loop_locals:
                self._flag(call, (
                    f"PRNGKey '{name}' is consumed by jax.random.{leaf} "
                    "inside a loop without per-iteration re-derivation — "
                    "every iteration replays identical bits (split or "
                    "fold_in the loop index first)"
                ))
            elif name in consumed:
                first = consumed[name]
                self._flag(call, (
                    f"PRNGKey '{name}' already consumed at line {first} is "
                    f"consumed again by jax.random.{leaf} — both draws use "
                    "identical bits (split/fold_in between consumptions)"
                ))
            else:
                consumed[name] = call.lineno

    def _block(self, stmts, consumed, assigned_in_loops, loop_locals=None):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested scopes are scanned separately
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                body_locals = _assigned_names(stmt.body)
                self._block(stmt.body, dict(consumed), assigned_in_loops,
                            loop_locals=body_locals)
                self._block(stmt.orelse, dict(consumed), assigned_in_loops)
                # names the loop rebinds are re-derived from the caller's
                # perspective; the rest keep their pre-loop state
                for n in body_locals:
                    consumed.pop(n, None)
                continue
            if isinstance(stmt, ast.If):
                a, b = dict(consumed), dict(consumed)
                self._block(stmt.body, a, assigned_in_loops, loop_locals)
                self._block(stmt.orelse, b, assigned_in_loops, loop_locals)
                consumed.clear()
                consumed.update(b)
                consumed.update(a)
                continue
            if isinstance(stmt, ast.Try):
                for block in ([stmt.body, stmt.orelse, stmt.finalbody]
                              + [h.body for h in stmt.handlers]):
                    self._block(block, consumed, assigned_in_loops,
                                loop_locals)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                self._consume(stmt, consumed, loop_locals)
                self._block(stmt.body, consumed, assigned_in_loops,
                            loop_locals)
                continue
            self._consume(stmt, consumed, loop_locals)
            rebound = set()
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    _target_names(t, rebound)
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign,
                                   ast.NamedExpr)):
                _target_names(stmt.target, rebound)
            for n in rebound:
                consumed.pop(n, None)


def _scan_prng_reuse(mod, env, findings):
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _PrngScan(mod, env, findings).run(node)


def _scan_prng_discard(mod, env, findings):
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Subscript):
            continue
        if not isinstance(node.value, ast.Call):
            continue
        if _jr_leaf(node.value, env) != "split":
            continue
        ix = node.slice
        if isinstance(ix, ast.UnaryOp) and isinstance(ix.op, ast.USub):
            ix = ix.operand
        if isinstance(ix, ast.Constant) and isinstance(ix.value, int):
            findings.append(Finding(
                rule=Numerics.PRNG_DISCARD, path=mod.path,
                line=node.lineno, col=node.col_offset,
                message=(
                    "jax.random.split(...)[literal] silently drops the "
                    "sibling key — and the kept half can collide with a "
                    "fold_in derivation of the same parent.  Unpack the "
                    "split and thread both halves (or fold_in a counter)"
                ),
            ))


def _is_const_seed(call):
    return bool(call.args) and all(
        isinstance(a, ast.Constant) and isinstance(a.value, (int, bool))
        for a in call.args
    )


def _scan_prng_constant(mod, env, findings):
    def steppy(name):
        low = name.lower()
        return (any(s in low for s in _STEPPY)
                and not any(s in low for s in _STEPPY_EXEMPT))

    def visit(node, fname, in_loop):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fname, in_loop = node.name, False
        elif isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            in_loop = True
        elif isinstance(node, ast.Call):
            leaf = _jr_leaf(node, env)
            if leaf in _KEY_CTORS and _is_const_seed(node) and (
                    in_loop or (fname and steppy(fname))):
                where = ("a loop" if in_loop
                         else f"per-step path '{fname}'")
                findings.append(Finding(
                    rule=Numerics.PRNG_CONSTANT, path=mod.path,
                    line=node.lineno, col=node.col_offset,
                    message=(
                        f"constant-seeded jax.random.{leaf} constructed "
                        f"inside {where} — every pass replays identical "
                        "noise; derive the key from the carried rng (or "
                        "fold_in the step counter)"
                    ),
                ))
        for child in ast.iter_child_nodes(node):
            visit(child, fname, in_loop)

    visit(mod.tree, None, False)


# ------------------------------------------------------- unordered fan-in
_UNORDERED_ITER_LEAVES = {
    "keys": "dict key iteration order",
    "values": "dict value iteration order",
    "items": "dict item iteration order",
    "listdir": "an unsorted os.listdir enumeration",
    "iterdir": "an unsorted Path.iterdir enumeration",
    "scandir": "an unsorted os.scandir enumeration",
    "glob": "an unsorted glob enumeration",
}


def _unordered_reason(node, tainted):
    """Why this expression's element ORDER is unstable, or None."""
    if isinstance(node, ast.Name):
        return tainted.get(node.id)
    if isinstance(node, (ast.Set, ast.SetComp)):
        return "set iteration order"
    if isinstance(node, (ast.ListComp, ast.GeneratorExp)):
        for gen in node.generators:
            reason = _unordered_reason(gen.iter, tainted)
            if reason:
                return reason
        return None
    if isinstance(node, ast.Starred):
        return _unordered_reason(node.value, tainted)
    if isinstance(node, ast.Call):
        name = dotted_name(node.func, require_name_root=False) or ""
        leaf = name.rsplit(".", 1)[-1]
        if leaf in ("sorted",):
            return None  # explicit ordering cleanses
        if leaf in _UNORDERED_ITER_LEAVES:
            return _UNORDERED_ITER_LEAVES[leaf]
        if leaf == "set":
            return "set iteration order"
        if leaf in ("list", "tuple", "reversed", "enumerate", "iter"):
            return (_unordered_reason(node.args[0], tainted)
                    if node.args else None)
    return None


def _scan_unordered_reduce(mod, env, findings):
    for fn in ast.walk(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        tainted = {}
        for stmt in ast.walk(fn):
            if isinstance(stmt, ast.Assign):
                reason = _unordered_reason(stmt.value, tainted)
                names = set()
                for t in stmt.targets:
                    _target_names(t, names)
                for n in names:
                    if reason:
                        tainted[n] = reason
                    else:
                        tainted.pop(n, None)
        for call in ast.walk(fn):
            if not isinstance(call, ast.Call):
                continue
            name = dotted_name(call.func, require_name_root=False) or ""
            leaf = name.rsplit(".", 1)[-1]
            if leaf not in _SINK_LEAVES and not (
                    leaf == "sum" and isinstance(call.func, ast.Name)):
                continue
            if not call.args:
                continue
            reason = _unordered_reason(call.args[0], tainted)
            if reason:
                findings.append(Finding(
                    rule=Numerics.UNORDERED_REDUCE, path=mod.path,
                    line=call.lineno, col=call.col_offset,
                    message=(
                        f"reduce fan-in '{leaf}' consumes operands in "
                        f"{reason} — fp addition does not commute "
                        "bitwise, so operand order IS the parity "
                        "contract; sort the enumeration first"
                    ),
                ))


# ------------------------------------------------------- codec accounting
class _CodecAudit:
    __slots__ = ("path", "codec_defs", "evidence", "called")

    def __init__(self, path):
        self.path = path
        self.codec_defs = []   # [(name, line, col)]
        self.evidence = False
        self.called = set()    # leaf names of every call in the module


def _audit_codecs(mod):
    audit = _CodecAudit(mod.path)
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name.lstrip("_").startswith(_CODEC_PREFIXES):
                audit.codec_defs.append(
                    (node.name, node.lineno, node.col_offset)
                )
        elif isinstance(node, ast.Call):
            name = dotted_name(node.func, require_name_root=False) or ""
            leaf = name.rsplit(".", 1)[-1]
            if leaf:
                audit.called.add(leaf)
            if leaf == "record_compression_health":
                audit.evidence = True
            elif leaf == "event":
                for kw in node.keywords:
                    if kw.arg in _COMPRESS_EVIDENCE_KWARGS:
                        audit.evidence = True
                    if (kw.arg == "cat"
                            and isinstance(kw.value, ast.Constant)
                            and kw.value.value == "compress"):
                        audit.evidence = True
            elif leaf == "wire":
                if any(kw.arg == "codec" for kw in node.keywords):
                    audit.evidence = True
    return audit


def _codec_findings(audits):
    findings = []
    for audit in audits:
        if not audit.codec_defs or audit.evidence:
            continue
        defined = {name for name, _l, _c in audit.codec_defs}
        accounted = any(
            other.evidence and (other.called & defined)
            for other in audits if other is not audit
        )
        if accounted:
            continue
        name, line, col = audit.codec_defs[0]
        findings.append(Finding(
            rule=Numerics.CODEC_UNBOUNDED, path=audit.path,
            line=line, col=col,
            message=(
                f"codec kernel(s) {sorted(defined)} never emit "
                "error/compression-ratio telemetry — neither this module "
                "nor any consumer calls record_compression_health / "
                "rec.event(cat='compress') / rec.wire(codec=...); a lossy "
                "wire path would ship unaccounted"
            ),
        ))
    return findings


# ---------------------------------------------------------------- tier run
def run_tier7_static(paths=None):
    """The tier-7 static half over ``paths`` (files or directories).
    Parse failures are skipped silently — the base static scan already
    reports them through its own error channel."""
    paths = list(paths) if paths else ["coinstac_dinunet_tpu"]
    findings, audits = [], []
    for path in iter_python_files(paths):
        display = os.path.relpath(path).replace(os.sep, "/")
        try:
            mod = Module.parse(path, display)
        except (SyntaxError, UnicodeDecodeError, OSError, ValueError):
            continue
        env = _jr_env(mod.tree)
        _scan_prng_reuse(mod, env, findings)
        _scan_prng_discard(mod, env, findings)
        _scan_prng_constant(mod, env, findings)
        _scan_unordered_reduce(mod, env, findings)
        audits.append(_audit_codecs(mod))
    findings.extend(_codec_findings(audits))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


# ------------------------------------------------- num-accum-narrow (jaxpr)
#: accumulation primitives whose output dtype is the accumulator dtype
_ACCUM_PRIMS = frozenset({"add_any", "cumsum", "psum", "reduce_sum"})

#: dtypes too narrow to accumulate in (mantissa loses low-order grads)
_NARROW = frozenset({"bfloat16", "float16"})


def run_accum_narrow(names=None, extra_jaxprs=None):
    """``num-accum-narrow`` over the tier-3 lowering cache: every deep-
    registry entry's jaxpr (built at most once per process — shared with
    ``--tier3``/``--deep``) is walked for accumulation primitives whose
    output dtype is bf16/f16.  ``extra_jaxprs`` maps a display path to a
    ClosedJaxpr (test fixtures).  Platform failures degrade to the typed
    ``num-config`` channel; never raises."""
    findings = []
    try:
        from . import deepcheck
        from .dataflow import entry_anchor_line, lower_entry, walk_jaxprs

        targets = []
        if extra_jaxprs:
            for label in sorted(extra_jaxprs):
                targets.append((label, label, 1, extra_jaxprs[label]))
        if extra_jaxprs is None or names is not None:
            deepcheck._register_builtin_entries()
            have = deepcheck.ensure_virtual_devices()
            if have < deepcheck.REQUIRED_DEVICES:
                return [Finding(
                    rule=Numerics.CONFIG,
                    path="coinstac_dinunet_tpu/analysis/numerics.py",
                    line=1, col=0,
                    message=(
                        "num-accum-narrow needs "
                        f"{deepcheck.REQUIRED_DEVICES} virtual devices but "
                        f"the initialized JAX backend has {have} — set "
                        "XLA_FLAGS=--xla_force_host_platform_device_count"
                        "=8 before anything imports jax"
                    ),
                )]
            wanted = set(names) if names else None
            for name in sorted(deepcheck.DEEP_REGISTRY):
                if wanted is not None and name not in wanted:
                    continue
                entry = lower_entry(name)
                if entry.closed_jaxpr is None:
                    continue  # tier3-lower owns the build error
                targets.append((
                    f"entry '{name}'", entry.path,
                    entry_anchor_line(entry.path), entry.closed_jaxpr,
                ))
        for label, path, line, closed_jaxpr in targets:
            sites = {}
            for jaxpr, _consts, _args in walk_jaxprs(closed_jaxpr):
                for eqn in jaxpr.eqns:
                    if eqn.primitive.name not in _ACCUM_PRIMS:
                        continue
                    for v in eqn.outvars:
                        dt = getattr(getattr(v, "aval", None), "dtype", None)
                        if dt is not None and dt.name in _NARROW:
                            key = (eqn.primitive.name, dt.name)
                            sites[key] = sites.get(key, 0) + 1
            if sites:
                detail = ", ".join(
                    f"{prim} in {dt} x{n}"
                    for (prim, dt), n in sorted(sites.items())
                )
                findings.append(Finding(
                    rule=Numerics.ACCUM_NARROW, path=path, line=line, col=0,
                    message=(
                        f"{label}: accumulation lowers in a narrow dtype "
                        f"({detail}) — low-order contributions round away; "
                        "accumulate in f32 and cast at the boundary "
                        "(docs/PERF.md)"
                    ),
                ))
    except Exception as exc:  # noqa: BLE001 — typed error channel
        findings.append(Finding(
            rule=Numerics.CONFIG,
            path="coinstac_dinunet_tpu/analysis/numerics.py", line=1, col=0,
            message=(
                "the num-accum-narrow audit could not run: "
                f"{type(exc).__name__}: {exc}"
            ),
        ))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
