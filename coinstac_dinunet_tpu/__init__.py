"""coinstac_dinunet_tpu — TPU-native federated deep-learning framework.

A brand-new JAX/XLA/pjit/Pallas re-design with the capabilities of
``trendscenter/coinstac-dinunet`` (see SURVEY.md): federated site/aggregator
training with pluggable gradient-aggregation engines (dSGD, PowerSGD, rankDAD),
k-fold orchestration, cross-site metric reduction, checkpointing, early
stopping, and training-curve logging.  Two transports share one set of
compiled kernels:

- **mesh transport** — simulated sites are ranks on a ``jax.sharding.Mesh``;
  the gradient plane lowers to XLA collectives over ICI/DCN
  (:class:`~.parallel.mesh.MeshFederation`).
- **engine transport** — the reference-compatible file+JSON protocol driven by
  an external engine or the bundled in-process simulator
  (:class:`~.engine.InProcessEngine`).
- **site-vectorized transport** — thousands of simulated sites batched
  under ONE jit along a stacked ``site`` axis
  (:class:`~.federation.SiteVectorizedFederation` /
  :class:`~.federation.SiteVectorizedEngine`; docs/FEDERATION.md).

Top-level exports mirror the reference package surface
(``coinstac_dinunet/__init__.py:11-14``) plus the TPU-native additions.
"""
__version__ = "0.1.0"

from .config import keys  # noqa: F401
from .data import COINNDataHandle, COINNDataset  # noqa: F401
from .engine import InProcessEngine, SiteRunner  # noqa: F401
from .federation import SiteVectorizedEngine, SiteVectorizedFederation  # noqa: F401
from .metrics import (  # noqa: F401
    AUCROCMetrics,
    COINNAverages,
    COINNMetrics,
    ConfusionMatrix,
    Prf1a,
)
from .nn import NNTrainer  # noqa: F401
from .nodes import COINNLocal, COINNRemote  # noqa: F401
from .parallel import COINNLearner, COINNReducer  # noqa: F401
from .parallel.mesh import MeshFederation  # noqa: F401
from .trainer import COINNTrainer  # noqa: F401

__all__ = [
    "COINNDataset",
    "COINNDataHandle",
    "COINNLearner",
    "COINNReducer",
    "COINNLocal",
    "COINNRemote",
    "COINNTrainer",
    "NNTrainer",
    "MeshFederation",
    "SiteVectorizedFederation",
    "SiteVectorizedEngine",
    "InProcessEngine",
    "SiteRunner",
    "COINNMetrics",
    "COINNAverages",
    "Prf1a",
    "ConfusionMatrix",
    "AUCROCMetrics",
]
