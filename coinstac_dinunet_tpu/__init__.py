"""coinstac_dinunet_tpu — TPU-native federated deep-learning framework.

A brand-new JAX/XLA/pjit/Pallas re-design with the capabilities of
``trendscenter/coinstac-dinunet`` (see SURVEY.md): federated site/aggregator
training with pluggable gradient-aggregation engines (dSGD, PowerSGD, rankDAD),
k-fold orchestration, cross-site metric reduction, checkpointing, early
stopping, and training-curve logging.  Two transports share one set of
compiled kernels:

- **mesh transport** — simulated sites are ranks on a ``jax.sharding.Mesh``;
  the gradient plane lowers to XLA collectives over ICI/DCN.
- **engine transport** — the reference-compatible file+JSON protocol driven by
  an external engine (or the bundled in-process simulator).
"""
__version__ = "0.1.0"

from .config import keys  # noqa: F401
from .data import COINNDataHandle, COINNDataset  # noqa: F401
from .metrics import (  # noqa: F401
    AUCROCMetrics,
    COINNAverages,
    COINNMetrics,
    ConfusionMatrix,
    Prf1a,
)

__all__ = [
    "COINNDataset",
    "COINNDataHandle",
    "COINNMetrics",
    "COINNAverages",
    "Prf1a",
    "ConfusionMatrix",
    "AUCROCMetrics",
]
