"""Federation-wide structured telemetry (spans, wire accounting, timelines).

Public surface:

- :class:`Recorder` / :data:`NULL_RECORDER` — per-node event sink with a
  zero-overhead disabled mode (``Recorder.for_node(cache, state)``).
- :func:`get_active` / :func:`activate` — the ambient-recorder stack that
  lets deep layers (wire serialization, reducers, the trainer) record
  without plumbing a recorder through every signature.
- :mod:`.collect` — merge per-node JSONL into one federation timeline,
  summary tables and Perfetto/Chrome-trace export; CLI at
  ``python -m coinstac_dinunet_tpu.telemetry``.
- :mod:`.health` — per-round numeric health series (grad norms, per-site
  cosine agreement, compression reconstruction error) recorded as typed
  ``metric`` records at the host-side choke points.
- :mod:`.watchdog` — pluggable anomaly detectors over those series
  (non-finite, explosion, divergence outlier, stall, compression spike,
  rank collapse, memory leak/pressure); observe-and-report, with opt-in
  site quarantine.
- :mod:`.perf` — the perf flight recorder: XLA cost analysis per compiled
  executable (``jit_cost``), per-round achieved-TFLOPS/MFU/samples-per-sec
  series vs a per-backend peak table, and device-memory sampling
  (``memory_stats()`` / live-buffer census).
- :mod:`.capture` — anomaly-triggered deep capture: a watchdog firing can
  arm the XLA profiler for the next round, retaining the profile under the
  node's output directory with a ``capture:profile`` event linking it.
- :mod:`.doctor` — postmortem report over a merged run (anomaly timeline,
  per-site divergence, roofline + MFU/memory floor verdicts, ranked
  verdicts); CLI at ``python -m coinstac_dinunet_tpu.telemetry doctor``.
- :mod:`.live` — the live ops plane: rotation/crash-safe incremental JSONL
  tailing (per-file byte cursors, torn-tail tolerance) into a
  federation-wide in-flight state machine with edge-triggered stall
  verdicts; CLI at ``python -m coinstac_dinunet_tpu.telemetry watch``.
- :mod:`.serve` — stdlib HTTP exporters over the live state: Prometheus
  text-format ``/metrics`` and a ``/healthz`` JSON summary.

jax-free by design: importing this package never pulls in jax (the recorder
bridges to ``jax.monitoring`` only if jax is already loaded, and
:mod:`.health` imports its jax numerics lazily inside the enabled-only
helpers).  See ``docs/TELEMETRY.md`` for the schema and workflow.
"""
from .recorder import (  # noqa: F401
    NULL_RECORDER,
    Recorder,
    SCHEMA_VERSION,
    activate,
    get_active,
)
from .watchdog import Watchdog, register_detector  # noqa: F401

__all__ = [
    "Recorder", "NULL_RECORDER", "SCHEMA_VERSION", "activate", "get_active",
    "Watchdog", "register_detector",
]
