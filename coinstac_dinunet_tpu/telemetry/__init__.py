"""Federation-wide structured telemetry (spans, wire accounting, timelines).

Public surface:

- :class:`Recorder` / :data:`NULL_RECORDER` — per-node event sink with a
  zero-overhead disabled mode (``Recorder.for_node(cache, state)``).
- :func:`get_active` / :func:`activate` — the ambient-recorder stack that
  lets deep layers (wire serialization, reducers, the trainer) record
  without plumbing a recorder through every signature.
- :mod:`.collect` — merge per-node JSONL into one federation timeline,
  summary tables and Perfetto/Chrome-trace export; CLI at
  ``python -m coinstac_dinunet_tpu.telemetry``.

Stdlib-only by design: importing this package never pulls in jax (the
recorder bridges to ``jax.monitoring`` only if jax is already loaded).
See ``docs/TELEMETRY.md`` for the schema and workflow.
"""
from .recorder import (  # noqa: F401
    NULL_RECORDER,
    Recorder,
    SCHEMA_VERSION,
    activate,
    get_active,
)

__all__ = [
    "Recorder", "NULL_RECORDER", "SCHEMA_VERSION", "activate", "get_active",
]
