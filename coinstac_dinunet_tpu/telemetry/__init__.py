"""Federation-wide structured telemetry (spans, wire accounting, timelines).

Public surface:

- :class:`Recorder` / :data:`NULL_RECORDER` — per-node event sink with a
  zero-overhead disabled mode (``Recorder.for_node(cache, state)``).
- :func:`get_active` / :func:`activate` — the ambient-recorder stack that
  lets deep layers (wire serialization, reducers, the trainer) record
  without plumbing a recorder through every signature.
- :mod:`.collect` — merge per-node JSONL into one federation timeline,
  summary tables and Perfetto/Chrome-trace export; CLI at
  ``python -m coinstac_dinunet_tpu.telemetry``.
- :mod:`.health` — per-round numeric health series (grad norms, per-site
  cosine agreement, compression reconstruction error) recorded as typed
  ``metric`` records at the host-side choke points.
- :mod:`.watchdog` — pluggable anomaly detectors over those series
  (non-finite, explosion, divergence outlier, stall, compression spike,
  rank collapse); observe-and-report, with opt-in site quarantine.
- :mod:`.doctor` — postmortem report over a merged run (anomaly timeline,
  per-site divergence, ranked verdicts); CLI at
  ``python -m coinstac_dinunet_tpu.telemetry doctor``.

jax-free by design: importing this package never pulls in jax (the recorder
bridges to ``jax.monitoring`` only if jax is already loaded, and
:mod:`.health` imports its jax numerics lazily inside the enabled-only
helpers).  See ``docs/TELEMETRY.md`` for the schema and workflow.
"""
from .recorder import (  # noqa: F401
    NULL_RECORDER,
    Recorder,
    SCHEMA_VERSION,
    activate,
    get_active,
)
from .watchdog import Watchdog, register_detector  # noqa: F401

__all__ = [
    "Recorder", "NULL_RECORDER", "SCHEMA_VERSION", "activate", "get_active",
    "Watchdog", "register_detector",
]
