"""Host-side training-health watchdog: pluggable anomaly detectors over the
metric series the health layer records.

The failure modes this watches for are exactly the ones the paper's regime
invites — many sites pushing gradients through lossy compression
(PowerSGD/rankDAD) with quorum dropout: silent divergence of one site, rank
collapse of the factorization, NaN corruption from a single bad node, a
validation metric that quietly stopped moving (decoupled/compressed gradient
paths amplify staleness and reconstruction error; arxiv 1906.12043,
2004.13336).  None of these crash the run — they degrade it, which is why a
watchdog has to watch the *numbers*, not the exceptions.

Design:

- **Observe-and-report by default.**  Every detector finding is (1) an
  ``anomaly:<name>`` event on the node's telemetry timeline, (2) an entry in
  the JSON-able ``cache['health']['anomalies']`` rollup the nodes ship over
  the wire (``LocalWire.HEALTH``/``RemoteWire.HEALTH``), and (3) a log
  warning.  Nothing changes the training math.
- **Opt-in quarantine.**  With ``cache['quarantine_on_anomaly']`` truthy, a
  site-attributed anomaly adds the site to ``cache['quarantined_sites']``,
  which the reducer folds into the existing nonfinite-skip weighting
  (weight 0 — the site stops influencing the average but stays in the
  protocol).
- **Edge-triggered.**  A detector fires when its condition *becomes* true
  and re-arms when the series recovers, so a persistently-NaN site is one
  anomaly, not one per round (the per-round evidence lives in the metric
  series itself).
- **Fresh-process safe.**  All detector state is a small JSON-able dict
  under ``cache['health']`` (listed in ``basetrainer._VOLATILE_CACHE_KEYS``),
  so it survives the invocation-per-round engine exactly like
  ``telemetry_round`` does.

Detector registration names MUST come from the
:class:`~..config.keys.Anomaly`/:class:`~..config.keys.Metric` vocabulary —
the ``telemetry-metric-name`` dinulint rule checks every
``register_detector(...)`` call statically.
"""
import math

from ..config.keys import Anomaly, Metric
from ..utils import logger
from .capture import maybe_arm
from .recorder import get_active

# bounded rollup: the health summary rides the wire every round
_MAX_ANOMALIES = 50

#: default detector classes in registration order
DEFAULT_DETECTORS = []


def register_detector(anomaly, metric=None):
    """Class decorator binding a detector to its anomaly name and (optional)
    watched metric.  ``metric=None`` means the detector sees EVERY series
    (the nonfinite check).  Both names are statically checked against the
    config/keys.py vocabulary by the ``telemetry-metric-name`` lint rule."""

    def deco(cls):
        cls.anomaly = str(anomaly)
        cls.metric = str(metric) if metric is not None else None
        DEFAULT_DETECTORS.append(cls)
        return cls

    return deco


class Detector:
    """One anomaly condition over one (or every) metric series.

    ``check(state, value, site, cache)`` sees the detector's own JSON-able
    state dict and returns ``None`` (healthy) or a dict of anomaly
    attributes (fires).  Implementations must be edge-triggered: use the
    state dict to remember the armed/fired condition.
    """

    anomaly = None
    metric = None

    def check(self, state, value, site, cache):  # pragma: no cover - interface
        return None


def _finite(v):
    return isinstance(v, (int, float)) and math.isfinite(v)


@register_detector(Anomaly.NONFINITE)
class NonfiniteDetector(Detector):
    """Any watched series going NaN/Inf — the one-bad-site corruption
    signal.  Per-(metric, site) edge trigger: fires on the transition into
    the non-finite state, re-arms on recovery."""

    def check(self, state, value, site, cache):
        key = f"{state['_metric']}@{site or ''}"
        bad = not _finite(value)
        was_bad = key in state.setdefault("bad", [])
        if bad and not was_bad:
            state["bad"].append(key)
            return {"detail": f"{state['_metric']} went non-finite"}
        if not bad and was_bad:
            state["bad"].remove(key)
        return None


class _EmaSpikeDetector(Detector):
    """Shared machinery: value > ratio × EMA after a warm-up, edge-triggered.
    The EMA is published back into the state so the health layer can record
    it as its own series (``grad_norm_ema``)."""

    ratio_key = None
    default_ratio = 10.0
    warmup = 5
    decay = 0.9

    def check(self, state, value, site, cache):
        if not _finite(value):
            return None  # the nonfinite detector owns that failure mode
        ema = state.get("ema")
        n = int(state.get("n", 0))
        ratio = float(cache.get(self.ratio_key, self.default_ratio))
        fired = None
        if ema is not None and n >= self.warmup and value > ratio * max(ema, 1e-30):
            if not state.get("tripped"):
                state["tripped"] = True
                fired = {
                    "detail": (
                        f"{self.metric} {value:.4g} exceeded "
                        f"{ratio:g}x EMA {ema:.4g}"
                    ),
                    "ema": round(ema, 6),
                }
            # a tripped spike must not drag the EMA up to the spike level —
            # freeze it so a sustained explosion stays visible
        else:
            state["tripped"] = False
            ema = value if ema is None else self.decay * ema + (1 - self.decay) * value
            state["ema"] = ema
        state["n"] = n + 1
        return fired


@register_detector(Anomaly.GRAD_EXPLOSION, metric=Metric.GRAD_NORM)
class GradExplosionDetector(_EmaSpikeDetector):
    """Gradient norm spiking vs its EMA (``cache['watchdog_explosion_ratio']``,
    default 10x, after 5 warm-up rounds)."""

    ratio_key = "watchdog_explosion_ratio"
    default_ratio = 10.0


@register_detector(Anomaly.COMPRESSION_SPIKE, metric=Metric.COMPRESSION_ERROR)
class CompressionSpikeDetector(_EmaSpikeDetector):
    """Compression reconstruction error spiking vs its EMA
    (``cache['watchdog_compression_ratio']``, default 5x)."""

    ratio_key = "watchdog_compression_ratio"
    default_ratio = 5.0


@register_detector(Anomaly.DIVERGENCE_OUTLIER, metric=Metric.SITE_COSINE)
class DivergenceOutlierDetector(Detector):
    """A site's gradient direction detaching from the consensus: cosine to
    the weighted mean below ``cache['watchdog_cosine_floor']`` (default 0.0
    — anti-aligned).  Per-site edge trigger."""

    def check(self, state, value, site, cache):
        if not _finite(value):
            return None
        floor = float(cache.get("watchdog_cosine_floor", 0.0))
        low = state.setdefault("low", [])
        key = str(site or "")
        if value < floor:
            if key not in low:
                low.append(key)
                return {
                    "detail": (
                        f"site cosine {value:.4f} below floor {floor:g}"
                    ),
                }
        elif key in low:
            low.remove(key)
        return None


@register_detector(Anomaly.VAL_STALL, metric=Metric.VAL_SCORE)
class ValStallDetector(Detector):
    """The monitored validation metric not improving for
    ``cache['watchdog_stall_patience']`` consecutive observations (default
    5); direction follows ``cache['metric_direction']``.  Fires once per
    stall; re-arms on the next improvement."""

    def check(self, state, value, site, cache):
        if not _finite(value):
            return None
        patience = int(cache.get("watchdog_stall_patience", 5))
        maximize = str(cache.get("metric_direction", "maximize")) == "maximize"
        best = state.get("best")
        improved = best is None or (value > best if maximize else value < best)
        if improved:
            state["best"] = value
            state["since"] = 0
            state["tripped"] = False
            return None
        state["since"] = int(state.get("since", 0)) + 1
        if state["since"] >= patience and not state.get("tripped"):
            state["tripped"] = True
            return {
                "detail": (
                    f"no improvement over best {best:.4g} for "
                    f"{state['since']} evaluations"
                ),
            }
        return None


@register_detector(Anomaly.RANK_COLLAPSE, metric=Metric.EFFECTIVE_RANK)
class RankCollapseDetector(Detector):
    """Effective rank dropping below
    ``cache['watchdog_rank_floor_frac']`` (default 0.5) of the first
    observed value — the factorization degenerating to (near) rank one,
    which silently discards gradient directions."""

    def check(self, state, value, site, cache):
        if not _finite(value):
            return None
        first = state.get("first")
        if first is None:
            state["first"] = value
            return None
        frac = float(cache.get("watchdog_rank_floor_frac", 0.5))
        if value < frac * first:
            if not state.get("tripped"):
                state["tripped"] = True
                return {
                    "detail": (
                        f"effective rank {value:.3f} below {frac:g}x the "
                        f"initial {first:.3f}"
                    ),
                }
        else:
            state["tripped"] = False
        return None


@register_detector(Anomaly.MEMORY_LEAK, metric=Metric.HBM_IN_USE)
class MemoryLeakDetector(Detector):
    """Device memory in use growing for ``cache['watchdog_leak_rounds']``
    consecutive observations (default 5, each >1% over the previous — XLA
    allocators jitter, monotone-to-the-byte would false-negative), after a
    ``cache['watchdog_leak_warmup']`` grace period (default 8: startup
    legitimately allocates params/opt state/first buffers round over
    round).  The buffers-retained-across-rounds signature: a healthy
    steady-state round returns to a flat in-use level; a leak ratchets.
    Fires once per sustained-growth excursion; re-arms when the series
    stops growing."""

    tolerance = 1.01

    def check(self, state, value, site, cache):
        if not _finite(value):
            return None
        prev = state.get("prev")
        state["prev"] = value
        n = int(state.get("n", 0))
        state["n"] = n + 1
        if n < int(cache.get("watchdog_leak_warmup", 8)):
            return None
        if prev is None or value <= prev * self.tolerance:
            state["streak"] = 0
            state["tripped"] = False
            return None
        streak = int(state.get("streak", 0)) + 1
        state["streak"] = streak
        patience = int(cache.get("watchdog_leak_rounds", 5))
        if streak >= patience and not state.get("tripped"):
            state["tripped"] = True
            return {
                "detail": (
                    f"device memory grew {streak} consecutive rounds to "
                    f"{value:.4g} bytes"
                ),
            }
        return None


@register_detector(Anomaly.MEMORY_PRESSURE, metric=Metric.HBM_UTILIZATION)
class MemoryPressureDetector(Detector):
    """Device memory utilization crossing
    ``cache['watchdog_memory_pressure']`` (default 0.92) — the next
    allocation spike is an OOM.  Edge-triggered on the crossing."""

    def check(self, state, value, site, cache):
        if not _finite(value):
            return None
        threshold = float(cache.get("watchdog_memory_pressure", 0.92))
        if value >= threshold:
            if not state.get("tripped"):
                state["tripped"] = True
                return {
                    "detail": (
                        f"device memory at {value:.1%} of its limit "
                        f"(threshold {threshold:g})"
                    ),
                }
        else:
            state["tripped"] = False
        return None


class Watchdog:
    """The per-node anomaly watchdog bound to a node cache + recorder.

    Cheap to construct (all state lives in the cache), so call sites build
    one per observation batch: ``Watchdog(cache).observe(name, value)``.
    """

    def __init__(self, cache, recorder=None, detectors=None):
        self.cache = cache
        self.rec = recorder if recorder is not None else get_active()
        self.detectors = [
            cls() for cls in (detectors if detectors is not None
                              else DEFAULT_DETECTORS)
        ]
        st = cache.get("health")
        if not isinstance(st, dict):
            st = cache["health"] = {}
        st.setdefault("detectors", {})
        st.setdefault("anomalies", [])
        self.state = st

    # ------------------------------------------------------------- observing
    def observe(self, name, value, site=None, **ctx):
        """Feed one sample of series ``name`` through every matching
        detector; emits anomalies as events + rollup entries.  Returns the
        list of anomaly names that fired."""
        name = str(name)
        try:
            value = float(value)
        except (TypeError, ValueError):
            return []
        fired = []
        for det in self.detectors:
            if det.metric is not None and det.metric != name:
                continue
            st = self.state["detectors"].setdefault(det.anomaly, {})
            st["_metric"] = name
            hit = det.check(st, value, site, self.cache)
            if hit:
                self._emit(det.anomaly, name, value, site, hit, ctx)
                fired.append(det.anomaly)
        return fired

    def ema(self, anomaly_name):
        """Published EMA of an EMA-based detector (None before warm-up)."""
        return self.state["detectors"].get(str(anomaly_name), {}).get("ema")

    # -------------------------------------------------------------- emission
    def _emit(self, anomaly, metric_name, value, site, hit, ctx):
        entry = {
            "anomaly": anomaly,
            "metric": metric_name,
            "value": (round(value, 6) if _finite(value) else str(value)),
            "round": int(self.cache.get("telemetry_round", 0) or 0),
            "epoch": int(self.cache.get("epoch", 0) or 0),
        }
        if site is not None:
            entry["site"] = str(site)
        entry.update({k: v for k, v in hit.items() if v is not None})
        roll = self.state["anomalies"]
        roll.append(entry)
        del roll[:-_MAX_ANOMALIES]
        self.rec.event(
            f"anomaly:{anomaly}", cat="anomaly", metric=metric_name,
            value=entry["value"], **({"site": entry["site"]} if site is not None else {}),
            **{k: v for k, v in hit.items() if v is not None}, **ctx,
        )
        # an anomaly is never verbosity-gated
        logger.warn(
            f"watchdog: {anomaly} on {metric_name}"
            + (f" (site {site})" if site is not None else "")
            + f" — {hit.get('detail', '')}",
            True,
        )
        # anomaly-triggered deep capture: arm the profiler for the NEXT
        # round when configured (telemetry/capture.py; default off)
        maybe_arm(self.cache, anomaly, self.rec)
        if self.cache.get("quarantine_on_anomaly") and site is not None:
            q = self.cache.setdefault("quarantined_sites", [])
            if str(site) not in q:
                q.append(str(site))
                self.rec.event(
                    "quarantine", cat="anomaly", site=str(site),
                    anomaly=anomaly,
                )
                logger.warn(
                    f"watchdog: quarantined site {site} "
                    f"({anomaly}; weight 0 in every following reduce)",
                    True,
                )

    # --------------------------------------------------------------- summary
    def summary(self):
        """Wire-sized health summary (the ``health`` wire key payload):
        recent anomalies plus per-anomaly counts; the node's wire
        retry-pressure counters when the resilience layer retried any load
        (``cache['wire_retry_stats']``, resilience/retry.py), so a flaky
        relay is visible federation-wide before it escalates to a dropout;
        and the perf flight recorder's latest utilization rollup
        (``telemetry/perf.py`` — samples/s, MFU, device memory), so the
        aggregator sees federation-wide utilization over the same wire.
        Empty dict = healthy."""
        anomalies = self.state.get("anomalies", [])
        wire = self.cache.get("wire_retry_stats") or {}
        wire = {k: v for k, v in wire.items() if v}
        perf = self.state.get("perf") or {}
        if (not anomalies and not self.cache.get("quarantined_sites")
                and not wire and not perf):
            return {}
        counts = {}
        for a in anomalies:
            counts[a["anomaly"]] = counts.get(a["anomaly"], 0) + 1
        out = {"counts": counts, "recent": anomalies[-10:]}
        if wire:
            out["wire"] = wire
        if perf:
            out["perf"] = dict(perf)
        if self.cache.get("quarantined_sites"):
            out["quarantined"] = list(self.cache["quarantined_sites"])
        return out
