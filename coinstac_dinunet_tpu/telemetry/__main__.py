"""``python -m coinstac_dinunet_tpu.telemetry`` — the federation timeline CLI.

Merge every node's ``telemetry.*.jsonl`` under a run directory, print the
per-phase/per-site summary table, and export a Perfetto/Chrome-trace JSON::

    python -m coinstac_dinunet_tpu.telemetry <workdir> --trace trace.json

Open the trace at https://ui.perfetto.dev (or ``chrome://tracing``).
"""
import argparse
import json
import os
import sys

from .collect import load_events, render_summary, summarize, write_chrome_trace


def build_parser():
    p = argparse.ArgumentParser(
        prog="python -m coinstac_dinunet_tpu.telemetry",
        description="merge per-node telemetry JSONL into one federation "
                    "timeline (summary table + Perfetto trace)",
    )
    p.add_argument("root", nargs="?", default=".",
                   help="run directory scanned recursively for "
                        "telemetry.*.jsonl (default: .)")
    p.add_argument("--trace", default=None, metavar="PATH",
                   help="write the merged Chrome-trace/Perfetto JSON here")
    p.add_argument("--summary-json", default=None, metavar="PATH",
                   help="write the machine-readable summary here")
    p.add_argument("--quiet", action="store_true",
                   help="suppress the summary table on stdout")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    events = load_events(args.root)
    if not events:
        print(f"no telemetry records under {args.root!r} — enable with "
              "cache['profile']=True (docs/TELEMETRY.md)", file=sys.stderr)
        return 1
    summary = summarize(events)
    if not args.quiet:
        print(render_summary(summary))
    if args.summary_json:
        with open(args.summary_json, "w", encoding="utf-8") as f:
            json.dump(summary, f, indent=2, sort_keys=True)
    if args.trace:
        trace = write_chrome_trace(args.trace, events)
        if not args.quiet:
            print(f"\nwrote {len(trace['traceEvents'])} trace events for "
                  f"{len(summary['nodes'])} nodes -> {args.trace} "
                  "(load at https://ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # `... | head` is a legitimate way to use this
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
