"""``python -m coinstac_dinunet_tpu.telemetry`` — the federation timeline CLI.

Merge every node's ``telemetry.*.jsonl`` under a run directory, print the
per-phase/per-site summary table, and export a Perfetto/Chrome-trace JSON::

    python -m coinstac_dinunet_tpu.telemetry <workdir> --trace trace.json

Open the trace at https://ui.perfetto.dev (or ``chrome://tracing``).

The ``doctor`` subcommand turns the same records into a postmortem report
(anomaly timeline, per-site divergence table, round-throughput trend, ranked
likely-cause verdicts)::

    python -m coinstac_dinunet_tpu.telemetry doctor <workdir> \\
        --markdown postmortem.md --json postmortem.json [--format github] \\
        [--bench-history BENCH_HISTORY.jsonl]

The ``watch`` subcommand is the LIVE counterpart (docs/TELEMETRY.md "Live
ops plane"): it tails the same JSONL incrementally while the run is alive,
renders a refreshing terminal status board, optionally serves Prometheus
``/metrics`` + ``/healthz``, and fires edge-triggered in-flight stall
verdicts (heartbeat silence, round-duration outlier, MFU collapse,
wire-retry storm).  With ``-- cmd...`` it spawns the run itself and follows
until it exits::

    python -m coinstac_dinunet_tpu.telemetry watch <workdir> --follow \\
        --until-exit --serve 9477 --assert-verdict heartbeat_silence \\
        --snapshot board.txt --metrics-out metrics.prom \\
        -- python scripts/telemetry_smoke.py --workdir <workdir> --fault-plan stall
"""
import argparse
import json
import os
import sys
import time

from .collect import load_events, render_summary, summarize, write_chrome_trace
from .doctor import (
    build_report,
    load_bench_history,
    render_github,
    render_markdown,
)


def build_parser():
    p = argparse.ArgumentParser(
        prog="python -m coinstac_dinunet_tpu.telemetry",
        description="merge per-node telemetry JSONL into one federation "
                    "timeline (summary table + Perfetto trace)",
    )
    p.add_argument("root", nargs="?", default=".",
                   help="run directory scanned recursively for "
                        "telemetry.*.jsonl (default: .)")
    p.add_argument("--trace", default=None, metavar="PATH",
                   help="write the merged Chrome-trace/Perfetto JSON here")
    p.add_argument("--summary-json", default=None, metavar="PATH",
                   help="write the machine-readable summary here")
    p.add_argument("--quiet", action="store_true",
                   help="suppress the summary table on stdout")
    return p


def build_doctor_parser():
    p = argparse.ArgumentParser(
        prog="python -m coinstac_dinunet_tpu.telemetry doctor",
        description="merge per-node telemetry into a postmortem report: "
                    "anomaly timeline, per-site divergence, round trend, "
                    "ranked likely-cause verdicts",
    )
    p.add_argument("root", nargs="?", default=".",
                   help="run directory scanned recursively for "
                        "telemetry.*.jsonl (default: .)")
    p.add_argument("--json", default=None, metavar="PATH",
                   help="write the machine-readable report here")
    p.add_argument("--markdown", default=None, metavar="PATH",
                   help="write the markdown postmortem here")
    p.add_argument("--format", choices=("text", "github"), default="text",
                   help="'github' prints ::error/::warning workflow "
                        "annotations for the verdicts instead of markdown")
    p.add_argument("--bench-history", default=None, metavar="PATH",
                   help="BENCH_HISTORY.jsonl (scripts/bench_history.py); "
                        "a >threshold samples/sec/chip drop vs the previous "
                        "entry becomes a verdict")
    p.add_argument("--regression-threshold", type=float, default=0.10,
                   help="bench regression fraction (default 0.10 = 10%%)")
    p.add_argument("--quiet", action="store_true",
                   help="suppress the report on stdout")
    return p


def build_watch_parser():
    p = argparse.ArgumentParser(
        prog="python -m coinstac_dinunet_tpu.telemetry watch",
        description="live federation status board: tail telemetry.*.jsonl "
                    "incrementally, render a refreshing board, export "
                    "Prometheus /metrics + /healthz, and fire in-flight "
                    "stall verdicts while the run is alive",
    )
    p.add_argument("root", nargs="?", default=".",
                   help="run directory tailed recursively for "
                        "telemetry.*.jsonl (may not exist yet; default: .)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="poll/refresh seconds (default 2)")
    p.add_argument("--follow", action="store_true",
                   help="keep tailing until interrupted (default without a "
                        "command: one poll, one board, exit)")
    p.add_argument("--until-exit", action="store_true",
                   help="with a spawned command (after --): stop when the "
                        "command exits (one final poll), even under "
                        "--follow; without it, --follow keeps tailing the "
                        "directory after the command finishes")
    p.add_argument("--max-seconds", type=float, default=None,
                   help="hard wall-clock stop for the watch loop")
    p.add_argument("--silence-after", type=float, default=30.0,
                   help="heartbeat-silence verdict threshold in seconds "
                        "(default 30)")
    p.add_argument("--round-outlier", type=float, default=4.0,
                   help="round-duration outlier multiple vs the rolling "
                        "median (default 4)")
    p.add_argument("--mfu-collapse", type=float, default=0.3,
                   help="MFU-collapse fraction of the EMA (default 0.3)")
    p.add_argument("--retry-storm", type=int, default=10,
                   help="wire retries per window that fire the retry-storm "
                        "verdict (default 10)")
    p.add_argument("--serve", type=int, default=None, metavar="PORT",
                   help="serve /metrics + /healthz on 127.0.0.1:PORT while "
                        "watching (0 = ephemeral port)")
    p.add_argument("--snapshot", default=None, metavar="PATH",
                   help="write the final board rendering here on exit")
    p.add_argument("--json", default=None, metavar="PATH",
                   help="write the final /healthz snapshot JSON here")
    p.add_argument("--metrics-out", default=None, metavar="PATH",
                   help="write a final /metrics scrape here on exit (a real "
                        "HTTP self-scrape when --serve is up, a direct "
                        "rendering otherwise)")
    p.add_argument("--cursor-file", default=None, metavar="PATH",
                   help="persist per-file tail cursors to this sidecar so a "
                        "restarted watch resumes instead of replaying")
    p.add_argument("--assert-verdict", default=None, metavar="KIND",
                   action="append",
                   help="exit 3 unless this verdict kind fired WHILE the "
                        "run was alive (repeatable; e.g. heartbeat_silence)")
    p.add_argument("--assert-event", default=None, metavar="NAME",
                   action="append",
                   help="exit 3 unless this telemetry event name was "
                        "observed WHILE the run was alive (repeatable; "
                        "e.g. worker:restart — the daemon chaos drill's "
                        "live supervision gate)")
    p.add_argument("--quiet", action="store_true",
                   help="suppress the periodic board on stdout (the final "
                        "board still prints / lands in --snapshot)")
    p.epilog = ("everything after a literal `--` is spawned as the watched "
                "command; the watch follows it until it exits and returns "
                "its exit code")
    return p


def watch_main(argv=None):
    from .live import LiveState, Tailer, render_board
    from .serve import OpsServer, render_prometheus

    # split the spawned command off BEFORE argparse: REMAINDER's greedy
    # matching would swallow our own flags placed after the root positional
    argv = list(sys.argv[1:] if argv is None else argv)
    command = []
    if "--" in argv:
        ix = argv.index("--")
        command = argv[ix + 1:]
        argv = argv[:ix]
    parser = build_watch_parser()
    args = parser.parse_args(argv)
    if args.until_exit and not command:
        parser.error("--until-exit requires a spawned command after --")

    tailer = Tailer(args.root, cursor_path=args.cursor_file)
    state = LiveState(
        silence_after=args.silence_after, round_outlier=args.round_outlier,
        mfu_collapse=args.mfu_collapse, retry_storm=args.retry_storm,
    )
    server = None
    if args.serve is not None:
        server = OpsServer(state.snapshot, port=args.serve)
        print(f"serving /metrics + /healthz on {server.url('')}",
              file=sys.stderr)

    clear = sys.stdout.isatty() and not args.quiet

    def emit_board():
        if args.quiet:
            return
        board = render_board(state.snapshot(), root=str(args.root))
        if clear:
            sys.stdout.write("\x1b[2J\x1b[H" + board + "\n")
        else:
            sys.stdout.write(board + "\n" + "-" * 72 + "\n")
        sys.stdout.flush()

    asserted_events = list(args.assert_event or ())
    events_during_run = set()

    def step(during_run):
        """One poll + rule evaluation; stamps verdicts fired while the run
        (the child, or an unconditioned follow) was still alive."""
        before = {n: state.event_counts.get(n, 0) for n in asserted_events}
        records = tailer.poll()
        state.truncated_lines = tailer.truncated_lines
        state.ingest(records)
        if during_run:
            for n in asserted_events:
                if state.event_counts.get(n, 0) > before[n]:
                    events_during_run.add(n)
        for v in state.check():
            v["during_run"] = bool(during_run)
            line = (f"!! [{v['severity']}] {v['verdict']}"
                    + (f" [{v.get('site')}]" if v.get("site") else "")
                    + f" — {v['cause']}: {v['evidence']}")
            print(line, file=sys.stderr)

    child = None
    if command:
        import subprocess

        # drain whatever is ALREADY in the workdir before the child
        # spawns: records left by an earlier run in a reused directory
        # land with during_run=False, so they can never satisfy
        # --assert-event/--assert-verdict on behalf of the new run
        step(during_run=False)
        child = subprocess.Popen(command)

    t_start = time.monotonic()
    rc = 0
    try:
        if not (command or args.follow):
            step(during_run=False)  # one-shot board over whatever is there
        else:
            child_done = False
            while True:
                if (child is not None and not child_done
                        and child.poll() is not None):
                    child_done = True
                    rc = child.returncode or 0
                # "the run is alive": the spawned command is still running,
                # or an unconditioned --follow with no command at all
                alive = ((child is not None and not child_done)
                         or (child is None and args.follow))
                step(during_run=alive)
                emit_board()
                if child_done and (args.until_exit or not args.follow):
                    break  # --until-exit (or no --follow): stop with the run
                if (args.max_seconds is not None
                        and time.monotonic() - t_start >= args.max_seconds):
                    break
                time.sleep(max(args.interval, 0.05))
            # final drain: the run's last flush may land after its exit
            step(during_run=False)
    except KeyboardInterrupt:
        pass
    finally:
        if child is not None and child.poll() is None:
            child.terminate()

    board = render_board(state.snapshot(), root=str(args.root))
    if not args.quiet:
        print(board)
    if args.snapshot:
        with open(args.snapshot, "w", encoding="utf-8") as f:
            f.write(board + "\n")
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(state.snapshot(), f, indent=2, sort_keys=True,
                      default=str)
    if args.metrics_out:
        text = (server.scrape("/metrics") if server is not None
                else render_prometheus(state.snapshot()))
        with open(args.metrics_out, "w", encoding="utf-8") as f:
            f.write(text)
    if server is not None:
        # close under a REAL recorder writing into the watched run dir:
        # the watch process has no ambient recorder, so without this the
        # typed telemetry:degraded event a failed join emits would land
        # on the null recorder and leave no postmortem evidence
        from .recorder import Recorder, activate

        rec = Recorder("watch", out_dir=str(args.root))
        with activate(rec):
            joined = server.close()
        rec.flush()
        if not joined:
            print("WARNING: ops server thread failed to join on close; "
                  "the listener may leak until process exit (typed "
                  "telemetry:degraded event recorded in "
                  f"{args.root})", file=sys.stderr)

    for kind in args.assert_verdict or ():
        hits = [v for v in state.verdicts if v["verdict"] == kind]
        if not any(v.get("during_run") for v in hits):
            print(
                f"ASSERT FAILED: verdict '{kind}' did not fire while the "
                f"run was alive ({len(hits)} fired post-run); verdicts: "
                f"{[v['verdict'] for v in state.verdicts]}",
                file=sys.stderr,
            )
            return 3
        print(f"asserted: '{kind}' fired in-flight "
              f"({len(hits)} occurrence(s))", file=sys.stderr)
    for name in asserted_events:
        total = state.event_counts.get(name, 0)
        if name not in events_during_run:
            print(
                f"ASSERT FAILED: event '{name}' was not observed while "
                f"the run was alive ({total} observed overall)",
                file=sys.stderr,
            )
            return 3
        print(f"asserted: '{name}' observed in-flight "
              f"({total} occurrence(s))", file=sys.stderr)
    return rc


def doctor_main(argv=None):
    args = build_doctor_parser().parse_args(argv)
    events = load_events(args.root)
    if not events:
        print(f"no telemetry records under {args.root!r} — enable with "
              "cache['profile']=True (docs/TELEMETRY.md)", file=sys.stderr)
        return 1
    report = build_report(
        events,
        bench_history=load_bench_history(args.bench_history),
        regression_threshold=args.regression_threshold,
    )
    md = render_markdown(report)
    if args.markdown:
        with open(args.markdown, "w", encoding="utf-8") as f:
            f.write(md + "\n")
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2, sort_keys=True)
    if not args.quiet:
        print(render_github(report) if args.format == "github" else md)
    return 0


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "doctor":
        return doctor_main(argv[1:])
    if argv and argv[0] == "watch":
        return watch_main(argv[1:])
    args = build_parser().parse_args(argv)
    events = load_events(args.root)
    if not events:
        print(f"no telemetry records under {args.root!r} — enable with "
              "cache['profile']=True (docs/TELEMETRY.md)", file=sys.stderr)
        return 1
    summary = summarize(events)
    if not args.quiet:
        print(render_summary(summary))
    if args.summary_json:
        with open(args.summary_json, "w", encoding="utf-8") as f:
            json.dump(summary, f, indent=2, sort_keys=True)
    if args.trace:
        trace = write_chrome_trace(args.trace, events)
        if not args.quiet:
            print(f"\nwrote {len(trace['traceEvents'])} trace events for "
                  f"{len(summary['nodes'])} nodes -> {args.trace} "
                  "(load at https://ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # `... | head` is a legitimate way to use this
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
