"""``python -m coinstac_dinunet_tpu.telemetry`` — the federation timeline CLI.

Merge every node's ``telemetry.*.jsonl`` under a run directory, print the
per-phase/per-site summary table, and export a Perfetto/Chrome-trace JSON::

    python -m coinstac_dinunet_tpu.telemetry <workdir> --trace trace.json

Open the trace at https://ui.perfetto.dev (or ``chrome://tracing``).

The ``doctor`` subcommand turns the same records into a postmortem report
(anomaly timeline, per-site divergence table, round-throughput trend, ranked
likely-cause verdicts)::

    python -m coinstac_dinunet_tpu.telemetry doctor <workdir> \\
        --markdown postmortem.md --json postmortem.json [--format github] \\
        [--bench-history BENCH_HISTORY.jsonl]
"""
import argparse
import json
import os
import sys

from .collect import load_events, render_summary, summarize, write_chrome_trace
from .doctor import (
    build_report,
    load_bench_history,
    render_github,
    render_markdown,
)


def build_parser():
    p = argparse.ArgumentParser(
        prog="python -m coinstac_dinunet_tpu.telemetry",
        description="merge per-node telemetry JSONL into one federation "
                    "timeline (summary table + Perfetto trace)",
    )
    p.add_argument("root", nargs="?", default=".",
                   help="run directory scanned recursively for "
                        "telemetry.*.jsonl (default: .)")
    p.add_argument("--trace", default=None, metavar="PATH",
                   help="write the merged Chrome-trace/Perfetto JSON here")
    p.add_argument("--summary-json", default=None, metavar="PATH",
                   help="write the machine-readable summary here")
    p.add_argument("--quiet", action="store_true",
                   help="suppress the summary table on stdout")
    return p


def build_doctor_parser():
    p = argparse.ArgumentParser(
        prog="python -m coinstac_dinunet_tpu.telemetry doctor",
        description="merge per-node telemetry into a postmortem report: "
                    "anomaly timeline, per-site divergence, round trend, "
                    "ranked likely-cause verdicts",
    )
    p.add_argument("root", nargs="?", default=".",
                   help="run directory scanned recursively for "
                        "telemetry.*.jsonl (default: .)")
    p.add_argument("--json", default=None, metavar="PATH",
                   help="write the machine-readable report here")
    p.add_argument("--markdown", default=None, metavar="PATH",
                   help="write the markdown postmortem here")
    p.add_argument("--format", choices=("text", "github"), default="text",
                   help="'github' prints ::error/::warning workflow "
                        "annotations for the verdicts instead of markdown")
    p.add_argument("--bench-history", default=None, metavar="PATH",
                   help="BENCH_HISTORY.jsonl (scripts/bench_history.py); "
                        "a >threshold samples/sec/chip drop vs the previous "
                        "entry becomes a verdict")
    p.add_argument("--regression-threshold", type=float, default=0.10,
                   help="bench regression fraction (default 0.10 = 10%%)")
    p.add_argument("--quiet", action="store_true",
                   help="suppress the report on stdout")
    return p


def doctor_main(argv=None):
    args = build_doctor_parser().parse_args(argv)
    events = load_events(args.root)
    if not events:
        print(f"no telemetry records under {args.root!r} — enable with "
              "cache['profile']=True (docs/TELEMETRY.md)", file=sys.stderr)
        return 1
    report = build_report(
        events,
        bench_history=load_bench_history(args.bench_history),
        regression_threshold=args.regression_threshold,
    )
    md = render_markdown(report)
    if args.markdown:
        with open(args.markdown, "w", encoding="utf-8") as f:
            f.write(md + "\n")
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2, sort_keys=True)
    if not args.quiet:
        print(render_github(report) if args.format == "github" else md)
    return 0


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "doctor":
        return doctor_main(argv[1:])
    args = build_parser().parse_args(argv)
    events = load_events(args.root)
    if not events:
        print(f"no telemetry records under {args.root!r} — enable with "
              "cache['profile']=True (docs/TELEMETRY.md)", file=sys.stderr)
        return 1
    summary = summarize(events)
    if not args.quiet:
        print(render_summary(summary))
    if args.summary_json:
        with open(args.summary_json, "w", encoding="utf-8") as f:
            json.dump(summary, f, indent=2, sort_keys=True)
    if args.trace:
        trace = write_chrome_trace(args.trace, events)
        if not args.quiet:
            print(f"\nwrote {len(trace['traceEvents'])} trace events for "
                  f"{len(summary['nodes'])} nodes -> {args.trace} "
                  "(load at https://ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # `... | head` is a legitimate way to use this
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
