"""Federation telemetry collector: merge per-node JSONL into one timeline.

Every node of a run (site nodes, the aggregator, the engine driver) appends
``telemetry.<node>.jsonl`` records into its own output directory
(:mod:`.recorder` documents the schema).  This module walks a run's working
directory, merges every node's records into one wall-clock-ordered
federation timeline, renders a per-node/per-phase summary table, and exports
Chrome-trace JSON (the ``traceEvents`` format) loadable by Perfetto
(https://ui.perfetto.dev) or ``chrome://tracing`` — so a whole k-fold
federated run is visually inspectable: site compute lanes, wire transfers
with byte counts, and the aggregator's reduces, all on one timebase.
"""
import json
import math
import os
import re

from ..config.keys import Metric
from .recorder import FILE_PREFIX, FILE_SUFFIX

_FILE_RE = re.compile(
    re.escape(FILE_PREFIX) + r".+" + re.escape(FILE_SUFFIX) + r"$"
)

# stable Perfetto lane order: the engine driver first, the aggregator next,
# sites after that (alphabetical), anything else last
_NODE_ORDER = {"engine": 0, "remote": 1}


def find_event_files(root):
    """All telemetry JSONL files under ``root`` (recursive, stable order)."""
    root = str(root)
    if os.path.isfile(root):
        return [root]
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for name in sorted(filenames):
            if _FILE_RE.match(name):
                out.append(os.path.join(dirpath, name))
    return out


def read_jsonl_segment(path, offset=0):
    """Tolerant incremental JSONL read: records from byte ``offset`` to the
    last COMPLETE (newline-terminated) line of ``path``.

    This is the one line reader shared by the post-hoc :func:`load_events`
    and the live tailer (:mod:`.live`): the Recorder writes each record as
    ``line + "\\n"`` in a single append, so an unterminated trailing line is
    by definition a torn write from a dying (or still-mid-append) site — it
    is never parsed (a truncation can otherwise *mis*-parse as a shorter
    valid value), never consumed, and counted instead.

    Returns ``(records, new_offset, bad_lines, has_partial_tail)``:
    ``new_offset`` points just past the last complete line (the resume
    cursor), ``bad_lines`` counts undecodable complete lines (corruption),
    ``has_partial_tail`` flags unterminated trailing bytes left unread.
    """
    with open(path, "rb") as f:
        f.seek(int(offset))
        data = f.read()
    end = data.rfind(b"\n")
    if end < 0:
        return [], int(offset), 0, bool(data)
    records, bad = [], 0
    for line in data[:end].split(b"\n"):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            bad += 1
            continue
        if isinstance(rec, dict):
            records.append(rec)
        else:
            bad += 1
    return records, int(offset) + end + 1, bad, end + 1 < len(data)


class EventLog(list):
    """A merged record list that additionally carries the torn/corrupt line
    count — plain-list compatible, so every existing ``load_events`` caller
    keeps working while :func:`summarize` surfaces ``truncated_lines``."""

    truncated_lines = 0


def load_events(root_or_files):
    """Parse one run's telemetry records, wall-clock ordered.

    ``root_or_files`` is a run directory (recursively scanned) or an
    explicit list of JSONL paths.  Torn trailing lines (a site killed
    mid-append) and undecodable lines are skipped, never fatal — they are
    COUNTED on the returned :class:`EventLog`'s ``truncated_lines`` and
    surfaced by :func:`summarize`.
    """
    if isinstance(root_or_files, (str, os.PathLike)):
        files = find_event_files(root_or_files)
    else:
        files = [str(p) for p in root_or_files]
    events = EventLog()
    truncated = 0
    for path in files:
        try:
            records, _, bad, partial = read_jsonl_segment(path)
        except OSError:
            continue
        truncated += bad + (1 if partial else 0)
        node = _node_from_filename(path)
        for rec in records:
            rec.setdefault("node", node)
        events.extend(records)
    events.sort(key=lambda r: (float(r.get("t0", 0.0)), r.get("node", "")))
    events.truncated_lines = truncated
    return events


def _node_from_filename(path):
    name = os.path.basename(path)
    if name.startswith(FILE_PREFIX) and name.endswith(FILE_SUFFIX):
        return name[len(FILE_PREFIX):-len(FILE_SUFFIX)]
    return "unknown"


def _node_sort_key(node):
    return (_NODE_ORDER.get(node, 2), str(node))


# ------------------------------------------------------------ wire overlap
def _merge_intervals(intervals):
    """Sorted union of (start, end) intervals."""
    merged = []
    for start, end in sorted(intervals):
        if merged and start <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], end))
        else:
            merged.append((start, end))
    return merged


def wire_overlap_ratio(events):
    """Fraction of the engine's WIRE time hidden under site COMPUTE — the
    async round engine's headline overlap metric (ISSUE 12).

    Wire time is the engine lane's aggregator invocation (the reduce runs
    inside it) plus the broadcast relay (``invoke:remote`` +
    ``engine:relay`` spans); compute time is the site invocation spans
    (``invoke:<site>``).  The ratio is ``|wire ∩ union(compute)| / |wire|``
    over the merged wall-clock timeline: 0 on a strictly serial engine
    (nothing computes while the wire runs), approaching 1 when stragglers
    compute straight through every reduce+relay.  Returns ``None`` when
    the events carry no wire spans (telemetry off / no engine lane)."""
    wire, compute = [], []
    for rec in events:
        if rec.get("kind") != "span" or rec.get("node") != "engine":
            continue
        name = str(rec.get("name", ""))
        try:
            t0 = float(rec["t0"])
            t1 = t0 + float(rec.get("dur", 0.0) or 0.0)
        except (KeyError, TypeError, ValueError):
            continue
        if t1 <= t0:
            continue
        if name == "invoke:remote" or name == "engine:relay":
            wire.append((t0, t1))
        elif name.startswith("invoke:"):
            compute.append((t0, t1))
    if not wire:
        return None
    wire = _merge_intervals(wire)
    compute = _merge_intervals(compute)
    total = sum(e - s for s, e in wire)
    overlap = 0.0
    ci = 0
    for ws, we in wire:
        while ci < len(compute) and compute[ci][1] <= ws:
            ci += 1
        j = ci
        while j < len(compute) and compute[j][0] < we:
            overlap += min(we, compute[j][1]) - max(ws, compute[j][0])
            j += 1
    return overlap / total if total > 0 else None


# ------------------------------------------------------------------ summary
def new_metric_stats():
    """Empty fold state for one metric series (shared with the doctor so
    the summary table and the postmortem can never disagree on semantics)."""
    return {"count": 0, "nonfinite": 0, "last": None, "min": None, "max": None}


def fold_metric_sample(stats, value):
    """Fold one ``metric`` record's value into ``stats``.  ``last``/``min``/
    ``max`` track FINITE samples only (non-finite ones are counted, not
    aggregated).  Returns the finite float, or None for a non-finite/
    non-numeric sample."""
    stats["count"] += 1
    try:
        v = float(value)
    except (TypeError, ValueError):
        v = float("nan")
    if math.isfinite(v):
        stats["last"] = v
        stats["min"] = v if stats["min"] is None else min(stats["min"], v)
        stats["max"] = v if stats["max"] is None else max(stats["max"], v)
        return v
    stats["nonfinite"] += 1
    return None


def summarize(events):
    """Aggregate a merged timeline into per-node tables.

    Returns ``{"nodes": [...], "spans": {node: {name: {calls,total_s,
    max_s}}}, "wire": {node: {saves,save_bytes,save_raw_bytes,loads,
    load_bytes,ratio}}, "counters": {node: {name: n}},
    "events": {node: {name: n}}, "metrics": {node: {name: {count,
    nonfinite,last,min,max}}}, "wall_s": span of the whole run}``.

    ``reduce:nonfinite_skip`` events additionally surface as a per-SITE
    ``nonfinite_skipped`` counter (attributed to the skipped site's lane,
    not the aggregator that noticed) — the skip was previously an
    aggregator event only, invisible in the summary table.
    """
    spans, wire, counters, evcounts, metrics = {}, {}, {}, {}, {}
    t_lo, t_hi = None, None
    for rec in events:
        node = rec.get("node", "unknown")
        t0 = float(rec.get("t0", 0.0))
        t1 = t0 + float(rec.get("dur", 0.0) or 0.0)
        t_lo = t0 if t_lo is None else min(t_lo, t0)
        t_hi = t1 if t_hi is None else max(t_hi, t1)
        kind = rec.get("kind")
        if kind == "span":
            s = spans.setdefault(node, {}).setdefault(
                rec.get("name", "?"), {"calls": 0, "total_s": 0.0, "max_s": 0.0}
            )
            dur = float(rec.get("dur", 0.0) or 0.0)
            s["calls"] += 1
            s["total_s"] += dur
            s["max_s"] = max(s["max_s"], dur)
        elif kind == "wire":
            w = wire.setdefault(node, {
                "saves": 0, "save_bytes": 0, "save_raw_bytes": 0,
                "loads": 0, "load_bytes": 0,
            })
            if rec.get("op") == "save":
                w["saves"] += 1
                w["save_bytes"] += int(rec.get("bytes", 0) or 0)
                w["save_raw_bytes"] += int(
                    rec.get("raw_bytes", rec.get("bytes", 0)) or 0
                )
            else:
                w["loads"] += 1
                w["load_bytes"] += int(rec.get("bytes", 0) or 0)
        elif kind == "counter":
            c = counters.setdefault(node, {})
            name = rec.get("name", "?")
            c[name] = c.get(name, 0) + int(rec.get("n", 0) or 0)
        elif kind == "metric":
            name = rec.get("name", "?")
            m = metrics.setdefault(node, {}).setdefault(
                name, new_metric_stats()
            )
            fold_metric_sample(m, rec.get("value"))
        elif kind == "event":
            e = evcounts.setdefault(node, {})
            name = rec.get("name", "?")
            e[name] = e.get(name, 0) + 1
            if name == "reduce:nonfinite_skip":
                # per-site visibility: credit each skipped SITE's lane with
                # a counter, so the summary table shows who got excluded
                for site in rec.get("sites", []) or []:
                    c = counters.setdefault(str(site), {})
                    c["nonfinite_skipped"] = c.get("nonfinite_skipped", 0) + 1
    for node, w in wire.items():
        w["ratio"] = (
            round(w["save_raw_bytes"] / w["save_bytes"], 4)
            if w["save_bytes"] else None
        )
    nodes = sorted(
        set(spans) | set(wire) | set(counters) | set(evcounts) | set(metrics),
        key=_node_sort_key,
    )
    return {
        "nodes": nodes, "spans": spans, "wire": wire, "counters": counters,
        "events": evcounts, "metrics": metrics,
        "wall_s": (round(t_hi - t_lo, 6) if t_lo is not None else 0.0),
        # torn/corrupt line count carried by load_events' EventLog (0 for a
        # plain list) — a dying site's last write is evidence, not noise
        "truncated_lines": int(getattr(events, "truncated_lines", 0) or 0),
    }


def _fmt_bytes(n):
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n}B"


def render_summary(summary):
    """Human-readable per-phase/per-site table for a merged timeline."""
    lines = [f"federation wall-clock: {summary['wall_s']:.3f}s"]
    if summary.get("truncated_lines"):
        lines.append(
            f"!! {summary['truncated_lines']} truncated/undecodable JSONL "
            "line(s) skipped (torn writes from dying writers)"
        )
    for node in summary["nodes"]:
        lines.append(f"\n[{node}]")
        spans = summary["spans"].get(node, {})
        if spans:
            width = max(len(n) for n in spans)
            lines.append(
                f"  {'span'.ljust(width)}  {'calls':>6} {'total_s':>10} "
                f"{'mean_ms':>9} {'max_ms':>9}"
            )
            for name in sorted(spans, key=lambda n: -spans[n]["total_s"]):
                s = spans[name]
                mean_ms = 1e3 * s["total_s"] / max(s["calls"], 1)
                lines.append(
                    f"  {name.ljust(width)}  {s['calls']:>6} "
                    f"{s['total_s']:>10.4f} {mean_ms:>9.2f} "
                    f"{1e3 * s['max_s']:>9.2f}"
                )
        w = summary["wire"].get(node)
        if w:
            ratio = f" (codec ratio {w['ratio']:.2f}x)" if w.get("ratio") else ""
            lines.append(
                f"  wire: out {w['saves']} files / "
                f"{_fmt_bytes(w['save_bytes'])}{ratio}; "
                f"in {w['loads']} files / {_fmt_bytes(w['load_bytes'])}"
            )
        c = summary["counters"].get(node)
        if c:
            lines.append(
                "  counters: " + ", ".join(
                    f"{k}={v}" for k, v in sorted(c.items())
                )
            )
        e = summary["events"].get(node)
        if e:
            lines.append(
                "  events: " + ", ".join(
                    f"{k}×{v}" for k, v in sorted(e.items())
                )
            )
        m = summary.get("metrics", {}).get(node)
        if m:
            parts = []
            for name, st in sorted(m.items()):
                last = "-" if st["last"] is None else f"{st['last']:.4g}"
                nf = f" !{st['nonfinite']}nf" if st["nonfinite"] else ""
                parts.append(f"{name}={last} (n={st['count']}{nf})")
            lines.append("  metrics: " + ", ".join(parts))
    return "\n".join(lines)


# ------------------------------------------------------------- chrome trace
# perf flight-recorder series (telemetry/perf.py) exported as per-node
# "utilization" counter tracks — built from the config/keys.py vocabulary
# so a new perf series can never silently fall back to the plain category
_UTILIZATION_METRICS = frozenset((
    Metric.SAMPLES_PER_SEC, Metric.ACHIEVED_TFLOPS, Metric.MFU,
    Metric.HBM_IN_USE, Metric.HBM_PEAK, Metric.HBM_LIMIT,
    Metric.HBM_UTILIZATION, Metric.ROUNDS_PER_SEC, Metric.SITES_PER_SEC,
))
_CTX_KEYS = ("round", "fold", "epoch", "phase")
_RECORD_KEYS = ("v", "kind", "name", "cat", "t0", "dur", "node", "op",
                "file", "bytes", "arrays", "codec", "raw_bytes", "ratio",
                "n", "value", "site") + _CTX_KEYS


def _args_for(rec):
    """Chrome-trace ``args`` payload: the federation context plus any
    record-specific attributes."""
    args = {k: rec[k] for k in _CTX_KEYS if k in rec}
    for k, v in rec.items():
        if k not in _RECORD_KEYS:
            args[k] = v
    for k in ("file", "bytes", "arrays", "codec", "raw_bytes", "ratio"):
        if k in rec:
            args[k] = rec[k]
    return args


def chrome_trace(events):
    """Merged timeline → Chrome trace-event JSON (Perfetto-loadable).

    One trace "process" per node (pid = stable lane order), spans as
    complete (``ph: "X"``) events, wire transfers as complete events on a
    dedicated wire thread lane, instantaneous events as ``ph: "i"``, and
    cumulative wire-byte counters (``ph: "C"``) so Perfetto plots the
    transfer volume over time.
    """
    nodes = sorted({r.get("node", "unknown") for r in events}, key=_node_sort_key)
    pid = {n: i + 1 for i, n in enumerate(nodes)}
    out = []
    for n in nodes:
        out.append({"name": "process_name", "ph": "M", "pid": pid[n], "tid": 0,
                    "args": {"name": str(n)}})
        out.append({"name": "process_sort_index", "ph": "M", "pid": pid[n],
                    "tid": 0, "args": {"sort_index": pid[n]}})
        for tid, label in ((1, "phases"), (2, "wire"), (3, "events")):
            out.append({"name": "thread_name", "ph": "M", "pid": pid[n],
                        "tid": tid, "args": {"name": label}})
    cum_bytes = {}
    cum_counters = {}
    for rec in events:
        node = rec.get("node", "unknown")
        p = pid[node]
        ts = float(rec.get("t0", 0.0)) * 1e6
        kind = rec.get("kind")
        if kind == "span":
            out.append({
                "name": rec.get("name", "?"), "cat": rec.get("cat", "phase"),
                "ph": "X", "ts": ts,
                "dur": max(float(rec.get("dur", 0.0) or 0.0) * 1e6, 1.0),
                "pid": p, "tid": 1, "args": _args_for(rec),
            })
        elif kind == "wire":
            op = rec.get("op", "save")
            out.append({
                "name": f"wire:{op}:{rec.get('file', '?')}", "cat": "wire",
                "ph": "X", "ts": ts,
                "dur": max(float(rec.get("dur", 0.0) or 0.0) * 1e6, 1.0),
                "pid": p, "tid": 2, "args": _args_for(rec),
            })
            key = (node, op)
            cum_bytes[key] = cum_bytes.get(key, 0) + int(rec.get("bytes", 0) or 0)
            out.append({
                "name": f"wire_bytes_{op}", "cat": "wire", "ph": "C", "ts": ts,
                "pid": p, "tid": 0,
                "args": {"bytes": cum_bytes[key]},
            })
        elif kind == "metric":
            try:
                v = float(rec.get("value"))
            except (TypeError, ValueError):
                v = float("nan")
            name = rec.get("name", "?")
            if math.isfinite(v):
                suffix = f":{rec['site']}" if rec.get("site") else ""
                out.append({
                    "name": f"metric:{name}{suffix}",
                    # perf flight-recorder series get their own Perfetto
                    # category so per-node utilization tracks are filterable
                    "cat": ("utilization" if name in _UTILIZATION_METRICS
                            else "metric"),
                    "ph": "C", "ts": ts, "pid": p, "tid": 0,
                    "args": {"value": v},
                })
            else:
                # a NaN sample breaks Perfetto counter tracks (and strict
                # JSON) — surface it as an instant marker with the value
                # stringified
                args = _args_for(rec)
                args["value"] = str(rec.get("value"))
                if rec.get("site"):
                    args["site"] = rec["site"]
                out.append({
                    "name": f"metric:{name}:nonfinite", "cat": "metric",
                    "ph": "i", "ts": ts, "pid": p, "tid": 3, "s": "t",
                    "args": args,
                })
        elif kind == "counter":
            # counter records are per-flush DELTAS (Recorder.flush drains
            # the counters); accumulate so the Perfetto track is the
            # monotone total, like the wire-bytes track
            key = (node, rec.get("name", "?"))
            cum_counters[key] = (
                cum_counters.get(key, 0) + int(rec.get("n", 0) or 0)
            )
            out.append({
                "name": rec.get("name", "?"), "cat": "counter", "ph": "C",
                "ts": ts, "pid": p, "tid": 0,
                "args": {"n": cum_counters[key]},
            })
        else:  # event
            out.append({
                "name": rec.get("name", "?"), "cat": rec.get("cat", "event"),
                "ph": "i", "ts": ts, "pid": p, "tid": 3, "s": "t",
                "args": _args_for(rec),
            })
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def write_chrome_trace(path, events):
    trace = chrome_trace(events)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(trace, f, separators=(",", ":"))
    return trace
