"""Structured federation telemetry: typed spans/events to per-node JSONL.

The COINSTAC process model makes conventional profilers useless for a
federated round: every node invocation may be a FRESH process, the round is
N site invocations + file relays + one aggregator invocation, and the only
durable surface is each node's output directory.  The :class:`Recorder`
leans into that — it appends typed records (spans with wall-clock + duration,
instantaneous events, wire-transfer accounting, aggregated counters) to a
``telemetry.<node>.jsonl`` file in the node's output directory, stamped with
the federation context (node id, engine round, fold, epoch, phase).  The
collector (:mod:`.collect`) merges every node's file into one federation
timeline and exports Chrome-trace/Perfetto JSON.

Design constraints, in priority order:

1. **Zero overhead when disabled.**  ``Recorder.for_node`` returns the
   :data:`NULL_RECORDER` singleton unless ``cache['profile']`` (or
   ``cache['telemetry']``) is truthy; every method on it is a constant-return
   no-op and ``span()`` hands back one shared, allocation-free context
   manager.  The hot-path cost of a disabled call site is one attribute
   lookup + one no-op call (asserted in ``tests/test_telemetry.py``).
2. **Host-side only.**  Telemetry is I/O + wall-clock — it must NEVER appear
   inside a jitted/shard_mapped function (it would be traced away at best,
   force host syncs at worst).  The ``trace-telemetry`` dinulint rule
   (:mod:`..analysis.trace_hazards`) enforces this statically.
3. **Crash-friendly and tailable.**  Records buffer in memory and flush as
   one appended write per node invocation, plus a size-bounded AND a
   wall-clock auto-flush (``cache['telemetry_flush_interval_s']``, default
   5 s) — so a dying site still leaves its timeline up to the last flush
   on disk, and a live tailer (:mod:`.live`) sees progress inside long
   invocations instead of one burst at the end.

Record schema (one JSON object per line; absent context fields are omitted)::

    {"v": 1, "kind": "span",    "name": ..., "cat": ..., "t0": epoch-secs,
     "dur": secs, "node": ..., "round": n, "fold": ..., "epoch": n,
     "phase": ..., ...attrs}
    {"v": 1, "kind": "event",   "name": ..., "cat": ..., "t0": ..., ...}
    {"v": 1, "kind": "metric",  "name": ..., "value": float, "t0": ...,
     "site": attributed-site-id?, ...context}
    {"v": 1, "kind": "wire",    "op": "save"|"load", "file": basename,
     "bytes": payload-bytes, "arrays": k, "codec": ..., "raw_bytes": n,
     "ratio": raw/payload, "payload_kind": "json"|"tensor"|"delta",
     "dur": secs, ...context}
    {"v": 1, "kind": "counter", "name": ..., "n": total, "t0": flush-time,
     ...context}

``t0`` is ``time.time()`` (wall clock — comparable across node processes on
one host, and across hosts to NTP accuracy); durations are measured with
``time.perf_counter()``.
"""
import contextlib
import json
import os
import sys
import threading
import time

SCHEMA_VERSION = 1
FILE_PREFIX = "telemetry."
FILE_SUFFIX = ".jsonl"

# records buffered before an automatic mid-invocation flush
_AUTOFLUSH_AT = 512

# wall-clock seconds between automatic flushes (the live-tailer contract:
# a long invocation must surface progress mid-epoch, not at its end).
# Overridable per node via cache['telemetry_flush_interval_s']
# (config/keys.py::Live.FLUSH_INTERVAL; 0 disables the timer and restores
# size-bounded-only flushing).  Only consulted on the ENABLED path — the
# disabled fast path never reaches _append, so the knob costs nothing there.
_FLUSH_INTERVAL_S = 5.0


class _NullSpan:
    """Shared, allocation-free no-op context manager."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _NullRecorder:
    """The disabled-mode fast path: every method is a no-op, ``span()``
    returns one shared context manager, and the singleton is falsy so call
    sites can guard bigger attribute computations with ``if rec.enabled:``."""

    __slots__ = ()
    enabled = False

    def __bool__(self):
        return False

    def span(self, name, cat="phase", **attrs):
        return _NULL_SPAN

    def record_span(self, name, t0, dur, cat="phase", **attrs):
        pass

    def begin_invocation(self, **context):
        pass

    def event(self, name, cat="event", **attrs):
        pass

    def metric(self, name, value, site=None, **attrs):
        pass

    def wire(self, op, path, nbytes=0, arrays=0, codec=None, raw_bytes=None,
             dur=0.0, payload_kind=None):
        pass

    def count(self, name, n=1):
        pass

    def set_context(self, **kw):
        pass

    def flush(self):
        pass


NULL_RECORDER = _NullRecorder()

# Active-recorder stack: index -1 is the ambient recorder instrumentation
# points reach via get_active().  Seeded with the null recorder so lookup
# never branches.  Per-process (the COINSTAC model runs one node per
# process; the in-process engine activates one node at a time).
_STACK = [NULL_RECORDER]


def get_active():
    """The ambient recorder (the null recorder when telemetry is off)."""
    return _STACK[-1]


@contextlib.contextmanager
def activate(recorder):
    """Make ``recorder`` the ambient recorder for the enclosed block —
    nodes wrap their whole invocation so deep layers (wire serialization,
    reducers, the trainer) reach the right sink without plumbing."""
    _STACK.append(recorder)
    try:
        yield recorder
    finally:
        _STACK.pop()


class _Span:
    """Measures one section; emits a span record (and, when a cache is
    attached, accumulates ``cache['profile_stats']`` at FULL precision —
    rounding per accumulation, as the old PhaseTimer did, drifts by up to
    5e-7 s per call)."""

    __slots__ = ("rec", "name", "cat", "attrs", "t0", "p0")

    def __init__(self, rec, name, cat, attrs):
        self.rec = rec
        self.name = name
        self.cat = cat
        self.attrs = attrs

    def __enter__(self):
        self.t0 = time.time()
        self.p0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dt = time.perf_counter() - self.p0
        self.rec._end_span(self.name, self.cat, self.t0, dt, self.attrs,
                           failed=exc_type is not None)
        if exc_type is not None:
            # a span dying with an exception may be the process's last act —
            # get the timeline (incl. this failed span) to disk now
            self.rec.flush()
        return False


class Recorder:
    """Per-node telemetry sink.

    ``node`` names the timeline lane (site id, ``"remote"``, ``"engine"``);
    ``cache`` (optional) supplies live federation context (round, fold,
    epoch) and receives ``profile_stats``; ``out_dir`` (optional) is where
    the JSONL file lands — without it the recorder is stats-only (the
    :class:`~..utils.profiling.PhaseTimer` compatibility mode).
    """

    enabled = True

    def __init__(self, node, cache=None, out_dir=None):
        self.node = str(node)
        self.cache = cache
        self.out_dir = str(out_dir) if out_dir else None
        self._buffer = []
        self._counters = {}
        self._context = {}
        # wire loads fan out over a thread pool (tensorutils.load_arrays_many
        # without the native runtime), so buffer/counter mutation and the
        # flush drain must be serialized
        self._lock = threading.Lock()
        self._io_lock = threading.Lock()  # keeps concurrent flushes' JSONL lines whole
        # time-based auto-flush (the live-tailer contract; see
        # _FLUSH_INTERVAL_S).  Resolved once at construction — one node
        # invocation never outlives its recorder's config.
        try:
            interval = float(
                (cache or {}).get("telemetry_flush_interval_s",
                                  _FLUSH_INTERVAL_S)
                or 0.0
            )
        except (TypeError, ValueError):
            interval = _FLUSH_INTERVAL_S
        self._flush_interval = max(interval, 0.0)
        self._flush_deadline = (
            time.monotonic() + self._flush_interval
            if self._flush_interval else None
        )
        _maybe_install_jax_compile_listener()
        _maybe_emit_degraded(self)

    # ------------------------------------------------------------- factories
    @classmethod
    def for_node(cls, cache, state=None, node=None):
        """The node-side factory: a real recorder when the node config asks
        for telemetry (``cache['profile']`` — the long-standing profiling
        flag — or ``cache['telemetry']``), the null singleton otherwise."""
        cache = cache if cache is not None else {}
        if not (cache.get("profile") or cache.get("telemetry")):
            return NULL_RECORDER
        state = state or {}
        node = node or state.get("clientId") or "node"
        return cls(node, cache=cache, out_dir=state.get("outputDirectory"))

    # -------------------------------------------------------------- recording
    def begin_invocation(self, **context):
        """Start of one node invocation: bump the persisted round counter
        (a plain cache key so it survives fresh-process round-trips; listed
        in ``basetrainer._VOLATILE_CACHE_KEYS`` so it never churns the
        shared compiled-step bucket) and stamp invocation context (phase).
        Shared by both node classes so their timelines cannot drift apart."""
        if self.cache is not None:
            self.cache["telemetry_round"] = (
                int(self.cache.get("telemetry_round", 0) or 0) + 1
            )
        self.set_context(**context)

    def set_context(self, **kw):
        """Static context stamped on every record (e.g. the engine's round
        counter, the node's current phase).  ``None`` clears a key."""
        for k, v in kw.items():
            if v is None:
                self._context.pop(k, None)
            else:
                self._context[k] = v

    def _ctx(self):
        ctx = {"node": self.node}
        cache = self.cache
        if cache is not None:
            rnd = cache.get("telemetry_round")
            if rnd is not None:
                ctx["round"] = int(rnd)
            fold = cache.get("split_ix")
            if fold is not None:
                ctx["fold"] = str(fold)
            epoch = cache.get("epoch")
            if epoch is not None:
                ctx["epoch"] = int(epoch)
        ctx.update(self._context)
        return ctx

    def _append(self, record):
        if self.out_dir is None:
            return  # stats-only mode (PhaseTimer shim): no sink, no buffering
        with self._lock:
            self._buffer.append(record)
            full = len(self._buffer) >= _AUTOFLUSH_AT or (
                self._flush_deadline is not None
                and time.monotonic() >= self._flush_deadline
            )
        if full:
            self.flush()

    def span(self, name, cat="phase", **attrs):
        """Context manager measuring one section as a span record."""
        return _Span(self, name, cat, attrs)

    def record_span(self, name, t0, dur, cat="phase", **attrs):
        """Emit an already-measured span (start wall-clock + duration).
        For sections whose boundaries are only known retroactively — e.g.
        the site-vectorized engine's round N, measured hook-to-hook."""
        self._end_span(name, cat, float(t0), float(dur), attrs)

    def _end_span(self, name, cat, t0, dt, attrs, failed=False):
        rec = {"v": SCHEMA_VERSION, "kind": "span", "name": name, "cat": cat,
               "t0": t0, "dur": dt}
        rec.update(self._ctx())
        if attrs:
            rec.update(attrs)
        if failed:
            rec["failed"] = True
        self._append(rec)
        if self.cache is not None:
            # PhaseTimer-compatible per-phase stats; full-precision
            # accumulation (JSON round-trips repr exactly, so nothing is
            # lost across fresh-process invocations either)
            stats = self.cache.setdefault("profile_stats", {})
            s = stats.setdefault(name, {"calls": 0, "total_s": 0.0, "max_s": 0.0})
            s["calls"] += 1
            s["total_s"] += dt
            s["max_s"] = max(s["max_s"], dt)

    def event(self, name, cat="event", **attrs):
        """Instantaneous record (quorum decisions, jit builds, failures)."""
        rec = {"v": SCHEMA_VERSION, "kind": "event", "name": name, "cat": cat,
               "t0": time.time()}
        rec.update(self._ctx())
        if attrs:
            rec.update(attrs)
        self._append(rec)

    def metric(self, name, value, site=None, **attrs):
        """One sample of a numeric health series (``kind: "metric"``).

        ``value`` is kept as a float even when non-finite — a NaN sample IS
        the signal for the nonfinite watchdog and the doctor's attribution
        (Python's json module round-trips ``NaN``/``Infinity`` tokens).
        ``site`` attributes an aggregator-side series to the originating
        site (e.g. per-site gradient cosine)."""
        rec = {"v": SCHEMA_VERSION, "kind": "metric", "name": str(name),
               "value": float(value), "t0": time.time()}
        if site is not None:
            rec["site"] = str(site)
        rec.update(self._ctx())
        if attrs:
            rec.update(attrs)
        self._append(rec)

    def wire(self, op, path, nbytes=0, arrays=0, codec=None, raw_bytes=None,
             dur=0.0, payload_kind=None):
        """One wire-payload transfer: ``op`` is ``save`` (outbound) or
        ``load`` (inbound), ``nbytes`` the on-disk payload size,
        ``raw_bytes`` the uncompressed array bytes (compression ratio =
        raw/payload), ``payload_kind`` the wire-schema lane the bytes rode
        (``json``/``tensor``/``delta`` — how ``dinulint --wire
        --reconcile`` buckets observed bytes per schema entry)."""
        rec = {"v": SCHEMA_VERSION, "kind": "wire", "op": op,
               "file": os.path.basename(str(path)), "t0": time.time(),
               "dur": float(dur), "bytes": int(nbytes), "arrays": int(arrays)}
        if payload_kind:
            rec["payload_kind"] = str(payload_kind)
        if codec:
            rec["codec"] = str(codec)
        if raw_bytes is not None:
            rec["raw_bytes"] = int(raw_bytes)
            if nbytes:
                rec["ratio"] = round(float(raw_bytes) / float(nbytes), 4)
        rec.update(self._ctx())
        self._append(rec)

    def count(self, name, n=1):
        """Cheap aggregated counter (e.g. compiled steps run); totals are
        emitted as one ``counter`` record per name at flush time."""
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    # ----------------------------------------------------------------- output
    def path(self):
        if not self.out_dir:
            return None
        return os.path.join(
            self.out_dir, f"{FILE_PREFIX}{_sanitize(self.node)}{FILE_SUFFIX}"
        )

    def flush(self):
        """Append buffered records (and drained counters) to the node's
        JSONL file in one write.  Without an ``out_dir`` this only drops the
        buffer (stats-only mode)."""
        with self._lock:
            if self._flush_interval:
                self._flush_deadline = time.monotonic() + self._flush_interval
            if self._counters:
                now = time.time()
                ctx = self._ctx()
                for name, n in sorted(self._counters.items()):
                    rec = {"v": SCHEMA_VERSION, "kind": "counter",
                           "name": name, "n": int(n), "t0": now}
                    rec.update(ctx)
                    self._buffer.append(rec)
                self._counters = {}
            buffered, self._buffer = self._buffer, []
        if not buffered:
            return
        path = self.path()
        if path is None:
            return
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            lines = "".join(
                json.dumps(r, separators=(",", ":"), default=str) + "\n"
                for r in buffered
            )
            with self._io_lock, open(path, "a", encoding="utf-8") as f:
                f.write(lines)
        except OSError:
            pass  # telemetry must never fail the run


def _sanitize(name):
    return "".join(c if (c.isalnum() or c in "-_") else "_" for c in str(name))


# --------------------------------------------------------------- jax bridge
_JAX_LISTENER_INSTALLED = False
# registration failure, kept for the one-time telemetry:degraded event (a
# missing jax.monitoring API must be VISIBLE in the trace, not silent —
# the compile-duration series simply ending would read as "no compiles")
_JAX_LISTENER_ERROR = None
_DEGRADED_EMITTED = False


def _maybe_install_jax_compile_listener():
    """Forward jax's own compile-duration monitoring events (backend
    compiles, tracing) to the ambient recorder — the recompile counter the
    per-invocation process model otherwise hides.  Installed once per
    process, only when jax is ALREADY imported (telemetry itself must never
    pull in jax), and tolerant of the monitoring API not existing (the
    failure is recorded as a ``telemetry:degraded`` event by the first
    enabled recorder, see :func:`_maybe_emit_degraded`)."""
    global _JAX_LISTENER_INSTALLED, _JAX_LISTENER_ERROR
    if _JAX_LISTENER_INSTALLED or "jax" not in sys.modules:
        return
    _JAX_LISTENER_INSTALLED = True  # one attempt per process, even on failure
    try:
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(_on_jax_duration)
    except Exception as exc:  # noqa: BLE001 — monitoring is best-effort
        _JAX_LISTENER_ERROR = f"{type(exc).__name__}: {exc}"[:300]


def _maybe_emit_degraded(recorder):
    """One ``telemetry:degraded`` event per process on the first recorder
    built after a failed jax.monitoring registration — the compile-duration
    bridge being dead is now evidence in the trace instead of silence."""
    global _DEGRADED_EMITTED
    if _JAX_LISTENER_ERROR is None or _DEGRADED_EMITTED:
        return
    _DEGRADED_EMITTED = True
    recorder.event(
        "telemetry:degraded", cat="telemetry",
        what="jax.monitoring compile-duration bridge unavailable",
        error=_JAX_LISTENER_ERROR,
    )


def _on_jax_duration(event, duration, **kw):
    if "compile" not in event and "trace" not in event:
        return
    rec = get_active()
    if rec.enabled:
        rec.event(
            f"jax:{event.rsplit('/', 1)[-1]}", cat="compile",
            secs=round(float(duration), 6),
        )
