"""``telemetry doctor`` — merge a run's telemetry into a postmortem report.

The collector (:mod:`.collect`) answers *where did the time and bytes go*;
the doctor answers *what went wrong and who did it*.  It merges every node's
spans + metric series + anomaly events into:

- an **anomaly timeline** (wall-clock ordered watchdog findings with round +
  site attribution),
- a **per-site divergence table** (cosine-to-mean stats, non-finite rounds,
  nonfinite-skip counts, anomaly counts per site),
- a **round-throughput trend** (engine:round span durations, first-half vs
  second-half drift),
- **ranked likely-cause verdicts** — ordered heuristics over the evidence
  (a site shipping NaNs outranks a validation stall outranks a slow round),
- optionally a **benchmark regression check** against
  ``BENCH_HISTORY.jsonl`` (``scripts/bench_history.py``; >10% samples/sec/
  chip drop vs the previous entry becomes a verdict).  The check is
  metric-aware over mixed ledgers: EVERY metric's latest same-metric pair
  is evaluated and the worst regression surfaces — so the per-engine
  ``engine_*_rounds_per_sec`` series and the async round engine's
  ``async_wire_overlap_ratio`` (wire time hidden under compute,
  ``bench_federation.py --async-staleness``) each regress independently.

Renderers: markdown (the human postmortem, uploaded as a CI artifact), JSON
(machines), and ``--format github`` workflow annotations for CI.
"""
import json
import math
import os
import sys

from .collect import fold_metric_sample, new_metric_stats

#: verdict severity order (rank 0 = most likely the root cause)
_SEVERITY = {"critical": 0, "warning": 1, "info": 2}


def _finite(v):
    try:
        return math.isfinite(float(v))
    except (TypeError, ValueError):
        return False


# ------------------------------------------------------------------ report
def build_report(events, bench_history=None, regression_threshold=0.10):
    """Merged timeline records → postmortem report dict.

    ``bench_history`` is a list of bench JSON dicts (oldest first) as read
    by :func:`load_bench_history`; the last two entries feed the regression
    verdict.
    """
    anomalies = []
    sites = {}
    metrics = {}
    rounds = []
    quarantined = set()
    # resilience-layer evidence: retry pressure, recovered corruption,
    # retry-attributed site deaths, injected chaos faults
    resilience = {"wire_retries": 0, "corruption_recovered": 0,
                  "invoke_retries": 0, "stale_standins": 0,
                  "staleness_blocks": 0}
    dead_sites = {}
    chaos = []
    # perf flight recorder evidence: the backend's roofline constants
    # (perf:backend), retained profiler captures (capture:profile), and
    # cost-analysis degradation (perf:cost_unavailable)
    backend = None
    captures = []
    cost_unavailable = []

    def site_entry(site):
        return sites.setdefault(str(site), {
            "cosine_n": 0, "cosine_sum": 0.0, "cosine_min": None,
            "nonfinite_rounds": 0, "skipped_rounds": 0, "anomalies": 0,
        })

    for rec in events:
        kind = rec.get("kind")
        name = rec.get("name", "")
        if kind == "metric":
            m = metrics.setdefault(name, new_metric_stats())
            v = fold_metric_sample(m, rec.get("value"))
            if name == "site_cosine" and rec.get("site") is not None:
                s = site_entry(rec["site"])
                if v is not None:
                    s["cosine_n"] += 1
                    s["cosine_sum"] += v
                    s["cosine_min"] = v if s["cosine_min"] is None else min(
                        s["cosine_min"], v
                    )
                else:
                    s["nonfinite_rounds"] += 1
        elif kind == "event":
            if name.startswith("anomaly:"):
                entry = {
                    "anomaly": name.split(":", 1)[1],
                    "t0": float(rec.get("t0", 0.0)),
                    "node": rec.get("node"),
                    "round": rec.get("round"),
                    "metric": rec.get("metric"),
                    "value": rec.get("value"),
                    "site": rec.get("site"),
                    "detail": rec.get("detail"),
                }
                anomalies.append(entry)
                if entry["site"] is not None:
                    site_entry(entry["site"])["anomalies"] += 1
            elif name == "reduce:nonfinite_skip":
                for s in rec.get("sites", []) or []:
                    site_entry(s)["skipped_rounds"] += 1
            elif name == "quarantine" and rec.get("site") is not None:
                quarantined.add(str(rec["site"]))
            elif name == "wire:retry":
                resilience["wire_retries"] += 1
            elif name == "wire:corruption_recovered":
                resilience["corruption_recovered"] += 1
            elif name == "invoke:retry":
                resilience["invoke_retries"] += 1
            elif name == "async:stale":
                resilience["stale_standins"] += 1
            elif name == "async:staleness_exceeded":
                resilience["staleness_blocks"] += 1
            elif name == "site_died" and rec.get("site") is not None:
                dead_sites[str(rec["site"])] = {
                    "round": rec.get("round"),
                    "attempts": int(rec.get("attempts", 1) or 1),
                    "retries_exhausted": bool(rec.get("retries_exhausted")),
                    "error": str(rec.get("error", ""))[:300],
                }
            elif name == "chaos:inject":
                chaos.append({
                    "kind": rec.get("fault"),
                    "round": rec.get("fault_round", rec.get("round")),
                    "site": rec.get("site"),
                    "file": rec.get("file"),
                })
            elif name == "perf:backend" and backend is None:
                backend = {
                    "device_kind": rec.get("device_kind"),
                    "devices": rec.get("devices"),
                    "peak_tflops": rec.get("peak_tflops"),
                    "peak_source": rec.get("peak_source"),
                    "ceiling_mfu": rec.get("ceiling_mfu"),
                }
            elif name == "capture:profile":
                captures.append({
                    "anomaly": rec.get("anomaly"),
                    "round": rec.get("round"),
                    "node": rec.get("node"),
                    "path": rec.get("path"),
                })
            elif name == "perf:cost_unavailable":
                cost_unavailable.append(str(rec.get("reason", "")))
        elif kind == "span" and name == "engine:round":
            rounds.append(float(rec.get("dur", 0.0) or 0.0))

    anomalies.sort(key=lambda a: a["t0"])
    # a permanent fault fires once per invocation attempt — collapse to one
    # entry per pinned fault, carrying the firing count
    deduped = {}
    for c in chaos:
        key = (c["kind"], c["round"], c["site"], c["file"])
        if key in deduped:
            deduped[key]["firings"] += 1
        else:
            deduped[key] = dict(c, firings=1)
    chaos = list(deduped.values())
    for s in sites.values():
        n = s.pop("cosine_n")
        total = s.pop("cosine_sum")
        s["cosine_mean"] = round(total / n, 4) if n else None
        if s["cosine_min"] is not None:
            s["cosine_min"] = round(s["cosine_min"], 4)

    round_stats = None
    if rounds:
        half = len(rounds) // 2
        first = rounds[:half] or rounds
        second = rounds[half:] or rounds
        mean = sum(rounds) / len(rounds)
        trend = (
            (sum(second) / len(second)) / max(sum(first) / len(first), 1e-12)
            - 1.0
        )
        round_stats = {
            "count": len(rounds),
            "mean_s": round(mean, 4),
            "max_s": round(max(rounds), 4),
            "trend_pct": round(100.0 * trend, 1),
        }

    bench = _bench_verdict_data(bench_history, regression_threshold)
    report = {
        "anomalies": anomalies,
        "sites": sites,
        "rounds": round_stats,
        "metrics": metrics,
        "quarantined": sorted(quarantined),
        "bench": bench,
        "resilience": resilience,
        "dead_sites": dead_sites,
        "chaos": chaos,
        "roofline": _roofline_data(metrics, backend, cost_unavailable),
        "captures": captures,
        "mfu_floor": _mfu_floor_data(
            bench_history, metrics, regression_threshold
        ),
    }
    report["verdicts"] = _rank_verdicts(report)
    return report


# --------------------------------------------------------------- roofline
_PERF_SERIES = ("achieved_tflops", "mfu", "samples_per_sec",
                "rounds_per_sec", "sites_per_sec")
_MEMORY_SERIES = {
    "in_use_bytes": "hbm_in_use_bytes", "peak_bytes": "hbm_peak_bytes",
    "limit_bytes": "hbm_limit_bytes", "utilization": "hbm_utilization",
}


def _roofline_data(metrics, backend, cost_unavailable):
    """Achieved-vs-ceiling-vs-peak comparison + device-memory summary from
    the folded metric series.  None when the run recorded no perf series
    at all (a pre-flight-recorder trace renders exactly as before)."""
    perf = {k: metrics[k] for k in _PERF_SERIES if k in metrics}
    memory = {k: metrics[m] for k, m in _MEMORY_SERIES.items()
              if m in metrics}
    if not perf and not memory and backend is None:
        return None
    out = {"backend": backend, "memory": memory or None}
    out.update({k: perf.get(k) for k in _PERF_SERIES})
    if cost_unavailable:
        # the series can be truncated/absent for a VISIBLE reason
        out["cost_unavailable"] = sorted(set(cost_unavailable))
    return out


def _mfu_floor_data(bench_history, metrics, threshold):
    """MFU-floor check: the run's best measured MFU vs the ledger's last
    recorded ``mfu`` (``bench.py`` writes one per entry).  One entry is
    enough — the floor is an absolute reference, unlike the two-entry
    throughput diff."""
    if not bench_history:
        return None
    ref = next(
        (float(e["mfu"]) for e in reversed(bench_history)
         if _finite(e.get("mfu")) and float(e["mfu"]) > 0),
        None,
    )
    measured = (metrics.get("mfu") or {}).get("max")
    if ref is None or measured is None:
        return None
    return {
        "ledger_mfu": ref,
        # 6 decimals: CPU-host MFU against a nominal peak is legitimately
        # ~1e-6, which a 4-decimal round would display as a confusing 0.0
        "measured_mfu": round(float(measured), 6),
        "threshold_pct": round(100.0 * threshold, 1),
        "below_floor": float(measured) < ref * (1.0 - threshold),
    }


def bench_regime(seed=None):
    """The measurement regime a bench ledger line is produced under:
    jax/numpy versions, platform triple, and the bench seed.  Stamped
    into every ledger line (``scripts/bench_federation.py`` /
    ``bench_history.py append``) so the regression verdict can tell a
    real throughput drop from a library upgrade, a different machine, or
    a different sampling seed.  Never imports jax just to stamp — the
    version comes from the already-imported module or the installed
    distribution metadata."""
    import platform as _platform

    jax_mod = sys.modules.get("jax")
    jax_v = getattr(jax_mod, "__version__", None)
    if jax_v is None:
        try:
            from importlib import metadata

            jax_v = metadata.version("jax")
        except Exception:  # noqa: BLE001 — stamp what's knowable
            jax_v = None
    try:
        import numpy as np

        np_v = np.__version__
    except Exception:  # noqa: BLE001
        np_v = None
    regime = {
        "jax": jax_v,
        "numpy": np_v,
        "platform": (f"{_platform.system()}-{_platform.machine()}"
                     f"-py{_platform.python_version()}"),
    }
    if seed is not None:
        regime["seed"] = int(seed)
    return regime


def regime_mismatch(prev, last):
    """The regime keys two ledger entries DISAGREE on, or None when they
    are comparable.  An unstamped side (pre-regime ledger lines) is
    comparable — only a key both sides stamped with different values
    refuses the diff."""
    ra, rb = prev.get("regime"), last.get("regime")
    if not isinstance(ra, dict) or not isinstance(rb, dict):
        return None
    bad = sorted(k for k in set(ra) & set(rb) if ra[k] != rb[k])
    return bad or None


def _bench_pair_data(prev, last, threshold):
    pv, lv = prev.get("value"), last.get("value")
    if not (_finite(pv) and _finite(lv)) or float(pv) <= 0:
        return None
    drop = 1.0 - float(lv) / float(pv)
    refused = regime_mismatch(prev, last)
    return {
        "previous": float(pv), "latest": float(lv),
        "drop_pct": round(100.0 * drop, 1),
        # a cross-regime pair is REFUSED, never silently diffed: the
        # drop (or gain) would be attributed to the code when it may be
        # the library, machine, or seed that moved
        "regressed": drop > threshold and not refused,
        "refused": bool(refused),
        "refused_keys": refused or [],
        "threshold_pct": round(100.0 * threshold, 1),
        # name the metric + unit so the verdict reads correctly for any
        # ledger (samples/sec/chip, rounds/sec, per-engine series, ...)
        "metric": str(last.get("metric") or "bench"),
        "unit": str(last.get("unit") or "samples/sec/chip"),
    }


def _bench_verdict_data(bench_history, threshold):
    """Latest same-metric pair comparison over a possibly mixed-metric
    ledger (the engine A/B appends one line per engine kind into
    BENCH_FEDERATION_HISTORY.jsonl — diffing a daemon entry against a
    vectorized one would be apples vs oranges).  EVERY metric's latest
    pair is evaluated; the verdict surfaces the worst regression, or the
    final entry's metric comparison when nothing regressed."""
    if not bench_history or len(bench_history) < 2:
        return None
    latest_pairs = {}  # metric -> (prev, last), walking oldest -> newest
    for e in bench_history:
        m = e.get("metric")
        prev_last = latest_pairs.get(m)
        latest_pairs[m] = ((prev_last[1] if prev_last else None), e)
    candidates = {}
    for m, (prev, last) in latest_pairs.items():
        if prev is None:
            continue
        data = _bench_pair_data(prev, last, threshold)
        if data is not None:
            candidates[m] = data
    if not candidates:
        return None
    regressed = [d for d in candidates.values() if d["regressed"]]
    if regressed:
        return max(regressed, key=lambda d: d["drop_pct"])
    refused = [d for d in candidates.values() if d.get("refused")]
    if refused:
        # a refusal outranks a clean comparison in the verdict: a silent
        # skip is exactly the failure mode the regime stamp exists to fix
        return max(refused, key=lambda d: abs(d["drop_pct"]))
    return candidates.get(
        bench_history[-1].get("metric"),
        next(iter(candidates.values())),
    )


def _rank_verdicts(report):
    """Evidence → ordered likely-cause list.  Severity first, then how much
    evidence backs the verdict."""
    verdicts = []

    def add(severity, cause, evidence, weight=1):
        verdicts.append({
            "severity": severity, "cause": cause, "evidence": evidence,
            "_w": weight,
        })

    # dead sites: the engine's site_died events carry the invocation
    # attempt count, so the verdict attributes the death to *exhausted
    # retries* (the retry/backoff policy ran out) vs a *hard failure*
    # declared on the first invocation (no retry configured)
    for site, d in sorted(report.get("dead_sites", {}).items()):
        if d["retries_exhausted"]:
            how = (
                f"declared dead after exhausting {d['attempts']} invocation "
                "attempts (retry/backoff ran out)"
            )
        else:
            how = "hard failure on its first invocation (no retry configured)"
        add(
            "critical",
            f"site {site} died mid-run",
            how
            + (f" at round {d['round']}" if d.get("round") is not None else "")
            + (f": {d['error']}" if d.get("error") else ""),
            weight=d["attempts"],
        )

    # one-bad-site corruption: the strongest, most attributable signal.
    # nonfinite_rounds (NaN site_cosine samples) and skipped_rounds
    # (reduce:nonfinite_skip events) describe the SAME corrupted reduces
    # from two record kinds — max, not sum, or the blast radius doubles
    for site, s in sorted(report["sites"].items()):
        bad = max(s["nonfinite_rounds"], s["skipped_rounds"])
        if bad:
            first = next(
                (a["round"] for a in report["anomalies"]
                 if a["site"] == site and a["anomaly"] == "nonfinite"),
                None,
            )
            add(
                "critical",
                f"site {site} shipped non-finite gradients",
                f"{bad} affected reduce round(s)"
                + (f", first anomaly at round {first}" if first is not None
                   else "")
                + (", quarantined" if site in report["quarantined"] else
                   ", excluded per-round by the nonfinite guard"),
                weight=bad,
            )
    by_kind = {}
    for a in report["anomalies"]:
        by_kind.setdefault(a["anomaly"], []).append(a)
    for site, s in sorted(report["sites"].items()):
        outliers = [a for a in by_kind.get("divergence_outlier", [])
                    if a["site"] == site]
        if outliers:
            add(
                "critical",
                f"site {site} diverged from the consensus gradient",
                f"{len(outliers)} divergence anomaly(ies); "
                f"mean cosine {s['cosine_mean']}, min {s['cosine_min']}",
                weight=len(outliers),
            )
    for kind, severity, cause in (
        ("grad_explosion", "critical", "gradient explosion"),
        ("memory_pressure", "critical",
         "device memory near its limit (OOM imminent)"),
        ("memory_leak", "warning",
         "device memory grew round over round (leak)"),
        ("compression_spike", "warning",
         "compression reconstruction error spiked"),
        ("rank_collapse", "warning",
         "compression factorization rank collapsed"),
        ("val_stall", "warning", "validation metric stalled"),
    ):
        hits = by_kind.get(kind, [])
        if hits:
            where = sorted({a["node"] for a in hits if a["node"]})
            first = hits[0]
            add(
                severity, cause,
                f"{len(hits)} anomaly(ies) on {', '.join(where) or '?'}; "
                f"first at round {first['round']}: {first['detail'] or ''}",
                weight=len(hits),
            )
    rounds = report.get("rounds")
    if rounds and rounds["count"] >= 4 and rounds["trend_pct"] > 20.0:
        add(
            "warning", "round throughput degraded over the run",
            f"second-half rounds {rounds['trend_pct']:+.1f}% vs first half "
            f"(mean {rounds['mean_s']}s over {rounds['count']} rounds)",
        )
    bench = report.get("bench")
    if bench and bench["regressed"]:
        add(
            "warning",
            "benchmark throughput regressed vs the previous run",
            f"{bench.get('unit', 'samples/sec/chip')} {bench['latest']:g} "
            f"vs {bench['previous']:g} ({bench['drop_pct']:+.1f}% drop, "
            f"threshold {bench['threshold_pct']:g}%)",
        )
    elif bench and bench.get("refused"):
        add(
            "warning",
            "bench regression check refused: cross-regime ledger pair",
            f"{bench['metric']} entries were measured under different "
            f"regimes ({', '.join(bench['refused_keys'])} changed) — "
            f"the {bench['drop_pct']:+.1f}% delta is not attributable to "
            "the code; re-baseline the ledger on the current regime",
        )
    floor = report.get("mfu_floor")
    if floor and floor["below_floor"]:
        add(
            "warning",
            "MFU below the benchmark ledger floor",
            f"measured MFU {floor['measured_mfu']:g} vs ledger "
            f"{floor['ledger_mfu']:g} (threshold "
            f"{floor['threshold_pct']:g}%) — the run sustained less of the "
            "hardware than the last recorded bench",
        )
    roof = report.get("roofline") or {}
    util = (roof.get("memory") or {}).get("utilization") or {}
    if util.get("max") is not None and util["max"] >= 0.9:
        add(
            "warning",
            "device memory headroom below 10%",
            f"peak HBM utilization {util['max']:.1%} — the next allocation "
            "spike is an OOM; shrink the batch or shard the state",
        )
    captures = report.get("captures") or []
    if captures:
        named = "; ".join(
            f"{c['anomaly']} @ round {c['round']} → {c['path']}"
            for c in captures
        )
        add(
            "info",
            f"{len(captures)} profiler capture(s) retained",
            f"anomaly-triggered deep captures attached: {named}",
            weight=len(captures),
        )
    res = report.get("resilience") or {}
    if res.get("corruption_recovered"):
        add(
            "warning",
            "wire payload corruption/truncation recovered via retry",
            f"{res['corruption_recovered']} payload(s) recovered after "
            f"{res['wire_retries']} wire retry(ies) — the data was intact "
            "on arrival, but the relay is flaky",
            weight=res["corruption_recovered"],
        )
    chaos = report.get("chaos") or []
    if chaos:
        named = ", ".join(
            f"{c['kind']} @ round {c['round']}"
            + (f"/{c['site']}" if c.get("site") else "")
            + (f" ({c['file']})" if c.get("file") else "")
            for c in chaos
        )
        add(
            "info",
            f"{len(chaos)} deterministic chaos fault(s) were injected",
            f"fault plan active: {named} — the failures above are expected "
            "and the recovery paths they exercised are the evidence",
            weight=len(chaos),
        )
    if not verdicts:
        add("info", "no anomalies detected",
            "all watched series stayed within bounds")
    verdicts.sort(key=lambda v: (_SEVERITY[v["severity"]], -v["_w"]))
    for rank, v in enumerate(verdicts, 1):
        v.pop("_w")
        v["rank"] = rank
    return verdicts


# --------------------------------------------------------------- renderers
def _stat(series, fmt="{:.4g}"):
    """last (min..max, n) rendering of one folded metric-stats dict."""
    if not series or series.get("last") is None:
        return "-"
    return (fmt.format(series["last"])
            + f" (min {fmt.format(series['min'])}, "
              f"max {fmt.format(series['max'])}, n={series['count']})")


def _gib(series):
    if not series or series.get("last") is None:
        return "-"
    scaled = dict(series)
    for k in ("last", "min", "max"):
        if isinstance(scaled.get(k), (int, float)):
            scaled[k] = scaled[k] / 2**30
    return _stat(scaled, fmt="{:.3f}GiB")


def _render_roofline(report):
    """The roofline + device-memory block: achieved TFLOPS/MFU against the
    structural ceiling and the backend peak, plus the HBM series.  Empty
    list when the run recorded no perf flight-recorder series."""
    roof = report.get("roofline")
    if not roof:
        return []
    lines = ["## Roofline (perf flight recorder)", ""]
    backend = roof.get("backend") or {}
    if backend.get("device_kind"):
        peak = backend.get("peak_tflops")
        lines.append(
            f"Backend: {backend['device_kind']} × "
            f"{backend.get('devices') or '?'}"
            + (f", peak {peak:g} TFLOPS ({backend.get('peak_source')})"
               if peak else ", peak unknown (set cache['peak_tflops'])")
            + (f", structural ceiling {backend['ceiling_mfu']:.0%} MFU "
               "(docs/PERF.md)" if backend.get("ceiling_mfu") else "")
            + "."
        )
        lines.append("")
    rows = [
        ("achieved TFLOPS", _stat(roof.get("achieved_tflops"))),
        ("MFU", _stat(roof.get("mfu"))),
        ("samples/sec", _stat(roof.get("samples_per_sec"))),
    ]
    if roof.get("rounds_per_sec"):
        rows.append(("rounds/sec", _stat(roof["rounds_per_sec"])))
    if roof.get("sites_per_sec"):
        rows.append(("sites/sec", _stat(roof["sites_per_sec"])))
    lines.extend(_md_table(("series", "last (min..max, samples)"), rows))
    lines.append("")
    memory = roof.get("memory")
    if memory:
        rows = [(label, _gib(memory.get(key)) if "bytes" in key
                 else _stat(memory.get(key)))
                for key, label in (
                    ("in_use_bytes", "HBM in use"),
                    ("peak_bytes", "HBM peak"),
                    ("limit_bytes", "HBM limit"),
                    ("utilization", "HBM utilization"),
                ) if memory.get(key)]
        lines.append("### Device memory")
        lines.append("")
        lines.extend(_md_table(("series", "last (min..max, samples)"), rows))
        lines.append("")
    floor = report.get("mfu_floor")
    if floor:
        state = ("**BELOW FLOOR**" if floor["below_floor"]
                 else "at or above the floor")
        lines.append(
            f"MFU floor: measured {floor['measured_mfu']:g} vs ledger "
            f"{floor['ledger_mfu']:g} (threshold {floor['threshold_pct']:g}%)"
            f" — {state}."
        )
        lines.append("")
    if roof.get("cost_unavailable"):
        lines.append(
            "Cost analysis unavailable for some executables: "
            + ", ".join(roof["cost_unavailable"]) + "."
        )
        lines.append("")
    return lines


def _md_table(headers, rows):
    out = ["| " + " | ".join(headers) + " |",
           "|" + "|".join("---" for _ in headers) + "|"]
    for row in rows:
        out.append("| " + " | ".join(str(c) for c in row) + " |")
    return out


def render_markdown(report):
    """The human postmortem (CI artifact / PR comment body)."""
    lines = ["# Federation health postmortem", ""]

    lines.append("## Verdicts (ranked)")
    lines.append("")
    for v in report["verdicts"]:
        lines.append(
            f"{v['rank']}. **[{v['severity']}] {v['cause']}** — {v['evidence']}"
        )
    lines.append("")

    chaos = report.get("chaos") or []
    if chaos:
        lines.append("## Injected chaos faults")
        lines.append("")
        lines.extend(_md_table(
            ("kind", "round", "site", "file", "firings"),
            [(c["kind"], c["round"], c.get("site") or "-",
              c.get("file") or "-", c.get("firings", 1)) for c in chaos],
        ))
        lines.append("")

    dead = report.get("dead_sites") or {}
    res = report.get("resilience") or {}
    if dead or any(res.values()):
        lines.append("## Resilience")
        lines.append("")
        lines.append(
            f"{res.get('wire_retries', 0)} wire retry(ies), "
            f"{res.get('corruption_recovered', 0)} corrupt/truncated "
            f"payload(s) recovered, {res.get('invoke_retries', 0)} "
            "invocation retry(ies)."
            + (f" Async rounds: {res['stale_standins']} straggler "
               f"stand-in(s) delivered, {res.get('staleness_blocks', 0)} "
               "forced block(s) past the staleness window."
               if res.get("stale_standins") or res.get("staleness_blocks")
               else "")
        )
        lines.append("")
        if dead:
            lines.extend(_md_table(
                ("dead site", "round", "attempts", "cause"),
                [(site,
                  d["round"] if d.get("round") is not None else "-",
                  d["attempts"],
                  "retries exhausted" if d["retries_exhausted"]
                  else "hard failure")
                 for site, d in sorted(dead.items())],
            ))
            lines.append("")

    if report["anomalies"]:
        lines.append("## Anomaly timeline")
        lines.append("")
        rows = [
            (a["round"] if a["round"] is not None else "-",
             a["node"] or "-", a["anomaly"], a["site"] or "-",
             a["metric"] or "-", a["value"], a["detail"] or "")
            for a in report["anomalies"]
        ]
        lines.extend(_md_table(
            ("round", "node", "anomaly", "site", "metric", "value", "detail"),
            rows,
        ))
        lines.append("")

    if report["sites"]:
        lines.append("## Per-site divergence")
        lines.append("")
        rows = []
        for site, s in sorted(report["sites"].items()):
            flags = []
            if site in report["quarantined"]:
                flags.append("quarantined")
            rows.append((
                site,
                s["cosine_mean"] if s["cosine_mean"] is not None else "-",
                s["cosine_min"] if s["cosine_min"] is not None else "-",
                s["nonfinite_rounds"], s["skipped_rounds"], s["anomalies"],
                ",".join(flags) or "-",
            ))
        lines.extend(_md_table(
            ("site", "cosine mean", "cosine min", "nonfinite", "skipped",
             "anomalies", "flags"),
            rows,
        ))
        lines.append("")

    rounds = report.get("rounds")
    if rounds:
        lines.append("## Round throughput")
        lines.append("")
        lines.append(
            f"{rounds['count']} engine rounds, mean {rounds['mean_s']}s, "
            f"max {rounds['max_s']}s, second-half trend "
            f"{rounds['trend_pct']:+.1f}%."
        )
        lines.append("")

    lines.extend(_render_roofline(report))

    captures = report.get("captures") or []
    if captures:
        lines.append("## Profiler captures")
        lines.append("")
        lines.extend(_md_table(
            ("anomaly", "round", "node", "profile"),
            [(c["anomaly"] or "-", c["round"] if c["round"] is not None
              else "-", c["node"] or "-", c["path"] or "-")
             for c in captures],
        ))
        lines.append("")

    bench = report.get("bench")
    if bench:
        lines.append("## Benchmark history")
        lines.append("")
        if bench["regressed"]:
            state = "**REGRESSED**"
        elif bench.get("refused"):
            state = ("**REFUSED** (cross-regime: "
                     f"{', '.join(bench['refused_keys'])} changed)")
        else:
            state = "within bounds"
        lines.append(
            f"{bench.get('unit', 'samples/sec/chip')} {bench['latest']:g} "
            f"vs previous {bench['previous']:g} ({bench['drop_pct']:+.1f}%; "
            f"threshold {bench['threshold_pct']:g}%) — {state}."
        )
        lines.append("")

    if report["metrics"]:
        lines.append("## Metric series")
        lines.append("")
        rows = [
            (name, m["count"], m["nonfinite"],
             "-" if m["last"] is None else f"{m['last']:.4g}",
             "-" if m["min"] is None else f"{m['min']:.4g}",
             "-" if m["max"] is None else f"{m['max']:.4g}")
            for name, m in sorted(report["metrics"].items())
        ]
        lines.extend(_md_table(
            ("metric", "samples", "nonfinite", "last", "min", "max"), rows,
        ))
        lines.append("")
    return "\n".join(lines)


def _github_escape(text):
    return str(text).replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")


def render_github(report):
    """GitHub workflow annotations: one ::error per critical verdict, one
    ::warning per warning verdict (inline on the PR's checks page)."""
    lines = []
    for v in report["verdicts"]:
        if v["severity"] == "info":
            continue
        cmd = "error" if v["severity"] == "critical" else "warning"
        lines.append(
            f"::{cmd} title=telemetry doctor::"
            f"{_github_escape(v['cause'] + ' — ' + v['evidence'])}"
        )
    lines.append(
        f"{len(report['anomalies'])} anomaly(ies), "
        f"{len(report['verdicts'])} verdict(s)"
    )
    return "\n".join(lines)


# ------------------------------------------------------------ bench history
def load_bench_history(path):
    """``BENCH_HISTORY.jsonl`` (one bench JSON line per run, oldest first)
    → list of dicts.  Missing file → empty list; corrupt lines skipped."""
    out = []
    if not path or not os.path.exists(path):
        return out
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out
