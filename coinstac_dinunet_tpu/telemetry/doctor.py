"""``telemetry doctor`` — merge a run's telemetry into a postmortem report.

The collector (:mod:`.collect`) answers *where did the time and bytes go*;
the doctor answers *what went wrong and who did it*.  It merges every node's
spans + metric series + anomaly events into:

- an **anomaly timeline** (wall-clock ordered watchdog findings with round +
  site attribution),
- a **per-site divergence table** (cosine-to-mean stats, non-finite rounds,
  nonfinite-skip counts, anomaly counts per site),
- a **round-throughput trend** (engine:round span durations, first-half vs
  second-half drift),
- **ranked likely-cause verdicts** — ordered heuristics over the evidence
  (a site shipping NaNs outranks a validation stall outranks a slow round),
- optionally a **benchmark regression check** against
  ``BENCH_HISTORY.jsonl`` (``scripts/bench_history.py``; >10% samples/sec/
  chip drop vs the previous entry becomes a verdict).

Renderers: markdown (the human postmortem, uploaded as a CI artifact), JSON
(machines), and ``--format github`` workflow annotations for CI.
"""
import json
import math
import os

from .collect import fold_metric_sample, new_metric_stats

#: verdict severity order (rank 0 = most likely the root cause)
_SEVERITY = {"critical": 0, "warning": 1, "info": 2}


def _finite(v):
    try:
        return math.isfinite(float(v))
    except (TypeError, ValueError):
        return False


# ------------------------------------------------------------------ report
def build_report(events, bench_history=None, regression_threshold=0.10):
    """Merged timeline records → postmortem report dict.

    ``bench_history`` is a list of bench JSON dicts (oldest first) as read
    by :func:`load_bench_history`; the last two entries feed the regression
    verdict.
    """
    anomalies = []
    sites = {}
    metrics = {}
    rounds = []
    quarantined = set()
    # resilience-layer evidence: retry pressure, recovered corruption,
    # retry-attributed site deaths, injected chaos faults
    resilience = {"wire_retries": 0, "corruption_recovered": 0,
                  "invoke_retries": 0}
    dead_sites = {}
    chaos = []

    def site_entry(site):
        return sites.setdefault(str(site), {
            "cosine_n": 0, "cosine_sum": 0.0, "cosine_min": None,
            "nonfinite_rounds": 0, "skipped_rounds": 0, "anomalies": 0,
        })

    for rec in events:
        kind = rec.get("kind")
        name = rec.get("name", "")
        if kind == "metric":
            m = metrics.setdefault(name, new_metric_stats())
            v = fold_metric_sample(m, rec.get("value"))
            if name == "site_cosine" and rec.get("site") is not None:
                s = site_entry(rec["site"])
                if v is not None:
                    s["cosine_n"] += 1
                    s["cosine_sum"] += v
                    s["cosine_min"] = v if s["cosine_min"] is None else min(
                        s["cosine_min"], v
                    )
                else:
                    s["nonfinite_rounds"] += 1
        elif kind == "event":
            if name.startswith("anomaly:"):
                entry = {
                    "anomaly": name.split(":", 1)[1],
                    "t0": float(rec.get("t0", 0.0)),
                    "node": rec.get("node"),
                    "round": rec.get("round"),
                    "metric": rec.get("metric"),
                    "value": rec.get("value"),
                    "site": rec.get("site"),
                    "detail": rec.get("detail"),
                }
                anomalies.append(entry)
                if entry["site"] is not None:
                    site_entry(entry["site"])["anomalies"] += 1
            elif name == "reduce:nonfinite_skip":
                for s in rec.get("sites", []) or []:
                    site_entry(s)["skipped_rounds"] += 1
            elif name == "quarantine" and rec.get("site") is not None:
                quarantined.add(str(rec["site"]))
            elif name == "wire:retry":
                resilience["wire_retries"] += 1
            elif name == "wire:corruption_recovered":
                resilience["corruption_recovered"] += 1
            elif name == "invoke:retry":
                resilience["invoke_retries"] += 1
            elif name == "site_died" and rec.get("site") is not None:
                dead_sites[str(rec["site"])] = {
                    "round": rec.get("round"),
                    "attempts": int(rec.get("attempts", 1) or 1),
                    "retries_exhausted": bool(rec.get("retries_exhausted")),
                    "error": str(rec.get("error", ""))[:300],
                }
            elif name == "chaos:inject":
                chaos.append({
                    "kind": rec.get("fault"),
                    "round": rec.get("fault_round", rec.get("round")),
                    "site": rec.get("site"),
                    "file": rec.get("file"),
                })
        elif kind == "span" and name == "engine:round":
            rounds.append(float(rec.get("dur", 0.0) or 0.0))

    anomalies.sort(key=lambda a: a["t0"])
    # a permanent fault fires once per invocation attempt — collapse to one
    # entry per pinned fault, carrying the firing count
    deduped = {}
    for c in chaos:
        key = (c["kind"], c["round"], c["site"], c["file"])
        if key in deduped:
            deduped[key]["firings"] += 1
        else:
            deduped[key] = dict(c, firings=1)
    chaos = list(deduped.values())
    for s in sites.values():
        n = s.pop("cosine_n")
        total = s.pop("cosine_sum")
        s["cosine_mean"] = round(total / n, 4) if n else None
        if s["cosine_min"] is not None:
            s["cosine_min"] = round(s["cosine_min"], 4)

    round_stats = None
    if rounds:
        half = len(rounds) // 2
        first = rounds[:half] or rounds
        second = rounds[half:] or rounds
        mean = sum(rounds) / len(rounds)
        trend = (
            (sum(second) / len(second)) / max(sum(first) / len(first), 1e-12)
            - 1.0
        )
        round_stats = {
            "count": len(rounds),
            "mean_s": round(mean, 4),
            "max_s": round(max(rounds), 4),
            "trend_pct": round(100.0 * trend, 1),
        }

    bench = _bench_verdict_data(bench_history, regression_threshold)
    report = {
        "anomalies": anomalies,
        "sites": sites,
        "rounds": round_stats,
        "metrics": metrics,
        "quarantined": sorted(quarantined),
        "bench": bench,
        "resilience": resilience,
        "dead_sites": dead_sites,
        "chaos": chaos,
    }
    report["verdicts"] = _rank_verdicts(report)
    return report


def _bench_verdict_data(bench_history, threshold):
    if not bench_history or len(bench_history) < 2:
        return None
    prev, last = bench_history[-2], bench_history[-1]
    pv, lv = prev.get("value"), last.get("value")
    if not (_finite(pv) and _finite(lv)) or float(pv) <= 0:
        return None
    drop = 1.0 - float(lv) / float(pv)
    return {
        "previous": float(pv), "latest": float(lv),
        "drop_pct": round(100.0 * drop, 1),
        "regressed": drop > threshold,
        "threshold_pct": round(100.0 * threshold, 1),
        # ledgers are per-metric files (BENCH_HISTORY.jsonl,
        # BENCH_FEDERATION_HISTORY.jsonl, ...): name the unit so the
        # verdict reads correctly for any of them
        "metric": str(last.get("metric") or "bench"),
        "unit": str(last.get("unit") or "samples/sec/chip"),
    }


def _rank_verdicts(report):
    """Evidence → ordered likely-cause list.  Severity first, then how much
    evidence backs the verdict."""
    verdicts = []

    def add(severity, cause, evidence, weight=1):
        verdicts.append({
            "severity": severity, "cause": cause, "evidence": evidence,
            "_w": weight,
        })

    # dead sites: the engine's site_died events carry the invocation
    # attempt count, so the verdict attributes the death to *exhausted
    # retries* (the retry/backoff policy ran out) vs a *hard failure*
    # declared on the first invocation (no retry configured)
    for site, d in sorted(report.get("dead_sites", {}).items()):
        if d["retries_exhausted"]:
            how = (
                f"declared dead after exhausting {d['attempts']} invocation "
                "attempts (retry/backoff ran out)"
            )
        else:
            how = "hard failure on its first invocation (no retry configured)"
        add(
            "critical",
            f"site {site} died mid-run",
            how
            + (f" at round {d['round']}" if d.get("round") is not None else "")
            + (f": {d['error']}" if d.get("error") else ""),
            weight=d["attempts"],
        )

    # one-bad-site corruption: the strongest, most attributable signal.
    # nonfinite_rounds (NaN site_cosine samples) and skipped_rounds
    # (reduce:nonfinite_skip events) describe the SAME corrupted reduces
    # from two record kinds — max, not sum, or the blast radius doubles
    for site, s in sorted(report["sites"].items()):
        bad = max(s["nonfinite_rounds"], s["skipped_rounds"])
        if bad:
            first = next(
                (a["round"] for a in report["anomalies"]
                 if a["site"] == site and a["anomaly"] == "nonfinite"),
                None,
            )
            add(
                "critical",
                f"site {site} shipped non-finite gradients",
                f"{bad} affected reduce round(s)"
                + (f", first anomaly at round {first}" if first is not None
                   else "")
                + (", quarantined" if site in report["quarantined"] else
                   ", excluded per-round by the nonfinite guard"),
                weight=bad,
            )
    by_kind = {}
    for a in report["anomalies"]:
        by_kind.setdefault(a["anomaly"], []).append(a)
    for site, s in sorted(report["sites"].items()):
        outliers = [a for a in by_kind.get("divergence_outlier", [])
                    if a["site"] == site]
        if outliers:
            add(
                "critical",
                f"site {site} diverged from the consensus gradient",
                f"{len(outliers)} divergence anomaly(ies); "
                f"mean cosine {s['cosine_mean']}, min {s['cosine_min']}",
                weight=len(outliers),
            )
    for kind, severity, cause in (
        ("grad_explosion", "critical", "gradient explosion"),
        ("compression_spike", "warning",
         "compression reconstruction error spiked"),
        ("rank_collapse", "warning",
         "compression factorization rank collapsed"),
        ("val_stall", "warning", "validation metric stalled"),
    ):
        hits = by_kind.get(kind, [])
        if hits:
            where = sorted({a["node"] for a in hits if a["node"]})
            first = hits[0]
            add(
                severity, cause,
                f"{len(hits)} anomaly(ies) on {', '.join(where) or '?'}; "
                f"first at round {first['round']}: {first['detail'] or ''}",
                weight=len(hits),
            )
    rounds = report.get("rounds")
    if rounds and rounds["count"] >= 4 and rounds["trend_pct"] > 20.0:
        add(
            "warning", "round throughput degraded over the run",
            f"second-half rounds {rounds['trend_pct']:+.1f}% vs first half "
            f"(mean {rounds['mean_s']}s over {rounds['count']} rounds)",
        )
    bench = report.get("bench")
    if bench and bench["regressed"]:
        add(
            "warning",
            "benchmark throughput regressed vs the previous run",
            f"{bench.get('unit', 'samples/sec/chip')} {bench['latest']:g} "
            f"vs {bench['previous']:g} ({bench['drop_pct']:+.1f}% drop, "
            f"threshold {bench['threshold_pct']:g}%)",
        )
    res = report.get("resilience") or {}
    if res.get("corruption_recovered"):
        add(
            "warning",
            "wire payload corruption/truncation recovered via retry",
            f"{res['corruption_recovered']} payload(s) recovered after "
            f"{res['wire_retries']} wire retry(ies) — the data was intact "
            "on arrival, but the relay is flaky",
            weight=res["corruption_recovered"],
        )
    chaos = report.get("chaos") or []
    if chaos:
        named = ", ".join(
            f"{c['kind']} @ round {c['round']}"
            + (f"/{c['site']}" if c.get("site") else "")
            + (f" ({c['file']})" if c.get("file") else "")
            for c in chaos
        )
        add(
            "info",
            f"{len(chaos)} deterministic chaos fault(s) were injected",
            f"fault plan active: {named} — the failures above are expected "
            "and the recovery paths they exercised are the evidence",
            weight=len(chaos),
        )
    if not verdicts:
        add("info", "no anomalies detected",
            "all watched series stayed within bounds")
    verdicts.sort(key=lambda v: (_SEVERITY[v["severity"]], -v["_w"]))
    for rank, v in enumerate(verdicts, 1):
        v.pop("_w")
        v["rank"] = rank
    return verdicts


# --------------------------------------------------------------- renderers
def _md_table(headers, rows):
    out = ["| " + " | ".join(headers) + " |",
           "|" + "|".join("---" for _ in headers) + "|"]
    for row in rows:
        out.append("| " + " | ".join(str(c) for c in row) + " |")
    return out


def render_markdown(report):
    """The human postmortem (CI artifact / PR comment body)."""
    lines = ["# Federation health postmortem", ""]

    lines.append("## Verdicts (ranked)")
    lines.append("")
    for v in report["verdicts"]:
        lines.append(
            f"{v['rank']}. **[{v['severity']}] {v['cause']}** — {v['evidence']}"
        )
    lines.append("")

    chaos = report.get("chaos") or []
    if chaos:
        lines.append("## Injected chaos faults")
        lines.append("")
        lines.extend(_md_table(
            ("kind", "round", "site", "file", "firings"),
            [(c["kind"], c["round"], c.get("site") or "-",
              c.get("file") or "-", c.get("firings", 1)) for c in chaos],
        ))
        lines.append("")

    dead = report.get("dead_sites") or {}
    res = report.get("resilience") or {}
    if dead or any(res.values()):
        lines.append("## Resilience")
        lines.append("")
        lines.append(
            f"{res.get('wire_retries', 0)} wire retry(ies), "
            f"{res.get('corruption_recovered', 0)} corrupt/truncated "
            f"payload(s) recovered, {res.get('invoke_retries', 0)} "
            "invocation retry(ies)."
        )
        lines.append("")
        if dead:
            lines.extend(_md_table(
                ("dead site", "round", "attempts", "cause"),
                [(site,
                  d["round"] if d.get("round") is not None else "-",
                  d["attempts"],
                  "retries exhausted" if d["retries_exhausted"]
                  else "hard failure")
                 for site, d in sorted(dead.items())],
            ))
            lines.append("")

    if report["anomalies"]:
        lines.append("## Anomaly timeline")
        lines.append("")
        rows = [
            (a["round"] if a["round"] is not None else "-",
             a["node"] or "-", a["anomaly"], a["site"] or "-",
             a["metric"] or "-", a["value"], a["detail"] or "")
            for a in report["anomalies"]
        ]
        lines.extend(_md_table(
            ("round", "node", "anomaly", "site", "metric", "value", "detail"),
            rows,
        ))
        lines.append("")

    if report["sites"]:
        lines.append("## Per-site divergence")
        lines.append("")
        rows = []
        for site, s in sorted(report["sites"].items()):
            flags = []
            if site in report["quarantined"]:
                flags.append("quarantined")
            rows.append((
                site,
                s["cosine_mean"] if s["cosine_mean"] is not None else "-",
                s["cosine_min"] if s["cosine_min"] is not None else "-",
                s["nonfinite_rounds"], s["skipped_rounds"], s["anomalies"],
                ",".join(flags) or "-",
            ))
        lines.extend(_md_table(
            ("site", "cosine mean", "cosine min", "nonfinite", "skipped",
             "anomalies", "flags"),
            rows,
        ))
        lines.append("")

    rounds = report.get("rounds")
    if rounds:
        lines.append("## Round throughput")
        lines.append("")
        lines.append(
            f"{rounds['count']} engine rounds, mean {rounds['mean_s']}s, "
            f"max {rounds['max_s']}s, second-half trend "
            f"{rounds['trend_pct']:+.1f}%."
        )
        lines.append("")

    bench = report.get("bench")
    if bench:
        lines.append("## Benchmark history")
        lines.append("")
        state = ("**REGRESSED**" if bench["regressed"] else "within bounds")
        lines.append(
            f"{bench.get('unit', 'samples/sec/chip')} {bench['latest']:g} "
            f"vs previous {bench['previous']:g} ({bench['drop_pct']:+.1f}%; "
            f"threshold {bench['threshold_pct']:g}%) — {state}."
        )
        lines.append("")

    if report["metrics"]:
        lines.append("## Metric series")
        lines.append("")
        rows = [
            (name, m["count"], m["nonfinite"],
             "-" if m["last"] is None else f"{m['last']:.4g}",
             "-" if m["min"] is None else f"{m['min']:.4g}",
             "-" if m["max"] is None else f"{m['max']:.4g}")
            for name, m in sorted(report["metrics"].items())
        ]
        lines.extend(_md_table(
            ("metric", "samples", "nonfinite", "last", "min", "max"), rows,
        ))
        lines.append("")
    return "\n".join(lines)


def _github_escape(text):
    return str(text).replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")


def render_github(report):
    """GitHub workflow annotations: one ::error per critical verdict, one
    ::warning per warning verdict (inline on the PR's checks page)."""
    lines = []
    for v in report["verdicts"]:
        if v["severity"] == "info":
            continue
        cmd = "error" if v["severity"] == "critical" else "warning"
        lines.append(
            f"::{cmd} title=telemetry doctor::"
            f"{_github_escape(v['cause'] + ' — ' + v['evidence'])}"
        )
    lines.append(
        f"{len(report['anomalies'])} anomaly(ies), "
        f"{len(report['verdicts'])} verdict(s)"
    )
    return "\n".join(lines)


# ------------------------------------------------------------ bench history
def load_bench_history(path):
    """``BENCH_HISTORY.jsonl`` (one bench JSON line per run, oldest first)
    → list of dicts.  Missing file → empty list; corrupt lines skipped."""
    out = []
    if not path or not os.path.exists(path):
        return out
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out
