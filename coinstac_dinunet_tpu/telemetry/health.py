"""Numeric health series for a federated round — the *is the training
healthy* layer on top of the PR-3 Recorder's *where does the time go*.

Every function here is a HOST-SIDE choke point helper: it computes a small
scalar (a norm, a cosine, an effective rank) from values the instrumented
code already holds on the host boundary, records it as a typed ``metric``
JSONL record, and feeds the node's :class:`~.watchdog.Watchdog`.  The
contract with the hot path:

- **Zero overhead when disabled.**  Call sites guard with
  ``if get_active().enabled:`` before computing anything — with
  ``cache['profile']`` off, the cost of a health point is the same one
  attribute lookup as every other telemetry site.
- **Never inside traced functions.**  Norm/cosine math runs op-by-op on
  device and pulls ONE scalar to host per series — always around the
  compiled call, never in it (the ``trace-telemetry`` dinulint rule keeps
  this true statically).

Metric names come from the :class:`~..config.keys.Metric` vocabulary; the
``telemetry-metric-name`` dinulint rule statically rejects any
``record_metric(...)`` call whose name is not in it.
"""
import math

import numpy as np

from ..config.keys import Anomaly, Metric
from .recorder import get_active
from .watchdog import Watchdog

__all__ = [
    "record_metric", "global_norm", "effective_rank", "relative_error",
    "record_grad_health", "record_update_health", "record_val_score",
    "record_site_agreement", "record_compression_health",
]


def record_metric(name, value, site=None, cache=None, recorder=None, **attrs):
    """Record one sample of a health series and run the watchdog over it.

    No-op when telemetry is disabled.  ``cache`` binds the watchdog (skip it
    for record-only series); extra ``attrs`` ride the JSONL record.
    """
    rec = recorder if recorder is not None else get_active()
    if not rec.enabled:
        return
    value = float(value)
    rec.metric(name, value, site=site, **attrs)
    if cache is not None:
        Watchdog(cache, rec).observe(name, value, site=site)


# ---------------------------------------------------------------- numerics
def global_norm(tree_or_leaves):
    """Global L2 norm over a pytree (or list) of arrays, as a host float.
    One device reduction per leaf + one host sync total."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(tree_or_leaves)
    if not leaves:
        return 0.0
    total = sum(
        jnp.sum(jnp.square(jnp.asarray(g, jnp.float32))) for g in leaves
    )
    return float(jnp.sqrt(total))


def relative_error(errors, references):
    """``sqrt(Σ‖err‖² / Σ‖ref‖²)`` over paired leaf lists — the aggregate
    relative reconstruction error of a compressed gradient."""
    import jax.numpy as jnp

    if not errors:
        return 0.0
    num = sum(jnp.sum(jnp.square(jnp.asarray(e, jnp.float32))) for e in errors)
    den = sum(jnp.sum(jnp.square(jnp.asarray(r, jnp.float32))) for r in references)
    return float(jnp.sqrt(num / jnp.maximum(den, 1e-30)))


def effective_rank(factor):
    """Entropy effective rank ``exp(H(σ²/Σσ²))`` of a rank-r factor's
    spectrum, via the tiny r×r Gram matrix (the factor is tall-skinny:
    PowerSGD's Q or rankDAD's B).  1.0 = fully collapsed, r = flat."""
    f = np.asarray(factor, np.float64)
    if f.ndim != 2 or 0 in f.shape:
        return 0.0
    if f.shape[0] < f.shape[1]:
        f = f.T
    gram = f.T @ f  # (r, r)
    if not np.all(np.isfinite(gram)):
        return float("nan")
    s2 = np.linalg.eigvalsh(gram)
    s2 = np.clip(s2, 0.0, None)
    total = float(s2.sum())
    if total <= 0.0:
        return 0.0
    p = s2 / total
    p = p[p > 0]
    return float(math.exp(-np.sum(p * np.log(p))))


# ------------------------------------------------------- choke-point helpers
def record_grad_health(cache, grads, aux=None, recorder=None):
    """Site-side backward round: global gradient norm + its watchdog EMA +
    the round's mean training loss.  Call ONLY under ``rec.enabled``."""
    rec = recorder if recorder is not None else get_active()
    if not rec.enabled:
        return
    norm = global_norm(grads)
    record_metric(Metric.GRAD_NORM, norm, cache=cache, recorder=rec)
    ema = Watchdog(cache, rec).ema(Anomaly.GRAD_EXPLOSION)
    if ema is not None:
        # record-only: the EMA is the explosion detector's own baseline
        record_metric(Metric.GRAD_NORM_EMA, ema, recorder=rec)
    if aux is not None and aux.get("loss") is not None:
        record_metric(
            Metric.TRAIN_LOSS, float(np.asarray(aux["loss"])),
            cache=cache, recorder=rec,
        )


def record_update_health(cache, grads, recorder=None):
    """Applied-update round: global norm of the (averaged) gradient the
    optimizer is about to apply."""
    rec = recorder if recorder is not None else get_active()
    if not rec.enabled:
        return
    record_metric(
        Metric.UPDATE_NORM, global_norm(grads), cache=cache, recorder=rec,
    )


def record_val_score(cache, score, recorder=None):
    """Epoch barrier: the monitored validation metric (stall detection)."""
    rec = recorder if recorder is not None else get_active()
    if not rec.enabled or score is None:
        return
    record_metric(Metric.VAL_SCORE, float(score), cache=cache, recorder=rec)


def record_site_agreement(cache, sites, cosines, weights=None, recorder=None,
                          payload=None):
    """Aggregator-side reduce: per-site cosine-to-mean series (NaN for a
    non-finite site — the attribution the doctor ranks on), the cross-site
    dispersion, and the survivor count.

    ``cosines`` is the (n_sites,) vector from
    :func:`~..parallel.reducer.site_cosines`; ``weights`` the participation
    weights (0 = excluded).  ``payload`` tags which wire payload the series
    came from (grads / powerSGD_P / dad_data ...).
    """
    rec = recorder if recorder is not None else get_active()
    if not rec.enabled:
        return
    cos = np.asarray(cosines, np.float64)
    w = (np.asarray(weights, np.float64) if weights is not None
         else np.ones(len(sites)))
    attrs = {"payload": payload} if payload else {}
    wd = Watchdog(cache, rec)
    finite = []
    for site, c, wi in zip(sites, cos, w):
        rec.metric(Metric.SITE_COSINE, float(c), site=str(site), **attrs)
        wd.observe(Metric.SITE_COSINE, float(c), site=str(site))
        if math.isfinite(float(c)) and wi > 0:
            finite.append(float(c))
    dispersion = float(np.std(finite)) if finite else 0.0
    record_metric(Metric.SITE_DISPERSION, dispersion, cache=cache,
                  recorder=rec, **attrs)
    record_metric(Metric.SURVIVORS, float(len(finite)), cache=cache,
                  recorder=rec, **attrs)


def record_compression_health(cache, rel_error, eff_rank, recorder=None,
                              engine=None):
    """Compression round: relative reconstruction error + effective rank of
    the factorization (spike / rank-collapse detection)."""
    rec = recorder if recorder is not None else get_active()
    if not rec.enabled:
        return
    attrs = {"engine": engine} if engine else {}
    record_metric(Metric.COMPRESSION_ERROR, float(rel_error), cache=cache,
                  recorder=rec, **attrs)
    if eff_rank is not None:
        record_metric(Metric.EFFECTIVE_RANK, float(eff_rank), cache=cache,
                      recorder=rec, **attrs)
