"""Anomaly-triggered deep capture: attach an XLA profile to the postmortem.

The watchdog (:mod:`.watchdog`) tells you *that* a round went wrong; the
profiler (:func:`~..utils.profiling.device_trace`) tells you *what the
device was doing* — but nothing ever triggered it, so postmortems shipped
timelines without profiles.  This module wires the two together:

- **Arming** (:func:`maybe_arm`): when a watchdog detector fires and
  ``cache['capture_on_anomaly']`` covers its kind (``True`` = any; a
  string/list names specific :class:`~..config.keys.Anomaly` kinds), a
  pending-capture marker lands in ``cache['health']`` (JSON-able, rides
  the same fresh-process persistence as the detector state).
- **Capturing** (:func:`captured_round`): the NEXT round's compiled-step
  choke point (``nn/basetrainer.py``, ``MeshEngine._run_fold_loop``) wraps
  itself in ``device_trace`` when a capture is pending.  The profile is
  retained under the node's ``outputDirectory`` (``profile_capture/
  round<N>_<anomaly>/``) and a ``capture:profile`` event links it to the
  triggering anomaly — the doctor lists it in the postmortem.
- **Budget**: ``cache['capture_max_profiles']`` (default 2) bounds
  retained profiles per node per run; anomalies can repeat, disk must not.

A profiler failure (already active, unsupported backend) emits a
``capture:failed`` event and never harms the run.  Zero overhead when
disabled: call sites only consult this module under ``rec.enabled``.
"""
import contextlib
import os

from ..config.keys import Capture
from .recorder import get_active

__all__ = ["maybe_arm", "captured_round", "capture_wanted"]

#: subdirectory of the node's outputDirectory holding retained profiles
CAPTURE_DIR = "profile_capture"

_DEFAULT_MAX_PROFILES = 2


def capture_wanted(cache, anomaly):
    """True when ``cache['capture_on_anomaly']`` covers ``anomaly``."""
    want = (cache or {}).get(Capture.ON_ANOMALY)
    if not want:
        return False
    if want is True:
        return True
    if isinstance(want, str):
        return want == str(anomaly)
    try:
        return str(anomaly) in [str(w) for w in want]
    except TypeError:
        return False


def maybe_arm(cache, anomaly, recorder=None):
    """Arm a deep capture for the next round if configuration asks for it.

    Called by the watchdog at anomaly-emission time.  First trigger wins
    (an armed capture is not re-targeted by later anomalies in the same
    round); the budget check happens here so an exhausted node stops
    arming instead of repeatedly skipping at capture time.
    """
    if cache is None or not capture_wanted(cache, anomaly):
        return False
    health = cache.setdefault("health", {})
    if health.get("capture_pending"):
        return False
    budget = int(cache.get(Capture.MAX_PROFILES, _DEFAULT_MAX_PROFILES))
    if int(health.get("captures_taken", 0)) >= budget:
        return False
    health["capture_pending"] = {
        "anomaly": str(anomaly),
        "armed_round": int(cache.get("telemetry_round", 0) or 0),
    }
    rec = recorder if recorder is not None else get_active()
    rec.event("capture:armed", cat="capture", anomaly=str(anomaly))
    return True


@contextlib.contextmanager
def _profiled(cache, out_dir, rec, pending):
    anomaly = pending.get("anomaly", "anomaly")
    rnd = int(cache.get("telemetry_round", 0) or 0)
    path = os.path.join(
        str(out_dir), CAPTURE_DIR, f"round{rnd}_{_sanitize(anomaly)}"
    )
    health = cache.setdefault("health", {})
    from ..utils.profiling import device_trace

    try:
        trace = device_trace(path)
        trace.__enter__()
    except Exception as exc:  # noqa: BLE001 — capture must never kill a run
        rec.event("capture:failed", cat="capture", anomaly=anomaly,
                  error=f"{type(exc).__name__}: {exc}"[:300])
        yield None
        return
    try:
        yield path
    finally:
        try:
            trace.__exit__(None, None, None)
        except Exception as exc:  # noqa: BLE001
            rec.event("capture:failed", cat="capture", anomaly=anomaly,
                      error=f"{type(exc).__name__}: {exc}"[:300])
        else:
            health["captures_taken"] = int(health.get("captures_taken", 0)) + 1
            rec.event(
                "capture:profile", cat="capture", anomaly=anomaly,
                path=path, armed_round=pending.get("armed_round"),
            )


def captured_round(cache, out_dir, recorder=None):
    """Context manager for a round's compiled step: a no-op unless a
    capture is pending (one dict lookup), else the XLA profiler wraps the
    block and the profile is retained + event-linked.  The pending marker
    is consumed either way — a failed capture does not retry forever, and
    a node with no output directory records a ``capture:failed`` instead
    of wedging the armed marker (which would block all future arming)."""
    pending = (cache or {}).get("health", {}).get("capture_pending")
    if not pending:
        return contextlib.nullcontext()
    cache["health"].pop("capture_pending", None)
    rec = recorder if recorder is not None else get_active()
    if not out_dir:
        rec.event(
            "capture:failed", cat="capture",
            anomaly=pending.get("anomaly"),
            error="no outputDirectory to retain the profile under",
        )
        return contextlib.nullcontext()
    return _profiled(cache, out_dir, rec, pending)


def _sanitize(name):
    return "".join(c if (c.isalnum() or c in "-_") else "_" for c in str(name))
