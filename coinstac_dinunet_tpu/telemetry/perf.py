"""Perf flight recorder — XLA cost/MFU accounting and device-memory series.

The ROADMAP's MFU arc ("regressions are verdicts, not vibes") needs
utilization measured continuously per round, not benchmarked occasionally
(Podracer, arXiv:2104.06272): ``bench.py`` knew the flagship's FLOPs but
only at bench time, nothing recorded device memory at all, and a throughput
regression between bench runs was invisible.  This module closes that gap
as a layer on the PR-3 :class:`~.recorder.Recorder`:

- :func:`step_cost` / :func:`step_flops` — the SHARED XLA cost-analysis
  helper (extracted from ``bench.py::_step_flops``): FLOPs + bytes accessed
  of a staged computation, with a TYPED failure reason
  (:data:`COST_UNAVAILABLE`, ``lower_failed: ...``) instead of a silent
  ``None``.  Prefers ``Lowered.cost_analysis()`` (no compile) and only
  falls back to compiling when the caller allows it.
- :func:`record_jit_cost` — called at every trainer ``jit_build``: records
  a ``jit_cost`` event (flops, bytes accessed) per compiled executable and
  stashes the FLOPs under ``cache['_perf_flops']`` (leading underscore:
  never part of the compiled-bucket key) so per-round utilization can be
  computed from wall time alone.
- :func:`record_step_perf` — per-round throughput series:
  ``samples_per_sec``, ``achieved_tflops``, ``mfu`` vs the per-backend
  peak table (:data:`PEAK_TFLOPS_BY_DEVICE_KIND`, ``cache['peak_tflops']``
  override), plus a JSON-able rollup under ``cache['health']['perf']``
  that rides the existing ``HEALTH`` wire keys — the aggregator sees
  federation-wide utilization without a new wire field.
- :func:`sample_device_memory` — per-round HBM in-use/peak/limit from
  ``device.memory_stats()`` where the backend provides it, with a
  live-buffer census fallback (``jax.live_arrays()``) elsewhere; feeds the
  watchdog's memory-leak and near-limit-pressure detectors.

Zero overhead when disabled: every public helper early-returns on the
null recorder, so a disabled call site costs the usual one attribute
lookup + one no-op call.  Host-side only, like all telemetry — never
inside a traced function (the ``trace-telemetry`` dinulint rule applies).
"""
import time

from ..config.keys import Metric, Perf
from .health import record_metric
from .recorder import get_active

__all__ = [
    "COST_UNAVAILABLE", "PEAK_TFLOPS_BY_DEVICE_KIND", "peak_flops_for",
    "step_cost", "step_flops", "record_jit_cost", "record_step_perf",
    "sample_device_memory",
]

#: typed reason when XLA reports no cost analysis for a computation
COST_UNAVAILABLE = "cost_analysis_unavailable"

#: bf16 peak FLOPS (TFLOPS, dense, no sparsity) by device-kind prefix —
#: the MFU denominator.  Single source of truth shared with ``bench.py``.
#: No CPU entry on purpose: a made-up CPU peak would be a fake MFU — set
#: ``cache['peak_tflops']`` explicitly for CPU/unknown backends.
PEAK_TFLOPS_BY_DEVICE_KIND = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,
    "TPU v5e": 197.0,
    "TPU v5": 459.0,
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,
}

#: flops-registry cache key (leading underscore: excluded from the shared
#: compiled-step bucket key by the basetrainer's underscore rule)
FLOPS_CACHE_KEY = "_perf_flops"


def peak_flops_for(device_kind):
    """Peak FLOPS/sec for a device kind from the table, or None."""
    kind = str(device_kind)
    for prefix, tflops in PEAK_TFLOPS_BY_DEVICE_KIND.items():
        if kind.startswith(prefix):
            return tflops * 1e12
    return None


def resolve_peak_flops(cache=None):
    """The MFU denominator in FLOPS/sec: ``cache['peak_tflops']`` wins,
    else the device-kind table, else None (MFU not recordable)."""
    if cache:
        override = cache.get(Perf.PEAK_TFLOPS)
        if override:
            return float(override) * 1e12
    import jax

    return peak_flops_for(jax.devices()[0].device_kind)


# ----------------------------------------------------------- cost analysis
def _cost_dict(cost):
    """Normalize XLA's cost analysis (dict, or list of per-executable
    dicts) to one {'flops', 'bytes_accessed'} dict, or None."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not isinstance(cost, dict) or "flops" not in cost:
        return None
    flops = float(cost["flops"])
    if flops < 0:  # XLA reports -1 for "unknown"
        return None
    return {
        "flops": flops,
        "bytes_accessed": float(cost.get("bytes accessed", 0.0) or 0.0),
    }


def step_cost(staged, *args, allow_compile=False):
    """XLA cost analysis of a jit-staged callable at ``args``.

    Returns ``(cost, reason)``: ``cost`` is ``{'flops', 'bytes_accessed'}``
    or None, in which case ``reason`` is the typed failure
    (``lower_failed: ...`` or :data:`COST_UNAVAILABLE`).  Lowering traces
    the function once (cheap); ``allow_compile=True`` additionally permits
    a throwaway compile when the lowered module reports no cost — only for
    callers that can afford a duplicate compile (``bench.py``).
    """
    try:
        lowered = staged.lower(*args)
    except Exception as exc:  # noqa: BLE001 — reason is the contract
        return None, f"lower_failed: {exc}"[:300]
    cost = None
    try:
        cost = _cost_dict(lowered.cost_analysis())
    except Exception:  # noqa: BLE001 — backend may not implement it
        cost = None
    if cost is None and allow_compile:
        try:
            cost = _cost_dict(lowered.compile().cost_analysis())
        except Exception as exc:  # noqa: BLE001
            return None, f"compile_failed: {exc}"[:300]
    if cost is None:
        return None, COST_UNAVAILABLE
    return cost, None


def step_flops(fn, *args, allow_compile=True):
    """Model FLOPs of one step — the shared successor to
    ``bench.py::_step_flops``.  ``fn`` may be a plain callable (it is
    jit-wrapped here) or an already-staged jit function.  Returns
    ``(flops, reason)``: exactly one of the two is None.
    """
    import jax

    staged = fn if hasattr(fn, "lower") else jax.jit(fn)
    cost, reason = step_cost(staged, *args, allow_compile=allow_compile)
    if cost is None:
        return None, reason
    return cost["flops"], None


def record_jit_cost(cache, key, staged, args, recorder=None):
    """Flight-record one compiled executable's cost at ``jit_build`` time.

    Emits a ``jit_cost`` event (flops + bytes accessed, ``cat=compile`` —
    the compile DURATION arrives separately via the ``jax.monitoring``
    bridge) and stashes the FLOPs under ``cache['_perf_flops'][key]`` for
    :func:`record_step_perf`.  An unavailable cost is a
    ``perf:cost_unavailable`` event with the typed reason — visible in the
    trace, never silent.  Returns the flops or None.
    """
    rec = recorder if recorder is not None else get_active()
    if not rec.enabled:
        return None
    _emit_backend_event(cache, rec)
    cost, reason = step_cost(staged, *args, allow_compile=False)
    if cost is None:
        rec.event("perf:cost_unavailable", cat="perf", fn=str(key),
                  reason=reason)
        return None
    rec.event(
        "jit_cost", cat="compile", fn=str(key), flops=cost["flops"],
        bytes_accessed=cost["bytes_accessed"],
    )
    if cache is not None:
        cache.setdefault(FLOPS_CACHE_KEY, {})[str(key)] = cost["flops"]
    return cost["flops"]


def _emit_backend_event(cache, rec):
    """One ``perf:backend`` event per recorder: device kind, peak TFLOPS
    (+ its source) and the structural MFU ceiling — the roofline constants
    the doctor reads back out of the merged timeline (it keeps the first).
    Per-recorder, not per-process: a later run in the same process must
    still get one into ITS timeline, and jit builds are rare enough
    (shared compiled bucket) that the duplication is a few lines."""
    if getattr(rec, "_perf_backend_emitted", False):
        return
    try:
        rec._perf_backend_emitted = True
    except AttributeError:  # a slotted custom sink: emit every time
        pass
    import jax

    kind = jax.devices()[0].device_kind
    override = (cache or {}).get(Perf.PEAK_TFLOPS)
    peak = resolve_peak_flops(cache)
    attrs = {"device_kind": str(kind), "devices": jax.device_count()}
    if peak:
        attrs["peak_tflops"] = round(peak / 1e12, 3)
        attrs["peak_source"] = "cache" if override else "table"
    ceiling = (cache or {}).get(Perf.MFU_CEILING)
    if ceiling:
        attrs["ceiling_mfu"] = float(ceiling)
    rec.event("perf:backend", cat="perf", **attrs)


# ------------------------------------------------------- per-round metrics
def record_step_perf(cache, key, dur_s, samples, recorder=None):
    """Per-round utilization series from one compiled step's wall time.

    ``key`` names the executable (the ``_compiled`` bucket key whose
    ``jit_cost`` stashed the FLOPs), ``dur_s`` the host-fenced wall
    seconds, ``samples`` the padded sample count the step consumed.
    Records ``samples_per_sec`` always, ``achieved_tflops`` when the
    FLOPs are known, ``mfu`` when the peak is too — and mirrors the
    latest values into ``cache['health']['perf']`` so they ride the
    ``HEALTH`` wire rollup federation-wide.
    """
    rec = recorder if recorder is not None else get_active()
    if not rec.enabled or not dur_s or dur_s <= 0:
        return
    sps = float(samples) / dur_s
    record_metric(Metric.SAMPLES_PER_SEC, sps, recorder=rec, fn=str(key))
    roll = {"samples_per_sec": round(sps, 2), "step_s": round(dur_s, 6)}
    flops = (cache.get(FLOPS_CACHE_KEY) or {}).get(str(key)) if cache else None
    if flops:
        tflops = flops / dur_s / 1e12
        record_metric(Metric.ACHIEVED_TFLOPS, tflops, recorder=rec,
                      fn=str(key))
        roll["achieved_tflops"] = round(tflops, 4)
        peak = resolve_peak_flops(cache)
        if peak:
            mfu = tflops * 1e12 / peak
            record_metric(Metric.MFU, mfu, recorder=rec, fn=str(key))
            roll["mfu"] = round(mfu, 4)
    if cache is not None:
        health = cache.setdefault("health", {})
        health.setdefault("perf", {}).update(roll)


def sample_device_memory(cache, recorder=None, leak_watch=True):
    """One device-memory sample: HBM in-use/peak/limit metric series plus
    the ``hbm_utilization`` fraction when a limit is known, feeding the
    watchdog's leak and pressure detectors.

    Source is ``device.memory_stats()`` where the backend implements it
    (TPU/GPU); otherwise a live-buffer census (``jax.live_arrays()``) with
    an optional ``cache['memory_limit_bytes']`` budget.  Returns the
    in-use byte count or None.

    ``leak_watch=False`` records the in-use sample without feeding the
    leak detector — for out-of-cadence samples (the validation phase),
    whose legitimate allocation spike would otherwise reset the detector's
    growth streak and mask a genuine training-loop leak.  The pressure
    detector still sees the utilization either way: an eval spike near the
    limit is a real OOM risk.
    """
    rec = recorder if recorder is not None else get_active()
    if not rec.enabled:
        return None
    import jax

    dev = jax.local_devices()[0]
    stats = None
    try:
        stats = dev.memory_stats()
    except Exception:  # noqa: BLE001 — optional backend API
        stats = None
    if stats:
        in_use = float(stats.get("bytes_in_use", 0) or 0)
        peak = stats.get("peak_bytes_in_use")
        limit = stats.get("bytes_limit")
        source = "memory_stats"
    else:
        try:
            in_use = float(sum(a.nbytes for a in jax.live_arrays()))
        except Exception:  # noqa: BLE001 — census is best-effort too
            return None
        peak, limit = None, None
        source = "live_buffer_census"
    if limit is None and cache:
        limit = cache.get(Perf.MEMORY_LIMIT)
    # leak detection watches the in-use series (cache-bound → watchdog)
    record_metric(Metric.HBM_IN_USE, in_use,
                  cache=(cache if leak_watch else None), recorder=rec,
                  source=source)
    if peak is not None:
        record_metric(Metric.HBM_PEAK, float(peak), recorder=rec)
    roll = {"hbm_in_use_bytes": in_use, "memory_source": source}
    if peak is not None:
        roll["hbm_peak_bytes"] = float(peak)
    if limit:
        record_metric(Metric.HBM_LIMIT, float(limit), recorder=rec)
        util = in_use / float(limit)
        record_metric(Metric.HBM_UTILIZATION, util, cache=cache,
                      recorder=rec)
        roll["hbm_limit_bytes"] = float(limit)
        roll["hbm_utilization"] = round(util, 4)
    if cache is not None:
        health = cache.setdefault("health", {})
        health.setdefault("perf", {}).update(roll)
    return in_use


class StepTimer:
    """Tiny helper for the per-round timing pattern: construct when the
    recorder is enabled, ``done(...)`` after the host fence.  Exists so
    choke points stay one line each."""

    __slots__ = ("t0",)

    def __init__(self):
        self.t0 = time.perf_counter()

    def done(self, cache, key, samples, recorder=None):
        record_step_perf(
            cache, key, time.perf_counter() - self.t0, samples,
            recorder=recorder,
        )
