"""Live federation ops plane: incremental JSONL tailing + in-flight state.

Everything else in :mod:`coinstac_dinunet_tpu.telemetry` is post-hoc — the
collector and the doctor re-read whole files after the run is over, so by
the time a postmortem names a wedged site a 1,000-site federation has been
burning chips for the entire window.  This module watches the SAME per-node
``telemetry.<node>.jsonl`` records *while the run is alive*:

- :func:`~.collect.read_jsonl_segment` (shared with the collector) reads
  complete lines only — an unterminated trailing line from a dying writer
  is counted, never parsed, never consumed.
- :class:`Tailer` turns that into an incremental, rotation/crash-safe
  poller: per-file byte cursors (optionally persisted to a sidecar JSON so
  a restarted tailer resumes without replaying), rotation/truncation
  detection via inode + size regression, and partial-tail carry-over (the
  torn line is re-examined next poll, when the writer may have finished it).
- :class:`LiveState` folds the event vocabulary into a federation-wide
  picture — per-site current round/phase/last heartbeat, rounds/sec and
  MFU EMAs, wire-byte rates, anomaly/chaos/retry counters, dead and
  quarantined sites — and fires **edge-triggered in-flight verdicts**
  (:class:`~..config.keys.Live` kinds) with the doctor's
  ``severity``/``cause``/``evidence`` shape, so the live board and the
  postmortem speak one language.
- :func:`render_board` renders the refreshing ``telemetry watch`` terminal
  status board (:mod:`.__main__`); :mod:`.serve` exports the same snapshot
  as Prometheus ``/metrics`` + ``/healthz``.

The heartbeat feed: both engines emit a lightweight ``engine:heartbeat``
event per node invocation (``engine.py`` site/remote loops; the
site-vectorized engine once per round with the alive count), and the
Recorder's wall-clock auto-flush (default 5 s) gets them to disk
mid-invocation.  Liveness is judged on record ``t0`` wall-clock stamps —
comparable across node processes on one host, the same contract the merged
timeline already relies on.

The persistent engine daemon (:mod:`~..federation.daemon`) plugs in
natively: its warm workers pulse the same ``engine:heartbeat`` per
completed invocation, and the supervisor's ``worker:restart`` events
surface as federation + per-site ``worker_restarts`` counters on the
board, ``/metrics`` and ``/healthz`` — a long-lived worker is exactly the
thing you watch with heartbeats, not autopsies.  Staleness-bounded async
rounds (ROADMAP item 2) plug into the same substrate.
"""
import json
import os
import statistics
import threading
import time
from collections import deque

from ..config.keys import Daemon, Live, Metric
from .collect import find_event_files, read_jsonl_segment

_EMA_DECAY = 0.8
_ROUND_WINDOW = 32      # rolling round-duration window (median basis)
_ROUND_MIN_SAMPLES = 5  # rounds before the outlier rule can judge
_MFU_MIN_SAMPLES = 5    # MFU samples before the collapse rule can judge
_WIRE_RATE_WINDOW_S = 15.0

#: verdict kind -> doctor severity (the shared vocabulary)
VERDICT_SEVERITY = {
    Live.VERDICT_SILENCE: "critical",
    Live.VERDICT_ROUND_OUTLIER: "warning",
    Live.VERDICT_MFU_COLLAPSE: "warning",
    Live.VERDICT_RETRY_STORM: "warning",
    Live.VERDICT_STALENESS: "warning",
    Live.VERDICT_PIPELINE: "warning",
    Live.VERDICT_QUORUM_EROSION: "warning",
}


# ------------------------------------------------------------------- tailer
class Tailer:
    """Incremental poller over a run directory's telemetry JSONL files.

    ``root`` is the run directory (re-scanned every poll, so lanes that
    appear mid-run — a fresh site's first flush — are picked up) or an
    explicit list of paths.  ``cursor_path`` (optional) persists the
    per-file byte cursors to a sidecar JSON after every poll, so a
    restarted tailer resumes where it left off instead of replaying the
    whole run.

    Crash/rotation safety, in order of the checks :meth:`poll` makes per
    file: an inode change or a size BELOW the cursor means the file was
    rotated/truncated (cursor resets to 0); reads consume complete lines
    only (``read_jsonl_segment``), so a torn trailing line from a dying —
    or merely mid-append — writer is left for the next poll; undecodable
    complete lines are counted on :attr:`truncated_lines` and skipped.
    """

    def __init__(self, root, cursor_path=None):
        self.root = root
        self.cursor_path = str(cursor_path) if cursor_path else None
        self._cursors = {}  # path -> {"offset": int, "ino": int}
        self.truncated_lines = 0
        self.polls = 0
        if self.cursor_path and os.path.exists(self.cursor_path):
            try:
                with open(self.cursor_path, "r", encoding="utf-8") as f:
                    saved = json.load(f)
                if isinstance(saved, dict):
                    self._cursors = {
                        str(p): {"offset": int(c.get("offset", 0)),
                                 "ino": int(c.get("ino", 0))}
                        for p, c in saved.get("files", {}).items()
                        if isinstance(c, dict)
                    }
                    self.truncated_lines = int(saved.get("truncated_lines", 0))
            except (OSError, ValueError):
                self._cursors = {}  # corrupt sidecar: start from scratch

    def _discover(self):
        if isinstance(self.root, (str, os.PathLike)):
            return find_event_files(str(self.root))
        return [str(p) for p in self.root]

    def poll(self):
        """All records appended since the previous poll, wall-clock ordered
        (each stamped with its lane's node name)."""
        from .collect import _node_from_filename

        records = []
        for path in self._discover():
            try:
                st = os.stat(path)
            except OSError:
                continue
            cur = self._cursors.get(path)
            offset = cur["offset"] if cur else 0
            if cur and (st.st_ino != cur["ino"] or st.st_size < offset):
                offset = 0  # rotated or truncated underneath us: re-read
            if st.st_size <= offset:
                self._cursors[path] = {"offset": offset, "ino": st.st_ino}
                continue
            try:
                recs, new_offset, bad, _partial = read_jsonl_segment(
                    path, offset
                )
            except OSError:
                continue
            self.truncated_lines += bad
            node = _node_from_filename(path)
            for rec in recs:
                rec.setdefault("node", node)
            records.extend(recs)
            self._cursors[path] = {"offset": new_offset, "ino": st.st_ino}
        records.sort(key=lambda r: (float(r.get("t0", 0.0)),
                                    r.get("node", "")))
        self.polls += 1
        self._save_cursors()
        return records

    def _save_cursors(self):
        """Atomic sidecar commit (tmp + replace) — a tailer killed
        mid-write must never leave a half-written cursor file that a
        restart would trust."""
        if not self.cursor_path:
            return
        try:
            os.makedirs(os.path.dirname(self.cursor_path) or ".",
                        exist_ok=True)
            tmp = self.cursor_path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"files": self._cursors,
                           "truncated_lines": self.truncated_lines}, f)
            os.replace(tmp, self.cursor_path)
        except OSError:
            pass  # the tailer must never fail the watch


# --------------------------------------------------------------- live state
def _site_entry():
    return {"round": 0, "phase": None, "epoch": None, "last_seen": None,
            "last_heartbeat": None, "anomalies": 0, "dead": False,
            "died_retries_exhausted": False, "quarantined": False,
            "worker_restarts": 0, "staleness": None, "run_ahead": None}


class LiveState:
    """Federation-wide in-flight state machine over the merged event stream.

    Feed it batches from a :class:`Tailer` (:meth:`ingest`), then
    :meth:`check` evaluates the edge-triggered liveness rules and returns
    any NEWLY fired verdicts; :meth:`snapshot` is the JSON-able view the
    board, ``/metrics`` and ``/healthz`` all render from.

    Thresholds come from the :class:`~..config.keys.Live` cache-key knobs
    (:meth:`from_cache`) or constructor arguments; liveness is judged on
    record ``t0`` stamps against ``now`` (injectable for tests).
    """

    def __init__(self, silence_after=30.0, round_outlier=4.0,
                 mfu_collapse=0.3, retry_storm=10, retry_window=30.0,
                 quorum_headroom=1):
        self.silence_after = float(silence_after)
        self.round_outlier = float(round_outlier)
        self.mfu_collapse = float(mfu_collapse)
        self.retry_storm = int(retry_storm)
        self.retry_window = float(retry_window)
        self.quorum_headroom = int(quorum_headroom)

        self.sites = {}
        self.round = 0
        self.rounds_done = 0
        self.round_durs = deque(maxlen=_ROUND_WINDOW)
        self.rounds_per_sec = None  # EMA of 1/engine:round duration
        self.mfu_last = None
        self.mfu_ema = None
        self.mfu_n = 0
        self.samples_per_sec = None
        self.wire = {"save_bytes": 0, "saves": 0, "load_bytes": 0, "loads": 0}
        self._wire_window = deque(maxlen=4096)  # (t0, op, bytes)
        self.anomalies = 0
        self.anomalies_by_kind = {}
        self.chaos = 0
        self.worker_restarts = 0
        # async round engine: the staleness bound k (learned from the
        # engine's async:* events) and per-site staleness gauges — the
        # staleness_exceeded verdict judges sites against k
        self.staleness_k = 0
        self.stale_standins = 0
        # run-ahead pipeline (Federation.RUN_AHEAD): the configured depth
        # d (learned from the engine's async:run_ahead/pipeline:* events),
        # per-site run-ahead gauges, the reducer-concurrency counter the
        # decoupling win is measured by, stall counts, and the
        # edge-trigger latches of the pipeline_stall verdict
        self.run_ahead_d = 0
        self.pipeline_stalls = 0
        self.reduce_concurrent_s = 0.0
        self._pipeline_breach = None
        self._pipeline_flowed = False
        # daemon frame-pipe byte counters (daemon:frame events) — the
        # delta-cache win is the tx/rx trend across a run
        self.frame_bytes = {"tx": 0, "rx": 0, "frames": 0}
        # elastic membership (ISSUE 15): the roster as the membership:*
        # event stream reports it — current epoch, live member count,
        # per-kind transition counters, joiners whose first record has
        # not shown yet, gracefully retired sites, and the quorum need
        # the aggregator's membership events carry when a policy is
        # configured (the quorum_erosion verdict's evidence)
        self.roster_epoch = 0
        self.roster_members = None
        self.membership_changes = {}
        self.joining = set()
        self.left = set()
        self.quorum_need = None
        # event-name counts (bounded by the event vocabulary): the watch
        # CLI's --assert-event gating reads this, it stays out of the
        # snapshot to keep /healthz stable
        self.event_counts = {}
        self.wire_retries = 0
        self._retry_times = deque(maxlen=4096)
        self.corruption_recovered = 0
        self.dead = set()
        self.quarantined = set()
        self.truncated_lines = 0
        self.last_event_t = None
        self.verdicts = []
        self._armed = {}  # rule key -> active verdict (edge-trigger state)
        # the OpsServer scrapes snapshot() from handler threads while the
        # watch loop ingests — iterating the deques/site dict unlocked
        # would intermittently raise "mutated during iteration" and flap
        # the scrape target down with a 500
        self._lock = threading.RLock()

    @classmethod
    def from_cache(cls, cache):
        """Thresholds from the :class:`~..config.keys.Live` cache keys —
        the embedding surface for engines that watch themselves (the
        daemon engine's workers are monitored through exactly these
        records via ``telemetry watch``)."""
        cache = cache or {}
        return cls(
            silence_after=cache.get(Live.SILENCE_AFTER, 30.0),
            round_outlier=cache.get(Live.ROUND_OUTLIER, 4.0),
            mfu_collapse=cache.get(Live.MFU_COLLAPSE, 0.3),
            retry_storm=cache.get(Live.RETRY_STORM, 10),
            retry_window=cache.get(Live.RETRY_WINDOW, 30.0),
            quorum_headroom=cache.get(Live.QUORUM_HEADROOM, 1),
        )

    def site(self, name):
        return self.sites.setdefault(str(name), _site_entry())

    # --------------------------------------------------------------- ingest
    def ingest(self, records):
        """Fold a batch of merged records (the existing event vocabulary —
        nothing here requires a new record kind beyond the heartbeat)."""
        with self._lock:
            self._ingest_locked(records)

    def _ingest_locked(self, records):
        for rec in records:
            t0 = float(rec.get("t0", 0.0) or 0.0)
            # liveness is judged on when the record's section ENDED: a span
            # stamps t0 at its start, so a long compile/epoch span would
            # otherwise make a perfectly live site look stale
            t_live = t0 + float(rec.get("dur", 0.0) or 0.0)
            if t_live:
                self.last_event_t = (t_live if self.last_event_t is None
                                     else max(self.last_event_t, t_live))
            node = str(rec.get("node", "unknown"))
            rnd = rec.get("round")
            if node not in ("engine", "remote", "unknown"):
                s = self.site(node)
                if s["last_seen"] is None or t_live > s["last_seen"]:
                    s["last_seen"] = t_live
                if rnd is not None:
                    s["round"] = max(s["round"], int(rnd))
                if rec.get("phase") is not None:
                    s["phase"] = str(rec["phase"])
                if rec.get("epoch") is not None:
                    s["epoch"] = int(rec["epoch"])
            if rnd is not None:
                self.round = max(self.round, int(rnd))
            kind = rec.get("kind")
            if kind == "event":
                self._ingest_event(rec, t0)
            elif kind == "span":
                if rec.get("name") == "engine:round":
                    self._ingest_round(rec)
            elif kind == "metric":
                self._ingest_metric(rec)
            elif kind == "wire":
                op = "save" if rec.get("op") == "save" else "load"
                nbytes = int(rec.get("bytes", 0) or 0)
                self.wire[f"{op}_bytes"] += nbytes
                self.wire[f"{op}s"] += 1
                self._wire_window.append((t0, op, nbytes))

    def _ingest_event(self, rec, t0):
        name = rec.get("name", "")
        site = rec.get("site")
        self.event_counts[name] = self.event_counts.get(name, 0) + 1
        if name == Live.HEARTBEAT:
            # the aggregator's pulse ("remote") feeds federation liveness
            # (last_event_t) but must NOT become a per-site row: the
            # doctor's per-site view has no remote entry, and the
            # always-invoked-last aggregator would be a standing false
            # candidate for the site silence verdict
            if site is not None and str(site) != "remote":
                s = self.site(site)
                # a joiner's first own record ends its joining grace
                self.joining.discard(str(site))
                if s["last_heartbeat"] is None or t0 > s["last_heartbeat"]:
                    s["last_heartbeat"] = t0
                if s["last_seen"] is None or t0 > s["last_seen"]:
                    s["last_seen"] = t0
                # the engine-lane heartbeat carries the round it pulsed in —
                # for fresh-process sites whose own lanes flush late, this
                # is the board's freshest per-site progress signal
                if rec.get("round") is not None:
                    s["round"] = max(s["round"], int(rec["round"]))
        elif name.startswith("anomaly:"):
            self.anomalies += 1
            kind = name.split(":", 1)[1]
            self.anomalies_by_kind[kind] = (
                self.anomalies_by_kind.get(kind, 0) + 1
            )
            if site is not None:
                self.site(site)["anomalies"] += 1
        elif name == "chaos:inject":
            self.chaos += 1
        elif name in ("async:stale", "async:staleness_exceeded"):
            # the async engine delivered a stand-in for (or was forced to
            # block on) a straggler: per-site staleness gauge + the bound
            # k the staleness_exceeded verdict judges against
            try:
                self.staleness_k = max(self.staleness_k,
                                       int(rec.get("k", 0) or 0))
            except (TypeError, ValueError):
                pass
            if name == "async:stale":
                self.stale_standins += 1
            if site is not None:
                try:
                    lag = int(rec.get("lag", 0))
                except (TypeError, ValueError):
                    lag = 0
                s = self.site(site)
                s["staleness"] = lag
                if name == "async:staleness_exceeded":
                    # latch the breach: the engine blocks right after this
                    # event and the post-block fresh delivery resets the
                    # gauge, usually inside the SAME flush batch — without
                    # the latch check() would never see the bad state
                    s["staleness_breach"] = max(
                        lag, s.get("staleness_breach") or 0
                    )
        elif name == "async:run_ahead":
            # a site was re-submitted ahead of the un-harvested broadcast:
            # per-site depth gauge + the configured horizon d, and a
            # flowing-pipeline signal that re-arms the stall verdict
            try:
                self.run_ahead_d = max(self.run_ahead_d,
                                       int(rec.get("d", 0) or 0))
            except (TypeError, ValueError):
                pass
            if site is not None:
                try:
                    self.site(site)["run_ahead"] = int(rec.get("depth", 0))
                except (TypeError, ValueError):
                    pass
            self._pipeline_flowed = True
        elif name == "pipeline:stall":
            # the reducer worker fell behind the run-ahead horizon: the
            # engine blocked on the oldest in-flight reduce.  Latched —
            # the very next harvest usually clears the gauge inside the
            # same flush batch, and check() must still see the breach.
            self.pipeline_stalls += 1
            try:
                self.run_ahead_d = max(self.run_ahead_d,
                                       int(rec.get("d", 0) or 0))
            except (TypeError, ValueError):
                pass
            self._pipeline_breach = {
                "site": site,
                "reduce_round": rec.get("reduce_round"),
                "waited_s": rec.get("waited_s"),
                "d": rec.get("d"),
            }
        elif name == "pipeline:reduce_concurrent":
            # seconds the reduce+relay ran while site invocations were in
            # flight — the decoupling the pipeline exists to create
            try:
                self.reduce_concurrent_s += float(rec.get("secs", 0) or 0)
            except (TypeError, ValueError):
                pass
            self._pipeline_flowed = True
        elif name == "daemon:frame":
            try:
                self.frame_bytes["tx"] += int(rec.get("tx_bytes", 0) or 0)
                self.frame_bytes["rx"] += int(rec.get("rx_bytes", 0) or 0)
                self.frame_bytes["frames"] += 1
            except (TypeError, ValueError):
                pass
        elif name == Daemon.EVENT_RESTART:
            # the daemon engine replaced a dead/wedged worker — the site
            # SURVIVED (supervision, not quorum), but the board/metrics
            # must show the churn, per site
            self.worker_restarts += 1
            if site is not None and str(site) != "remote":
                self.site(site)["worker_restarts"] += 1
        elif name.startswith("membership:"):
            # elastic-membership roster transitions (ISSUE 15,
            # federation/membership.py + the engines' churn hooks): the
            # roster line, the membership_changes_total{kind=} exports
            # and the quorum_erosion verdict all key off these
            kind = name.split(":", 1)[1]
            self.membership_changes[kind] = (
                self.membership_changes.get(kind, 0) + 1
            )
            try:
                self.roster_epoch = max(self.roster_epoch,
                                        int(rec.get("epoch", 0) or 0))
            except (TypeError, ValueError):
                pass
            if rec.get("members") is not None:
                try:
                    self.roster_members = int(rec["members"])
                except (TypeError, ValueError):
                    pass
            if rec.get("quorum_need") is not None:
                try:
                    self.quorum_need = int(rec["quorum_need"])
                except (TypeError, ValueError):
                    pass
            if site is not None and kind in ("join", "rejoin", "leave"):
                site = str(site)
                if kind == "leave":
                    self.left.add(site)
                    self.joining.discard(site)
                else:
                    # a (re-)admission heals every prior exclusion: the
                    # fresh incarnation is not the dead/left one
                    self.left.discard(site)
                    self.dead.discard(site)
                    self.joining.add(site)
                    if site in self.sites:
                        self.sites[site]["dead"] = False
        elif name == "wire:retry":
            self.wire_retries += 1
            self._retry_times.append(t0)
        elif name == "wire:corruption_recovered":
            self.corruption_recovered += 1
        elif name == "site_died" and site is not None:
            self.dead.add(str(site))
            s = self.site(site)
            s["dead"] = True
            s["died_retries_exhausted"] = bool(rec.get("retries_exhausted"))
        elif name == "quarantine" and site is not None:
            self.quarantined.add(str(site))
            self.site(site)["quarantined"] = True

    def _ingest_round(self, rec):
        dur = float(rec.get("dur", 0.0) or 0.0)
        if dur <= 0:
            return
        self.rounds_done += 1
        self.round_durs.append(dur)
        rps = 1.0 / dur
        self.rounds_per_sec = (
            rps if self.rounds_per_sec is None
            else _EMA_DECAY * self.rounds_per_sec + (1 - _EMA_DECAY) * rps
        )

    def _ingest_metric(self, rec):
        name = rec.get("name")
        try:
            v = float(rec.get("value"))
        except (TypeError, ValueError):
            return
        if v != v:  # NaN samples belong to the watchdog, not the board
            return
        if name == Metric.MFU:
            self.mfu_last = v
            self.mfu_n += 1
            if self.mfu_ema is None:
                self.mfu_ema = v
            elif v >= self.mfu_collapse * self.mfu_ema:
                # a collapsed sample must not drag the EMA down to itself —
                # freeze it so a sustained collapse stays visible (the
                # watchdog's EMA detectors make the same choice)
                self.mfu_ema = _EMA_DECAY * self.mfu_ema + (1 - _EMA_DECAY) * v
        elif name == Metric.SAMPLES_PER_SEC:
            self.samples_per_sec = v
        elif name == Metric.SITE_STALENESS and rec.get("site") is not None:
            # both the engine (per delivery/stand-in) and the aggregator's
            # window check record the series — latest sample wins
            self.site(rec["site"])["staleness"] = int(v)
        elif name == Metric.SITE_RUN_AHEAD and rec.get("site") is not None:
            # recorded at every pipelined re-submission (0 = consumed the
            # newest broadcast; j = running j broadcasts ahead)
            self.site(rec["site"])["run_ahead"] = int(v)
        elif name == Metric.ROUNDS_PER_SEC:
            # the vectorized engine records the series directly; trust it
            self.rounds_per_sec = (
                v if self.rounds_per_sec is None
                else _EMA_DECAY * self.rounds_per_sec + (1 - _EMA_DECAY) * v
            )

    # --------------------------------------------------------------- verdicts
    def _fire(self, key, verdict_kind, cause, evidence, now, site=None):
        if key in self._armed:
            return None
        v = {
            "verdict": verdict_kind,
            "severity": VERDICT_SEVERITY[verdict_kind],
            "cause": cause,
            "evidence": evidence,
            "round": self.round,
            "t": now,
        }
        if site is not None:
            v["site"] = str(site)
        self._armed[key] = v
        self.verdicts.append(v)
        return v

    def _rearm(self, key):
        self._armed.pop(key, None)

    def check(self, now=None):
        """Evaluate the liveness rules; returns NEWLY fired verdicts (each
        rule is edge-triggered: it fires on the transition into the bad
        state and re-arms when the condition clears)."""
        now = time.time() if now is None else float(now)
        with self._lock:
            return self._check_locked(now)

    def _check_locked(self, now):
        fired = []

        # the federation itself must still be talking — against a finished
        # (or wholly wedged) run every per-site rule would storm, and a
        # dead RUN is the watch CLI's exit condition, not a site verdict
        run_live = (
            self.last_event_t is not None
            and now - self.last_event_t <= self.silence_after
        )

        for name in sorted(self.sites):
            s = self.sites[name]
            key = f"silence:{name}"
            last = max(s["last_seen"] or 0.0, s["last_heartbeat"] or 0.0)
            if not last:
                continue
            age = now - last
            # "silent" = the federation moved MORE THAN ONE round past the
            # site and its lane aged out.  The round-lag guard matters:
            # serial engines invoke sites one after another, so while round
            # r+1 is in progress every not-yet-invoked lane legitimately
            # still shows round r — a one-round lag is the steady state of
            # a healthy serial federation, a two-round lag means a whole
            # round completed without the site.
            lagging = s["round"] < self.round - 1
            if run_live and lagging and age > self.silence_after:
                v = self._fire(
                    key, Live.VERDICT_SILENCE,
                    f"site {name} heartbeat silent mid-run",
                    f"no record for {age:.1f}s (threshold "
                    f"{self.silence_after:g}s) and the federation moved on "
                    f"to round {self.round} while the site is stuck at "
                    f"round {s['round']}"
                    + (", site_died recorded" if s["dead"] else
                       " with no site_died event — wedged, not dropped"),
                    now, site=name,
                )
                if v:
                    fired.append(v)
            elif age <= self.silence_after or not lagging:
                self._rearm(key)

        # async staleness: a site MORE than k rounds behind means the
        # engine had to block on it (or it died and its gauge froze past
        # the window) — the straggler is gating the federation again.
        # Edge-triggered per site; re-arms when the site is back inside
        # the window.  Dead-site attribution reuses the site_died
        # retry-exhaustion evidence (the doctor's vocabulary).
        if self.staleness_k:
            for name in sorted(self.sites):
                s = self.sites[name]
                key = f"staleness:{name}"
                # the latched breach (consumed here) outranks the live
                # gauge: breach + recovery can land in ONE ingest batch
                breach = s.pop("staleness_breach", None)
                st = s.get("staleness")
                if breach is not None and (st is None or breach > st):
                    st = breach
                if st is not None and st > self.staleness_k:
                    if s["dead"]:
                        how = (
                            "site declared dead ("
                            + ("retries exhausted"
                               if s.get("died_retries_exhausted")
                               else "hard failure")
                            + ") — its last contribution ages past the "
                            "window"
                        )
                    else:
                        how = ("the engine must block on it — the "
                               "straggler gates the federation again")
                    v = self._fire(
                        key, Live.VERDICT_STALENESS,
                        f"site {name} fell more than k rounds behind",
                        f"staleness {st} > bound k={self.staleness_k} at "
                        f"round {self.round}; {how}",
                        now, site=name,
                    )
                    if v:
                        fired.append(v)
                elif st is not None and st <= self.staleness_k:
                    self._rearm(key)

        # run-ahead pipeline: the reducer worker fell behind the horizon
        # (a pipeline:stall latched since the last check) — edge-triggered
        # federation-wide; re-arms once the pipeline visibly flows again
        # (a concurrent reduce completed or a run-ahead re-submission
        # happened after the breach).
        breach, self._pipeline_breach = self._pipeline_breach, None
        if breach is not None:
            where = (f" (site {breach['site']} hit the horizon)"
                     if breach.get("site") else "")
            waited = breach.get("waited_s")
            v = self._fire(
                "pipeline_stall", Live.VERDICT_PIPELINE,
                "reducer worker fell behind the run-ahead horizon",
                f"the engine blocked {waited if waited is not None else '?'}s"
                f" on the round-{breach.get('reduce_round')} reduce at "
                f"run-ahead depth d={breach.get('d')}{where}; the wire "
                f"tail is gating compute again "
                f"({self.pipeline_stalls} stall(s) so far)",
                now, site=breach.get("site"),
            )
            if v:
                fired.append(v)
            self._pipeline_flowed = False
        elif self._pipeline_flowed:
            self._rearm("pipeline_stall")
            self._pipeline_flowed = False

        # elastic membership (ISSUE 15): the live roster eroded to within
        # the configured headroom of the quorum need — one more leave or
        # death fails the run.  Edge-triggered federation-wide, armed only
        # while the aggregator's membership events report a quorum need
        # (i.e. a site_quorum policy is configured); re-arms when joins/
        # rejoins rebuild the headroom.
        if self.quorum_need is not None and self.roster_members is not None:
            alive = self.roster_members - len(self.dead - self.left)
            headroom = alive - self.quorum_need
            if headroom < self.quorum_headroom:
                v = self._fire(
                    "quorum_erosion", Live.VERDICT_QUORUM_EROSION,
                    "live roster eroding toward the quorum floor",
                    f"{alive} live members vs quorum need "
                    f"{self.quorum_need} (headroom {headroom} < "
                    f"{self.quorum_headroom}) at roster epoch "
                    f"{self.roster_epoch}: one more leave or death fails "
                    "the run",
                    now,
                )
                if v:
                    fired.append(v)
            else:
                self._rearm("quorum_erosion")

        if len(self.round_durs) >= _ROUND_MIN_SAMPLES:
            *window, last = self.round_durs
            med = statistics.median(window)
            if med > 0 and last > self.round_outlier * med:
                v = self._fire(
                    "round_outlier", Live.VERDICT_ROUND_OUTLIER,
                    "round duration blew past the rolling median",
                    f"round {self.round} took {last:.3f}s vs rolling median "
                    f"{med:.3f}s (> {self.round_outlier:g}x)",
                    now,
                )
                if v:
                    fired.append(v)
            else:
                self._rearm("round_outlier")

        if (self.mfu_n >= _MFU_MIN_SAMPLES and self.mfu_ema
                and self.mfu_last is not None):
            if self.mfu_last < self.mfu_collapse * self.mfu_ema:
                v = self._fire(
                    "mfu_collapse", Live.VERDICT_MFU_COLLAPSE,
                    "MFU collapsed vs its own running average",
                    f"mfu {self.mfu_last:.4g} below {self.mfu_collapse:g}x "
                    f"EMA {self.mfu_ema:.4g} at round {self.round}",
                    now,
                )
                if v:
                    fired.append(v)
            else:
                self._rearm("mfu_collapse")

        recent = sum(1 for t in self._retry_times
                     if t > now - self.retry_window)
        if recent >= self.retry_storm:
            v = self._fire(
                "retry_storm", Live.VERDICT_RETRY_STORM,
                "wire retries bursting (flaky relay)",
                f"{recent} wire retries in the last {self.retry_window:g}s "
                f"(threshold {self.retry_storm}); "
                f"{self.corruption_recovered} corrupt payload(s) recovered "
                "so far",
                now,
            )
            if v:
                fired.append(v)
        elif recent <= self.retry_storm // 2:
            self._rearm("retry_storm")

        return fired

    # --------------------------------------------------------------- snapshot
    def status(self):
        """'critical' | 'warning' | 'ok' from the currently-ARMED verdicts
        (a fired-and-recovered rule no longer colors the status)."""
        with self._lock:
            sev = {v["severity"] for v in self._armed.values()}
        if "critical" in sev or self.dead:
            return "critical"
        if "warning" in sev:
            return "warning"
        return "ok"

    def _wire_rates(self, now):
        lo = now - _WIRE_RATE_WINDOW_S
        rates = {"save": 0.0, "load": 0.0}
        for t0, op, nbytes in self._wire_window:
            if t0 > lo:
                rates[op] += nbytes / _WIRE_RATE_WINDOW_S
        return rates

    def snapshot(self, now=None):
        """JSON-able federation view — the one structure the watch board,
        ``/metrics`` and ``/healthz`` all render from."""
        now = time.time() if now is None else float(now)
        with self._lock:
            return self._snapshot_locked(now)

    def _snapshot_locked(self, now):
        rates = self._wire_rates(now)
        sites = {}
        for name in sorted(self.sites):
            s = self.sites[name]
            last = max(s["last_seen"] or 0.0, s["last_heartbeat"] or 0.0)
            sites[name] = {
                "round": s["round"],
                "phase": s["phase"],
                "epoch": s["epoch"],
                "heartbeat_age_s": (round(now - last, 3) if last else None),
                "anomalies": s["anomalies"],
                "worker_restarts": s["worker_restarts"],
                "staleness": s["staleness"],
                "run_ahead": s["run_ahead"],
                "status": ("dead" if s["dead"] else
                           "quarantined" if s["quarantined"] else
                           "silent" if f"silence:{name}" in self._armed else
                           "alive"),
            }
        return {
            "status": self.status(),
            "live": (self.last_event_t is not None
                     and now - self.last_event_t <= self.silence_after),
            "now": now,
            "round": self.round,
            "rounds_done": self.rounds_done,
            "rounds_per_sec": self.rounds_per_sec,
            "mfu": {"last": self.mfu_last, "ema": self.mfu_ema,
                    "samples": self.mfu_n},
            "samples_per_sec": self.samples_per_sec,
            "wire": dict(self.wire, save_rate_bps=round(rates["save"], 1),
                         load_rate_bps=round(rates["load"], 1)),
            "anomalies": {"total": self.anomalies,
                          "by_kind": dict(self.anomalies_by_kind)},
            "chaos_injections": self.chaos,
            "worker_restarts": self.worker_restarts,
            "staleness_k": self.staleness_k,
            "stale_standins": self.stale_standins,
            "run_ahead_d": self.run_ahead_d,
            "pipeline_stalls": self.pipeline_stalls,
            "reduce_concurrent_s": round(self.reduce_concurrent_s, 4),
            "frame_bytes": dict(self.frame_bytes),
            "roster": {
                "epoch": self.roster_epoch,
                "members": self.roster_members,
                "joining": sorted(self.joining),
                "left": sorted(self.left),
                "dead": sorted(self.dead - self.left),
                "changes": dict(self.membership_changes),
                "quorum_need": self.quorum_need,
            },
            "wire_retries": self.wire_retries,
            "corruption_recovered": self.corruption_recovered,
            "dead_sites": sorted(self.dead),
            "quarantined_sites": sorted(self.quarantined),
            "truncated_lines": self.truncated_lines,
            "sites": sites,
            "verdicts": list(self.verdicts),
        }


# -------------------------------------------------------------------- board
def _fmt_bytes(n):
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n}B"


def _fmt(v, fmt="{:.3g}"):
    return "-" if v is None else fmt.format(v)


def render_board(snap, root=""):
    """The ``telemetry watch`` terminal status board for one snapshot."""
    head = f"federation live board — {root}" if root else "federation live board"
    lines = [head,
             f"status {snap['status'].upper()}"
             + ("" if snap["live"] else " (no recent records — run over or wedged)")]
    lines.append(
        f"round {snap['round']} · {snap['rounds_done']} engine rounds · "
        f"{_fmt(snap['rounds_per_sec'])} rounds/s · "
        f"mfu {_fmt(snap['mfu']['last'])} (ema {_fmt(snap['mfu']['ema'])}) · "
        f"{_fmt(snap['samples_per_sec'])} samples/s"
    )
    w = snap["wire"]
    lines.append(
        f"wire out {w['saves']} files / {_fmt_bytes(w['save_bytes'])} "
        f"({_fmt_bytes(w['save_rate_bps'])}/s) · "
        f"in {w['loads']} files / {_fmt_bytes(w['load_bytes'])} "
        f"({_fmt_bytes(w['load_rate_bps'])}/s) · "
        f"retries {snap['wire_retries']} · "
        f"recovered {snap['corruption_recovered']}"
    )
    lines.append(
        f"anomalies {snap['anomalies']['total']} · "
        f"chaos {snap['chaos_injections']} · "
        f"worker restarts {snap.get('worker_restarts', 0)} · "
        + (f"stale stand-ins {snap.get('stale_standins', 0)} "
           f"(k={snap['staleness_k']}) · "
           if snap.get("staleness_k") else "")
        + (f"run-ahead d={snap['run_ahead_d']} "
           f"(reduce overlap {snap.get('reduce_concurrent_s', 0):.1f}s, "
           f"{snap.get('pipeline_stalls', 0)} stall(s)) · "
           if snap.get("run_ahead_d") else "")
        + f"truncated lines {snap['truncated_lines']} · "
        f"dead: {', '.join(snap['dead_sites']) or '-'} · "
        f"quarantined: {', '.join(snap['quarantined_sites']) or '-'}"
    )
    fb = snap.get("frame_bytes") or {}
    if fb.get("frames"):
        lines.append(
            f"daemon frames {fb['frames']} · tx {_fmt_bytes(fb['tx'])} · "
            f"rx {_fmt_bytes(fb['rx'])}"
        )
    roster = snap.get("roster") or {}
    if roster.get("epoch"):
        # elastic membership only: the line appears once the roster has a
        # versioned epoch (a membership:* event flowed) — fixed-roster
        # boards stay unchanged
        ch = roster.get("changes") or {}
        changes = " ".join(
            f"{k}={ch[k]}" for k in ("join", "leave", "rejoin", "refused")
            if ch.get(k)
        )
        need = roster.get("quorum_need")
        lines.append(
            f"roster epoch {roster['epoch']} · "
            f"members {roster.get('members') if roster.get('members') is not None else '-'} · "
            f"joining: {', '.join(roster.get('joining') or ()) or '-'} · "
            f"left: {', '.join(roster.get('left') or ()) or '-'} · "
            f"dead: {len(roster.get('dead') or ())}"
            + (f" · quorum need {need}" if need is not None else "")
            + (f" · {changes}" if changes else "")
        )
    if snap["sites"]:
        width = max(len(n) for n in snap["sites"])
        # the staleness/run-ahead columns appear only on async/pipelined
        # runs (k and d learned from the engine's async:*/pipeline:*
        # events) — lockstep boards stay unchanged
        k = int(snap.get("staleness_k") or 0)
        d = int(snap.get("run_ahead_d") or 0)
        stale_hdr = f" {'stale':>5}" if k else ""
        ahead_hdr = f" {'ahead':>5}" if d else ""
        lines.append("")
        lines.append(
            f"  {'site'.ljust(width)}  {'round':>5} {'epoch':>5} "
            f"{'phase':<16} {'heartbeat':>10}{stale_hdr}{ahead_hdr} "
            f"{'anoms':>5}  status"
        )
        for name, s in snap["sites"].items():
            age = ("-" if s["heartbeat_age_s"] is None
                   else f"{s['heartbeat_age_s']:.1f}s ago")
            status = s["status"].upper() if s["status"] != "alive" else "alive"
            stale_col = ""
            if k:
                st = s.get("staleness")
                stale_col = f" {'-' if st is None else st:>5}"
            ahead_col = ""
            if d:
                ra = s.get("run_ahead")
                ahead_col = f" {'-' if ra is None else ra:>5}"
            lines.append(
                f"  {name.ljust(width)}  {s['round']:>5} "
                f"{'-' if s['epoch'] is None else s['epoch']:>5} "
                f"{(s['phase'] or '-'):<16} {age:>10}{stale_col}{ahead_col} "
                f"{s['anomalies']:>5}  {status}"
            )
    if snap["verdicts"]:
        lines.append("")
        lines.append("in-flight verdicts:")
        for v in snap["verdicts"]:
            site = f" [{v['site']}]" if v.get("site") else ""
            lines.append(
                f"  [{v['severity']}] {v['verdict']}{site} — "
                f"{v['cause']}: {v['evidence']}"
            )
    return "\n".join(lines)
