"""Stdlib-only HTTP exporters for the live ops plane: ``/metrics`` + ``/healthz``.

Serves the :class:`~.live.LiveState` snapshot two ways:

- ``GET /metrics`` — Prometheus text exposition format (version 0.0.4):
  per-site round/heartbeat-age/liveness gauges, federation rounds/sec, MFU,
  samples/sec, wire-byte and anomaly/chaos/retry counters, and a
  ``verdicts_total{kind=...}`` counter per in-flight verdict kind.  Every
  metric name is ``coinstac_dinunet_<series>``
  (:attr:`~..config.keys.Live.PROM_PREFIX`); the series suffixes reuse the
  :class:`~..config.keys.Metric` vocabulary verbatim, which the
  ``telemetry-metric-name`` dinulint rule pins to the legal Prometheus
  charset so the mapping can never mangle a name.
- ``GET /healthz`` — the whole snapshot as JSON, with the top-level
  ``status`` (``ok``/``warning``/``critical``) an orchestrator's liveness
  probe can key on.

No dependencies beyond ``http.server`` — the exporter must work inside the
same minimal site container the engine invokes, which is also why the
server binds loopback by default (an operator exposes it deliberately).
"""
import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..config.keys import Live
from .recorder import get_active as _telemetry

PROM_PREFIX = Live.PROM_PREFIX

_NAME_OK = re.compile(r"^[a-z_][a-z0-9_]*$")
_LABEL_BAD = re.compile(r'[\\"\n]')


def prometheus_name(series):
    """``<PROM_PREFIX>_<series>``, with any character outside the legal
    Prometheus metric charset replaced by ``_``.  The config/keys.py
    vocabularies are lint-pinned to already-legal spellings, so for every
    declared series this mapping is the identity plus the prefix."""
    series = re.sub(r"[^a-z0-9_]", "_", str(series).lower())
    if not _NAME_OK.match(series):
        series = "_" + series
    return f"{PROM_PREFIX}_{series}"


def _label(value):
    return _LABEL_BAD.sub("_", str(value))


class _PromWriter:
    def __init__(self):
        self.lines = []
        self._seen = set()

    def gauge(self, series, value, help_text, labels=None):
        self.sample(series, value, help_text, "gauge", labels)

    def counter(self, series, value, help_text, labels=None):
        self.sample(series, value, help_text, "counter", labels)

    def sample(self, series, value, help_text, kind, labels=None):
        if value is None:
            return
        name = prometheus_name(series)
        if name not in self._seen:
            self._seen.add(name)
            self.lines.append(f"# HELP {name} {help_text}")
            self.lines.append(f"# TYPE {name} {kind}")
        label_s = ""
        if labels:
            label_s = "{" + ",".join(
                f'{k}="{_label(v)}"' for k, v in sorted(labels.items())
            ) + "}"
        # full precision (repr round-trips float64 exactly): %g's 6
        # significant digits would quantize large counters (wire bytes on a
        # long run), making small increments invisible to rate()/increase()
        self.lines.append(f"{name}{label_s} {float(value)!r}")

    def render(self):
        return "\n".join(self.lines) + "\n"


def render_prometheus(snap):
    """LiveState snapshot -> Prometheus text exposition."""
    w = _PromWriter()
    w.gauge("up", 1, "live ops plane exporter is serving")
    w.gauge("round", snap.get("round"), "highest engine round observed")
    w.counter("rounds_total", snap.get("rounds_done"),
              "completed engine:round spans observed")
    w.gauge("rounds_per_sec", snap.get("rounds_per_sec"),
            "EMA of federation round throughput")
    mfu = snap.get("mfu") or {}
    w.gauge("mfu", mfu.get("last"), "latest model FLOPS utilization sample")
    w.gauge("samples_per_sec", snap.get("samples_per_sec"),
            "latest training throughput sample")
    # one loop per FAMILY: the text exposition format requires every line
    # of one metric to form a single contiguous group — interleaving the
    # families per op/site would be rejected by strict collectors
    wire = snap.get("wire") or {}
    for op in ("save", "load"):
        w.counter("wire_bytes_total", wire.get(f"{op}_bytes"),
                  "cumulative wire payload bytes by direction",
                  labels={"op": op})
    for op in ("save", "load"):
        w.gauge("wire_bytes_per_sec", wire.get(f"{op}_rate_bps"),
                "wire payload byte rate over the rolling window",
                labels={"op": op})
    anomalies = snap.get("anomalies") or {}
    w.counter("anomalies_total", anomalies.get("total"),
              "watchdog anomaly events observed")
    w.counter("chaos_injections_total", snap.get("chaos_injections"),
              "deterministic chaos faults injected")
    w.counter("worker_restarts_total", snap.get("worker_restarts"),
              "daemon workers killed and restarted by the supervisor")
    w.counter("wire_retries_total", snap.get("wire_retries"),
              "wire load retries observed")
    w.counter("corruption_recovered_total", snap.get("corruption_recovered"),
              "corrupt/truncated payloads recovered via retry")
    w.counter("truncated_lines_total", snap.get("truncated_lines"),
              "torn/undecodable telemetry JSONL lines skipped by the tailer")
    w.gauge("dead_sites", len(snap.get("dead_sites") or ()),
            "sites declared dead by the engine")
    sites = snap.get("sites") or {}
    for name, s in sites.items():
        w.gauge("site_round", s.get("round"),
                "per-site latest observed round", labels={"site": name})
    for name, s in sites.items():
        w.gauge("site_heartbeat_age_seconds", s.get("heartbeat_age_s"),
                "seconds since the site's last record/heartbeat",
                labels={"site": name})
    for name, s in sites.items():
        w.gauge("site_dead", 1 if s.get("status") == "dead" else 0,
                "1 when the engine declared the site dead",
                labels={"site": name})
    for name, s in sites.items():
        w.counter("site_anomalies_total", s.get("anomalies"),
                  "watchdog anomalies attributed to the site",
                  labels={"site": name})
    for name, s in sites.items():
        w.counter("site_worker_restarts_total", s.get("worker_restarts"),
                  "daemon worker restarts attributed to the site",
                  labels={"site": name})
    for name, s in sites.items():
        # async rounds only: absent (None) outside async mode so lockstep
        # scrapes carry no empty series
        w.gauge("site_staleness", s.get("staleness"),
                "rounds the site's last contribution lags the aggregator "
                "(async staleness window)", labels={"site": name})
    w.gauge("staleness_k", snap.get("staleness_k") or None,
            "configured async staleness bound k (absent on lockstep runs)")
    w.counter("stale_standins_total", snap.get("stale_standins"),
              "straggler stand-ins delivered by the async round engine")
    for name, s in sites.items():
        # run-ahead pipelining only: absent (None) otherwise, so
        # non-pipelined scrapes carry no empty series
        w.gauge("site_run_ahead", s.get("run_ahead"),
                "broadcasts the site's pending invocation is running "
                "ahead of the last one it consumed (run-ahead pipeline)",
                labels={"site": name})
    w.gauge("run_ahead_d", snap.get("run_ahead_d") or None,
            "configured run-ahead pipelining depth d (absent when off)")
    w.counter("reduce_concurrent_seconds_total",
              snap.get("reduce_concurrent_s"),
              "seconds the reduce+relay tail ran while site invocations "
              "were in flight (the pipelining win)")
    w.counter("pipeline_stalls_total", snap.get("pipeline_stalls"),
              "times the engine blocked on the reducer worker at the "
              "run-ahead horizon")
    fb = snap.get("frame_bytes") or {}
    for direction in ("tx", "rx"):
        w.counter("daemon_frame_bytes_total", fb.get(direction),
                  "daemon frame-pipe bytes by direction (the delta-cache "
                  "win is the per-invoke trend)",
                  labels={"dir": direction})
    roster = snap.get("roster") or {}
    w.gauge("roster_size", roster.get("members"),
            "live elastic-membership roster size (absent on fixed-roster "
            "runs)")
    changes = roster.get("changes") or {}
    if roster.get("epoch") or changes:
        for kind in ("join", "leave", "rejoin", "refused"):
            w.counter("membership_changes_total", changes.get(kind, 0),
                      "elastic-membership roster transitions, by kind",
                      labels={"kind": kind})
    by_kind = {}
    for v in snap.get("verdicts") or ():
        by_kind[v["verdict"]] = by_kind.get(v["verdict"], 0) + 1
    for kind in (Live.VERDICT_SILENCE, Live.VERDICT_ROUND_OUTLIER,
                 Live.VERDICT_MFU_COLLAPSE, Live.VERDICT_RETRY_STORM,
                 Live.VERDICT_STALENESS, Live.VERDICT_PIPELINE,
                 Live.VERDICT_QUORUM_EROSION):
        w.counter("verdicts_total", by_kind.get(kind, 0),
                  "in-flight stall verdicts fired, by kind",
                  labels={"kind": kind})
    return w.render()


def render_healthz(snap):
    """LiveState snapshot -> the ``/healthz`` JSON body."""
    return json.dumps(snap, indent=2, sort_keys=True, default=str)


class OpsServer:
    """Threaded loopback HTTP server over a snapshot provider.

    ``snapshot_fn()`` must return the current :meth:`.live.LiveState
    .snapshot` dict — it is called per request, so the scrape always sees
    the freshest ingested state (the watch loop mutates the LiveState from
    one thread; snapshot() only reads, and a slightly-torn read is
    acceptable for monitoring data).
    """

    def __init__(self, snapshot_fn, host="127.0.0.1", port=0):
        self._snapshot_fn = snapshot_fn

        class Handler(BaseHTTPRequestHandler):
            def do_GET(handler):  # noqa: N805 — http.server API
                try:
                    if handler.path.split("?", 1)[0] == "/metrics":
                        body = render_prometheus(snapshot_fn()).encode()
                        ctype = "text/plain; version=0.0.4; charset=utf-8"
                    elif handler.path.split("?", 1)[0] == "/healthz":
                        body = render_healthz(snapshot_fn()).encode()
                        ctype = "application/json"
                    else:
                        handler.send_error(404, "try /metrics or /healthz")
                        return
                except Exception as exc:  # noqa: BLE001 — a scrape must not kill the watch
                    handler.send_error(500, str(exc)[:200])
                    return
                handler.send_response(200)
                handler.send_header("Content-Type", ctype)
                handler.send_header("Content-Length", str(len(body)))
                handler.end_headers()
                handler.wfile.write(body)

            def log_message(handler, *a):  # scrapes are not board output
                pass

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            daemon=True, name="telemetry-ops-server",
        )
        self._thread.start()

    @property
    def port(self):
        return self._httpd.server_address[1]

    @property
    def host(self):
        return self._httpd.server_address[0]

    def url(self, path="/metrics"):
        return f"http://{self.host}:{self.port}{path}"

    def scrape(self, path="/metrics", timeout=5.0):
        """A genuine HTTP self-scrape (what CI archives as the artifact)."""
        from urllib.request import urlopen

        with urlopen(self.url(path), timeout=timeout) as resp:
            return resp.read().decode("utf-8")

    def close(self, timeout=2.0):
        """Stop serving and JOIN the serving thread.  Returns True when
        the thread exited within ``timeout``; a thread that failed to
        join (a scrape wedged in a handler) leaves a listener behind
        between CI jobs, so the failure is surfaced as a typed
        ``telemetry:degraded`` event on the ambient recorder — evidence
        in the trace instead of a silent leak."""
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            _telemetry().event(
                "telemetry:degraded", cat="telemetry",
                what="ops server thread failed to join on close "
                     "(listener may leak until process exit)",
                thread=self._thread.name, timeout_s=float(timeout),
            )
            return False
        return True
