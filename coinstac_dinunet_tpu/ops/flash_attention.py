"""Fused blockwise (flash) attention for TPU — Pallas kernel + XLA fallback.

The reference framework has no attention anywhere (its models are user-supplied
CNN/MLP classifiers, SURVEY.md §5 "Long-context: absent"); this op is part of
the TPU build's first-class long-context support.  It is the single-device
building block that :func:`~coinstac_dinunet_tpu.parallel.ring_attention.
ring_attention` chains around a mesh axis for sequence parallelism.

Design:

- **Online softmax, never materializing the (Tq, Tk) score matrix** in HBM:
  the Pallas kernel keeps a (block_q, head_dim) accumulator plus running
  (max, sum) rows in VMEM and streams key/value blocks through the MXU.
- **Position-offset masking** instead of a mask tensor: causal and key-length
  masks are computed in-kernel from ``(q_offset, k_offset, kv_len)`` scalars
  (SMEM), which is what lets the same kernel serve ring attention, where the
  key block's global position changes every ring step.
- **f32 softmax state regardless of input dtype** (bf16 in, f32 accumulate on
  the MXU via ``preferred_element_type``).
- **Fully-masked rows** use a large-negative sentinel rather than ``-inf`` so
  the kernel stays NaN-free; such rows emit zero output and report
  ``lse ≈ -1e30``, which the log-sum-exp merge in the ring step annihilates.
- **Position scalars are int32 end-to-end** (SMEM holds int32 natively); an
  f32 round-trip would corrupt masks beyond 2^24 tokens.
- **kv streams through the grid**: the kv axis is the innermost (sequential)
  grid dimension with the online-softmax state carried in VMEM scratch, so
  K/V VMEM residency is one (block_k, head_dim) tile regardless of sequence
  length.
- Backward is a blockwise XLA recompute from the saved ``(out, lse)``
  residuals (the standard flash backward identities, including the lse
  cotangent the ring merge produces) — no score matrix crosses passes.

``impl='xla'`` computes the same (out, lse) contract with plain fused XLA ops
— the CPU/test path and the ground truth for the kernel's unit tests.
"""
import functools
import math
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

try:  # soft import: CPU-only deployments fall back to impl='xla'
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    # renamed TPUCompilerParams (0.4.x) -> CompilerParams (>=0.7)
    _CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    _HAVE_PALLAS = True
except Exception:  # noqa: BLE001
    _HAVE_PALLAS = False

_NEG = -1.0e30  # finite "-inf": keeps exp/max NaN-free for fully-masked rows
_MASKED_LSE = _NEG / 2  # rows whose running max stays below this are fully masked
_LANE = 128


def _valid_mask(shape, aux, row_axis, col_axis, iq, j, block_q, block_k, causal):
    """Key-validity (and optionally causal) mask from global positions.

    ``aux = [q_offset, k_offset, kv_len]`` int32 scalars (int32 end-to-end —
    an f32 round-trip would lose exactness above 2^24 positions); row/col
    global ids are the offsets plus block-local coordinates.
    """
    q_off = aux[0].astype(jnp.int32)
    k_off = aux[1].astype(jnp.int32)
    kv_len = aux[2].astype(jnp.int32)
    rows = q_off + iq * block_q + lax.broadcasted_iota(jnp.int32, shape, row_axis)
    cols_local = j * block_k + lax.broadcasted_iota(jnp.int32, shape, col_axis)
    valid = cols_local < kv_len
    if causal:
        valid = jnp.logical_and(valid, rows >= k_off + cols_local)
    return valid


def _flash_kernel(aux_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                  acc_ref, m_ref, l_ref, *, scale, causal, block_q, block_k):
    """One (q-block, k-block) tile.  The kv axis is the innermost grid
    dimension (sequential on TPU), so only one (block_k, d) key/value tile is
    resident in VMEM at a time — arbitrarily long sequences stream through.
    The online-softmax state (acc, running max, running sum) lives in VMEM
    scratch, which persists across the sequential kv steps; max/sum are kept
    lane-broadcast (all 128 lanes equal) so no lane-slicing is needed."""
    iq = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)

    q = q_ref[0].astype(jnp.float32) * scale  # (block_q, d)
    k = k_ref[0].astype(jnp.float32)          # (block_k, d)
    v = v_ref[0].astype(jnp.float32)
    scalars = (aux_ref[0, 0], aux_ref[0, 1], aux_ref[0, 2])
    s = lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # (block_q, block_k)
    valid = _valid_mask(s.shape, scalars, 0, 1, iq, j, block_q, block_k, causal)
    s = jnp.where(valid, s, _NEG)

    m_prev = m_ref[:]                                   # (block_q, LANE)
    l_prev = l_ref[:]
    m_cur = jnp.max(s, axis=-1, keepdims=True)          # (block_q, 1)
    m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
    alpha = jnp.exp(m_prev - m_new)                     # lane-broadcast
    p = jnp.exp(s - m_new[:, :1])
    l_new = l_prev * alpha + jnp.broadcast_to(
        jnp.sum(p, axis=-1, keepdims=True), l_prev.shape
    )
    acc_ref[:] = acc_ref[:] * alpha[:, :1] + lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    m_ref[:] = m_new
    l_ref[:] = l_new

    @pl.when(j == nk - 1)
    def _final():
        m1 = jnp.max(m_ref[:], axis=-1)                 # lanes equal → value
        l1 = jnp.max(l_ref[:], axis=-1)
        l_safe = jnp.maximum(l1, 1e-30)
        # fully-masked rows (m stuck at the sentinel) emit zeros, not mean(V)
        row_ok = m1 > _MASKED_LSE
        o_ref[0] = jnp.where(
            row_ok[:, None], acc_ref[:] / l_safe[:, None], 0.0
        )
        # lse is (1, block_q, 1): the trailing singleton keeps the block shape
        # legal for Mosaic (last two dims must be 8/128-divisible or full-size)
        lse_ref[0, :, 0] = m1 + jnp.log(l_safe)


def _pad_to(x, axis, multiple):
    pad = (-x.shape[axis]) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _flash_pallas(q, k, v, aux, scale, causal, block_q, block_k, interpret):
    """(BH, Tq, d), (BH, Tk, d) → (out (BH, Tq, d) f32, lse (BH, Tq) f32)."""
    bh, tq, d = q.shape
    qp = _pad_to(q, 1, block_q)
    kp = _pad_to(k, 1, block_k)
    vp = _pad_to(v, 1, block_k)
    tqp, tkp = qp.shape[1], kp.shape[1]
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, tqp // block_q, tkp // block_k),
        in_specs=[
            pl.BlockSpec((1, 3), lambda b, i, j: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tqp, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, tqp, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),      # acc
            pltpu.VMEM((block_q, _LANE), jnp.float32),  # running max (lanes equal)
            pltpu.VMEM((block_q, _LANE), jnp.float32),  # running sum (lanes equal)
        ],
        # the kv axis (j) MUST run sequentially: scratch carries the
        # online-softmax state across j and the output is written only at
        # j == nk-1.  TPU grids default to sequential execution, but pin it
        # so the compiler can never parallelize the carried axis.
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(aux.reshape(1, 3), qp, kp, vp)
    return out[:, :tq], lse[:, :tq, 0]


def _flash_xla(q, k, v, aux, scale, causal):
    """Same (out, lse) contract with plain XLA ops (CPU/reference path)."""
    s = jnp.einsum(
        "bqd,bkd->bqk", q.astype(jnp.float32) * scale, k.astype(jnp.float32)
    )
    valid = _valid_mask(s.shape, aux, 1, 2, 0, 0, 0, 1, causal)
    s = jnp.where(valid, s, _NEG)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.maximum(jnp.sum(p, axis=-1), 1e-30)
    out = jnp.einsum("bqk,bkd->bqd", p / l[..., None], v.astype(jnp.float32))
    # fully-masked rows emit zeros (not mean(V)); their lse keeps the sentinel
    out = jnp.where((m > _MASKED_LSE)[..., None], out, 0.0)
    return out, m + jnp.log(l)


def _flash_core(q, k, v, aux, scale, causal, impl, block_q, block_k):
    if impl == "xla":
        return _flash_xla(q, k, v, aux, scale, causal)
    if not _HAVE_PALLAS:
        raise RuntimeError("Pallas unavailable; use impl='xla'")
    return _flash_pallas(
        q, k, v, aux, scale, causal, block_q, block_k,
        interpret=(impl == "pallas_interpret"),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_pair(q, k, v, aux, scale, causal, impl, block_q, block_k):
    return _flash_core(q, k, v, aux, scale, causal, impl, block_q, block_k)


def _flash_fwd(q, k, v, aux, scale, causal, impl, block_q, block_k):
    out, lse = _flash_core(q, k, v, aux, scale, causal, impl, block_q, block_k)
    return (out, lse), (q, k, v, aux, out, lse)


_LSE_PAD = 1.0e30  # padded q rows: exp(s - pad) == 0, so they contribute nothing


def _dq_kernel(aux_ref, q_ref, k_ref, v_ref, lse_ref, delta_ref, glse_ref,
               gout_ref, dq_ref, dq_acc, *, scale, causal, block_q, block_k):
    """dq tile: q-block fixed, key blocks stream through the sequential
    innermost grid axis with the accumulator in VMEM scratch — the same
    streaming structure as the forward, applied to the flash backward
    identity ``ds = p · (dp − Δ + g_lse)``."""
    iq = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    qf = q_ref[0].astype(jnp.float32) * scale          # (bq, d)
    kf = k_ref[0].astype(jnp.float32)                  # (bk, d)
    vf = v_ref[0].astype(jnp.float32)
    g_out = gout_ref[0].astype(jnp.float32)            # (bq, d)
    lse = lse_ref[0]                                   # (bq, 1) f32
    lse = jnp.where(lse <= _MASKED_LSE, 0.0, lse)
    delta = delta_ref[0]                               # (bq, 1)
    g_lse = glse_ref[0]                                # (bq, 1)
    scalars = (aux_ref[0, 0], aux_ref[0, 1], aux_ref[0, 2])

    s = lax.dot_general(
        qf, kf, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )                                                  # (bq, bk)
    valid = _valid_mask(s.shape, scalars, 0, 1, iq, j, block_q, block_k, causal)
    p = jnp.where(valid, jnp.exp(jnp.where(valid, s, _NEG) - lse), 0.0)
    dp = lax.dot_general(
        g_out, vf, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - delta + g_lse)
    dq_acc[:] = dq_acc[:] + lax.dot_general(
        ds, kf, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale

    @pl.when(j == nk - 1)
    def _final():
        dq_ref[0] = dq_acc[:]


def _dkv_kernel(aux_ref, k_ref, v_ref, q_ref, gout_ref, lse_ref, delta_ref,
                glse_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                *, scale, causal, block_q, block_k):
    """dk/dv tile: key-block fixed, q blocks stream sequentially.  Works on
    the TRANSPOSED score tile ``sᵀ = k qᵀ`` so both accumulators keep the
    (block_k, d) layout.  Padded q rows contribute exactly zero: their
    ``g_out``/Δ/``g_lse`` pad with zeros and their lse pads with +1e30
    (``p = exp(s − 1e30) = 0``) — no explicit row mask needed."""
    ik = pl.program_id(1)
    j = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    kf = k_ref[0].astype(jnp.float32)                  # (bk, d)
    vf = v_ref[0].astype(jnp.float32)
    qf = q_ref[0].astype(jnp.float32) * scale          # (bq, d)
    g_out = gout_ref[0].astype(jnp.float32)            # (bq, d)
    lse = lse_ref[0]                                   # (bq, 1)
    lse = jnp.where(lse <= _MASKED_LSE, 0.0, lse)
    delta = delta_ref[0]
    g_lse = glse_ref[0]
    scalars = (aux_ref[0, 0], aux_ref[0, 1], aux_ref[0, 2])

    s_t = lax.dot_general(
        kf, qf, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )                                                  # (bk, bq)
    # q rows live on axis 1, key cols on axis 0 of the transposed tile
    valid = _valid_mask(s_t.shape, scalars, 1, 0, j, ik, block_q, block_k, causal)
    p_t = jnp.where(
        valid, jnp.exp(jnp.where(valid, s_t, _NEG) - lse[:, 0][None, :]), 0.0
    )
    dv_acc[:] = dv_acc[:] + lax.dot_general(
        p_t, g_out, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    dp_t = lax.dot_general(
        vf, g_out, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )                                                  # (bk, bq)
    ds_t = p_t * (dp_t - delta[:, 0][None, :] + g_lse[:, 0][None, :])
    dk_acc[:] = dk_acc[:] + lax.dot_general(
        ds_t, qf, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )

    @pl.when(j == nq - 1)
    def _final():
        dk_ref[0] = dk_acc[:]
        dv_ref[0] = dv_acc[:]


def _flash_bwd_pallas(q, k, v, aux, out, lse, g_out, g_lse, scale, causal,
                      block_q, block_k, interpret):
    """Both backward kernels; returns (dq, dk, dv) in f32."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    delta = jnp.sum(g_out * out.astype(jnp.float32), axis=-1)  # (bh, tq)

    qp = _pad_to(q, 1, block_q)
    gop = _pad_to(g_out, 1, block_q)
    kp = _pad_to(k, 1, block_k)
    vp = _pad_to(v, 1, block_k)
    tqp, tkp = qp.shape[1], kp.shape[1]
    pad_rows = tqp - tq

    def col(x, pad_value):
        x = jnp.pad(x, ((0, 0), (0, pad_rows)), constant_values=pad_value)
        return x[..., None].astype(jnp.float32)  # (bh, tqp, 1)

    lse_c = col(lse, _LSE_PAD)
    delta_c = col(delta, 0.0)
    glse_c = col(g_lse, 0.0)
    aux2 = aux.reshape(1, 3)

    qspec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    kspec_j = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0))
    cspec_i = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))
    smem = pl.BlockSpec((1, 3), lambda b, i, j: (0, 0), memory_space=pltpu.SMEM)
    seq = _CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"),
    )

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(bh, tqp // block_q, tkp // block_k),
        in_specs=[smem, qspec, kspec_j, kspec_j, cspec_i, cspec_i, cspec_i,
                  qspec],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tqp, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=seq,
        interpret=interpret,
    )(aux2, qp, kp, vp, lse_c, delta_c, glse_c, gop)

    kspec_i = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0))
    qspec_j = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0))
    cspec_j = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(bh, tkp // block_k, tqp // block_q),
        in_specs=[smem, kspec_i, kspec_i, qspec_j, qspec_j, cspec_j, cspec_j,
                  cspec_j],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tkp, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, tkp, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=seq,
        interpret=interpret,
    )(aux2, kp, vp, qp, gop, lse_c, delta_c, glse_c)

    return dq[:, :tq], dk[:, :tk], dv[:, :tk]


def _flash_bwd(scale, causal, impl, block_q, block_k, res, g):
    """Blockwise backward, never materializing the (Tq, Tk) score matrix.

    ``impl='pallas'``: the two-kernel flash backward above (dq with keys
    streaming; dk/dv on the transposed tile with queries streaming) —
    on-chip accumulators, one (block, d) tile resident per stream step.
    Otherwise: an XLA ``lax.scan`` over key blocks computing the same
    identities — peak extra memory O(Tq · block_k) per batch row.  Both
    consume the saved ``(out, lse)`` residuals, including the lse cotangent
    the ring merge produces."""
    q, k, v, aux, out, lse = res
    if (impl in ("pallas", "pallas_interpret") and _HAVE_PALLAS
            and not os.environ.get("COINN_FLASH_XLA_BWD")):
        dq, dk, dv = _flash_bwd_pallas(
            q, k, v, aux, out, lse,
            g[0].astype(jnp.float32), g[1].astype(jnp.float32),
            scale, causal, block_q, block_k,
            interpret=(impl == "pallas_interpret"),
        )
        aux_ct = np.zeros(aux.shape, dtype=jax.dtypes.float0)
        return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
                aux_ct)
    g_out = g[0].astype(jnp.float32)
    g_lse = g[1].astype(jnp.float32)  # ring merge differentiates through lse
    qf = q.astype(jnp.float32) * scale
    tk = k.shape[1]
    kp = _pad_to(k.astype(jnp.float32), 1, block_k)
    vp = _pad_to(v.astype(jnp.float32), 1, block_k)
    nk = kp.shape[1] // block_k
    k_blocks = kp.reshape(kp.shape[0], nk, block_k, kp.shape[2])
    v_blocks = vp.reshape(*k_blocks.shape)
    # fully-masked rows carry the _NEG sentinel lse; zero it so exp stays
    # finite (their p is hard-zeroed by the validity mask anyway)
    lse_safe = jnp.where(lse <= _MASKED_LSE, 0.0, lse)[..., None]
    delta = jnp.sum(g_out * out, axis=-1, keepdims=True)  # flash D_i identity

    def body(dq, xs):
        k_j, v_j, j = xs
        s = jnp.einsum("bqd,bkd->bqk", qf, k_j)
        valid = _valid_mask(s.shape, aux, 1, 2, 0, j, 0, block_k, causal)
        p = jnp.where(valid, jnp.exp(jnp.where(valid, s, _NEG) - lse_safe), 0.0)
        dp = jnp.einsum("bqd,bkd->bqk", g_out, v_j)
        ds = p * (dp - delta + g_lse[..., None])
        dq = dq + jnp.einsum("bqk,bkd->bqd", ds, k_j) * scale
        dk_j = jnp.einsum("bqk,bqd->bkd", ds, qf)
        dv_j = jnp.einsum("bqk,bqd->bkd", p, g_out)
        return dq, (dk_j, dv_j)

    dq0 = jnp.zeros_like(qf)
    dq, (dk_b, dv_b) = lax.scan(
        body, dq0,
        (k_blocks.swapaxes(0, 1), v_blocks.swapaxes(0, 1), jnp.arange(nk)),
    )
    join = lambda b: b.swapaxes(0, 1).reshape(kp.shape)[:, :tk]
    # aux is int32 → its tangent space is float0
    aux_ct = np.zeros(aux.shape, dtype=jax.dtypes.float0)
    return (
        dq.astype(q.dtype), join(dk_b).astype(k.dtype),
        join(dv_b).astype(v.dtype), aux_ct,
    )


_flash_pair.defvjp(_flash_fwd, _flash_bwd)


def default_impl():
    return "pallas" if jax.default_backend() == "tpu" else "xla"


def flash_attention(q, k, v, q_offset=0, k_offset=0, kv_len=None, causal=False,
                    scale=None, impl=None, block_q=_LANE, block_k=_LANE,
                    return_lse=False):
    """Fused scaled-dot-product attention.

    Args:
      q: ``(batch, heads, Tq, head_dim)`` queries (f32 or bf16).
      k, v: ``(batch, heads, Tk, head_dim)`` keys/values.
      q_offset, k_offset: global position of row/col 0 — the causal mask is
        computed on ``q_offset + i >= k_offset + j``.  May be traced values
        (ring attention passes the rotating source rank's offset).
      kv_len: number of valid keys (default ``Tk``); keys past it are masked.
      causal: apply the causal mask.
      scale: score scale (default ``1/sqrt(head_dim)``).
      impl: ``'pallas'`` (TPU kernel), ``'pallas_interpret'`` (kernel under
        the interpreter — CPU tests), ``'xla'`` (plain ops), or None for the
        platform default.
      return_lse: also return the per-row log-sum-exp ``(batch, heads, Tq)``
        (f32) — required by the ring-attention merge.

    Returns:
      ``out (batch, heads, Tq, head_dim)`` in ``q.dtype``; optionally
      ``(out, lse)``.
    """
    b, h, tq, d = q.shape
    tk = k.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if impl is None:
        impl = default_impl()
    aux = jnp.asarray(
        [q_offset, k_offset, tk if kv_len is None else kv_len], jnp.int32
    )
    out, lse = _flash_pair(
        q.reshape(b * h, tq, d), k.reshape(b * h, tk, d),
        v.reshape(b * h, tk, d), aux, float(scale), bool(causal),
        impl, int(block_q), int(block_k),
    )
    out = out.reshape(b, h, tq, d).astype(q.dtype)
    if return_lse:
        return out, lse.reshape(b, h, tq)
    return out
