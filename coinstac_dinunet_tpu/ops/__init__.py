"""Compiled math kernels (the framework's "native layer").

The reference delegates its hot numerical work to torch CUDA kernels
(power iteration / orthogonalization: ``rankdad/spi.py:9-86``,
``powersgd/__init__.py:15-38``).  Here the equivalents are XLA-compiled
jax functions (with a Pallas TPU kernel path for the hottest op) — see
SURVEY.md §2 ("Consequence for the TPU build").
"""
from .flash_attention import flash_attention  # noqa: F401
from .power_iteration import orthogonalize, power_iteration_BC  # noqa: F401
from .quantize import dequantize_int8, quantize_int8  # noqa: F401

__all__ = [
    "power_iteration_BC", "orthogonalize", "flash_attention",
    "quantize_int8", "dequantize_int8",
]
