"""Group-wise int8 quantization with stochastic rounding — wire compression.

The reference's only wire-size lever is casting gradients to float16
(``precision_bits``, ``distrib/learner.py:17``, ``config/__init__.py``).  This
op adds an int8 codec — 4× smaller than f32, 2× smaller than the reference's
best — built TPU-first:

- **Group-wise scales**: the flattened tensor is viewed as groups of 128
  lanes; each group stores one f32 scale (absmax/127).  Groups match the VPU
  lane width, so the kernel is one vectorized pass.
- **Stochastic rounding** (``pltpu.stochastic_round`` on TPU; numpy fallback
  elsewhere): quantization noise is zero-mean, so averaging many sites'
  quantized gradients stays unbiased — deterministic rounding would bias the
  federated mean.
- Used by the engine transport as a transparent payload codec
  (``utils/tensorutils.py`` ``save_arrays(codec='int8')``): the receiver gets
  float arrays back and the learners/reducers never know.

No reference counterpart to cite beyond the precision knob; design follows
the public stochastic-rounding quantization pattern (pallas guide §19).
"""
import numpy as np

import jax
import jax.numpy as jnp

try:  # soft import — CPU-only deployments use the numpy path
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except Exception:  # noqa: BLE001
    _HAVE_PALLAS = False

# the kernel's stochastic rounding uses the pltpu prng, which off-TPU only the
# TPU-flavored interpreter (pltpu.InterpretParams, JAX >= 0.7) implements;
# plain interpret=True has no lowering for 'prng_seed' on cpu
if _HAVE_PALLAS and hasattr(pltpu, "InterpretParams"):
    _TPU_INTERPRET_PARAMS = pltpu.InterpretParams
else:
    _TPU_INTERPRET_PARAMS = None
_HAVE_TPU_INTERPRET = _TPU_INTERPRET_PARAMS is not None

GROUP = 128  # elements per scale group = VPU lane width


def _quant_kernel(seed_ref, x_ref, v_ref, s_ref):
    # salt with the grid position as a SECOND seed word: adding it to the
    # caller seed would collide block b of array i with block 0 of array i+b
    # (save_wire hands out consecutive per-array seeds), re-correlating the
    # rounding noise that cross-site averaging depends on being independent
    pltpu.prng_seed(seed_ref[0], pl.program_id(0))
    x = x_ref[:]  # (block_rows, GROUP) f32
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = jnp.maximum(absmax / 127.0, 1e-30)
    scaled = x / scale
    # stochastic round by hand (floor + Bernoulli(frac)) — same semantics as
    # pltpu.stochastic_round but portable to the CPU interpreter for tests.
    # Mosaic can't cast uint32→f32 directly: drop to 24 bits via int32 (exact
    # in f32) first.
    bits = pltpu.bitcast(pltpu.prng_random_bits(scaled.shape), jnp.uint32)
    bits24 = pltpu.bitcast(
        jax.lax.shift_right_logical(bits, jnp.uint32(8)), jnp.int32
    )
    u = bits24.astype(jnp.float32) * (1.0 / 16777216.0)
    lo = jnp.floor(scaled)
    vals = lo + (u < (scaled - lo)).astype(jnp.float32)
    v_ref[:] = jnp.clip(vals, -127, 127).astype(jnp.int8)
    s_ref[:] = scale


# rows staged per grid step: 2048×128 f32 input ≈ 1 MiB VMEM (+ int8 out),
# so arbitrarily large gradients stream through without exceeding VMEM
_BLOCK_ROWS = 2048


def _quantize_pallas(groups, seed, interpret):
    rows = groups.shape[0]
    block = min(_BLOCK_ROWS, rows)
    return pl.pallas_call(
        _quant_kernel,
        grid=(pl.cdiv(rows, block),),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((block, GROUP), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((block, GROUP), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, GROUP), jnp.int8),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        ],
        # the TPU-flavored interpreter implements pltpu prng on CPU
        interpret=_TPU_INTERPRET_PARAMS() if interpret else False,
    )(jnp.asarray([seed], jnp.int32), groups)


def _quantize_numpy(groups, seed):
    rng = np.random.default_rng(seed)
    absmax = np.max(np.abs(groups), axis=1, keepdims=True)
    scale = np.maximum(absmax / 127.0, 1e-30).astype(np.float32)
    scaled = groups / scale
    lo = np.floor(scaled)
    frac = scaled - lo
    vals = lo + (rng.random(scaled.shape) < frac)
    return np.clip(vals, -127, 127).astype(np.int8), scale


def quantize_int8(x, seed=0, impl=None):
    """Quantize any-shape float array → ``(values int8, scales f32)``.

    ``values`` has the flattened-padded shape ``(ceil(n/128), 128)``; dequant
    restores the original shape.  ``impl``: ``'pallas'``/``'pallas_interpret'``
    (TPU kernel), ``'numpy'``, or None for platform default.
    """
    if impl is None:
        impl = "pallas" if jax.default_backend() == "tpu" else "numpy"
    if impl == "pallas_interpret" and not _HAVE_TPU_INTERPRET:
        raise NotImplementedError(
            "impl='pallas_interpret' needs the TPU-flavored Pallas "
            "interpreter (pltpu.InterpretParams, JAX >= 0.7); this JAX has "
            "no CPU lowering for the pltpu prng — use impl='numpy'"
        )
    seed = int(seed) % (2 ** 31)  # callers may pass crc+counter sums ≥ int32 max
    shape = tuple(np.shape(x))
    flat = np.asarray(x, np.float32).reshape(-1) if impl == "numpy" else \
        jnp.asarray(x, jnp.float32).reshape(-1)
    n = flat.shape[0]
    if n == 0:
        return (np.zeros((0, GROUP), np.int8), np.zeros((0, 1), np.float32), shape)
    pad = (-n) % GROUP
    if impl == "numpy":
        groups = np.pad(flat, (0, pad)).reshape(-1, GROUP)
        vals, scales = _quantize_numpy(groups, seed)
    else:
        groups = jnp.pad(flat, (0, pad)).reshape(-1, GROUP)
        vals, scales = _quantize_pallas(groups, seed, impl == "pallas_interpret")
    return vals, scales, shape


def dequantize_int8(values, scales, shape):
    """Inverse of :func:`quantize_int8` → float32 array of ``shape``."""
    values = np.asarray(values, np.float32)
    scales = np.asarray(scales, np.float32)
    flat = (values * scales).reshape(-1)
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    return flat[:n].reshape(shape)
