"""Fused GroupNorm(+ReLU) with a closed-form backward.

The flagship's profile (docs/PERF.md) charges ~0.77 ms of the 5.8 ms
backward to GroupNorm: autodiff of the two-pass normalization re-derives
the statistics' gradients through the full reduction graph.  This version

- computes the SAME statistics as ``flax.linen.GroupNorm`` (float32
  mean/var via the fast mean-of-squares formula, ``use_fast_variance``
  semantics, same ``epsilon`` placement), so it is numerically
  interchangeable with the shipped models' norm layers;
- saves only ``(x, mean, rstd)`` and applies the closed-form GN backward
  (one fused elementwise pass + two small per-group reductions) instead of
  differentiating the forward graph;
- optionally fuses the trailing ReLU (the ``_ConvBlock`` pattern) so the
  activation needs no extra HBM round-trip in either direction.

No reference counterpart (torch GroupNorm + cuDNN there); the exactness
contract is against ``flax.linen.GroupNorm`` — see
``tests/test_groupnorm.py``.
"""
import functools

import jax
import jax.numpy as jnp

__all__ = ["group_norm", "fused_group_norm_module", "norm_relu"]


def _stats(x32, groups):
    """(B, S, G, c) float32 view + flax-compatible mean/var over (S, c)."""
    mean = jnp.mean(x32, axis=(1, 3), keepdims=True)
    # use_fast_variance: E[x²] − E[x]² (flax default)
    var = jnp.mean(x32 * x32, axis=(1, 3), keepdims=True) - mean * mean
    var = jnp.maximum(var, 0.0)
    return mean, var


def _grouped(x, groups):
    b, c = x.shape[0], x.shape[-1]
    return x.reshape(b, -1, groups, c // groups)


# groups/eps/relu are STATIC (shape-determining) — nondiff_argnums keeps
# them concrete when the op is traced inside jit
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _gn(x, scale, bias, groups, eps, relu):
    y, _ = _gn_fwd_impl(x, scale, bias, groups, eps, relu)
    return y


def _gn_fwd_impl(x, scale, bias, groups, eps, relu):
    orig_shape, orig_dtype = x.shape, x.dtype
    xg = _grouped(x, groups).astype(jnp.float32)
    mean, var = _stats(xg, groups)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (xg - mean) * rstd
    c = orig_shape[-1] // groups
    g = scale.astype(jnp.float32).reshape(1, 1, groups, c)
    b = bias.astype(jnp.float32).reshape(1, 1, groups, c)
    y = xhat * g + b
    if relu:
        y = jnp.maximum(y, 0.0)
    y = y.reshape(orig_shape).astype(orig_dtype)
    return y, (x, mean, rstd)


def _gn_fwd(x, scale, bias, groups, eps, relu):
    y, res = _gn_fwd_impl(x, scale, bias, groups, eps, relu)
    return y, (res, scale, bias)


def _gn_bwd(groups, eps, relu, saved, dy):
    (x, mean, rstd), scale, bias = saved
    orig_shape = x.shape
    c = orig_shape[-1] // groups
    xg = _grouped(x, groups).astype(jnp.float32)
    xhat = (xg - mean) * rstd
    g = scale.astype(jnp.float32).reshape(1, 1, groups, c)
    dyg = _grouped(dy, groups).astype(jnp.float32)
    if relu:
        # gate by the forward activation sign (recomputed from residuals —
        # one fused elementwise chain, no extra saved tensor)
        b = bias.astype(jnp.float32).reshape(1, 1, groups, c)
        dyg = jnp.where(xhat * g + b > 0, dyg, 0.0)
    # d(scale)/d(bias): per-channel reductions
    dscale = jnp.sum(dyg * xhat, axis=(0, 1)).reshape(-1)
    dbias = jnp.sum(dyg, axis=(0, 1)).reshape(-1)
    # closed-form dx: rstd * (dxhat − mean(dxhat) − xhat·mean(dxhat·xhat))
    dxhat = dyg * g
    m1 = jnp.mean(dxhat, axis=(1, 3), keepdims=True)
    m2 = jnp.mean(dxhat * xhat, axis=(1, 3), keepdims=True)
    dx = rstd * (dxhat - m1 - xhat * m2)
    return (
        dx.reshape(orig_shape).astype(x.dtype),
        dscale.astype(scale.dtype),
        dbias.astype(bias.dtype),
    )


_gn.defvjp(_gn_fwd, _gn_bwd)


def group_norm(x, scale, bias, groups, eps=1e-6, relu=False):
    """GroupNorm over the channels-last axis (+ optional fused ReLU).

    Matches ``flax.linen.GroupNorm(num_groups=groups, epsilon=eps)`` (f32
    statistics, fast variance) followed by ``relu`` when requested; the
    backward is the closed-form GN gradient computed from saved
    ``(x, mean, rstd)``.  ``x``: ``(B, *spatial, C)``; ``scale``/``bias``:
    ``(C,)``; returns ``x.dtype``.
    """
    if x.shape[-1] % groups:
        raise ValueError(f"channels {x.shape[-1]} not divisible by {groups}")
    return _gn(x, scale, bias, int(groups), float(eps), bool(relu))


def norm_relu(x, features, dtype, fused, relu, name):
    """GroupNorm(+optional relu) dispatch shared by the CNN families: the
    fused closed-form-backward op when ``fused``, plain ``nn.GroupNorm``
    (+relu) otherwise — either way with ``min(8, features)`` groups and the
    param path pinned to the plain layout via ``name``.  Must be called
    inside a flax ``@nn.compact`` ``__call__``."""
    import flax.linen as nn

    groups = min(8, features)
    if fused:
        return fused_group_norm_module()(
            num_groups=groups, use_relu=relu, dtype=dtype, name=name
        )(x)
    y = nn.GroupNorm(num_groups=groups, dtype=dtype, name=name)(x)
    return nn.relu(y) if relu else y


def fused_group_norm_module():
    import flax.linen as nn

    class _FusedGroupNorm(nn.Module):
        """Drop-in for ``nn.GroupNorm(num_groups)(x)`` (+ optional fused
        relu) — param names/shapes identical (``scale``/``bias``, (C,)), so
        checkpoints and the torch importer see the same tree.  Pass
        ``name="GroupNorm_N"`` to keep auto-numbered paths stable when
        swapping it into an existing model."""

        num_groups: int
        epsilon: float = 1e-6
        use_relu: bool = False
        dtype: jnp.dtype = jnp.float32  # kept for signature parity; stats are f32

        @nn.compact
        def __call__(self, x):
            ch = x.shape[-1]
            scale = self.param("scale", nn.initializers.ones, (ch,), jnp.float32)
            bias = self.param("bias", nn.initializers.zeros, (ch,), jnp.float32)
            return group_norm(
                x, scale, bias, self.num_groups, self.epsilon, self.use_relu
            )

    return _FusedGroupNorm
