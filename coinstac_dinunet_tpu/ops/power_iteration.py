"""Low-rank compression kernels: subspace power iteration + orthogonalization.

Capability parity with the reference's ``power_iteration_BC`` deflation power
method (``rankdad/spi.py:9-86``) and PowerSGD's ``_orthogonalize`` Gram-Schmidt
(``powersgd/__init__.py:15-38``), re-designed for the TPU:

- **Block subspace iteration instead of one-by-one deflation.**  The reference
  extracts singular directions sequentially (r dependent passes with
  tolerance-based early stop — data-dependent control flow that cannot
  compile).  We iterate a whole rank-r block at once: every step is a chain of
  large matmuls on the MXU, the iteration count is static
  (``lax.fori_loop``), and the block converges to the same top-r subspace.
- **QR instead of modified Gram-Schmidt.**  ``jnp.linalg.qr`` is an XLA-native
  batched primitive; column-by-column Gram-Schmidt is a scalar loop.

Math.  Given paired matrices ``B (N, dout)`` and ``C (N, din)`` (per-layer
output-gradients and input-activations; ``A = Bᵀ C`` is (transposed) the
weight gradient), the reference powers the operator ``A Aᵀ`` — i.e. it
computes a truncated SVD of the gradient matrix (both its ``cm > cn``
branches are the same operator, re-associated for cost).  We do block
iteration on the same operator, never materializing ``A`` (dout×din) or the
N×N Gram matrix:

    Q ← orth( Bᵀ (C (Cᵀ (B Q))) )        # A Aᵀ Q, cost O(N (dout+din) r)

then ship ``Br = Qᵀ (r, dout)`` and ``Cr = (B Q)ᵀ C (r, din)``:

    Brᵀ Cr  =  Q Qᵀ A  ≈  A  =  Bᵀ C.

Concatenating sites' ``(Br, Cr)`` along the rank axis and multiplying is
exactly the sum of their approximations — the aggregator-side concat
semantics of rankDAD (``rankdad/__init__.py:70-98``) for free.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax


def orthogonalize(m, eps=1e-8):
    """Column-orthonormalize ``m (n, r)`` (requires ``n >= r``).

    Rank-1 fast path is a plain normalize; otherwise reduced QR (XLA-native).
    Columns of (near-)zero norm come out as zero columns, matching the
    reference's epsilon-guarded Gram-Schmidt behavior.
    """
    m = jnp.asarray(m)
    if m.ndim != 2:
        raise ValueError(f"orthogonalize expects a matrix, got shape {m.shape}")
    if m.shape[1] == 1:
        norm = jnp.linalg.norm(m)
        return m / jnp.maximum(norm, eps)
    q, r = jnp.linalg.qr(m)
    # zero out columns QR fabricated for rank-deficient input
    keep = (jnp.abs(jnp.diagonal(r)) > eps).astype(m.dtype)
    return q * keep[None, :]


@functools.partial(jax.jit, static_argnames=("rank", "iterations"))
def power_iteration_BC(B, C, key, rank=10, iterations=5):
    """Joint low-rank factors of the pair ``(B, C)``; see module docstring.

    Args:
      B: ``(N, dout)`` — per-sample output gradients of one layer.
      C: ``(N, din)`` — per-sample input activations of the same layer.
      key: PRNG key for the random subspace start.
      rank: target rank ``r`` (ref default 10, ``rankdad/spi.py:116``).
      iterations: fixed power-iteration count (ref default 5 with early stop,
        ``spi.py:117``; fixed here for compile-friendliness).

    Returns:
      ``(Br, Cr)`` with shapes ``(r, dout)`` and ``(r, din)`` such that
      ``Brᵀ @ Cr ≈ Bᵀ @ C``.  Exact (zero-padded to static rank r) whenever
      the pair's true rank is ≤ r: if ``N <= r`` the raw pair ships; if
      ``dout <= r`` the identity basis ships.
    """
    B = jnp.asarray(B)
    C = jnp.asarray(C)
    n, dout = B.shape
    din = C.shape[1]

    if n <= rank:
        # nothing to compress: ship the raw pair, padded to static rank
        pad = rank - n
        return (
            jnp.pad(B, ((0, pad), (0, 0))),
            jnp.pad(C, ((0, pad), (0, 0))),
        )

    if dout <= rank:
        # output space smaller than rank: identity basis is exact
        Br = jnp.pad(jnp.eye(dout, dtype=B.dtype), ((0, rank - dout), (0, 0)))
        Cr = jnp.pad(B.T @ C, ((0, rank - dout), (0, 0)))
        return Br, Cr

    def body(_, Q):
        # (A Aᵀ) Q with A = Bᵀ C, associated to stay in N- and r-width ops
        return orthogonalize(B.T @ (C @ (C.T @ (B @ Q))))

    Q0 = orthogonalize(jax.random.normal(key, (dout, rank), dtype=B.dtype))
    Q = lax.fori_loop(0, iterations, body, Q0)
    Br = Q.T
    Cr = (B @ Q).T @ C
    return Br, Cr
