"""Space-to-depth reparametrization of stride-2 convolutions (N-D).

The MLPerf ResNet conv0 trick, generalized: a stride-2 odd-kernel SAME conv
on a few-channel input underfills the TPU MXU's 128-wide contraction (the
channel dim pads onto the lanes).  Rewriting the input as 2^n-blocked
channels and convolving with an equivalently remapped kernel computes the
IDENTICAL function with a 2^n·cin-deep contraction.

Derivation (per spatial dim, kernel k odd, stride 2, even input size):
SAME padding is ``lo=(k-2)//2, hi=k-2-lo``; output position ``o`` reads
input index ``2o + r`` for relative tap ``r = t - lo``.  Under block-2
space-to-depth that index lives in block ``o + floor(r/2)`` at in-block
offset ``r mod 2``, so tap ``t`` maps to kernel position
``p = floor(r/2) - b_min`` over blocks and channel-slot ``r mod 2``; the
remapped conv has kernel size ``K2 = b_max - b_min + 1``, stride 1, and
explicit padding ``(-b_min, K2 - 1 + b_min)``.  The whole remap is a fixed
one-hot matrix applied to the canonical kernel, so the PARAMETER keeps its
canonical ``(k,)*n + (cin, f)`` shape — checkpoints and cross-framework
comparisons are unaffected.

Reference counterpart: none (the reference's torch models rely on cuDNN's
implicit-GEMM conv; SURVEY §2 "native layer" note).
"""
import functools

import numpy as np

import jax.numpy as jnp
from jax import lax


@functools.lru_cache(maxsize=None)
def _dim_spec(k):
    """Per-dimension tap map for kernel size ``k`` (odd), stride 2, SAME.

    Returns (K2, pad, taps) where ``taps[t] = (p, slot)``: canonical tap
    ``t`` lands at remapped kernel position ``p`` reading block-channel
    slot ``slot``; ``pad = (lo, hi)`` is the remapped conv's explicit
    padding."""
    if k % 2 == 0:
        raise ValueError(f"space-to-depth remap expects an odd kernel, got {k}")
    lo = (k - 2) // 2 if k > 1 else 0
    rs = [t - lo for t in range(k)]
    bmins = [r // 2 for r in rs]
    b_min, b_max = min(bmins), max(bmins)
    K2 = b_max - b_min + 1
    taps = tuple((r // 2 - b_min, r % 2) for r in rs)
    return K2, (-b_min, K2 - 1 + b_min), taps


@functools.lru_cache(maxsize=None)
def s2d_kernel_map(kernel_shape, cin):
    """One-hot matrix mapping the canonical kernel to the remapped one.

    ``kernel_shape``: the spatial dims (k,)*n.  Returns (T, K2_shape, pads)
    with ``T`` of shape ``(prod(k)·cin, prod(K2)·2^n·cin)``; the remapped
    kernel is ``(T.T @ kernel.reshape(-1, f)).reshape(*K2_shape, 2^n·cin,
    f)`` and the blocked input's channel slot order is
    ``(offset_dims..., cin)`` — matching :func:`space_to_depth_nd`."""
    n = len(kernel_shape)
    specs = [_dim_spec(k) for k in kernel_shape]
    K2s = tuple(s[0] for s in specs)
    pads = tuple(s[1] for s in specs)
    T = np.zeros((int(np.prod(kernel_shape)) * cin,
                  int(np.prod(K2s)) * (2 ** n) * cin), np.float32)
    for t_flat in range(int(np.prod(kernel_shape))):
        ts, rem = [], t_flat
        for k in reversed(kernel_shape):
            ts.append(rem % k)
            rem //= k
        ts.reverse()
        ps = [specs[d][2][ts[d]][0] for d in range(n)]
        slots = [specs[d][2][ts[d]][1] for d in range(n)]
        p_flat = 0
        for d in range(n):
            p_flat = p_flat * K2s[d] + ps[d]
        slot_flat = 0
        for d in range(n):
            slot_flat = slot_flat * 2 + slots[d]
        for c in range(cin):
            T[t_flat * cin + c,
              (p_flat * (2 ** n) + slot_flat) * cin + c] = 1.0
    return T, K2s, pads


def space_to_depth_nd(x):
    """(B, s1..sn, C) with even spatial dims -> (B, s1/2..sn/2, 2^n·C).

    Channel order: (block-offset dims major, original channel minor) — the
    order :func:`s2d_kernel_map` emits."""
    n = x.ndim - 2
    b, *spatial, c = x.shape
    shape = [b]
    for s in spatial:
        shape += [s // 2, 2]
    shape += [c]
    xs = x.reshape(shape)
    # (B, s1/2, 2, s2/2, 2, ..., C) -> (B, s1/2.., 2(offsets).., C)
    perm = ([0] + [1 + 2 * d for d in range(n)]
            + [2 + 2 * d for d in range(n)] + [1 + 2 * n])
    xs = xs.transpose(perm)
    return xs.reshape([b] + [s // 2 for s in spatial] + [(2 ** n) * c])


_CONV_DIMS = {
    1: ("NHC", "HIO", "NHC"),
    2: ("NHWC", "HWIO", "NHWC"),
    3: ("NDHWC", "DHWIO", "NDHWC"),
}


def s2d_stride2_conv(x, kernel):
    """Stride-2 SAME conv with canonical ``kernel`` ((k,)*n, cin, f), run as
    its block-2 space-to-depth reparametrization.  Requires even spatial
    dims and odd k; callers gate and fall back to the plain conv otherwise."""
    n = x.ndim - 2
    *ks, cin, f = kernel.shape
    T, K2s, pads = s2d_kernel_map(tuple(ks), cin)
    k2 = (jnp.asarray(T, kernel.dtype).T @ kernel.reshape(-1, f))
    k2 = k2.reshape(*K2s, (2 ** n) * cin, f)
    xs = space_to_depth_nd(x)
    return lax.conv_general_dilated(
        xs, k2, (1,) * n, pads, dimension_numbers=_CONV_DIMS[n]
    )


def use_s2d(x_spatial_shape, kernel_spatial_shape):
    """True when the s2d path applies: even input dims, odd kernel, and the
    ``COINN_NO_S2D`` kill-switch not set."""
    import os

    no = os.environ.get("COINN_NO_S2D", "").lower() not in ("", "0", "false")
    return (not no
            and all(s % 2 == 0 for s in x_spatial_shape)
            and all(k % 2 == 1 for k in kernel_spatial_shape))


def stride2_conv(x, kernel):
    """Stride-2 SAME conv that takes the space-to-depth fast path when
    shapes allow (identical math either way) — the one-call form both CNN
    stems use.  ``kernel``: canonical ((k,)*n, cin, f)."""
    n = x.ndim - 2
    if use_s2d(x.shape[1:-1], kernel.shape[:-2]):
        return s2d_stride2_conv(x, kernel)
    return lax.conv_general_dilated(
        x, kernel, (2,) * n, "SAME", dimension_numbers=_CONV_DIMS[n]
    )
