"""Training-curve plotting (≙ reference ``vision/plotter.py:20-65``).

``log_header`` convention: ``|``-separated sub-plot groups, each group a
``,``-separated list of column names — e.g. ``"loss|precision,recall,f1"``
plots loss alone and the three scores together.  One PNG per log key.
"""
import os

import numpy as np


def _rolling_mean(x, w):
    if len(x) < 2 * w:
        return None
    kernel = np.ones(w) / w
    return np.convolve(x, kernel, mode="valid")


def _epoch_ticks(n_rows, epoch, max_ticks=10):
    """(tick positions, labels) relabeling the row axis in epoch numbers
    (≙ ref ``vision/plotter.py:51-60``), thinned to ``max_ticks``.

    Works in both directions: more rows than epochs (several log rows per
    epoch) and fewer rows than epochs (``validation_epochs > 1`` — row i is
    epoch ``(i+1)·epoch/n_rows``).
    """
    step = max(int(np.ceil(n_rows / max_ticks)), 1)
    positions = list(range(0, n_rows, step))
    labels = []
    for p in positions:
        e = (p + 1) * float(epoch) / n_rows
        labels.append(int(round(e)) if abs(e - round(e)) < 1e-9 else round(e, 1))
    return positions, labels


def plot_progress(cache, log_dir=None, plot_keys=("train_log",), epoch=None):
    """Render raw + rolling-mean curves for every key's accumulated rows.

    ``epoch``: when given and different from the row count, x-ticks are
    remapped so labels read in epochs rather than log rows."""
    import matplotlib

    matplotlib.use("Agg", force=True)
    import matplotlib.pyplot as plt

    log_dir = log_dir or cache.get("log_dir", ".")
    os.makedirs(log_dir, exist_ok=True)
    header = cache.get("log_header", "loss")
    groups = [
        [c.strip() for c in grp.split(",") if c.strip()]
        for grp in header.split("|")
    ]
    for key in plot_keys:
        rows = cache.get(key, [])
        if len(rows) < 2:
            continue
        data = np.asarray([list(np.ravel(r)) for r in rows], dtype=float)
        ncols = data.shape[1]
        # assign columns to groups left-to-right; spill into the last group
        fig, axes = plt.subplots(
            1, len(groups), figsize=(6 * len(groups), 4), squeeze=False
        )
        col = 0
        for gi, grp in enumerate(groups):
            ax = axes[0][gi]
            take = grp if gi < len(groups) - 1 else grp + [
                f"col{c}" for c in range(col + len(grp), ncols)
            ]
            for name in take:
                if col >= ncols:
                    break
                series = data[:, col]
                ax.plot(series, alpha=0.4, label=name)
                rm = _rolling_mean(series, max(len(series) // 10, 2))
                if rm is not None:
                    ax.plot(
                        np.arange(len(series) - len(rm), len(series)), rm,
                        linewidth=2,
                    )
                col += 1
            if epoch and int(epoch) != len(rows) and int(epoch) > 0:
                ticks, labels = _epoch_ticks(len(rows), epoch)
                ax.set_xticks(ticks)
                ax.set_xticklabels(labels)
                ax.set_xlabel("epoch")
            else:
                ax.set_xlabel("epoch" if "log" in key else "step")
            ax.legend(loc="best", fontsize=8)
            ax.grid(alpha=0.3)
        fig.tight_layout()
        fig.savefig(os.path.join(log_dir, f"{key}.png"), dpi=100)
        plt.close(fig)
