"""Neuroimaging / segmentation image utilities (TPU-framework edition).

Capability parity with the reference's ``vision/imageutils.py:21-348`` —
image container with mask/ground-truth/CLAHE, TP-FP-FN RGB visualization,
patch chunking / mirror-expansion / merging for U-Net-style tiling, largest
connected component, small-component pruning, pixel neighbors — re-designed
rather than translated:

- **N-dimensional.** The reference's patch machinery is 2-D only
  (``imageutils.py:177-279``); the flagship workload here is volumetric
  (VBM 3-D), so chunking/merging/expansion work for any rank.  2-D calls
  behave like the reference.
- **Vectorized.** Per-pixel Python loops (ref ``:317-325``) are replaced by
  ``scipy.ndimage`` labeling + ``numpy`` reductions; patch merging
  accumulates into sum/count buffers with slice assignment instead of
  whole-image pads per patch (ref ``:238-250`` allocates two full-image
  arrays per patch).
- **Known-defect fixes** (SURVEY.md §2 latent defects): patch averaging
  counts *coverage* rather than ``padded > 0`` — the reference undercounts
  wherever a patch legitimately contains zeros, biasing overlap averages
  upward; component pruning measures the true max pairwise extent of each
  component's bounding box instead of first-vs-last scan-order pixels.
- **Optional deps gated.** PIL/cv2 are imported lazily; CLAHE falls back to
  a numpy tile-interpolated implementation when OpenCV is absent.
"""
import copy as _copy
import math
import os

import numpy as np

from ..utils import logger

__all__ = [
    "Image",
    "get_rgb_scores",
    "get_praf1",
    "rescale",
    "rescale2d",
    "rescale3d",
    "get_signed_diff_int8",
    "whiten_image2d",
    "get_chunk_indexes",
    "get_chunk_indices_by_index",
    "merge_patches",
    "expand_and_mirror_patch",
    "largest_cc",
    "map_img_to_img2d",
    "remove_connected_comp",
    "get_pix_neigh",
]


class Image:
    """Container for an image, its mask, and its ground truth.

    Mirrors the reference container API (``vision/imageutils.py:21-85``):
    ``load``/``load_mask``/``load_ground_truth`` resolve files through a
    filename-mapping callable, ``apply_mask`` zeroes outside the mask, and
    ``apply_clahe`` contrast-equalizes in place.  Load failures are logged
    and swallowed (matching ``safe_collate``-style robustness, ref
    ``data/data.py:23-27``).
    """

    def __init__(self, dtype=np.uint8):
        self.dir = None
        self.file = None
        self.array = None
        self.mask = None
        self.ground_truth = None
        self.extras = {}
        self.dtype = dtype

    @staticmethod
    def _read(path, dtype):
        from PIL import Image as PILImage

        return np.array(PILImage.open(path), dtype=dtype)

    @property
    def path(self):
        return os.path.join(self.dir, self.file)

    def load(self, dir, file):
        try:
            self.dir, self.file = dir, file
            self.array = self._read(self.path, self.dtype)
        except Exception as e:  # noqa: BLE001 — parity: log-and-continue
            logger.error(f"loading image file {file}: {e}")

    def load_mask(self, mask_dir=None, fget_mask=lambda x: x):
        try:
            self.mask = self._read(
                os.path.join(mask_dir, fget_mask(self.file)), self.dtype
            )
        except Exception as e:  # noqa: BLE001
            logger.error(f"loading mask for {self.file}: {e}")

    def load_ground_truth(self, gt_dir=None, fget_ground_truth=lambda x: x):
        try:
            self.ground_truth = self._read(
                os.path.join(gt_dir, fget_ground_truth(self.file)), self.dtype
            )
        except Exception as e:  # noqa: BLE001
            logger.error(f"loading ground truth for {self.file}: {e}")

    def get_array(self, dir="", getter=lambda x: x, file=None):
        return self._read(os.path.join(dir, getter(file or self.file)), self.dtype)

    def apply_mask(self):
        if self.mask is not None:
            self.array[self.mask == 0] = 0

    def apply_clahe(self, clip_limit=2.0, tile_shape=(8, 8)):
        if self.array.ndim == 2:
            self.array = _clahe(self.array, clip_limit, tile_shape)
        elif self.array.ndim == 3:
            for c in range(min(self.array.shape[-1], 3)):
                self.array[..., c] = _clahe(self.array[..., c], clip_limit, tile_shape)
        else:
            logger.warn(f"apply_clahe skipped: unsupported {self.array.ndim}-D array")

    def __copy__(self):
        out = Image(dtype=_copy.deepcopy(self.dtype))
        out.dir = self.dir
        out.file = self.file
        out.array = _copy.copy(self.array)
        out.mask = _copy.copy(self.mask)
        out.ground_truth = _copy.copy(self.ground_truth)
        out.extras = _copy.deepcopy(self.extras)
        return out


def _clahe(arr2d, clip_limit, tile_shape):
    """CLAHE via OpenCV when present, else a numpy tile-equalization fallback."""
    try:
        import cv2

        return cv2.createCLAHE(
            clipLimit=clip_limit, tileGridSize=tuple(tile_shape)
        ).apply(np.ascontiguousarray(arr2d, np.uint8))
    except Exception:  # noqa: BLE001 — cv2 absent or non-uint8 input
        return _clahe_numpy(arr2d, clip_limit, tile_shape)


def _clahe_numpy(arr2d, clip_limit, tile_shape):
    """Tile-wise clipped histogram equalization, bilinearly interpolated.

    Fallback used only when OpenCV is unavailable; same knobs (clip limit in
    multiples of the uniform bin height, tile grid shape).
    """
    arr = np.ascontiguousarray(arr2d, np.uint8)
    h, w = arr.shape
    th, tw = max(h // tile_shape[0], 1), max(w // tile_shape[1], 1)
    gy, gx = math.ceil(h / th), math.ceil(w / tw)
    # per-tile clipped CDF lookup tables
    luts = np.zeros((gy, gx, 256), np.float32)
    for i in range(gy):
        for j in range(gx):
            tile = arr[i * th:(i + 1) * th, j * tw:(j + 1) * tw]
            hist = np.bincount(tile.ravel(), minlength=256).astype(np.float64)
            clip = clip_limit * tile.size / 256.0
            excess = np.maximum(hist - clip, 0).sum()
            hist = np.minimum(hist, clip) + excess / 256.0
            cdf = hist.cumsum()
            cdf = cdf / max(cdf[-1], 1.0)
            luts[i, j] = (cdf * 255.0).astype(np.float32)
    # bilinear interpolation between the four surrounding tile LUTs
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    fy = (yy + 0.5) / th - 0.5
    fx = (xx + 0.5) / tw - 0.5
    y0 = np.clip(np.floor(fy).astype(int), 0, gy - 1)
    x0 = np.clip(np.floor(fx).astype(int), 0, gx - 1)
    y1 = np.clip(y0 + 1, 0, gy - 1)
    x1 = np.clip(x0 + 1, 0, gx - 1)
    wy = np.clip(fy - y0, 0.0, 1.0)
    wx = np.clip(fx - x0, 0.0, 1.0)
    v = arr
    out = (
        luts[y0, x0, v] * (1 - wy) * (1 - wx)
        + luts[y1, x0, v] * wy * (1 - wx)
        + luts[y0, x1, v] * (1 - wy) * wx
        + luts[y1, x1, v] * wy * wx
    )
    return out.astype(np.uint8)


def _binarize(a):
    a = a.copy()
    a[a == 255] = 1
    return a


def get_rgb_scores(arr_2d=None, truth=None):
    """RGB TP/FP/FN map of a binary prediction vs ground truth
    (≙ ref ``imageutils.py:88-107``): TP white, FP green, FN red, TN black.
    """
    code = _binarize(arr_2d).astype(np.int64) + 2 * _binarize(truth).astype(np.int64)
    palette = np.array(
        [[0, 0, 0], [0, 255, 0], [255, 0, 0], [255, 255, 255]], np.uint8
    )
    return palette[code]


def get_praf1(arr_2d=None, truth=None):
    """Precision/recall/F1/accuracy between two binary arrays, 5 decimals
    (≙ ref ``imageutils.py:110-151``)."""
    code = _binarize(arr_2d).astype(np.int64) + 2 * _binarize(truth).astype(np.int64)
    counts = np.bincount(code.ravel(), minlength=4)
    tn, fp, fn, tp = (int(c) for c in counts[:4])
    p = tp / (tp + fp) if tp + fp else 0
    r = tp / (tp + fn) if tp + fn else 0
    a = (tp + tn) / max(tp + fp + fn + tn, 1)
    f1 = 2 * p * r / (p + r) if p + r else 0
    return {
        "Precision": round(p, 5),
        "Recall": round(r, 5),
        "Accuracy": round(a, 5),
        "F1": round(f1, 5),
    }


def rescale(arr):
    """Min-max rescale to [0, 1] (any rank)."""
    arr = np.asarray(arr, np.float64)
    lo, hi = arr.min(), arr.max()
    return (arr - lo) / max(hi - lo, np.finfo(np.float64).tiny)


rescale2d = rescale  # parity aliases (ref ``imageutils.py:154-161``)


def rescale3d(arrays):
    return [rescale(a) for a in arrays]


def get_signed_diff_int8(image_arr1=None, image_arr2=None):
    """Signed difference image, rescaled to uint8 (≙ ref ``:164-168``)."""
    diff = np.asarray(image_arr1, np.int16) - np.asarray(image_arr2, np.int16)
    return (rescale(diff.astype(np.int8)) * 255).astype(np.uint8)


def whiten_image2d(img_arr2d=None):
    """Zero-mean/unit-std whiten then rescale to uint8 (≙ ref ``:171-174``)."""
    z = (img_arr2d - img_arr2d.mean()) / max(img_arr2d.std(), 1e-12)
    return (rescale(z) * 255).astype(np.uint8)


def get_chunk_indexes(img_shape, chunk_shape, offset=None):
    """Corners of sliding patches covering an N-D image.

    Yields ``[d0_from, d0_to, d1_from, d1_to, ...]`` per patch.  Strided by
    ``offset`` per axis; the trailing patch in each axis is shifted back so it
    ends exactly at the image border (same tiling semantics as ref
    ``imageutils.py:177-208``, generalized from 2-D to any rank — VBM volumes
    tile with the identical call).
    """
    if offset is None:
        offset = chunk_shape
    axes = []
    for size, chunk, step in zip(img_shape, chunk_shape, offset):
        starts = []
        for i in range(0, size, step):
            if i + chunk >= size:
                starts.append(max(size - chunk, 0))
                break
            starts.append(i)
        axes.append(starts)
    grids = np.meshgrid(*[np.arange(len(a)) for a in axes], indexing="ij")
    for idx in zip(*(g.ravel() for g in grids)):
        out = []
        for ax, i in enumerate(idx):
            start = axes[ax][i]
            # clamp: an image smaller than the chunk yields one full-image patch
            out += [int(start), int(min(start + chunk_shape[ax], img_shape[ax]))]
        yield out


def get_chunk_indices_by_index(img_shape, chunk_shape, indices=None):
    """Patch corners centered on given points, clamped inside the image
    (≙ ref ``imageutils.py:211-226``, any rank)."""
    out = []
    for center in indices:
        corners = []
        for size, chunk, c in zip(img_shape, chunk_shape, center):
            lo, hi = c - chunk // 2, c - chunk // 2 + chunk
            if lo < 0:
                lo, hi = 0, chunk
            if hi > size:
                lo, hi = max(size - chunk, 0), size
            corners += [int(lo), int(min(hi, size))]
        out.append(corners)
    return out


def merge_patches(patches, image_size, patch_size, offset=None, out_dtype=None):
    """Reassemble patches produced by :func:`get_chunk_indexes`; overlaps
    averaged by true coverage count.

    Unlike the reference (``imageutils.py:229-250``) this accumulates into a
    single sum/count buffer pair with slice assignment (no per-patch
    full-image pad), counts every covered pixel — the reference's
    ``padded > 0`` test drops zero-valued patch pixels from the denominator —
    and preserves the patch dtype (probability maps stay float;
    ``out_dtype`` overrides, the reference always clamped to uint8).
    """
    acc = np.zeros(tuple(image_size), np.float64)
    cnt = np.zeros(tuple(image_size), np.int64)
    for i, corners in enumerate(get_chunk_indexes(image_size, patch_size, offset)):
        sl = tuple(
            slice(corners[2 * d], corners[2 * d + 1]) for d in range(len(image_size))
        )
        shape = tuple(s.stop - s.start for s in sl)
        acc[sl] += np.asarray(patches[i]).reshape(shape)
        cnt[sl] += 1
    if out_dtype is None:
        out_dtype = np.asarray(patches[0]).dtype
    return (acc / np.maximum(cnt, 1)).astype(out_dtype)


def expand_and_mirror_patch(full_img_shape, orig_patch_indices, expand_by):
    """Grow a patch window by ``expand_by`` per axis; where the grown window
    leaves the image, report mirror-padding amounts instead.

    Returns ``(clamped_corners, pad_per_axis)`` where ``clamped_corners`` is
    ``(lo0, hi0, lo1, hi1, …)`` and ``pad_per_axis`` feeds ``np.pad(...,
    mode='reflect')`` — the U-Net wide-context trick (≙ ref
    ``imageutils.py:253-279``, generalized to any rank)."""
    ndim = len(full_img_shape)
    corners, pads = [], []
    for d in range(ndim):
        half = int(expand_by[d] / 2)
        lo = orig_patch_indices[2 * d] - half
        hi = orig_patch_indices[2 * d + 1] + half
        pad_lo = max(-lo, 0)
        pad_hi = max(hi - full_img_shape[d], 0)
        corners += [lo + pad_lo, hi - pad_hi]
        pads.append((pad_lo, pad_hi))
    return tuple(corners) + (pads,)


def _label(binary_arr):
    from scipy import ndimage

    structure = np.ones((3,) * np.asarray(binary_arr).ndim, np.int8)
    return ndimage.label(binary_arr, structure)


def largest_cc(binary_arr=None):
    """Boolean mask of the largest connected component (≙ ref ``:282-287``;
    scipy.ndimage instead of skimage, full connectivity, any rank)."""
    labels, n = _label(binary_arr)
    if n == 0:
        return None
    sizes = np.bincount(labels.ravel())[1:]
    return labels == (int(np.argmax(sizes)) + 1)


def map_img_to_img2d(map_to, img):
    """Overlay binary ``img`` in red onto a grayscale/RGB base
    (≙ ref ``imageutils.py:290-301``)."""
    arr = np.asarray(map_to).copy()
    if arr.ndim == 2:
        arr = np.stack([arr] * 3, axis=-1).astype(np.uint8)
    hot = img == 255
    arr[..., 0][hot] = 255
    arr[..., 1][hot] = 0
    arr[..., 2][hot] = 0
    return arr


def remove_connected_comp(segmented_img, connected_comp_diam_limit=20):
    """Drop connected components whose bounding-box diagonal is below the
    diameter limit (≙ ref ``imageutils.py:304-325``).

    Vectorized: one labeling pass + per-component bounding boxes via
    ``ndimage.find_objects``; the component's extent is its bbox diagonal
    (the reference measured the distance between the first and last pixels in
    scan order — an underestimate for most shapes)."""
    from scipy import ndimage

    img = np.asarray(segmented_img).copy()
    labels, n = _label(img)
    keep = np.ones(n + 1, bool)
    for i, sl in enumerate(ndimage.find_objects(labels), start=1):
        if sl is None:
            continue
        diam = math.sqrt(sum((s.stop - 1 - s.start) ** 2 for s in sl))
        keep[i] = diam >= connected_comp_diam_limit
    img[~keep[labels]] = 0
    return img


def get_pix_neigh(i, j, eight=False):
    """4- or 8-neighborhood of pixel (i, j), same ordering as the reference
    (``imageutils.py:328-348``)."""
    if eight:
        return [
            (i - 1, j - 1), (i - 1, j), (i - 1, j + 1), (i, j - 1),
            (i, j + 1), (i + 1, j - 1), (i + 1, j), (i + 1, j + 1),
        ]
    return [(i - 1, j), (i, j + 1), (i + 1, j), (i, j - 1)]
