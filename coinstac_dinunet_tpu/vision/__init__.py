"""Vision extras: training-curve plotting + image/patch utilities.

Parity: reference ``coinstac_dinunet/vision/`` (``plotter.py``,
``imageutils.py``).
"""
from . import imageutils  # noqa: F401
from .imageutils import *  # noqa: F401,F403 — everything in imageutils.__all__
from .imageutils import __all__ as _iu_all
from .plotter import plot_progress  # noqa: F401

__all__ = ["plot_progress", "imageutils", *_iu_all]
