"""Vision extras: training-curve plotting + image/patch utilities.

Parity: reference ``coinstac_dinunet/vision/`` (``plotter.py``,
``imageutils.py``).
"""
from .plotter import plot_progress  # noqa: F401

__all__ = ["plot_progress"]
